"""Serving-path resilience over real sockets: deadlines (504), load
shedding (503), /health semantics, the feedback-sink breaker, hardened
/reload (probe + rollback), the ingest storage breaker, and the
supervisor's interruptible jittered restart backoff
(docs/operations.md "Failure modes and degradation")."""

import json
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.core.workflow import run_train
from predictionio_tpu.data.event import Event
from predictionio_tpu.server.engine_server import EngineServer
from predictionio_tpu.server.event_server import EventServer
from predictionio_tpu.server.eventsink import DirectEventSink, HTTPEventSink
from predictionio_tpu.server.http import HTTPServer, Response, Router
from predictionio_tpu.utils.faults import FAULTS, FaultError
from tests.test_servers import ServerThread, free_port, http

FACTORY = "predictionio_tpu.templates.recommendation.engine:engine_factory"

VARIANT = {
    "id": "default",
    "engineFactory": FACTORY,
    "datasource": {"params": {"appName": "QuickApp"}},
    "algorithms": [{"name": "als",
                    "params": {"rank": 8, "numIterations": 8,
                               "lambda": 0.05}}],
}


@pytest.fixture(autouse=True)
def disarm_faults():
    """Every test leaves the process-wide fault registry clean — an
    armed leftover plan would silently poison later tests."""
    FAULTS.disarm()
    yield
    FAULTS.disarm()


def http_full(method, url, body=None, headers=None):
    """Like tests.test_servers.http but also returns response headers
    (the Retry-After contract is part of what's under test)."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read().decode() or "null"), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "null"), dict(e.headers)


def seed_and_train(storage, app_name="QuickApp"):
    """App + ratings straight into storage (no event server needed),
    then one real train. Returns (app, instance_id)."""
    a = storage.meta.create_app(app_name)
    storage.events.init_channel(a.id)
    for u in range(12):
        for i in range(10):
            if (u + i) % 2 == 0:
                storage.events.insert(Event(
                    event="rate", entity_type="user", entity_id=str(u),
                    target_entity_type="item", target_entity_id=str(i),
                    properties={"rating": 4.0}), a.id)
    iid = run_train(FACTORY, variant=VARIANT, storage=storage, use_mesh=False)
    return a, iid


class TestQueryDeadline:
    def test_hung_query_answers_504_within_the_deadline(self, storage):
        seed_and_train(storage)
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port,
                              query_timeout_ms=300)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            # healthy first
            assert http("POST", f"{base}/queries.json",
                        {"user": "2", "num": 3})[0] == 200
            # storage/model hang: the worker sleeps far past the deadline
            FAULTS.arm("serving.query", latency=3.0)
            t0 = time.perf_counter()
            code, body = http("POST", f"{base}/queries.json",
                              {"user": "2", "num": 3})
            elapsed = time.perf_counter() - t0
            assert code == 504
            assert "deadline" in body["message"]
            # answered at ~the 300ms deadline, nowhere near the 3s hang
            assert elapsed < 2.0
            # deadline counter moved
            assert server._m_deadline._values.get((), 0) >= 1
            FAULTS.disarm()
            # recovered: next query is fine (stragglers don't wedge it)
            assert http("POST", f"{base}/queries.json",
                        {"user": "2", "num": 3})[0] == 200

    def test_error_paths_still_observe_latency_metrics(self, storage):
        # satellite: pio_engine_query_seconds must observe 400/500 too
        seed_and_train(storage)
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"

            def hist_total():
                # labelled histogram: one bucket-count series per status
                return sum(sum(c) for c in server._m_latency._counts.values())

            before_hist = hist_total()
            before_400 = server._m_queries._values.get(("400",), 0)
            code, _ = http("POST", f"{base}/queries.json", {"nope": 1})
            assert code == 400
            assert server._m_queries._values.get(("400",), 0) == before_400 + 1
            assert hist_total() == before_hist + 1


class TestLoadShedding:
    def test_past_the_cap_sheds_503_with_retry_after(self, storage):
        seed_and_train(storage)
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port, max_inflight=1)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            FAULTS.arm("serving.query", latency=1.0)
            results = {}

            def slow():
                results["slow"] = http("POST", f"{base}/queries.json",
                                       {"user": "2", "num": 3})

            t = threading.Thread(target=slow)
            t.start()
            # wait until the slow query is admitted (inflight == 1)
            deadline = time.time() + 5
            while server._inflight < 1 and time.time() < deadline:
                time.sleep(0.01)
            assert server._inflight == 1
            t0 = time.perf_counter()
            code, body, headers = http_full(
                "POST", f"{base}/queries.json", {"user": "3", "num": 3})
            shed_elapsed = time.perf_counter() - t0
            t.join(timeout=10)
            assert code == 503
            assert "overloaded" in body["message"]
            assert int(headers["Retry-After"]) >= 1
            assert shed_elapsed < 0.5   # shed instantly, no queueing
            assert results["slow"][0] == 200  # the admitted one finished
            # shed metric is per-app ("-" = no X-PIO-App header)
            assert server._m_shed._values.get(("-",), 0) >= 1


class TestHealth:
    def test_ok_when_serving_normally(self, storage):
        seed_and_train(storage)
        port = free_port()
        with ServerThread(EngineServer(engine_factory=FACTORY,
                                       storage=storage,
                                       host="127.0.0.1", port=port)):
            code, body = http("GET", f"http://127.0.0.1:{port}/health")
            assert code == 200
            assert body["status"] == "ok"
            assert body["breakers"]["feedback_sink"] == "closed"

    def test_not_ready_without_an_engine_then_reload_recovers(self, storage):
        # deploy-before-first-train: comes up not-ready, /reload later
        # brings the model in (require_engine=False)
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port,
                              require_engine=False)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            code, body = http("GET", f"{base}/health")
            assert code == 503 and body["status"] == "not-ready"
            code, body, headers = http_full(
                "POST", f"{base}/queries.json", {"user": "1", "num": 2})
            assert code == 503 and "Retry-After" in headers
            seed_and_train(storage)
            code, body = http("GET", f"{base}/reload")
            assert code == 200
            assert http("GET", f"{base}/health")[1]["status"] == "ok"
            assert http("POST", f"{base}/queries.json",
                        {"user": "2", "num": 3})[0] == 200

    def test_degraded_while_a_breaker_is_open_stays_200(self, storage):
        seed_and_train(storage)
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port)
        with ServerThread(server):
            for _ in range(5):
                server._sink_breaker.record_failure()
            code, body = http("GET", f"http://127.0.0.1:{port}/health")
            # 200, NOT 5xx: a supervisor must not restart a server that
            # is degrading correctly — restarts don't fix a down sink
            assert code == 200
            assert body["status"] == "degraded"
            assert "feedback_sink" in body["reason"]


class FailingSink:
    """An EventSink whose dependency is hard-down."""

    def __init__(self):
        self.attempts = 0

    def send(self, event):
        self.attempts += 1
        raise OSError("event server unreachable")


class TestFeedbackBreaker:
    def test_sustained_sink_failure_opens_breaker_serving_unaffected(
            self, storage):
        seed_and_train(storage)
        port = free_port()
        sink = FailingSink()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port,
                              feedback=True, event_sink=sink)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            for u in range(12):
                code, _ = http("POST", f"{base}/queries.json",
                               {"user": str(u % 5), "num": 2})
                assert code == 200  # feedback failures never break serving
            # wait for the feedback workers to drain
            deadline = time.time() + 10
            while time.time() < deadline:
                with server._counts_lock:
                    inflight = server._feedback_inflight
                if inflight == 0:
                    break
                time.sleep(0.05)
            counts = dict(server._m_feedback._values)
            assert server._sink_breaker.state == "open"
            # past the threshold, failures are fast breaker drops —
            # the sink itself stops being hammered
            assert counts.get(("breaker_open",), 0) >= 1
            assert counts.get(("error",), 0) >= server._sink_breaker.failure_threshold
            assert sink.attempts < 12


class TestHardenedReload:
    def test_reload_under_load_never_serves_an_error(self, storage):
        _, first = seed_and_train(storage)
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            assert http("POST", f"{base}/queries.json",
                        {"user": "2", "num": 3})[0] == 200
            second = run_train(FACTORY, variant=VARIANT, storage=storage,
                               use_mesh=False)
            stop = threading.Event()
            statuses = []

            def hammer():
                while not stop.is_set():
                    statuses.append(http("POST", f"{base}/queries.json",
                                         {"user": "2", "num": 3})[0])

            t = threading.Thread(target=hammer)
            t.start()
            try:
                code, body = http("GET", f"{base}/reload")
            finally:
                time.sleep(0.2)
                stop.set()
                t.join(timeout=10)
            assert code == 200 and body["engineInstanceId"] == second
            assert body["reloadGeneration"] == 1
            # old-or-new instance answered EVERY query; never an error
            assert statuses and set(statuses) == {200}

    def test_probe_failure_rolls_back_to_last_good_engine(self, storage):
        _, first = seed_and_train(storage)
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            # capture a last-good query for the probe
            assert http("POST", f"{base}/queries.json",
                        {"user": "2", "num": 3})[0] == 200
            run_train(FACTORY, variant=VARIANT, storage=storage,
                      use_mesh=False)
            # the candidate loads fine but cannot SERVE (probe fails)
            FAULTS.arm("serving.reload", error="candidate cannot serve")
            code, body = http("GET", f"{base}/reload")
            assert code == 500
            assert "rolled back" in body["message"]
            assert body["engineInstanceId"] == first
            # the last-good engine kept serving throughout
            assert http("GET", f"{base}/")[1]["engineInstanceId"] == first
            assert http("POST", f"{base}/queries.json",
                        {"user": "2", "num": 3})[0] == 200
            assert server._m_reloads._values.get(("rolled_back",), 0) >= 1
            # fault cleared → the same reload now succeeds
            FAULTS.disarm()
            code, body = http("GET", f"{base}/reload")
            assert code == 200 and body["engineInstanceId"] != first


class TestIngestStorageBreaker:
    def make_app(self, storage):
        a = storage.meta.create_app("BreakerApp")
        storage.events.init_channel(a.id)
        return a, storage.meta.create_access_key(a.id)

    def test_storage_outage_trips_breaker_to_fast_503(self, storage):
        _, key = self.make_app(storage)
        port = free_port()
        server = EventServer(storage=storage, host="127.0.0.1", port=port,
                             ingest_batching=True)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            ev = {"event": "view", "entityType": "user", "entityId": "u",
                  "targetEntityType": "item", "targetEntityId": "i"}
            url = f"{base}/events.json?accessKey={key.key}"
            assert http("POST", url, ev)[0] == 201  # healthy first
            FAULTS.arm("ingest.commit", error="event storage down")
            threshold = server._ingest.breaker.failure_threshold
            # each failed commit is a 500 until the breaker trips
            codes = [http("POST", url, ev)[0] for _ in range(threshold)]
            assert set(codes) == {500}
            assert server._ingest.breaker.state == "open"
            # now: IMMEDIATE 503 + Retry-After, storage never touched
            t0 = time.perf_counter()
            code, body, headers = http_full("POST", url, ev)
            assert code == 503
            assert "circuit breaker open" in body["message"]
            assert int(headers["Retry-After"]) >= 1
            assert time.perf_counter() - t0 < 0.5
            assert server._ingest.breaker_rejected >= 1
            # /health reports the degradation (still 200)
            code, health = http("GET", f"{base}/health")
            assert code == 200 and health["status"] == "degraded"
            assert health["ingest"]["breaker"] == "open"
            # recovery: storage back + breaker closed again → 201
            FAULTS.disarm()
            server._ingest.breaker.reset()
            assert http("POST", url, ev)[0] == 201
            assert http("GET", f"{base}/health")[1]["status"] == "ok"


class TestSupervisorBackoff:
    def test_restart_delays_are_jittered_exponential(self):
        from predictionio_tpu.tools.supervise import Supervisor

        sup = Supervisor(["true"], backoff=1.0, backoff_max=8.0)
        delays = sup._new_delays()
        for target in (1.0, 2.0, 4.0, 8.0, 8.0):
            d = next(delays)
            assert target / 2 <= d <= target

    def test_stop_interrupts_a_long_backoff_promptly(self):
        from predictionio_tpu.tools.supervise import Supervisor

        # the child crashes instantly; backoff would sleep 2.5-5s
        sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(1)"],
                         backoff=5.0, backoff_max=5.0, log=lambda *a: None)
        out = {}

        def run():
            out["code"] = sup.run()

        t = threading.Thread(target=run)
        t.start()
        # let it crash and enter the backoff sleep
        deadline = time.time() + 10
        while sup.restarts < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert sup.restarts >= 1
        t0 = time.perf_counter()
        sup.stop()
        t.join(timeout=10)
        assert not t.is_alive()
        # stopped in ~one 0.2s slice, not the full 2.5-5s backoff
        assert time.perf_counter() - t0 < 2.0
        assert out["code"] == 0


class TestReplicaIdentity:
    """Satellite: /health carries a process identity (instance uid,
    start time, reload generation) so a fleet router can tell a
    RESTARTED replica from a flapping one."""

    def test_health_carries_stable_process_identity(self, storage):
        seed_and_train(storage)
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            code, body = http("GET", f"{base}/health")
            assert code == 200
            assert body["instance"] == server.instance_uid
            assert len(body["instance"]) == 12
            assert body["startedAt"] == round(server.start_epoch, 3)
            assert body["reloadGeneration"] == 0
            # identity is per-process, not per-request
            assert http("GET", f"{base}/health")[1]["instance"] \
                == body["instance"]

    def test_not_ready_surfaces_identity_and_a_real_retry_hint(
            self, storage):
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port,
                              require_engine=False)
        with ServerThread(server):
            code, body, headers = http_full(
                "GET", f"http://127.0.0.1:{port}/health")
            assert code == 503 and body["status"] == "not-ready"
            assert body["instance"] == server.instance_uid
            # the hint is a number the server computed, not a constant
            # header bolted on at the end
            assert body["retryAfterSec"] > 0
            assert int(headers["Retry-After"]) >= 1

    def test_shed_503_hint_tracks_observed_latency(self, storage):
        seed_and_train(storage)
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port, max_inflight=1)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            assert http("POST", f"{base}/queries.json",
                        {"user": "2", "num": 3})[0] == 200
            assert server._lat_ewma > 0
            ewma_at_shed = server._lat_ewma  # the slow query hasn't
            # completed when the shed happens, so this is the EWMA the
            # hint is computed from
            FAULTS.arm("serving.query", latency=1.0)
            done = {}

            def slow():
                done["r"] = http("POST", f"{base}/queries.json",
                                 {"user": "2", "num": 3})

            t = threading.Thread(target=slow)
            t.start()
            deadline = time.time() + 5
            while server._inflight < 1 and time.time() < deadline:
                time.sleep(0.01)
            code, body, _ = http_full("POST", f"{base}/queries.json",
                                      {"user": "3", "num": 3})
            t.join(timeout=10)
            assert code == 503
            # shed hint = max(0.1, 2x the EWMA of served queries)
            assert body["retryAfterSec"] == pytest.approx(
                max(0.1, 2.0 * ewma_at_shed), rel=0.5)


class TestHopDeadline:
    def test_forwarded_deadline_tightens_the_query_timeout(self, storage):
        # a router's X-PIO-Deadline-Ms must bound the query even when
        # the server's own --query-timeout-ms is far looser
        seed_and_train(storage)
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port,
                              query_timeout_ms=30000)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            FAULTS.arm("serving.query", latency=3.0)
            t0 = time.perf_counter()
            code, body = http("POST", f"{base}/queries.json",
                              {"user": "2", "num": 3},
                              headers={"X-PIO-Deadline-Ms": "300"})
            elapsed = time.perf_counter() - t0
            assert code == 504
            assert elapsed < 2.0  # the 300ms hop budget won, not 30s

    def test_garbage_deadline_header_is_ignored(self, storage):
        seed_and_train(storage)
        port = free_port()
        server = EngineServer(engine_factory=FACTORY, storage=storage,
                              host="127.0.0.1", port=port)
        with ServerThread(server):
            code, _ = http("POST", f"http://127.0.0.1:{port}/queries.json",
                           {"user": "2", "num": 3},
                           headers={"X-PIO-Deadline-Ms": "bogus"})
            assert code == 200


class ThrottlingEventStub:
    """A fake Event Server that throttles the first N posts with 429 +
    Retry-After before accepting (or rejects outright)."""

    def __init__(self, port, throttles=0, retry_after="0.3", reject=None):
        self.port = port
        self.posts = 0
        self.throttles = throttles
        self.retry_after = retry_after
        self.reject = reject  # fixed 4xx status instead of accepting
        router = Router()
        router.route("POST", "/events.json", self._post)
        self.http = HTTPServer(router, "127.0.0.1", port,
                               access_log=False, server_name="stub-events")

    async def serve_forever(self):
        await self.http.serve_forever()

    async def _post(self, req):
        self.posts += 1
        if self.reject is not None:
            return Response.json({"message": "no"}, status=self.reject)
        if self.posts <= self.throttles:
            resp = Response.json({"message": "slow down"}, status=429)
            resp.headers["Retry-After"] = self.retry_after
            return resp
        return Response.json({"eventId": "e1"}, status=201)


def make_event():
    return Event(event="rate", entity_type="user", entity_id="7",
                 target_entity_type="item", target_entity_id="3",
                 properties={"rating": 5.0})


class TestEventSinkRetryAfter:
    """Satellite: the HTTP sink honors the Event Server's Retry-After
    on 429 instead of its own exponential guess."""

    def test_429_is_retried_after_the_servers_hint(self):
        stub = ThrottlingEventStub(free_port(), throttles=1)
        with ServerThread(stub):
            sink = HTTPEventSink(f"http://127.0.0.1:{stub.port}", "key",
                                 retries=2)
            t0 = time.perf_counter()
            sink.send(make_event())  # must not raise
            elapsed = time.perf_counter() - t0
            assert stub.posts == 2
            # the sink's own backoff pause would be <= 50ms (base 0.05,
            # full jitter); waiting ~0.3s proves the header drove it
            assert elapsed >= 0.28

    def test_4xx_rejection_is_never_retried(self):
        stub = ThrottlingEventStub(free_port(), reject=400)
        with ServerThread(stub):
            sink = HTTPEventSink(f"http://127.0.0.1:{stub.port}", "key",
                                 retries=3)
            with pytest.raises(ValueError, match="rejected"):
                sink.send(make_event())
            assert stub.posts == 1  # deterministic rejection: one shot

    def test_fault_site_covers_the_direct_sink(self, storage):
        a = storage.meta.create_app("SinkApp")
        storage.events.init_channel(a.id)
        sink = DirectEventSink(storage, "SinkApp")
        FAULTS.arm("eventsink.send", error="sink down")
        with pytest.raises(FaultError):
            sink.send(make_event())
        FAULTS.disarm()
        sink.send(make_event())  # recovered: delivered for real
        assert len(list(storage.events.find(a.id))) == 1
