"""External-process engine bridge — the ``PythonEngine`` analogue.

Reference: [U] e2/.../engine/PythonEngine.scala (unverified, SURVEY.md
§2a): in 0.14 the JVM framework could host an engine whose DASE logic
ran in a forked PySpark process. Inverted here: this framework is
Python, so the bridge hosts an engine written in *any* language as a
subprocess speaking a line-JSON protocol:

    <cmd> train <train.jsonl> <model_dir>     one-shot; exit 0 = trained
    <cmd> serve <model_dir>                   long-lived; one JSON query
                                              per stdin line → one JSON
                                              prediction per stdout line

Training data is materialized to JSONL host-side (one record per line);
the external trainer owns its own compute. The serve child is spawned
lazily on first predict and kept resident — the process-level analogue
of a model held in HBM.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import tempfile
import threading
from typing import Any, Dict, List, Optional

from predictionio_tpu.controller.base import WorkflowContext
from predictionio_tpu.controller.components import Algorithm


class ExternalAlgorithm(Algorithm):
    """Runs train/serve in a subprocess. ``params``: {"command":
    [argv...], "timeout": seconds (train), "env": {...}}."""

    def __init__(self, params: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(params or {})
        if not self.params.get("command"):
            raise ValueError("ExternalAlgorithm needs params['command']")
        self._child: Optional[subprocess.Popen] = None
        # serializes the write+readline round-trip: the engine server
        # dispatches concurrent queries via asyncio.to_thread
        self._lock = threading.Lock()

    def _command(self) -> List[str]:
        return list(self.params["command"])

    def _env(self) -> Dict[str, str]:
        return {**os.environ, **self.params.get("env", {})}

    # -- train -----------------------------------------------------------------

    def train(self, ctx: WorkflowContext, prepared_data: Any) -> str:
        """``prepared_data``: an iterable of JSON-serializable records.
        Returns the model directory path (persisted via save_model)."""
        workdir = tempfile.mkdtemp(prefix="pio-external-")
        train_path = os.path.join(workdir, "train.jsonl")
        model_dir = os.path.join(workdir, "model")
        os.makedirs(model_dir, exist_ok=True)
        with open(train_path, "w") as f:
            for rec in prepared_data:
                f.write(json.dumps(rec) + "\n")
        proc = subprocess.run(
            self._command() + ["train", train_path, model_dir],
            env=self._env(), capture_output=True, text=True,
            timeout=self.params.get("timeout", 3600),
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"external trainer failed (rc={proc.returncode}): "
                f"{proc.stderr[-2000:]}")
        return model_dir

    # -- persistence: copy the external model dir into the instance dir --------

    def save_model(self, model: str, instance_dir: Optional[str]) -> Optional[bytes]:
        if instance_dir is None:
            raise ValueError("ExternalAlgorithm requires an instance dir")
        dest = os.path.join(instance_dir, "external_model")
        if os.path.abspath(model) != os.path.abspath(dest):
            shutil.copytree(model, dest, dirs_exist_ok=True)
            workdir = os.path.dirname(os.path.abspath(model))
            if os.path.basename(workdir).startswith("pio-external-"):
                shutil.rmtree(workdir, ignore_errors=True)
        return None

    def load_model(self, blob: Optional[bytes], instance_dir: Optional[str]) -> str:
        dest = os.path.join(instance_dir or "", "external_model")
        if not os.path.isdir(dest):
            raise FileNotFoundError(f"external model dir missing: {dest}")
        return dest

    # -- serve -----------------------------------------------------------------

    def _ensure_child(self, model_dir: str) -> subprocess.Popen:
        if self._child is None or self._child.poll() is not None:
            self._child = subprocess.Popen(
                self._command() + ["serve", model_dir],
                env=self._env(), stdin=subprocess.PIPE,
                stdout=subprocess.PIPE, text=True, bufsize=1,
            )
        return self._child

    def predict(self, model: str, query: Any) -> Any:
        with self._lock:
            child = self._ensure_child(model)
            assert child.stdin is not None and child.stdout is not None
            child.stdin.write(json.dumps(query) + "\n")
            child.stdin.flush()
            line = child.stdout.readline()
        if not line:
            raise RuntimeError("external serve process closed its stdout")
        return json.loads(line)

    def close(self) -> None:
        if self._child is not None and self._child.poll() is None:
            self._child.terminate()
            try:
                self._child.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._child.kill()
                self._child.wait()  # reap — no zombie in a resident server
        self._child = None
