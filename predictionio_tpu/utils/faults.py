"""Deterministic fault injection for the serving and storage paths.

Every robustness claim in this tree (deadlines, load shedding,
breakers) needs a way to MAKE the failure happen: a storage backend
that hangs, an Event Server that is down, a commit that fails one time
in ten. This module provides named **injection sites** — one-line
``faults.inject("eventsink.send")`` calls placed where the code talks
to something that can fail — and **plans** armed against those sites:

- ``latency`` — sleep N seconds per hit (a hung/slow dependency);
- ``error``   — raise :class:`FaultError` (a down dependency);
- ``rate``    — fire the plan with probability p per hit, from a
  SEEDED per-plan RNG, so a "flaky" run is reproducible bit-for-bit;
- ``count``   — fire at most N times, then fall dormant (a transient
  blip that retry logic should absorb).

The ``data.corrupt.*`` sites are special: instead of sleeping or
raising they **flip a byte** in data passing through
:meth:`FaultRegistry.corrupt` (bit rot on the read path), so checksum
verification — not error handling — is what the test exercises. A
plan's ``rate``/``count``/``seed`` directives gate the flip as usual;
``latency``/``error`` are ignored at these sites.

Arming is programmatic (tests, ``profile_serving.py --fault``) or via
the ``PIO_FAULTS`` environment variable, read once at import:

    PIO_FAULTS="eventsink.send:error=down;serving.query:latency=0.2,rate=0.5"

Sites are separated by ``;``; each site takes comma-separated
``key=value`` directives (``latency`` seconds, ``error`` message,
``rate`` probability, ``count`` max fires, ``seed`` RNG seed).

**Zero overhead when disarmed**: ``inject()`` is one attribute read
and one predictable branch — no lock, no dict lookup — until the first
``arm()``. Production binaries keep their injection sites; the tier-1
suite asserts the registry is disarmed by default.

Known sites (grep ``faults.inject`` for the authoritative list):

======================  ===================================================
``serving.query``       engine-server query worker (model/storage hang)
``serving.reload``      prepare_deploy during ``/reload`` (bad new model)
``eventsink.send``      feedback sink delivery (Event Server down)
``ingest.commit``       coalescer group commit (event storage down)
``models.s3``           S3 model-store operations
``models.hdfs``         HDFS model-store operations
``trace.export``        span export (ring + JSONL) — fail-open: an armed
                        error here must never fail the traced request
``router.replica.down``  fleet-router forward path — replica refuses /
                        drops the proxied request (down replica)
``router.replica.slow``  fleet-router forward path — added latency on the
                        proxied request (slow replica; drives hedging)
``router.health.flap``  fleet-router active ``/health`` probe (flapping
                        or partitioned replica)
``train.crash``         continuous trainer, mid-delta-train — process
                        dies (SIGKILL-equivalent); resume must pick up
                        from the checkpoint, not restart from scratch
``train.lease.lost``    continuous trainer heartbeat renewal — the
                        single-writer lease was stolen; the trainer
                        must abandon the cycle and never publish
``promote.regression``  guardrail scoring of a candidate generation —
                        forces the candidate to look regressed so the
                        gate (or bake window) must refuse/roll back
``segments.cold``       cold-tier segment store operations (put/get/
                        delete), shared by the local/S3/HDFS tiers —
                        a down cold store must fail reads loudly, not
                        hang writers
``data.corrupt.eventlog``  byte-flip on ``pio fsck`` eventlog reads
``data.corrupt.snapshot``  byte-flip on snapshot npz load
``data.corrupt.model``     byte-flip on model-blob load/download
``data.corrupt.segment``   byte-flip on cold-tier segment fetch
``ann.index.corrupt``   byte-flip on ANN retrieval-index load
                        (``PQIndex.from_bytes`` — covers the
                        ``ann_index.bin`` file and blob-embedded
                        indexes; ``/reload`` must refuse, fsck exit ≥ 2)
``variant.assign.skew``  variant-split assignment — the weighted hash
                        is bypassed and every query lands on the
                        default arm (a skewed split the per-variant
                        request series must make visible)
``variant.reload.partial``  variant swap mid-``/reload`` — the
                        candidate died after loading but before
                        publishing; the champion must keep serving and
                        the split must fall back to 100/0
``tenant.quota.exhausted``  per-app ingest quota gate — the tenant's
                        token bucket reads empty, so its events get
                        the app-scoped 429 + computed Retry-After
                        (other tenants must be unaffected)
``segments.shard.hot``  hot-partition writer sharding — the entity-id
                        hash is bypassed and every append lands on
                        writer shard 0 (the skew the per-shard append
                        series must make visible)
``slo.probe.fail``      router synthetic prober, before the canary is
                        sent — the probe fails (or stalls) so the SLO
                        burn-rate series must spike and ``/health``
                        must degrade on the fast windows
``tsdb.scrape.stall``   metrics-history scrape tick (every server) —
                        a wedged/failing scraper costs history ticks,
                        never the serving path; watch
                        ``pio_tsdb_scrapes_total{result="error"}``
``incident.capture.stall``  incident-bundle capture task (every
                        server) — a wedged/failing capture costs the
                        postmortem bundle, never the serving path;
                        watch ``pio_incident_captures_total{result}``
``replication.follower.lag``  follower WAL apply path — a slow/down
                        follower; the leader must degrade (mark the
                        link unhealthy, keep acking) never block;
                        watch ``pio_repl_lag_bytes``
``replication.wal.torn``  byte-flip on a replicated WAL batch before
                        the CRC check — the follower must refuse the
                        frame (422) and keep its cursor; watch
                        ``pio_repl_batches_total{result="torn"}``
``replication.leader.partition``  event-plane leader heartbeat — the
                        lease renewal fails as if partitioned; the
                        leader must fence itself (writes 503) before
                        the TTL lets a follower promote
``autoscale.flap``      autoscaler decision tick — the raw desire is
                        inverted every tick (a poisoned signal); the
                        cooldown/flap-damping guardrails, not the
                        thresholds, must bound membership churn
``remediate.wrong_target``  remediation target selection — the engine
                        picks a plausible WRONG target (a healthy
                        replica); pre-action verification must refuse
                        it, never act on it
``remediate.storm``     auto-remediation dedup — the same finding
                        re-fires every tick as if brand new; the
                        per-playbook rate limit alone must bound the
                        blast radius
======================  ===================================================
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional


class FaultError(RuntimeError):
    """The error an ``error`` plan raises at its site."""


@dataclass
class FaultPlan:
    site: str
    latency: float = 0.0
    error: Optional[str] = None
    rate: float = 1.0
    count: Optional[int] = None
    seed: int = 0
    fired: int = 0
    _rng: random.Random = field(init=False, repr=False)

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)


class FaultRegistry:
    """Process-wide registry of armed fault plans, keyed by site."""

    def __init__(self, env: Optional[Dict[str, str]] = None) -> None:
        self._lock = threading.Lock()
        self._plans: Dict[str, FaultPlan] = {}
        self._hits: Dict[str, int] = {}
        #: fast-path flag: read without the lock by inject(); only ever
        #: True while at least one plan is armed
        self.armed = False
        spec = (os.environ if env is None else env).get("PIO_FAULTS", "")
        if spec:
            self.arm_spec(spec)

    # -- arming ----------------------------------------------------------------

    def arm(self, site: str, *, latency: float = 0.0,
            error: Optional[str] = None, rate: float = 1.0,
            count: Optional[int] = None, seed: int = 0) -> FaultPlan:
        """Arm one plan at ``site`` (replacing any previous plan there).
        A plan with neither latency nor error still counts hits — a
        pure probe for "did this code path run"."""
        if not (0.0 <= rate <= 1.0):
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        plan = FaultPlan(site=site, latency=latency, error=error,
                         rate=rate, count=count, seed=seed)
        with self._lock:
            self._plans[site] = plan
            self.armed = True
        return plan

    def arm_spec(self, spec: str) -> None:
        """Arm from a ``PIO_FAULTS``-format string (see module doc)."""
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            site, _, directives = part.partition(":")
            site = site.strip()
            if not site or not directives:
                raise ValueError(
                    f"bad PIO_FAULTS entry {part!r}: want site:key=value[,...]")
            kwargs: Dict[str, object] = {}
            for d in directives.split(","):
                key, eq, value = d.strip().partition("=")
                if key == "latency":
                    kwargs["latency"] = float(value)
                elif key == "error":
                    kwargs["error"] = value if eq else "injected fault"
                elif key == "rate":
                    kwargs["rate"] = float(value)
                elif key == "count":
                    kwargs["count"] = int(value)
                elif key == "seed":
                    kwargs["seed"] = int(value)
                else:
                    raise ValueError(
                        f"unknown PIO_FAULTS directive {key!r} in {part!r}")
            self.arm(site, **kwargs)  # type: ignore[arg-type]

    def disarm(self, site: Optional[str] = None) -> None:
        """Disarm one site, or everything (and reset hit counters)."""
        with self._lock:
            if site is None:
                self._plans.clear()
                self._hits.clear()
            else:
                self._plans.pop(site, None)
            self.armed = bool(self._plans)

    # -- introspection ---------------------------------------------------------

    def plans(self) -> Dict[str, FaultPlan]:
        with self._lock:
            return dict(self._plans)

    def hits(self, site: str) -> int:
        """Times ``inject(site)`` ran while the registry was armed."""
        with self._lock:
            return self._hits.get(site, 0)

    def fired(self, site: str) -> int:
        """Times the plan at ``site`` actually injected its fault."""
        with self._lock:
            plan = self._plans.get(site)
            return plan.fired if plan is not None else 0

    # -- injection -------------------------------------------------------------

    def _evaluate(self, site: str) -> Optional[FaultPlan]:
        """Count the hit and decide whether the plan fires (lock held
        briefly; the latency sleep happens OUTSIDE the lock)."""
        with self._lock:
            self._hits[site] = self._hits.get(site, 0) + 1
            plan = self._plans.get(site)
            if plan is None:
                return None
            if plan.count is not None and plan.fired >= plan.count:
                return None
            if plan.rate < 1.0 and plan._rng.random() >= plan.rate:
                return None
            plan.fired += 1
            return plan

    def hit(self, site: str) -> None:
        """Sync injection point (worker threads, storage drivers)."""
        if not self.armed:
            return
        plan = self._evaluate(site)
        if plan is None:
            return
        if plan.latency > 0:
            time.sleep(plan.latency)
        if plan.error is not None:
            raise FaultError(f"[{site}] {plan.error}")

    def corrupt(self, site: str, data: bytes) -> bytes:
        """Byte-flip injection for the ``data.corrupt.*`` sites: when
        the armed plan fires, return a copy of ``data`` with the
        middle byte inverted (deterministic position, so a test can
        predict exactly which artifact region is damaged); otherwise
        return ``data`` unchanged. Disarmed cost: one attribute read."""
        if not self.armed or not data:
            return data
        plan = self._evaluate(site)
        if plan is None:
            return data
        flipped = bytearray(data)
        flipped[len(flipped) // 2] ^= 0xFF
        return bytes(flipped)

    async def ahit(self, site: str) -> None:
        """Async injection point — latency sleeps on the event loop
        without blocking it."""
        if not self.armed:
            return
        plan = self._evaluate(site)
        if plan is None:
            return
        if plan.latency > 0:
            import asyncio

            await asyncio.sleep(plan.latency)
        if plan.error is not None:
            raise FaultError(f"[{site}] {plan.error}")


#: the process-wide registry (armed from PIO_FAULTS at import)
FAULTS = FaultRegistry()


def inject(site: str) -> None:
    """Module-level shorthand for ``FAULTS.hit(site)`` — the one-liner
    placed at injection sites."""
    if FAULTS.armed:
        FAULTS.hit(site)


def corrupt_bytes(site: str, data: bytes) -> bytes:
    """Module-level shorthand for ``FAULTS.corrupt(site, data)`` — the
    one-liner placed on read paths that feed checksum verification."""
    if FAULTS.armed:
        return FAULTS.corrupt(site, data)
    return data
