"""Benchmark: ALS training throughput, MovieLens-20M-scale (driver metric).

Protocol (BASELINE.md): throughput = ratings × iterations / train
wall-clock (excluding event-store read / data prep — layout construction
is :func:`als_prepare`, MLlib-InBlock-equivalent, done once per dataset)
/ chips. Rank 64, 10 iterations, f32 solves. The reference (Apache
PredictionIO on Spark/MLlib) publishes no numbers and the environment
has no egress to fetch ML-20M, so the dataset is a synthetic clone of
its shape: 138,493 users × 26,744 items × 20M ratings, power-law degree
distribution, ratings in {0.5 … 5.0}. First measured run established
the baseline (see BENCH_BASELINE.json).

Also reported (VERDICT r1 asks):
- ``mfu`` / ``hbm_gbps``: progress measured against hardware rooflines
  (model flops / peak bf16; modeled HBM bytes / wall-clock), not against
  last round's self-baseline.
- ``predict_p50_device_ms``: device-program latency of the serving
  score→top-k dispatch, measured by chaining N dependent executions of
  the compiled program on device inside one fetch (the tunneled chip on
  this image executes lazily and adds a ~66 ms round trip per fetch, so
  per-call host timing measures the tunnel, not the program).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Flags: --quick (1/20 size, CI smoke), --rank, --iters, --nnz.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_BASELINE.json")

V5E_PEAK_BF16 = 197e12   # FLOP/s per chip
V5E_HBM_BPS = 819e9      # bytes/s per chip


def synthetic_ml20m(nnz: int, n_users: int = 138_493, n_items: int = 26_744,
                    seed: int = 7):
    """Power-law user/item popularity, Zipf-ish, like MovieLens."""
    rng = np.random.default_rng(seed)
    u_pop = rng.zipf(1.35, size=nnz * 2) % n_users
    i_pop = rng.zipf(1.25, size=nnz * 2) % n_items
    users = u_pop[:nnz].astype(np.int32)
    items = i_pop[:nnz].astype(np.int32)
    ratings = (rng.integers(1, 11, size=nnz) * 0.5).astype(np.float32)
    return users, items, ratings


def _train_flops(prep, rank: int, iterations: int) -> float:
    """Executed FLOPs: batched weighted Gram + rhs per padded rating
    slot, the dense-head GEMMs (weight rows × factor outer products),
    plus the per-entity Cholesky factor/inverse/apply."""
    k = rank
    padded = sum(b.n_slabs * b.slab * b.C
                 for side in (prep.u_side, prep.i_side)
                 for b in side.buckets)
    gram = 2.0 * padded * k * (k + 2)          # A (k×(k+1)) + b (k) builds
    dense = sum(2.0 * side.dense.nb * side.dense.n_other * k * (k + 1)
                + side.dense.n_other * k * k    # FF outer products
                for side in (prep.u_side, prep.i_side)
                if side.dense is not None)
    solves = (prep.n_users + prep.n_items) * (2 * k**3 / 3 + 4 * k**2)
    return iterations * (gram + dense + solves)


def _train_bytes(prep, rank: int, iterations: int) -> float:
    """Modeled HBM traffic: the factor gather (k·4 bytes per padded
    rating slot) + layout operands, the dense-head weight rows + FF
    write/read, and factor writes."""
    k = rank
    padded = sum(b.n_slabs * b.slab * b.C
                 for side in (prep.u_side, prep.i_side)
                 for b in side.buckets)
    dense = sum(side.dense.nb * side.dense.n_other * 8      # w_cnt+w_val
                + 2 * side.dense.n_other * k * k * 4        # FF w+r
                for side in (prep.u_side, prep.i_side)
                if side.dense is not None)
    per_iter = (padded * (k * 4 + 12) + dense
                + (prep.n_users + prep.n_items) * k * 4)
    return iterations * float(per_iter)


def _device_predict_latency(scorer, n_users: int, iters: int = 200) -> float:
    """Steady-state device latency (ms) of the serving score→top-k
    program: chain ``iters`` dependent executions on device, one fetch."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models.als import _gather_score_topk_impl

    k = 16
    n_valid = scorer.n_items

    def chained(U, Vp, uid, n):
        def body(_, uid):
            packed = _gather_score_topk_impl(
                U, Vp, uid, k=k, n_valid=n_valid, pallas=False,
                tile=scorer._TILE)
            # feed top item id back in as the next user id → dependency
            return (packed[:, k].astype(jnp.int32) % n_users)

        return jax.lax.fori_loop(0, n, body, uid)

    f = jax.jit(chained, static_argnames=("n",))
    uid = jnp.asarray([0], jnp.int32)
    # warm BOTH static-n variants (each is its own compile cache entry)
    np.asarray(f(scorer._U, scorer._V_padded, uid, 1))
    np.asarray(f(scorer._U, scorer._V_padded, uid, iters))
    t0 = time.perf_counter()
    np.asarray(f(scorer._U, scorer._V_padded, uid, 1))
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    np.asarray(f(scorer._U, scorer._V_padded, uid, iters))
    t_many = time.perf_counter() - t0
    return max(t_many - t_one, 0.0) / (iters - 1) * 1e3


def _backend_watchdog(seconds: float):
    """The tunneled chip's PJRT init can HANG indefinitely when the
    relay's far side is wedged (observed: a killed client left the chip
    unclaimable for hours and even backend registration blocked). The
    driver must get a loud failure, not a hung process: if the first
    device op hasn't completed within ``seconds``, explain and exit 2.
    Returns the event to set once the backend answered."""
    import threading

    done = threading.Event()

    def fire():
        if not done.wait(seconds):
            # a PARSEABLE record, not prose + rc=2: BENCH rounds 3–5
            # came back "parsed": null because this path printed an
            # explanation the driver could not ingest. The driver keys
            # on "metric"; "skipped": true marks no-measurement so the
            # previous round's numbers stay the reference.
            print(json.dumps({
                "metric": "als_train_throughput_ml20m_synthetic",
                "skipped": True,
                "reason": ("accelerator backend unreachable after "
                           f"{seconds:.0f}s (tunnel relay wedged?) — no "
                           "measurement possible; chip-free validation: "
                           "docs/perf.md 'AOT compile validation' "
                           "(profile_aot.py); live-chip sequence: "
                           "docs/perf/hardware_runbook.md"),
            }), flush=True)
            os._exit(0)

    threading.Thread(target=fire, daemon=True).start()
    return done


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--rank", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--nnz", type=int, default=20_000_000)
    ap.add_argument("--backend-timeout", type=float, default=float(
        os.environ.get("PIO_BENCH_BACKEND_TIMEOUT", "900")))
    args = ap.parse_args()

    backend_up = _backend_watchdog(args.backend_timeout)

    from predictionio_tpu.models.als import (ALSParams, RatingsCOO,
                                             als_prepare, als_train_prepared)
    from predictionio_tpu.utils import compilecache

    xla_cache = compilecache.enable()

    # first device op under the watchdog: proves the backend answers
    import jax
    import jax.numpy as jnp

    np.asarray(jnp.ones(1))
    backend_up.set()

    nnz = args.nnz // 20 if args.quick else args.nnz
    n_users = 138_493 // (20 if args.quick else 1)
    n_items = 26_744 // (4 if args.quick else 1)
    users, items, ratings = synthetic_ml20m(nnz, n_users, n_items)
    coo = RatingsCOO(users, items, ratings, n_users, n_items)
    params = ALSParams(rank=args.rank, iterations=args.iters, reg=0.05, seed=1)

    import jax

    n_chips = 1  # single-chip bench (tunneled v5e); sharded path covers multi
    t0 = time.perf_counter()
    prep = als_prepare(coo)
    t_prep = time.perf_counter() - t0

    t0 = time.perf_counter()
    U, V = als_train_prepared(prep, params)   # includes compile + h2d
    t_total = time.perf_counter() - t0

    # warm run: pure execute (compile cached, layout resident on device)
    t1 = time.perf_counter()
    U, V = als_train_prepared(prep, params)
    t_exec = time.perf_counter() - t1

    # the tunneled chip on this image moves device→host bytes at
    # ~20 MB/s — measure that transfer alone (a same-size dummy fetch)
    # so device execution time can be reported honestly alongside the
    # wall time a user of THIS image sees
    import jax
    import jax.numpy as jnp

    dummy = jnp.zeros(((prep.n_users + prep.n_items), args.rank),
                      jnp.float32) + 1.0
    np.asarray(dummy * 1.0)  # warm the transfer path
    t2 = time.perf_counter()
    np.asarray(dummy * 2.0)
    t_d2h = time.perf_counter() - t2
    t_dev = max(t_exec - t_d2h, 1e-9)

    assert np.isfinite(U).all() and np.isfinite(V).all()
    throughput = (coo.nnz * args.iters) / t_exec / n_chips
    flops = _train_flops(prep, args.rank, args.iters)
    mfu = flops / t_exec / (V5E_PEAK_BF16 * n_chips)
    mfu_device = flops / t_dev / (V5E_PEAK_BF16 * n_chips)
    hbm_gbps = _train_bytes(prep, args.rank, args.iters) / t_dev / 1e9

    # dispatch accounting (chip-free abstract trace, utils/opcount): the
    # r5 wall was device-op COUNT, not FLOPs, so the bench emits it as a
    # first-class metric next to mfu_device — both paths counted even
    # when only one actually ran on this chip
    from predictionio_tpu import ops as ops_mod
    from predictionio_tpu.utils import opcount as opcount_mod

    dispatch_rep = opcount_mod.als_dispatch_report(prep, params)
    gram_mode = ops_mod.resolve_gram_mode(jax.default_backend())

    # r4 grid contract on hardware: 3 extra reg candidates on the SAME
    # prep must pay ZERO compiles (reg is a traced scalar) — wall time
    # ≈ 3 × train_sec_warm. Measured here so the BENCH file carries the
    # proof without a separate harness run.
    from predictionio_tpu.models import als as als_mod

    grid_info = als_mod._compiled_bucketed.cache_info()
    t3 = time.perf_counter()
    for reg in (0.01, 0.1, 1.0):
        als_train_prepared(prep, ALSParams(
            rank=args.rank, iterations=args.iters, reg=reg, seed=1))
    t_grid3 = time.perf_counter() - t3
    grid_compiles = (als_mod._compiled_bucketed.cache_info().misses
                     - grid_info.misses)

    # second driver metric (BASELINE.md): predict p50, recommendation
    # top-10 from the resident model — the engine-server hot path minus
    # HTTP framing. Sequential single-query calls, warm.
    from predictionio_tpu.models.als import ResidentScorer

    scorer = ResidentScorer(U, V)
    rng = np.random.default_rng(3)
    n_queries = 1_000 if args.quick else 10_000
    qusers = rng.integers(0, n_users, n_queries + 100)
    for u in qusers[:100]:  # warm both compile and caches
        scorer.recommend_batch(np.asarray([u]), 10)
    lat = np.empty(n_queries)
    for i, u in enumerate(qusers[100:]):
        q0 = time.perf_counter()
        scorer.recommend_batch(np.asarray([u]), 10)
        lat[i] = time.perf_counter() - q0
    p50_ms = float(np.percentile(lat, 50) * 1e3)
    p99_ms = float(np.percentile(lat, 99) * 1e3)
    p50_dev_ms = _device_predict_latency(scorer, n_users)

    # AOT bucket flywheel (server/aot): warm the serving ladder the way
    # `pio deploy --aot-buckets auto` would, drive each bucket at its
    # real batch size, and report the per-bucket device-latency p50s
    # recorded by the pio_predict_device_seconds histogram. The compile
    # delta over the serving loop must be zero — any hot-path compile
    # is a warmup gap.
    from predictionio_tpu.server import aot as aot_mod

    def _jit_dispatches():
        # serving dispatches that did NOT run a precompiled executable —
        # each one is a potential on-path XLA compile (warmup gap)
        return sum(v for k, v in aot_mod._DISPATCHES._values.items()
                   if k[1] == "jit")

    ladder = aot_mod.BucketLadder.geometric(16 if args.quick else 64)
    scorer.warm_buckets(ladder, ks=(10,))
    gaps_before = _jit_dispatches()
    for B in ladder:
        users = rng.integers(0, n_users, size=B)
        for _ in range(20):
            scorer.recommend_batch(np.asarray(users, np.int32), 10)
    aot_gaps = _jit_dispatches() - gaps_before
    p50_by_bucket = aot_mod.device_p50_ms_by_bucket()

    # ANN flywheel (predictionio_tpu/ann): PQ-index the trained item
    # factors, warm the ANN ladder, and report recall@10 vs the exact
    # resident scorer plus the per-bucket ANN-vs-exact device p50 — the
    # PQ trade-off printed next to the exact numbers it trades against.
    from predictionio_tpu import ann as ann_mod

    ann_m = next(m for m in (8, 4, 2, 1) if args.rank % m == 0)
    ann_index = ann_mod.build_index(
        V, ann_m, 256, iters=4, sample=min(65536, n_items))
    ann_scorer = ann_mod.ANNScorer(U, V, ann_index, shortlist=128)
    ann_scorer.warm_buckets(ladder, ks=(10,))
    gaps_before = _jit_dispatches()
    ann_hits = ann_total = 0
    for B in ladder:
        busers = np.asarray(rng.integers(0, n_users, size=B), np.int32)
        for rep in range(5):
            er = scorer.recommend_batch(busers, 10)
            ar = ann_scorer.recommend_batch(busers, 10)
            if rep == 0:
                for (ei, _), (ai, _) in zip(er, ar):
                    ann_hits += np.intersect1d(ei, ai).size
                    ann_total += len(ei)
    ann_gaps = _jit_dispatches() - gaps_before
    ann_p50_by_bucket = aot_mod.device_p50_ms_by_bucket(path="ann")

    # Variant multiplexing flywheel (server/variants): two same-geometry
    # variants resident at once must share every executable. Preview the
    # 90/10 dispatch share with the exact assignment hash serving uses,
    # warm a challenger scorer (must be pure executable-cache hits),
    # and report each variant's single-query device-path p50.
    from predictionio_tpu.server.variants import weighted_assign

    arms = [("champion", 9.0), ("challenger", 1.0)]
    dispatch = {"champion": 0, "challenger": 0}
    for i in range(n_queries):
        dispatch[weighted_assign(str(i), arms)] += 1
    chal_scorer = ResidentScorer(U * 0.999, V)  # same geometry, new weights
    ex_before = aot_mod.EXECUTABLES.counts().get("compile", 0)
    chal_scorer.warm_buckets(ladder, ks=(10,))
    variant_warm_compiles = (aot_mod.EXECUTABLES.counts().get("compile", 0)
                             - ex_before)
    variant_p50 = {}
    m = 500 if args.quick else 2_000
    for vname, vscorer in (("champion", scorer), ("challenger", chal_scorer)):
        for u in qusers[:50]:
            vscorer.recommend_batch(np.asarray([u]), 10)
        vlat = np.empty(m)
        for i, u in enumerate(qusers[50:50 + m]):
            q0 = time.perf_counter()
            vscorer.recommend_batch(np.asarray([u]), 10)
            vlat[i] = time.perf_counter() - q0
        variant_p50[vname] = round(float(np.percentile(vlat, 50) * 1e3), 3)

    baseline = None
    if os.path.exists(BASELINE_FILE):
        try:
            with open(BASELINE_FILE) as f:
                baseline = json.load(f).get("value")
        except Exception:
            baseline = None
    vs = (throughput / baseline) if baseline else 1.0

    print(json.dumps({
        "metric": "als_train_throughput_ml20m_synthetic",
        "value": round(throughput, 1),
        "unit": "rating-updates/sec/chip (ratings x iters / train-sec / chips)",
        "vs_baseline": round(vs, 4),
        "detail": {
            "nnz": coo.nnz, "rank": args.rank, "iterations": args.iters,
            "n_users": n_users, "n_items": n_items,
            "train_sec_warm": round(t_exec, 3),
            "train_sec_incl_compile": round(t_total, 3),
            # first-class target (VERDICT r2 ask #2): the one-shot `pio
            # train` a user runs pays prepare+compile+train; compile_sec
            # is ~0 on a warm persistent cache (xla_cache_dir)
            "compile_sec": round(t_total - t_exec, 3),
            "cold_train_sec_end_to_end": round(t_prep + t_total, 3),
            "xla_cache_dir": xla_cache,
            "prepare_sec": round(t_prep, 3),
            "mfu": round(mfu, 4),
            # device-side accounting: train_sec_warm minus the measured
            # ~2s tunnel fetch of the 42MB factor output (an image
            # artifact, ~5ms on a real TPU VM)
            "train_sec_device": round(t_dev, 3),
            "d2h_fetch_sec": round(t_d2h, 3),
            "mfu_device": round(mfu_device, 4),
            "model_tflops": round(flops / 1e12, 2),
            "hbm_gbps": round(hbm_gbps, 1),
            # dispatch wall: device ops per iteration for the fused
            # gather→Gram path vs the XLA path (abstract jaxpr count,
            # utils/opcount) and the gram mode this run resolved to
            "device_ops_per_iter": dispatch_rep["device_ops_per_iter"],
            "device_ops_per_iter_xla":
                dispatch_rep["device_ops_per_iter_xla"],
            "dispatch_collapse_ratio":
                round(dispatch_rep["dispatch_collapse_ratio"], 1),
            "gram_mode": gram_mode,
            # reg-grid contract: 3 extra reg candidates on the same
            # prep; must show 0 extra compiles (traced scalars, r4)
            "grid_reg3_sec": round(t_grid3, 3),
            "grid_reg3_extra_compiles": int(grid_compiles),
            "predict_p50_ms": round(p50_ms, 3),
            "predict_p99_ms": round(p99_ms, 3),
            "predict_p50_device_ms": round(p50_dev_ms, 4),
            # per-bucket device p50 across the warmed AOT ladder
            # (histogram upper-bound estimate) + the zero-compile
            # contract over the bucketed serving loop
            "predict_p50_device_ms_by_bucket": p50_by_bucket,
            "aot_buckets": list(ladder.buckets),
            "aot_serving_jit_fallbacks": int(aot_gaps),
            # ANN retrieval: recall@10 of the PQ ADC+re-rank path vs
            # the exact scorer on the same query batches, and its
            # per-bucket device p50 (dispatch path="ann")
            "ann_recall_at_10": round(ann_hits / max(ann_total, 1), 4),
            "ann_p50_device_ms_by_bucket": ann_p50_by_bucket,
            "ann_serving_jit_fallbacks": int(ann_gaps),
            "ann_index_build_sec": ann_index.meta.get("build_sec"),
            # variant multiplexing: the 90/10 dispatch share the sticky
            # hash actually produces over n_queries distinct entities,
            # each resident variant's device-path p50, and the compile
            # cost of making the second variant resident (must be 0 —
            # same geometry ⇒ pure executable-cache adoption)
            "variant_dispatch_share": {
                k: round(v / n_queries, 4) for k, v in dispatch.items()},
            "variant_device_p50_ms": variant_p50,
            "variant_warm_extra_compiles": int(variant_warm_compiles),
            "predict_queries": n_queries,
            # On this image's tunneled ("axon") chip, every device→host
            # fetch costs a ~66ms round trip, so the end-to-end p50 is
            # the tunnel floor; predict_p50_device_ms is the measured
            # on-device program latency (chained dependent executions,
            # one fetch).
            "predict_note": "end-to-end p50 bounded by tunnel round-trip "
                            "on this image; predict_p50_device_ms is the "
                            "measured device-program latency",
            # layout knobs in effect (r5: slab default 2^20 after the
            # on-device dispatch-granularity A/B — docs/perf.md)
            "slab_elems": als_mod._SLAB_ELEMS,
            "solve_chunk": als_mod._SOLVE_CHUNK,
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
        },
    }))


if __name__ == "__main__":
    main()
