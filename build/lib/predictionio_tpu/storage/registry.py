"""Backend registry: env-driven selection of meta/event/model stores.

Equivalent of the reference's ``Storage`` object (reference: [U]
data/.../storage/Storage.scala — unverified, SURVEY.md §2a), which reads
``PIO_STORAGE_REPOSITORIES_{METADATA,EVENTDATA,MODELDATA}_{NAME,SOURCE}``
and ``PIO_STORAGE_SOURCES_<S>_{TYPE,...}`` env vars and reflectively
loads backend jars. Here backends register by TYPE name in a plain dict
(extensible via ``register_event_backend`` — the Python-entry-points
replacement for JVM reflection), and the same env var names are honored
for drop-in familiarity.

Defaults (no env set): everything under ``$PIO_HOME or ~/.pio_store`` —
SQLite meta DB, SQLITE events, LOCALFS models.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from predictionio_tpu.data.events import EventStore, MemoryEventStore, SqliteEventStore
from predictionio_tpu.storage.meta import MetaStore
from predictionio_tpu.storage.models import LocalFSModelStore, MemoryModelStore, ModelStore


def pio_home() -> str:
    return os.environ.get("PIO_HOME") or os.path.join(
        os.path.expanduser("~"), ".pio_store"
    )


@dataclass
class StorageConfig:
    """Resolved storage configuration (one 'source' per repository).

    ``sources`` holds every configured source's extra settings
    (``PIO_STORAGE_SOURCES_<NAME>_<KEY>`` → ``sources[NAME][KEY]``) and
    ``*_source`` records which named source backs each repository, so a
    backend factory can read ITS source's settings instead of scanning
    the environment (two S3 sources must not shadow each other).
    """

    metadata_type: str = "SQLITE"
    eventdata_type: str = "SQLITE"
    modeldata_type: str = "LOCALFS"
    metadata_source: str = ""
    eventdata_source: str = ""
    modeldata_source: str = ""
    sources: Dict[str, Dict[str, str]] = field(default_factory=dict)
    home: str = field(default_factory=pio_home)

    def source_properties(self, repo: str) -> Dict[str, str]:
        """Settings of the source backing ``repo`` ('METADATA', …)."""
        name = getattr(self, f"{repo.lower()}_source", "")
        return self.sources.get(name, {})

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "StorageConfig":
        e = dict(os.environ if env is None else env)

        def repo_source(repo: str) -> str:
            return e.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE", "")

        # Source names may contain underscores (e.g. MY_PG), and so may
        # setting keys (BUCKET_NAME). Candidate names come from the
        # repository SOURCE declarations plus every *_TYPE key; each env
        # var then binds to the LONGEST candidate name prefixing it.
        prefix = "PIO_STORAGE_SOURCES_"
        rests = [k[len(prefix):] for k in e if k.startswith(prefix)]
        names = {repo_source(r) for r in ("METADATA", "EVENTDATA", "MODELDATA")}
        names |= {r[: -len("_TYPE")] for r in rests if r.endswith("_TYPE")}
        names.discard("")
        sources: Dict[str, Dict[str, str]] = {}
        for rest in rests:
            owner = max((n for n in names if rest.startswith(n + "_")),
                        key=len, default="")
            if owner:
                sources.setdefault(owner, {})[rest[len(owner) + 1:]] = \
                    e[prefix + rest]

        def source_type(repo: str, default: str) -> str:
            src = repo_source(repo)
            if src:
                return sources.get(src, {}).get("TYPE", default).upper()
            return default

        return cls(
            metadata_type=source_type("METADATA", "SQLITE"),
            eventdata_type=source_type("EVENTDATA", "SQLITE"),
            modeldata_type=source_type("MODELDATA", "LOCALFS"),
            metadata_source=repo_source("METADATA"),
            eventdata_source=repo_source("EVENTDATA"),
            modeldata_source=repo_source("MODELDATA"),
            sources=sources,
            home=e.get("PIO_HOME", pio_home()),
        )


_EVENT_BACKENDS: Dict[str, Callable[[StorageConfig], EventStore]] = {}
_MODEL_BACKENDS: Dict[str, Callable[[StorageConfig], ModelStore]] = {}
_META_BACKENDS: Dict[str, Callable[[StorageConfig], MetaStore]] = {}


def register_event_backend(name: str, factory: Callable[[StorageConfig], EventStore]) -> None:
    _EVENT_BACKENDS[name.upper()] = factory


def register_model_backend(name: str, factory: Callable[[StorageConfig], ModelStore]) -> None:
    _MODEL_BACKENDS[name.upper()] = factory


def register_meta_backend(name: str, factory: Callable[[StorageConfig], MetaStore]) -> None:
    _META_BACKENDS[name.upper()] = factory


register_event_backend("MEMORY", lambda cfg: MemoryEventStore())
register_event_backend(
    "SQLITE",
    lambda cfg: SqliteEventStore(
        os.path.join(_ensure(cfg.home), "events.db")),
)
def _eventlog_factory(cfg: "StorageConfig") -> EventStore:
    # lazy import: building the C++ engine only happens when selected
    from predictionio_tpu.data.filestore import NativeEventLogStore

    return NativeEventLogStore(os.path.join(_ensure(cfg.home), "eventlog"))


register_event_backend("EVENTLOG", _eventlog_factory)
register_model_backend("MEMORY", lambda cfg: MemoryModelStore())
register_model_backend(
    "LOCALFS", lambda cfg: LocalFSModelStore(os.path.join(_ensure(cfg.home), "models"))
)
register_meta_backend("MEMORY", lambda cfg: MetaStore(":memory:"))
register_meta_backend(
    "SQLITE", lambda cfg: MetaStore(os.path.join(_ensure(cfg.home), "meta.db"))
)

# network backends (S3/HDFS model stores, gated SQL servers) register
# their TYPE names here; their drivers bind lazily at first use
from predictionio_tpu.storage import remote as _remote  # noqa: E402

_remote.register_all()

# the embedded indexed store registers the reference's ELASTICSEARCH type
from predictionio_tpu.storage import indexed as _indexed  # noqa: E402

_indexed.register_all()


def _ensure(home: str) -> str:
    os.makedirs(home, exist_ok=True)
    return home


class Storage:
    """Aggregated handle on the three repositories (lazy singletons)."""

    def __init__(self, config: Optional[StorageConfig] = None) -> None:
        self.config = config or StorageConfig.from_env()
        self._lock = threading.Lock()
        self._meta: Optional[MetaStore] = None
        self._events: Optional[EventStore] = None
        self._models: Optional[ModelStore] = None

    @property
    def meta(self) -> MetaStore:
        with self._lock:
            if self._meta is None:
                try:
                    factory = _META_BACKENDS[self.config.metadata_type]
                except KeyError:
                    raise KeyError(
                        f"unknown METADATA backend {self.config.metadata_type!r}; "
                        f"registered: {sorted(_META_BACKENDS)}")
                self._meta = factory(self.config)
            return self._meta

    @property
    def events(self) -> EventStore:
        with self._lock:
            if self._events is None:
                try:
                    factory = _EVENT_BACKENDS[self.config.eventdata_type]
                except KeyError:
                    raise KeyError(
                        f"unknown EVENTDATA backend {self.config.eventdata_type!r}; "
                        f"registered: {sorted(_EVENT_BACKENDS)}")
                self._events = factory(self.config)
            return self._events

    @property
    def models(self) -> ModelStore:
        with self._lock:
            if self._models is None:
                try:
                    factory = _MODEL_BACKENDS[self.config.modeldata_type]
                except KeyError:
                    raise KeyError(
                        f"unknown MODELDATA backend {self.config.modeldata_type!r}; "
                        f"registered: {sorted(_MODEL_BACKENDS)}")
                self._models = factory(self.config)
            return self._models

    def verify(self) -> Dict[str, str]:
        """Connectivity check for `pio status` (reference: Storage.verifyAllDataObjects)."""
        out = {}
        self.meta.list_apps()
        out["metadata"] = self.config.metadata_type
        self.events.init_channel(0)
        out["eventdata"] = self.config.eventdata_type
        self.models.list_ids()
        out["modeldata"] = self.config.modeldata_type
        return out


_default: Optional[Storage] = None
_default_lock = threading.Lock()


def get_storage() -> Storage:
    global _default
    with _default_lock:
        if _default is None:
            _default = Storage()
        return _default


def set_storage(storage: Optional[Storage]) -> None:
    """Override the process-wide storage (tests, embedded use)."""
    global _default
    with _default_lock:
        _default = storage
