"""Multi-tenant QoS primitives: quotas, token buckets, fair admission.

Tenancy in this engine is the app/access-key/channel model inherited
from upstream PredictionIO; this module makes it a *serving* concept
rather than just a partition key.  Three jax-free building blocks:

``TokenBucket``
    A classic rate+burst bucket with a computed ``retry_after`` —
    congestion pricing for one tenant, not a global gate.

``TenantQuotas``
    The operator-facing policy store: a ``quotas.json`` next to the
    event data (written by ``pio apps quota``) with per-app overrides
    over fleet-wide defaults.  Hot-reloaded by mtime so a quota bump
    lands without a restart.  Arms the ``tenant.quota.exhausted``
    fault site so the 429 path can be drilled on demand.

``FairInflight``
    Weighted-fair admission under the engine server's global
    ``max_inflight``: while the server has headroom every tenant is
    admitted (work-conserving — a single tenant may use the whole
    budget when alone), but at saturation a tenant is only admitted up
    to its weighted share, so the burster sheds first and quiet
    tenants keep their seats.

Everything here must stay importable without jax: the CLI's
``pio apps quota`` verb and the event server's ingest path both load
it on machines with no accelerator runtime.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, Optional, Tuple

from predictionio_tpu.utils import faults
from predictionio_tpu.utils.atomic_write import atomic_write_text

QUOTAS_FILENAME = "quotas.json"

#: fleet-wide policy applied to any app without an explicit override.
#: rate=0 means "unlimited" (no bucket maintained), which keeps the
#: zero-config single-tenant deployment byte-identical to before.
DEFAULTS = {
    "rate": 0.0,           # ingest events/second sustained (0 = unlimited)
    "burst": 0.0,          # ingest bucket depth (0 = rate for 1s, min 1)
    "weight": 1.0,         # share of engine-server inflight at saturation
    "writer_shards": 1,    # ACTIVE-segment writer shards per namespace
    "deadline_ms": 0.0,    # router deadline cap for this app (0 = router default)
}


class TokenBucket:
    """Rate+burst token bucket with a computed backoff hint.

    ``take(n)`` is all-or-nothing; on refusal ``retry_after(n)`` says
    how long until ``n`` tokens will have accrued at the steady rate —
    the honest Retry-After for a 429, proportional to the deficit
    rather than a constant.
    """

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic) -> None:
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    def _refill_locked(self, now: float) -> None:
        # caller holds self._lock
        if now > self._last:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now

    def take(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill_locked(self._clock())
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available (>= 0.05)."""
        with self._lock:
            self._refill_locked(self._clock())
            deficit = n - self._tokens
        if deficit <= 0 or self.rate <= 0:
            return 0.05
        return max(0.05, deficit / self.rate)


class TenantQuotas:
    """Per-app QoS policy: quotas.json defaults + overrides, hot-reloaded.

    File shape (all fields optional; see ``DEFAULTS``)::

        {"defaults": {"rate": 500, "burst": 1000, "weight": 1,
                      "writer_shards": 1},
         "apps": {"7": {"rate": 50, "burst": 100, "weight": 0.5}}}

    ``admit(app_id, n)`` is the ingest gate: it charges ``n`` events
    against the app's bucket and, on refusal, returns the computed
    Retry-After.  Buckets are created lazily and survive reloads so a
    quota *edit* does not hand a burster a fresh burst allowance
    unless its rate/burst actually changed.
    """

    def __init__(self, path: Optional[str] = None,
                 defaults: Optional[Dict] = None,
                 clock=time.monotonic) -> None:
        self.path = path
        self._clock = clock
        self._lock = threading.Lock()
        self._defaults = dict(DEFAULTS)
        if defaults:
            self._defaults.update(defaults)
        self._apps: Dict[str, Dict] = {}
        self._buckets: Dict[str, TokenBucket] = {}
        self._mtime: float = -1.0
        self._next_check = 0.0
        self._reload_locked()

    # -- policy file ----------------------------------------------------

    @staticmethod
    def for_home(home: str, **kw) -> "TenantQuotas":
        return TenantQuotas(os.path.join(home, QUOTAS_FILENAME), **kw)

    def _reload_locked(self) -> None:
        if not self.path:
            return
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            mtime = -1.0
        if mtime == self._mtime:
            return
        self._mtime = mtime
        apps: Dict[str, Dict] = {}
        defaults = dict(DEFAULTS)
        if mtime >= 0:
            try:
                with open(self.path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
                defaults.update(doc.get("defaults") or {})
                for app, over in (doc.get("apps") or {}).items():
                    apps[str(app)] = dict(over)
            except (OSError, ValueError):
                # a torn/garbled policy file must never take ingest
                # down; keep the previous policy until it parses again
                return
        self._defaults = defaults
        self._apps = apps
        # rebuild buckets only where the effective rate/burst changed
        for app in list(self._buckets):
            rate, burst = self._rate_burst_locked(app)
            b = self._buckets[app]
            if rate <= 0:
                del self._buckets[app]
            elif (b.rate, b.burst) != (rate, burst):
                self._buckets[app] = TokenBucket(rate, burst,
                                                 clock=self._clock)

    def _maybe_reload(self) -> None:
        # throttle the mtime probe: the gate sits on the per-event hot
        # path, so a policy edit lands within ~1s, not instantly
        now = self._clock()
        if now < self._next_check:
            return
        with self._lock:
            self._next_check = now + 1.0
            self._reload_locked()

    def set_quota(self, app_id: str, **fields) -> Dict:
        """Persist an override for ``app_id`` (the ``pio apps quota``
        verb).  Passing ``None`` for a field clears that override."""
        doc = {"defaults": {}, "apps": {}}
        if self.path and os.path.exists(self.path):
            try:
                with open(self.path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                pass
        doc.setdefault("apps", {})
        over = dict(doc["apps"].get(str(app_id)) or {})
        for k, v in fields.items():
            if k not in DEFAULTS:
                raise ValueError(f"unknown quota field {k!r} "
                                 f"(expected one of {sorted(DEFAULTS)})")
            if v is None:
                over.pop(k, None)
            else:
                over[k] = v
        if over:
            doc["apps"][str(app_id)] = over
        else:
            doc["apps"].pop(str(app_id), None)
        if self.path:
            atomic_write_text(self.path,
                              json.dumps(doc, indent=2, sort_keys=True))
        with self._lock:
            self._mtime = -2.0  # force re-read on next lookup
            self._reload_locked()
        return over

    # -- lookups --------------------------------------------------------

    def _field(self, app_id: str, key: str):
        over = self._apps.get(str(app_id))
        if over and key in over:
            return over[key]
        return self._defaults[key]

    def _rate_burst_locked(self, app_id: str) -> Tuple[float, float]:
        rate = float(self._field(app_id, "rate"))
        burst = float(self._field(app_id, "burst"))
        if burst <= 0:
            burst = max(rate, 1.0)
        return rate, burst

    def weight(self, app_id: str) -> float:
        self._maybe_reload()
        with self._lock:
            return max(float(self._field(app_id, "weight")), 0.0)

    def writer_shards(self, app_id: str) -> int:
        self._maybe_reload()
        with self._lock:
            return max(int(self._field(app_id, "writer_shards")), 1)

    def deadline_ms(self, app_id: str) -> float:
        """Router deadline cap for this app; 0 means "router default"."""
        self._maybe_reload()
        with self._lock:
            return max(float(self._field(app_id, "deadline_ms")), 0.0)

    def describe(self, app_id: str) -> Dict:
        """Effective policy for one app (CLI ``show`` output)."""
        self._maybe_reload()
        with self._lock:
            rate, burst = self._rate_burst_locked(app_id)
            return {"rate": rate, "burst": burst,
                    "weight": float(self._field(app_id, "weight")),
                    "writer_shards": int(self._field(app_id,
                                                     "writer_shards")),
                    "deadline_ms": float(self._field(app_id,
                                                     "deadline_ms"))}

    # -- the ingest gate ------------------------------------------------

    def admit(self, app_id: str, n: int = 1) -> Tuple[bool, float]:
        """Charge ``n`` events to ``app_id``; returns ``(ok,
        retry_after_seconds)``.  Unlimited apps (rate 0) always pass
        without a bucket."""
        self._maybe_reload()
        app = str(app_id)
        with self._lock:
            rate, burst = self._rate_burst_locked(app)
            bucket = self._buckets.get(app)
            if rate <= 0:
                bucket = None
            elif bucket is None:
                bucket = self._buckets[app] = TokenBucket(
                    rate, burst, clock=self._clock)
        try:
            # chaos drill: an armed error here empties the bucket —
            # the tenant sees its own 429 + Retry-After on demand
            faults.inject("tenant.quota.exhausted")
        except faults.FaultError:
            if bucket is None:
                return False, 1.0
            return False, bucket.retry_after(n)
        if bucket is None or bucket.take(n):
            return True, 0.0
        return False, bucket.retry_after(n)


class FairInflight:
    """Weighted-fair admission under a single global inflight cap.

    Two gates, both hard: the global ``limit`` (never exceeded, so the
    backend sees exactly the concurrency it was sized for) and a
    per-app cap at the app's weighted share of that limit, computed
    over the *currently active* tenant set.  With one tenant active
    its share IS the limit, so the single-tenant deployment behaves
    exactly as before; under contention the tenant over its share —
    the burster — is the one shed, and it can never monopolize the cap
    between other tenants' arrivals.  Ceiling rounding makes the
    shares sum to at least the limit, so the cap stays reachable under
    full contention.

    The active set is "apps seen in the last ``active_window``
    seconds": weights of long-idle tenants stop diluting the shares of
    the tenants actually present.

    Loop-thread-only by design (matches ``EngineServer._inflight``):
    acquire/release happen before any await on the server's event
    loop, so no lock is taken.
    """

    def __init__(self, limit: int,
                 weight_of=None,
                 active_window: float = 5.0,
                 clock=time.monotonic) -> None:
        self.limit = int(limit)
        self._weight_of = weight_of or (lambda app: 1.0)
        self.active_window = float(active_window)
        self._clock = clock
        self._inflight: Dict[str, int] = {}
        self._last_seen: Dict[str, float] = {}
        self.total = 0

    def share(self, app_id: str) -> int:
        """This app's current fair share of ``limit`` (>= 1)."""
        now = self._clock()
        horizon = now - self.active_window
        total_w = 0.0
        for app, seen in list(self._last_seen.items()):
            if seen < horizon and not self._inflight.get(app):
                del self._last_seen[app]
                continue
            total_w += max(self._weight_of(app), 0.0)
        w = max(self._weight_of(str(app_id)), 0.0)
        if str(app_id) not in self._last_seen:
            total_w += w
        if total_w <= 0 or w <= 0:
            return 1
        return max(1, int(math.ceil(self.limit * w / total_w)))

    def try_acquire(self, app_id: str) -> bool:
        app = str(app_id)
        self._last_seen[app] = self._clock()
        if self.limit:
            if self.total >= self.limit:
                return False
            if self._inflight.get(app, 0) >= self.share(app):
                return False
        self._inflight[app] = self._inflight.get(app, 0) + 1
        self.total += 1
        return True

    def release(self, app_id: str) -> None:
        app = str(app_id)
        n = self._inflight.get(app, 0)
        if n <= 1:
            self._inflight.pop(app, None)
        else:
            self._inflight[app] = n - 1
        self.total = max(0, self.total - 1)

    def inflight(self, app_id: Optional[str] = None) -> int:
        if app_id is None:
            return self.total
        return self._inflight.get(str(app_id), 0)

    def snapshot(self) -> Dict[str, int]:
        return dict(self._inflight)
