"""Network storage backends: S3 / HDFS model stores, gated SQL servers.

The reference shipped six network backends (HBase, JDBC, Elasticsearch,
HDFS, LocalFS, S3 — SURVEY.md §2a); this environment has no network
services or drivers, so these register their TYPE names with factories
that bind lazily: the S3 and HDFS model stores are full implementations
that connect when their driver (boto3 / pyarrow+libhdfs) is present and
raise :class:`StorageClientError` with install instructions when not;
the PostgreSQL/MySQL event+meta types are gated the same way at
registration (their SQL dialects ride the SQLite implementations'
schema once a DB-API driver exists).

Config (same env scheme as every backend, reference pio-env.sh names):

    PIO_STORAGE_SOURCES_<S>_TYPE=S3|HDFS|PGSQL|MYSQL
    PIO_STORAGE_SOURCES_<S>_BUCKET_NAME / _BASE_PATH   (S3)
    PIO_STORAGE_SOURCES_<S>_HOSTS / _PORTS / _PATH     (HDFS)
    PIO_STORAGE_SOURCES_<S>_URL / _USERNAME / _PASSWORD (SQL)
"""

from __future__ import annotations

import os
import re
from typing import List, Optional

from predictionio_tpu.storage.models import ModelStore


class StorageClientError(RuntimeError):
    """Backend selected but unusable (missing driver / bad config) —
    reference: StorageClientException."""


def _source_env(key: str, default: str = "") -> str:
    # any source name may carry the setting; first match wins. Source
    # names are discovered from their (mandatory) _TYPE key, so names
    # with underscores (MY_PG) resolve too — and because the name is
    # matched as a whole, *_BASE_PATH can never shadow a lookup of PATH.
    names = [m.group(1) for k in os.environ
             if (m := re.match(r"^PIO_STORAGE_SOURCES_(.+)_TYPE$", k))]
    for name in names:
        v = os.environ.get(f"PIO_STORAGE_SOURCES_{name}_{key}")
        if v is not None:
            return v
    return default


class S3ModelStore(ModelStore):
    """Model blobs on S3 (reference: [U] storage/s3/ S3Models).

    ``props`` = the backing source's settings (StorageConfig
    ``source_properties``); direct construction may pass bucket/base
    explicitly or fall back to a single-source env scan.
    """

    def __init__(self, bucket: Optional[str] = None,
                 base_path: Optional[str] = None,
                 props: Optional[dict] = None) -> None:
        try:
            import boto3  # type: ignore[import-not-found]
        except ImportError as e:
            raise StorageClientError(
                "MODELDATA type S3 requires the boto3 driver "
                "(pip install boto3)") from e
        props = props or {}
        self.bucket = (bucket or props.get("BUCKET_NAME")
                       or _source_env("BUCKET_NAME"))
        if not self.bucket:
            raise StorageClientError(
                "S3 model store needs PIO_STORAGE_SOURCES_<S>_BUCKET_NAME")
        self.base = (base_path or props.get("BASE_PATH")
                     or _source_env("BASE_PATH", "pio_models")).strip("/")
        self._s3 = boto3.client("s3")

    def _key(self, instance_id: str) -> str:
        return f"{self.base}/{instance_id}.bin"

    def put(self, instance_id: str, blob: bytes) -> None:
        self._s3.put_object(Bucket=self.bucket, Key=self._key(instance_id),
                            Body=blob)

    def get(self, instance_id: str) -> Optional[bytes]:
        try:
            r = self._s3.get_object(Bucket=self.bucket,
                                    Key=self._key(instance_id))
        except self._s3.exceptions.NoSuchKey:
            return None
        return r["Body"].read()

    def delete(self, instance_id: str) -> bool:
        self._s3.delete_object(Bucket=self.bucket, Key=self._key(instance_id))
        return True

    def list_ids(self) -> List[str]:
        out, token = [], None
        while True:
            kw = {"Bucket": self.bucket, "Prefix": self.base + "/"}
            if token:
                kw["ContinuationToken"] = token
            r = self._s3.list_objects_v2(**kw)
            out += [o["Key"][len(self.base) + 1:-4]
                    for o in r.get("Contents", ())
                    if o["Key"].endswith(".bin")]
            if not r.get("IsTruncated"):
                return out
            token = r.get("NextContinuationToken")


class HDFSModelStore(ModelStore):
    """Model blobs on HDFS via pyarrow (reference: [U] storage/hdfs/
    HDFSModels). Needs libhdfs (a Hadoop install) at runtime."""

    def __init__(self, host: Optional[str] = None, port: Optional[int] = None,
                 path: Optional[str] = None,
                 props: Optional[dict] = None) -> None:
        try:
            from pyarrow import fs
        except ImportError as e:  # pragma: no cover - pyarrow is baked in
            raise StorageClientError(
                "MODELDATA type HDFS requires pyarrow") from e
        props = props or {}
        host = host or props.get("HOSTS") or _source_env("HOSTS", "default")
        port = port if port is not None else int(
            props.get("PORTS") or _source_env("PORTS", "8020"))
        self.root = (path or props.get("PATH")
                     or _source_env("PATH", "/pio_models")).rstrip("/")
        try:
            self._fs = fs.HadoopFileSystem(host, port)
        except Exception as e:
            raise StorageClientError(
                f"cannot reach HDFS at {host}:{port} (libhdfs present?): {e}"
            ) from e

    def _key(self, instance_id: str) -> str:
        return f"{self.root}/{instance_id}.bin"

    def put(self, instance_id: str, blob: bytes) -> None:
        from pyarrow import fs

        self._fs.create_dir(self.root, recursive=True)
        with self._fs.open_output_stream(self._key(instance_id)) as f:
            f.write(blob)

    def get(self, instance_id: str) -> Optional[bytes]:
        from pyarrow import fs

        info = self._fs.get_file_info(self._key(instance_id))
        if info.type == fs.FileType.NotFound:
            return None
        with self._fs.open_input_stream(self._key(instance_id)) as f:
            return f.read()

    def delete(self, instance_id: str) -> bool:
        from pyarrow import fs

        info = self._fs.get_file_info(self._key(instance_id))
        if info.type == fs.FileType.NotFound:
            return False
        self._fs.delete_file(self._key(instance_id))
        return True

    def list_ids(self) -> List[str]:
        from pyarrow import fs

        sel = fs.FileSelector(self.root, allow_not_found=True)
        return [i.base_name[:-4] for i in self._fs.get_file_info(sel)
                if i.base_name.endswith(".bin")]


def _sql_server_gate(type_name: str, driver: str, pip_name: str):
    def factory(cfg):
        try:
            __import__(driver)
        except ImportError as e:
            raise StorageClientError(
                f"storage type {type_name} requires the {driver} driver "
                f"(pip install {pip_name}); with no SQL-server driver in "
                "this environment use SQLITE (same schema, single file) or "
                "EVENTLOG (native engine)") from e
        raise StorageClientError(  # pragma: no cover - needs the driver
            f"{type_name} driver found but server-backed stores are not "
            "wired in this build; see predictionio_tpu/storage/remote.py")

    return factory


def register_all() -> None:
    from predictionio_tpu.storage import registry as reg

    reg.register_model_backend(
        "S3", lambda cfg: S3ModelStore(
            props=cfg.source_properties("MODELDATA")))
    reg.register_model_backend(
        "HDFS", lambda cfg: HDFSModelStore(
            props=cfg.source_properties("MODELDATA")))
    # the reference's pio-env idiom points METADATA and EVENTDATA at the
    # same SQL source — gate both repositories
    pg = _sql_server_gate("PGSQL", "psycopg2", "psycopg2-binary")
    my = _sql_server_gate("MYSQL", "pymysql", "pymysql")
    reg.register_event_backend("PGSQL", pg)
    reg.register_event_backend("MYSQL", my)
    reg.register_meta_backend("PGSQL", pg)
    reg.register_meta_backend("MYSQL", my)
