"""Snapshot-cache speedup for the repeat-`pio train` scan (chip-free).

Measures the tentpole claim behind docs/perf.md "Incremental columnar
snapshot cache": a warm train over a mostly-append-only log should cost
O(new events), not O(event log). Builds a synthetic 1M-event EVENTLOG
namespace via the native NDJSON ingest, then times the full
``read_training_interactions`` call three ways:

- ``cold``  — cache disabled: the status-quo full C++ rescan every
              train pays today;
- ``prime`` — first cached read: full rescan + snapshot write;
- ``warm``  — after appending a 1k-event delta: snapshot load + delta
              scan + concat, the steady-state retrain read.

The headline ratio compares the SCAN layer (``_scan_with_cache``, the
surface the cache replaces — the same span pio_columnar_scan_seconds
measures); end-to-end ``read_training_interactions`` times are also
reported, diluted by the interaction-building pass both paths share.
Verifies warm == cold array-for-array before reporting, and prints ONE
JSON line with the times and the cold/warm scan ratio.

Usage::

    python profile_snapshot.py [--events 1000000] [--delta 1000]
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time


def _lines(lo: int, hi: int) -> bytes:
    out = []
    for i in range(lo, hi):
        sec = i % 60
        minute = (i // 60) % 60
        hour = (i // 3600) % 24
        day = 1 + (i // 86400) % 27
        out.append(
            '{"event":"rate","entityType":"user","entityId":"u%d",'
            '"targetEntityType":"item","targetEntityId":"i%d",'
            '"properties":{"rating":%d.5},'
            '"eventTime":"2026-%02d-%02dT%02d:%02d:%02d.%06dZ"}'
            % (i % 20000, i % 4000, i % 5,
               1 + (i // 2332800) % 12, day, hour, minute, sec, i % 1000000))
    return ("\n".join(out) + "\n").encode()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--events", type=int, default=1_000_000)
    ap.add_argument("--delta", type=int, default=1000)
    ap.add_argument("--chunk", type=int, default=100_000)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")  # no accelerator needed

    import numpy as np

    from predictionio_tpu.data.store import (read_training_interactions,
                                             set_scan_cache)
    from predictionio_tpu.storage.registry import (Storage, StorageConfig,
                                                   set_storage)

    with tempfile.TemporaryDirectory() as home:
        os.environ["PIO_SCAN_CACHE_DIR"] = os.path.join(home, "scan_cache")
        cfg = StorageConfig.from_env({
            "PIO_HOME": home,
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "NATIVE",
            "PIO_STORAGE_SOURCES_NATIVE_TYPE": "EVENTLOG",
        })
        st = Storage(cfg)
        set_storage(st)
        app = st.meta.create_app("SnapProfApp")

        t0 = time.perf_counter()
        for lo in range(0, args.events, args.chunk):
            hi = min(lo + args.chunk, args.events)
            blob = _lines(lo, hi)
            n, fallback = st.events.append_jsonl(blob, hi - lo, app.id)
            assert n == hi - lo and not fallback, \
                f"native ingest fell back for {len(fallback)} lines"
        t_ingest = time.perf_counter() - t0

        from predictionio_tpu.data import store as store_mod

        def read():
            return read_training_interactions(
                "SnapProfApp", value_key="rating",
                value_spec={"rate": "prop"}, storage=st).arrays()

        def scan():
            return store_mod._scan_with_cache(
                st.events.scan_columnar, st, app.id, None, None, None,
                None, None, None, "rating")

        def timed(fn, repeat=1):
            best, out = float("inf"), None
            for _ in range(repeat):
                t0 = time.perf_counter()
                out = fn()
                best = min(best, time.perf_counter() - t0)
            return best, out

        prev = set_scan_cache(False)
        t_cold, c_cold = timed(scan, repeat=2)
        t_read_cold, a_cold = timed(read)
        set_scan_cache(prev)

        t_prime, _c = timed(scan)                # rescan + snapshot write

        lo, hi = args.events, args.events + args.delta
        n, fallback = st.events.append_jsonl(_lines(lo, hi), hi - lo, app.id)
        assert n == hi - lo and not fallback

        # steady state: a small delta does not recompact the snapshot,
        # so repeated warm scans all do load + delta + concat
        t_warm, c_warm = timed(scan, repeat=3)
        t_read_warm, a_warm = timed(read)

        prev = set_scan_cache(False)
        _t, c_ref = timed(scan)                  # post-delta full rescan
        _t, a_ref = timed(read)
        set_scan_cache(prev)

        assert c_warm.n == c_ref.n == c_cold.n + args.delta
        assert (c_warm.times_us == c_ref.times_us).all()
        assert (c_warm.entity_idx == c_ref.entity_idx).all()
        assert (c_warm.target_idx == c_ref.target_idx).all()
        assert list(c_warm.entity_ids) == list(c_ref.entity_ids)
        for x, y in zip(a_warm, a_ref):
            assert np.array_equal(x, y), "warm read diverged from rescan"

        st.events.close()
        print(json.dumps({
            "events": args.events, "delta": args.delta,
            "ingest_s": round(t_ingest, 3),
            "cold_scan_s": round(t_cold, 3),
            "prime_scan_s": round(t_prime, 3),
            "warm_scan_s": round(t_warm, 3),
            "scan_speedup_cold_over_warm": round(t_cold / t_warm, 1),
            "cold_read_s": round(t_read_cold, 3),
            "warm_read_s": round(t_read_warm, 3),
        }))


if __name__ == "__main__":
    main()
