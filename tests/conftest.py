"""Test harness configuration.

All tests run JAX on CPU with a *virtual 8-device mesh* — the analogue of
the reference's `SparkContext("local[*]")` trick (SURVEY.md §4): every
collective / sharding / pjit code path is exercised with real SPMD
semantics, no TPU required.

Environment note: this image's sitecustomize imports jax and registers
the TPU ("axon") backend at interpreter startup, so JAX_PLATFORMS is
decided before conftest runs. The CPU client, however, is created
lazily — setting XLA_FLAGS here (before anything calls
jax.devices("cpu")) still yields the 8 virtual CPU devices, and
PIO_MESH_PLATFORM=cpu points the framework's mesh construction at them.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["PIO_MESH_PLATFORM"] = "cpu"

import jax  # noqa: E402
import pytest  # noqa: E402

# Restrict jax to the CPU platform BEFORE any backend initialization:
# merely asking for jax.devices("cpu") would initialize every platform in
# JAX_PLATFORMS first, and a wedged TPU tunnel then hangs the whole test
# run. Tests must never depend on the tunneled TPU chip.
jax.config.update("jax_platforms", "cpu")

from predictionio_tpu.storage.meta import MetaStore  # noqa: E402
from predictionio_tpu.storage.models import MemoryModelStore  # noqa: E402
from predictionio_tpu.data.events import MemoryEventStore  # noqa: E402
from predictionio_tpu.storage.registry import Storage, StorageConfig, set_storage  # noqa: E402


@pytest.fixture()
def storage():
    """A fresh, fully in-memory Storage installed as process default."""
    st = Storage(StorageConfig(metadata_type="MEMORY",
                               eventdata_type="MEMORY",
                               modeldata_type="MEMORY"))
    # force instantiation so the fixtures are shared instances
    st._meta = MetaStore(":memory:")
    st._events = MemoryEventStore()
    st._models = MemoryModelStore()
    set_storage(st)
    yield st
    set_storage(None)


@pytest.fixture(scope="session")
def cpu_mesh():
    """8-virtual-device CPU mesh for collective/sharding tests."""
    from predictionio_tpu.parallel.mesh import MeshConfig, make_mesh

    return make_mesh(MeshConfig(axes={"data": 8}))


def pytest_sessionfinish(session, exitstatus):
    """When ``PIO_TEST_INCIDENT_EXPORT`` names a directory, copy every
    incident bundle the run left under the pytest basetemp into it —
    CI uploads these as postmortem artifacts on failure. A bundle is
    any directory holding a ``manifest.json`` (chaos-marked tests
    write real ones via the flight recorder)."""
    export = os.environ.get("PIO_TEST_INCIDENT_EXPORT")
    if not export:
        return
    import shutil

    tmp = session.config._tmp_path_factory.getbasetemp() \
        if hasattr(session.config, "_tmp_path_factory") else None
    if tmp is None or not tmp.exists():
        return
    os.makedirs(export, exist_ok=True)
    for manifest in tmp.rglob("manifest.json"):
        bundle = manifest.parent
        dest = os.path.join(export, bundle.name)
        try:
            shutil.copytree(str(bundle), dest, dirs_exist_ok=True)
        except OSError:
            pass  # artifact export is best-effort, never a test failure
