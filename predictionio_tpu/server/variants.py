"""Multi-model HBM multiplexing: resident variant sets on one replica.

Upstream PredictionIO's engine-variant A/B story (``pio eval``, engine
variants — PAPER.md survey §0) is offline-only: one deployed process
serves exactly one model. This module makes several model GENERATIONS
(champion / challenger / canary, from the model registry) resident on
ONE serving replica at once, each with its own AOT bucket ladder warmed
through the process-wide executable cache — so multiplexing is a
dispatch problem, not a compile problem (same-geometry variants share
every executable bit-for-bit).

Dispatch is a **deterministic weighted split**: each query's entity is
hashed with a salt and walked through the cumulative weights, so a user
sticks to their assigned arm for as long as the weights stand (sticky
assignment — the property online metrics need: a user's feedback accrues
against the variant that actually served them). Weights are editable at
runtime (``POST /variants/weights``, ``pio variants set-weights``) with
probe-then-apply semantics: a weight can only be put on a variant that
is resident AND warmed.

Failure containment: a variant whose ``/reload`` swap dies mid-flight
(fault site ``variant.reload.partial``) is marked failed and drops out
of the effective split — the default arm (champion) absorbs its weight
and keeps serving. The default arm itself rolls back like the classic
single-model ``/reload``: the last-good engine is retained.

Fault sites (utils/faults.py Known-sites table):

- ``variant.assign.skew``   — assignment hash bypassed; every query
  lands on the default arm (a skewed split the chaos harness must see)
- ``variant.reload.partial`` — a variant swap dies after the candidate
  loaded but before it published (mid-swap kill)
"""

from __future__ import annotations

import hashlib
import json
import re
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from predictionio_tpu.utils import faults

#: variant names: dns-label-ish, so they are safe in headers,
#: Prometheus label values, and CLI specs
_NAME = re.compile(r"^[a-z0-9][a-z0-9_-]*$")

#: reserved names with registry-resolution semantics
CHAMPION = "champion"


class VariantError(ValueError):
    """Bad variant spec / weights / unknown variant."""


@dataclass
class VariantSpec:
    """One arm of the split, as configured (``name[@gen]:weight``)."""

    name: str
    weight: float
    gen: Optional[int] = None  # pinned registry generation


@dataclass
class ResidentVariant:
    """One arm of the split, as loaded into HBM."""

    spec: VariantSpec
    gen: Optional[int] = None
    instance_id: Optional[str] = None
    deployed: Any = None
    warmup: Any = None          # per-variant AOTWarmup (or None)
    state: str = "loading"      # loading | ready | failed
    error: Optional[str] = None
    swapped_at: float = 0.0
    swaps: int = 0

    def serving(self) -> bool:
        return self.state == "ready" and self.deployed is not None


def parse_weights(spec: str) -> List[VariantSpec]:
    """Parse a split spec: comma-separated ``name[@gen]:weight`` arms
    (``=`` accepted for ``:``), e.g. ``champion:9,challenger:1`` or
    ``champion@3:90,canary@5:10``. Order matters: the FIRST arm is the
    default — it absorbs the weight of failed arms and is where the
    ``variant.assign.skew`` drill lands all traffic.
    """
    out: List[VariantSpec] = []
    seen = set()
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        m = re.match(r"^(?P<name>[^@:=]+)(?:@(?P<gen>\d+))?[:=]"
                     r"(?P<w>[0-9.]+)$", part)
        if not m:
            raise VariantError(
                f"bad variant spec {part!r} (want name[@gen]:weight)")
        name = m.group("name").strip()
        if not _NAME.match(name):
            raise VariantError(f"bad variant name {name!r}")
        if name in seen:
            raise VariantError(f"duplicate variant {name!r}")
        seen.add(name)
        try:
            w = float(m.group("w"))
        except ValueError:
            raise VariantError(f"bad weight in {part!r}") from None
        if w < 0:
            raise VariantError(f"negative weight in {part!r}")
        out.append(VariantSpec(
            name=name, weight=w,
            gen=int(m.group("gen")) if m.group("gen") else None))
    if not out:
        raise VariantError("empty variant spec")
    if sum(v.weight for v in out) <= 0:
        raise VariantError("variant weights sum to zero")
    return out


def weighted_assign(entity: str, arms: List[Tuple[str, float]],
                    salt: str = "pio") -> str:
    """Deterministic sticky assignment: hash (salt, entity) into [0, 1)
    and walk the cumulative weights. Pure and jax-free — the CLI and
    bench preview splits with the exact function serving uses.
    """
    total = sum(w for _, w in arms)
    if total <= 0 or not arms:
        raise VariantError("no arms with positive weight")
    digest = hashlib.sha256(
        f"{salt}|{entity}".encode("utf-8")).digest()
    x = int.from_bytes(digest[:8], "big") / float(1 << 64)
    acc = 0.0
    for name, w in arms:
        acc += w / total
        if x < acc:
            return name
    return arms[-1][0]  # float rounding: last arm catches the tail


def entity_of(query: Any) -> str:
    """The split key for one query: the entity the query is ABOUT, so
    one user's requests stick to one arm. Falls back to the canonical
    JSON of the whole query (still deterministic, just per-shape)."""
    if isinstance(query, dict):
        for key in ("user", "uid", "entity", "entityId", "item", "id"):
            v = query.get(key)
            if v is not None:
                return str(v)
    try:
        return json.dumps(query, sort_keys=True, default=str)
    except (TypeError, ValueError):
        return str(query)


class VariantSet:
    """The resident variant set of one serving replica.

    Resolution: each arm names a model generation in the PR 9
    ``ModelRegistry`` — ``champion`` is the registry champion,
    ``name@N`` pins generation N, and any other unpinned name resolves
    to the NEWEST non-champion generation (the natural challenger).
    Loading the default (first) arm must succeed; any other arm that
    fails to load or warm is marked failed and excluded from the
    effective split, its weight folding into the default arm.
    """

    def __init__(self, storage: Any, specs: Any,
                 engine_factory: Optional[str] = None,
                 variant_id: str = "",
                 salt: str = "pio",
                 warm_factory: Optional[Callable[[], Any]] = None,
                 prepare: Optional[Callable[[str], Any]] = None) -> None:
        self.storage = storage
        self.specs: List[VariantSpec] = (
            parse_weights(specs) if isinstance(specs, str) else list(specs))
        self.engine_factory = engine_factory
        self.variant_id = variant_id
        self.salt = salt
        self._warm_factory = warm_factory
        self._prepare = prepare or self._prepare_default
        self._registry: Any = None
        self._lock = threading.Lock()
        self.weights_epoch = 0
        self._variants: Dict[str, ResidentVariant] = {
            s.name: ResidentVariant(spec=s) for s in self.specs}

    # -- resolution / loading ----------------------------------------------

    @property
    def default(self) -> str:
        """The first configured arm — champion by convention."""
        return self.specs[0].name

    def names(self) -> List[str]:
        return [s.name for s in self.specs]

    def get(self, name: str) -> ResidentVariant:
        try:
            return self._variants[name]
        except KeyError:
            raise VariantError(f"unknown variant {name!r}") from None

    def registry(self) -> Any:
        if self._registry is None:
            from predictionio_tpu.storage.models import model_registry

            self._registry = model_registry(self.storage)
        return self._registry

    def resolve(self, spec: VariantSpec) -> Tuple[int, str]:
        """Map one arm to a (generation, instance_id) in the registry."""
        reg = self.registry()
        entries = {e["gen"]: e for e in reg.generations()}
        if not entries:
            raise VariantError("model registry is empty")
        if spec.gen is not None:
            e = entries.get(spec.gen)
            if e is None:
                raise VariantError(
                    f"variant {spec.name!r} pins gen-{spec.gen:06d} "
                    "which is not in the registry")
            return e["gen"], e["instance_id"]
        champ = reg.champion()
        if spec.name == CHAMPION:
            if champ is None:
                raise VariantError("registry has no champion")
            return champ["gen"], champ["instance_id"]
        # unpinned non-champion arm: newest generation that is not the
        # champion and was not judged off the board
        champ_gen = champ["gen"] if champ else None
        live = [g for g, e in entries.items()
                if g != champ_gen
                and e.get("status") not in ("retired", "rolled_back")]
        if not live:
            raise VariantError(
                f"variant {spec.name!r}: no non-champion generation "
                "to serve as challenger")
        g = max(live)
        return g, entries[g]["instance_id"]

    def _prepare_default(self, instance_id: str) -> Any:
        from predictionio_tpu.core.workflow import prepare_deploy

        return prepare_deploy(
            engine_factory=self.engine_factory, instance_id=instance_id,
            storage=self.storage, variant_id=self.variant_id)

    def _load_one(self, rv: ResidentVariant) -> None:
        gen, iid = self.resolve(rv.spec)
        deployed = self._prepare(iid)
        if self._warm_factory is not None and rv.warmup is None:
            rv.warmup = self._warm_factory()
        with self._lock:
            rv.gen, rv.instance_id = gen, iid
            rv.deployed = deployed
            rv.state = "ready"
            rv.error = None
            rv.swapped_at = time.time()

    def load(self) -> None:
        """Load every arm. The default arm must load — its error
        propagates; any other arm that fails is marked failed (its
        weight folds into the default arm) and serving proceeds."""
        for spec in self.specs:
            rv = self._variants[spec.name]
            try:
                self._load_one(rv)
            except Exception as e:
                if spec.name == self.default:
                    raise
                with self._lock:
                    rv.state = "failed"
                    rv.error = f"{type(e).__name__}: {e}"

    def start_warmups(self) -> None:
        """Kick each loaded arm's AOT warmup (background threads, same
        contract as the single-model deploy-time warmup)."""
        for rv in self._variants.values():
            if rv.warmup is not None and rv.serving():
                rv.warmup.start(rv.deployed)

    def warm_sync_all(self) -> None:
        """Warm every loaded arm synchronously (tests/harness)."""
        for rv in self._variants.values():
            if rv.warmup is not None and rv.serving():
                rv.warmup.warm_sync(rv.deployed)
                rv.warmup.mark_ready()

    def warm_state(self) -> str:
        """Aggregate AOT state over SERVING arms: ``warming`` while any
        ladder still compiles, ``failed`` if any warmup failed (jit
        fallback — degraded, not down), else ``ready``."""
        states = [rv.warmup.state for rv in self._variants.values()
                  if rv.warmup is not None and rv.serving()]
        if any(s in ("idle", "warming") for s in states):
            return "warming"
        if any(s == "failed" for s in states):
            return "failed"
        return "ready"

    # -- the split ----------------------------------------------------------

    def effective_weights(self) -> List[Tuple[str, float]]:
        """Configured weights over SERVING arms only — a failed or
        still-loading arm's weight lands on the default arm, so losing
        the challenger means a 100/0 split, never an error."""
        arms: List[Tuple[str, float]] = []
        orphaned = 0.0
        for spec in self.specs:
            rv = self._variants[spec.name]
            if rv.serving():
                arms.append((spec.name, spec.weight))
            else:
                orphaned += spec.weight
        if not arms:
            return []
        if orphaned > 0:
            arms = [(n, w + orphaned) if n == self.default else (n, w)
                    for n, w in arms]
        return arms

    def choose(self, entity: str, override: Optional[str] = None) -> str:
        """Pick the serving arm for one query. ``override`` is the
        ``X-PIO-Variant`` request header — it must name a SERVING arm.
        """
        if override:
            rv = self._variants.get(override)
            if rv is None or not rv.serving():
                raise VariantError(
                    f"variant {override!r} is not resident and serving")
            return override
        try:
            # chaos drill: an armed error here bypasses the hash — all
            # traffic piles onto the default arm (a visible skew)
            faults.inject("variant.assign.skew")
        except faults.FaultError:
            return self.default
        arms = self.effective_weights()
        if not arms:
            raise VariantError("no serving variants")
        return weighted_assign(entity, arms, self.salt)

    def set_weights(self, weights: Dict[str, float]) -> List[Tuple[str, float]]:
        """Probe-then-apply: every named arm must be resident AND
        serving before any weight moves. Returns the new effective
        split. Arms not named keep weight 0 (an explicit retire)."""
        if not weights:
            raise VariantError("empty weights")
        parsed: Dict[str, float] = {}
        for name, w in weights.items():
            rv = self._variants.get(name)
            if rv is None:
                raise VariantError(f"unknown variant {name!r}")
            if not rv.serving():
                raise VariantError(
                    f"variant {name!r} is {rv.state}, not serving — "
                    "refusing to weight it")
            w = float(w)
            if w < 0:
                raise VariantError(f"negative weight for {name!r}")
            parsed[name] = w
        if sum(parsed.values()) <= 0:
            raise VariantError("weights sum to zero")
        with self._lock:
            for spec in self.specs:
                spec.weight = parsed.get(spec.name, 0.0)
            self.weights_epoch += 1
        return self.effective_weights()

    # -- reload -------------------------------------------------------------

    def reload_variant(self, name: str,
                       probe: Optional[Callable[[Any], None]] = None,
                       ) -> Dict[str, Any]:
        """Swap ONE arm onto its freshly-resolved generation, leaving
        every other arm untouched. Runs load → (fault site) → warm →
        probe → publish; the swap is the last step, so a candidate
        that dies anywhere earlier never serves.

        Outcomes: ``promoted`` (swap landed); ``rolled_back`` (default
        arm kept its last-good engine); ``failed`` (a non-default arm
        dropped out of the split — the champion absorbs its weight).
        """
        rv = self.get(name)
        old = (rv.gen, rv.instance_id, rv.deployed, rv.state, rv.error)
        try:
            gen, iid = self.resolve(rv.spec)
            deployed = self._prepare(iid)
            # mid-swap kill site: the candidate is loaded but has not
            # published — a crash here must strand NOTHING in the split
            faults.inject("variant.reload.partial")
            if self._warm_factory is not None and rv.warmup is None:
                rv.warmup = self._warm_factory()
            if rv.warmup is not None:
                rv.warmup.warm_sync(deployed)
                rv.warmup.mark_ready()
            if probe is not None:
                probe(deployed)
        except Exception as e:
            err = f"{type(e).__name__}: {e}"
            if name == self.default and old[2] is not None:
                # champion semantics: last-good engine keeps serving
                return {"variant": name, "outcome": "rolled_back",
                        "generation": old[0], "error": err}
            with self._lock:
                rv.deployed = None
                rv.state = "failed"
                rv.error = err
            return {"variant": name, "outcome": "failed",
                    "generation": old[0], "error": err}
        with self._lock:
            rv.gen, rv.instance_id = gen, iid
            rv.deployed = deployed
            rv.state = "ready"
            rv.error = None
            rv.swapped_at = time.time()
            rv.swaps += 1
        return {"variant": name, "outcome": "promoted", "generation": gen,
                "engineInstanceId": iid}

    # -- observability ------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The /health + /variants view: per-arm generation, warmup
        state, configured and effective weights."""
        eff = dict(self.effective_weights())
        total = sum(eff.values()) or 1.0
        variants: Dict[str, Any] = {}
        for spec in self.specs:
            rv = self._variants[spec.name]
            variants[spec.name] = {
                "generation": rv.gen,
                "engineInstanceId": rv.instance_id,
                "state": rv.state,
                "weight": spec.weight,
                "effectiveWeight": round(eff.get(spec.name, 0.0) / total, 6),
                "warmup": (rv.warmup.progress()
                           if rv.warmup is not None else None),
                "swappedAt": round(rv.swapped_at, 3) or None,
                "swaps": rv.swaps,
                "error": rv.error,
            }
        return {"salt": self.salt, "default": self.default,
                "weightsEpoch": self.weights_epoch, "variants": variants}
