"""The ``pio`` CLI.

Reference: [U] tools/.../console/Console.scala + commands/ (scopt
parser dispatching every verb; unverified, SURVEY.md §3). Verb surface
preserved: ``app`` (new/list/show/delete/data-delete/channel-new/
channel-delete), ``accesskey`` (new/list/delete), ``eventserver``,
``train``, ``deploy``, ``undeploy``, ``eval``, ``batchpredict``,
``export``, ``import``, ``status``, ``fsck``, ``trace``, ``dashboard``,
``adminserver``, ``template``, ``build``, ``run``, ``shell``,
``version``. Where the
reference shelled out to sbt/spark-submit, training runs in-process on
the JAX mesh — ``build`` is static validation rather than compilation.

Usage: ``python -m predictionio_tpu.tools.cli <verb> …`` (or the
``pio`` console script once installed).
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from predictionio_tpu.storage.registry import get_storage
from predictionio_tpu.version import __version__


def _die(msg: str, code: int = 1) -> "NoReturn":  # type: ignore[name-defined]
    print(f"[error] {msg}", file=sys.stderr)
    raise SystemExit(code)


def _load_variant_file(engine_dir: str, variant: Optional[str]) -> Dict[str, Any]:
    path = variant or os.path.join(engine_dir, "engine.json")
    if not os.path.exists(path):
        _die(f"engine variant file not found: {path}")
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def _resolve(spec: str) -> Any:
    from predictionio_tpu.utils.imports import resolve_spec

    return resolve_spec(spec)


# -- app ----------------------------------------------------------------------


def cmd_app(args: argparse.Namespace) -> None:
    st = get_storage()
    meta = st.meta
    if args.app_cmd == "new":
        if meta.get_app_by_name(args.name):
            _die(f"app {args.name!r} already exists")
        app = meta.create_app(args.name, args.description or "")
        st.events.init_channel(app.id)
        ak = meta.create_access_key(app.id, key=args.access_key)
        print(f"[info] Created app {app.name!r} (id {app.id}).")
        print(f"[info] Access Key: {ak.key}")
    elif args.app_cmd == "list":
        for app in meta.list_apps():
            keys = meta.list_access_keys(app.id)
            print(f"{app.id:>6}  {app.name:<24} keys={len(keys)}  {app.description}")
    elif args.app_cmd == "show":
        app = meta.get_app_by_name(args.name) or _die(f"no app {args.name!r}")
        print(f"id={app.id} name={app.name} description={app.description!r}")
        for ak in meta.list_access_keys(app.id):
            events = ",".join(ak.events) or "(all)"
            print(f"  accesskey {ak.key}  events={events}")
        for ch in meta.list_channels(app.id):
            print(f"  channel {ch.id}: {ch.name}")
    elif args.app_cmd == "delete":
        app = meta.get_app_by_name(args.name) or _die(f"no app {args.name!r}")
        for ch in meta.list_channels(app.id):
            st.events.remove_channel(app.id, ch.id)
        st.events.remove_channel(app.id)
        meta.delete_app(app.id)
        print(f"[info] Deleted app {args.name!r}.")
    elif args.app_cmd == "data-delete":
        app = meta.get_app_by_name(args.name) or _die(f"no app {args.name!r}")
        if args.channel:
            ch = meta.get_channel_by_name(app.id, args.channel) or _die(
                f"no channel {args.channel!r}")
            st.events.wipe(app.id, ch.id)
        else:
            st.events.wipe(app.id)
        print(f"[info] Wiped event data of app {args.name!r}.")
    elif args.app_cmd == "channel-new":
        app = meta.get_app_by_name(args.name) or _die(f"no app {args.name!r}")
        ch = meta.create_channel(app.id, args.channel)
        st.events.init_channel(app.id, ch.id)
        print(f"[info] Created channel {ch.name!r} (id {ch.id}) in app {app.name!r}.")
    elif args.app_cmd == "channel-delete":
        app = meta.get_app_by_name(args.name) or _die(f"no app {args.name!r}")
        ch = meta.get_channel_by_name(app.id, args.channel) or _die(
            f"no channel {args.channel!r}")
        st.events.remove_channel(app.id, ch.id)
        meta.delete_channel(ch.id)
        print(f"[info] Deleted channel {args.channel!r}.")
    elif args.app_cmd == "quota":
        # jax-free by design: writes quotas.json next to the event
        # data; every server hot-reloads it within ~1s of the edit
        from predictionio_tpu.server.tenancy import TenantQuotas

        app = meta.get_app_by_name(args.name) or _die(f"no app {args.name!r}")
        quotas = (TenantQuotas(args.quotas_file) if args.quotas_file
                  else TenantQuotas.for_home(st.config.home))
        fields: Dict[str, Any] = {}
        if args.rate is not None:
            fields["rate"] = args.rate
        if args.burst is not None:
            fields["burst"] = args.burst
        if args.weight is not None:
            fields["weight"] = args.weight
        if args.writer_shards is not None:
            fields["writer_shards"] = args.writer_shards
        if args.deadline_ms is not None:
            fields["deadline_ms"] = args.deadline_ms
        for k in args.clear or []:
            fields[k.replace("-", "_")] = None
        if fields:
            quotas.set_quota(str(app.id), **fields)
            print(f"[info] Updated quota overrides for app "
                  f"{app.name!r} (id {app.id}) in {quotas.path}.")
        eff = quotas.describe(str(app.id))
        print(json.dumps({"app": app.name, "appId": app.id,
                          "effective": eff}, indent=2, sort_keys=True))


def cmd_accesskey(args: argparse.Namespace) -> None:
    meta = get_storage().meta
    if args.ak_cmd == "new":
        app = meta.get_app_by_name(args.app_name) or _die(f"no app {args.app_name!r}")
        events = args.events.split(",") if args.events else []
        ak = meta.create_access_key(app.id, events=[e for e in events if e])
        print(f"[info] Access Key: {ak.key}")
    elif args.ak_cmd == "list":
        app = meta.get_app_by_name(args.app_name) if args.app_name else None
        for ak in meta.list_access_keys(app.id if app else None):
            events = ",".join(ak.events) or "(all)"
            print(f"{ak.key}  app={ak.app_id}  events={events}")
    elif args.ak_cmd == "delete":
        if not meta.delete_access_key(args.key):
            _die("no such access key")
        print("[info] Deleted access key.")


# -- servers ------------------------------------------------------------------


def _configure_tracing(args: argparse.Namespace) -> None:
    """Arm the process-wide tracer from the shared server flags."""
    if getattr(args, "access_log", False):
        import logging

        # the access log emits at INFO on "pio.access"; without a
        # handler the stdlib lastResort (WARNING+) would drop every line
        lg = logging.getLogger("pio.access")
        if not lg.handlers:
            h = logging.StreamHandler()
            h.setFormatter(logging.Formatter("%(message)s"))
            lg.addHandler(h)
            lg.setLevel(logging.INFO)
            lg.propagate = False
    if not getattr(args, "tracing", False):
        return
    from predictionio_tpu.storage.registry import StorageConfig
    from predictionio_tpu.utils import tracing

    path = args.trace_file
    if path is None:
        path = tracing.default_trace_path(StorageConfig.from_env().home)
    tracing.TRACER.configure(
        enabled=True,
        sample_rate=args.trace_sample,
        slow_query_ms=args.slow_query_ms,
        jsonl_path=path or None,
    )
    print(f"[info] tracing enabled (sample={args.trace_sample}, "
          f"file={path or '(ring only)'})")


def cmd_eventserver(args: argparse.Namespace) -> None:
    from predictionio_tpu.server.event_server import EventServer

    _configure_tracing(args)
    replication = None
    if args.lease_home:
        from predictionio_tpu.server.repl_server import ReplNode
        from predictionio_tpu.storage.registry import StorageConfig

        ip = args.ip if args.ip not in ("0.0.0.0", "::") else "127.0.0.1"
        advertise = args.advertise_url or f"http://{ip}:{args.port}"
        replication = ReplNode(
            lease_home=args.lease_home,
            advertise_url=advertise,
            home=StorageConfig.from_env().home,
            replicate_to=args.replicate_to,
            lease_ttl=args.lease_ttl)
    server = EventServer(host=args.ip, port=args.port, stats=args.stats,
                         ingest_batching=args.ingest_batching,
                         ingest_max_batch=args.ingest_max_batch,
                         ingest_queue_depth=args.ingest_queue_depth,
                         auth_cache_ttl=args.auth_cache_ttl,
                         durable_acks=args.durable_acks,
                         access_log=args.access_log,
                         segment_maintenance=args.segment_maintenance,
                         tenant_quotas=args.tenant_quotas,
                         incident_dir=_incident_dir(args),
                         replication=replication)
    mode = "group-commit" if args.ingest_batching else "per-event commit"
    if replication is not None:
        mode += f", replicated event plane ({replication.advertise_url})"
    print(f"[info] Event Server listening on {args.ip}:{args.port} ({mode})")
    server.run()


def cmd_deploy(args: argparse.Namespace) -> None:
    from predictionio_tpu.server.engine_server import EngineServer

    _configure_tracing(args)
    variant = _load_variant_file(args.engine_dir, args.variant)
    factory = variant.get("engineFactory") or _die("engine.json missing engineFactory")
    sys.path.insert(0, os.path.abspath(args.engine_dir))
    server = EngineServer(
        engine_factory=factory,
        instance_id=args.engine_instance_id,
        host=args.ip, port=args.port,
        variant_id=str(variant.get("id", "")),
        feedback=args.feedback,
        feedback_url=args.feedback_url,
        feedback_access_key=args.feedback_accesskey,
        feedback_channel=args.feedback_channel,
        batching=args.batching,
        batch_max=args.batch_max,
        batch_wait_ms=args.batch_wait_ms,
        aot_buckets=args.aot_buckets,
        aot_topk=args.aot_topk,
        query_timeout_ms=args.query_timeout_ms,
        max_inflight=args.max_inflight,
        access_log=args.access_log,
        variants=args.variants,
        variant_salt=args.variant_salt,
        tenant_quotas=args.tenant_quotas,
        incident_dir=_incident_dir(args),
    )
    if args.variants:
        snap = server._mux.snapshot()
        arms = ", ".join(
            f"{n}=gen-{v['generation']:06d}" if v["generation"] is not None
            else f"{n}={v['state']}"
            for n, v in snap["variants"].items())
        print(f"[info] Engine Server ({arms}) "
              f"listening on {args.ip}:{args.port}")
    else:
        print(f"[info] Engine Server "
              f"(instance {server.deployed.instance.id}) "
              f"listening on {args.ip}:{args.port}")
    server.run()


def cmd_undeploy(args: argparse.Namespace) -> None:
    import urllib.request

    url = f"http://{args.ip}:{args.port}/stop"
    with urllib.request.urlopen(url, timeout=10) as r:
        print(r.read().decode())


def cmd_router(args: argparse.Namespace) -> None:
    """Fleet router: one endpoint over N engine-server replicas —
    health-aware P2C routing, retry budget, hedging, rolling reload
    (docs/operations.md "Fleet deployment")."""
    if args.router_cmd == "serve":
        from predictionio_tpu.server.router import FleetRouter

        _configure_tracing(args)
        replicas = ([u for u in args.replicas.split(",") if u.strip()]
                    if args.replicas else None)
        pool = None
        autoscale_cfg = None
        if args.pool_spawn:
            if not args.manifest:
                _die("--pool-spawn needs --manifest (the file the pool "
                     "rewrites and the router watches)")
            import shlex

            from predictionio_tpu.tools.supervise import ReplicaPool

            pool = ReplicaPool(shlex.split(args.pool_spawn),
                               args.manifest)
            for _ in range(max(1, args.min_replicas)):
                name = pool.add_replica()
                print(f"[info] pool replica {name} ready")
            if not args.no_autoscale:
                from predictionio_tpu.server.autoscale import (
                    AutoscaleConfig,
                )

                autoscale_cfg = AutoscaleConfig(
                    min_replicas=max(1, args.min_replicas),
                    max_replicas=max(1, args.max_replicas),
                    interval=args.autoscale_interval)
        router = FleetRouter(
            replicas=replicas,
            manifest=args.manifest,
            host=args.ip, port=args.port,
            health_interval=args.health_interval,
            retry_budget_ratio=args.retry_budget,
            hedge=not args.no_hedge,
            hedge_min_ms=args.hedge_min_ms,
            default_deadline_ms=args.deadline_ms,
            per_try_timeout_ms=args.per_try_timeout_ms,
            drain_timeout=args.drain_timeout,
            ready_timeout=args.ready_timeout,
            access_log=args.access_log,
            tenant_quotas=args.tenant_quotas,
            slo_config=args.slo_config,
            scrape_interval=args.scrape_interval,
            probe_interval=args.probe_interval,
            incident_dir=_incident_dir(args),
            pool=pool,
            autoscale=autoscale_cfg,
            remediations=args.remediations,
        )
        print(f"[info] Fleet router on {args.ip}:{args.port} over "
              f"{len(router.replicas)} replicas "
              f"({', '.join(r.name for r in router.replicas)})")
        if autoscale_cfg is not None:
            print(f"[info] autoscaler on: {autoscale_cfg.min_replicas}"
                  f"-{autoscale_cfg.max_replicas} replicas, tick every "
                  f"{autoscale_cfg.interval:g}s (--no-autoscale to "
                  "disable)")
        try:
            router.run()
        finally:
            if pool is not None:
                pool.stop_all()
        return

    import urllib.error
    import urllib.request

    base = args.url.rstrip("/")
    if args.router_cmd == "status":
        with urllib.request.urlopen(f"{base}/router/status",
                                    timeout=args.timeout) as r:
            print(json.dumps(json.loads(r.read()), indent=2, sort_keys=True))
        return
    # reload: POST /router/reload[?rolling=1] — long timeout, a rolling
    # pass drains + re-warms every replica sequentially
    qs = "?rolling=1" if args.rolling else ""
    req = urllib.request.Request(f"{base}/router/reload{qs}", data=b"",
                                 method="POST")
    try:
        with urllib.request.urlopen(req, timeout=args.timeout) as r:
            out = json.loads(r.read())
    except urllib.error.HTTPError as e:
        out = json.loads(e.read() or b"{}")
    print(json.dumps(out, indent=2, sort_keys=True))
    if not out.get("ok"):
        _die("fleet reload failed")


def cmd_slo(args: argparse.Namespace) -> None:
    """SLO burn-rate status from a running router (jax-free — runs on
    an ops box). Exit 1 while any SLO is fast-burning, so the runbook's
    "is it still burning?" check is one shell command."""
    base = args.url.rstrip("/")
    try:
        doc = _http_json(f"{base}/slo/status", timeout=args.timeout)
    except Exception as e:  # noqa: BLE001 — ops verb, readable failure
        _die(f"GET {base}/slo/status failed: {type(e).__name__}: {e}")
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        windows = (doc.get("windows") or {})
        th = (doc.get("thresholds") or {})
        print(f"[slo] {base}  fast windows "
              f"{'/'.join(windows.get('fast', []))} > {th.get('fast')}  "
              f"slow {'/'.join(windows.get('slow', []))} > {th.get('slow')}")
        labels = {0: "ok", 1: "SLOW BURN", 2: "FAST BURN"}
        for s in doc.get("slos", []):
            burns = "  ".join(f"{w}={b:g}" for w, b in
                              sorted((s.get("burnRate") or {}).items()))
            print(f"  {s['name']:<24} objective={s['objective']:g}  "
                  f"{burns}  {labels.get(s.get('alerting'), '?')}")
    if doc.get("fastBurning"):
        raise SystemExit(1)


def cmd_top(args: argparse.Namespace) -> None:
    """Terminal fleet dashboard over the router's federated history
    (jax-free). A dumb refresh loop: everything shown is computed
    server-side by GET /top."""
    base = args.url.rstrip("/")
    watch = getattr(args, "watch", 0.0) or 0.0
    once = (args.once or args.json) and not watch
    interval = watch or args.interval

    def frame() -> None:
        doc = _http_json(f"{base}/top?window={args.window}",
                         timeout=args.timeout)
        if args.json:
            print(json.dumps(doc, indent=2, sort_keys=True))
            return
        if "_status" in doc:
            print(f"[top] {base}: HTTP {doc['_status']}: "
                  f"{doc.get('message')}")
            return
        if not once:
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
        qps = doc.get("qps") or {}
        by = ", ".join(f"{k}:{v:g}" for k, v in
                       sorted((qps.get("byStatus") or {}).items()))
        print(f"pio top — {base}  window={doc.get('windowSeconds'):g}s")
        print(f"qps {qps.get('total', 0):g}" + (f"  ({by})" if by else ""))
        paths = doc.get("paths") or {}
        if paths:
            print(f"{'PATH':<16}{'QPS':>8}{'P50MS':>10}{'P99MS':>10}")
            for p, row in sorted(paths.items()):
                p50, p99 = row.get("p50Ms"), row.get("p99Ms")
                print(f"{p:<16}{row.get('qps', 0):>8g}"
                      f"{'-' if p50 is None else p50:>10}"
                      f"{'-' if p99 is None else p99:>10}")
        variants = doc.get("variants") or {}
        if variants:
            print(f"{'VARIANT':<16}{'QPS':>8}{'SHARE':>10}")
            for v, row in sorted(variants.items()):
                print(f"{v:<16}{row.get('qps', 0):>8g}"
                      f"{row.get('share', 0) * 100:>9.1f}%")
        sheds = doc.get("tenantSheds") or {}
        if sheds:
            print("sheds/s  " + "  ".join(
                f"{a}={r:g}" for a, r in sorted(sheds.items())))
        probe = doc.get("probe") or {}
        if probe:
            print("probe/s  " + "  ".join(
                f"{o}={r:g}" for o, r in sorted(probe.items())))
        slo = doc.get("slo") or {}
        labels = {0: "ok", 1: "SLOW", 2: "FAST-BURN"}
        for s in slo.get("slos", []):
            burns = "  ".join(f"{w}={b:g}" for w, b in
                              sorted((s.get("burnRate") or {}).items()))
            print(f"slo {s['name']:<22} {burns}  "
                  f"{labels.get(s.get('alerting'), '?')}")
        print(f"{'REPLICA':<22}{'STATE':<11}{'BREAKER':<9}"
              f"{'EWMA-MS':>8}  GEN")
        for r in doc.get("replicas", []):
            gen = r.get("modelGeneration")
            print(f"{r.get('url', '?'):<22}{r.get('state', '?'):<11}"
                  f"{r.get('breaker', '?'):<9}{r.get('ewmaMs', 0):>8g}"
                  f"  {'-' if gen is None else gen}")

    try:
        frame()
        while not once:
            time.sleep(max(0.2, interval))
            frame()
    except KeyboardInterrupt:
        pass
    except Exception as e:  # noqa: BLE001 — ops verb, readable failure
        _die(f"GET {base}/top failed: {type(e).__name__}: {e}")


# -- train / eval / batchpredict ----------------------------------------------


def cmd_train(args: argparse.Namespace) -> None:
    if getattr(args, "scan_workers", None):
        # per-invocation override of the segment-scan fan-out; the
        # EVENTLOG store reads it wherever the Storage gets built
        os.environ["PIO_SCAN_WORKERS"] = str(args.scan_workers)
    if getattr(args, "read_from", "leader") != "leader":
        from predictionio_tpu.data.replication import select_read_home
        from predictionio_tpu.storage.registry import pio_home

        home = select_read_home(args.read_from, pio_home(),
                                getattr(args, "replica_home", None))
        # the storage home is resolved from the env wherever the
        # Storage gets built — repoint it at the replicated copy
        os.environ["PIO_HOME"] = home
        print(f"[info] Training reads from {args.read_from} home: {home}")
    variant = _load_variant_file(args.engine_dir, args.variant)
    factory = variant.get("engineFactory") or _die("engine.json missing engineFactory")
    # engine dir on sys.path so user engine modules import
    sys.path.insert(0, os.path.abspath(args.engine_dir))
    if getattr(args, "continuous", False):
        _run_continuous(args, variant, factory)
        return
    from predictionio_tpu.core.workflow import run_train

    instance_id = run_train(
        engine_factory=factory,
        variant=variant,
        verbose=args.verbose,
        use_mesh=not args.no_mesh,
        batch=args.batch or "",
        resume=bool(getattr(args, "resume", False)),
        scan_cache=False if getattr(args, "no_scan_cache", False) else None,
    )
    print(f"[info] Training completed. Engine instance: {instance_id}")


def _run_continuous(args: argparse.Namespace, variant: Dict[str, Any],
                    factory: str) -> None:
    """The supervised continuous-training loop (``pio train
    --continuous``): lease → watermark wake → delta train (resumable)
    → registry candidate → guardrail gate → promote + /reload push →
    bake window with automatic rollback. See server/trainer.py and
    docs/operations.md "Continuous training"."""
    from predictionio_tpu.server.trainer import ContinuousTrainer, TrainerConfig

    dsp = (variant.get("datasource") or {}).get("params") or {}
    app_name = args.app or dsp.get("app_name") or dsp.get("appName")
    if not app_name:
        _die("--continuous needs --app or an appName in the variant's "
             "datasource params")
    cfg = TrainerConfig(
        engine_factory=factory,
        app_name=app_name,
        variant=variant,
        variant_id=str(variant.get("id", "")),
        channel=args.channel,
        min_delta_events=args.min_delta_events,
        poll_interval=args.poll_interval,
        lease_ttl=args.lease_ttl,
        retain=args.retain,
        guardrail_holdout=args.guardrail_holdout,
        guardrail_max_regress=args.guardrail_max_regress,
        guardrail_min_events=args.guardrail_min_events,
        gate=args.gate,
        eval_leaderboard_max_age=args.eval_leaderboard_max_age,
        online_champion=args.online_champion,
        online_challenger=args.online_challenger,
        online_min_pairs=args.online_min_pairs,
        online_max_regress=args.online_max_regress,
        bake_seconds=args.bake_seconds,
        bake_error_rate=args.bake_error_rate,
        bake_p95_ratio=args.bake_p95_ratio,
        reload_urls=args.reload_url or [],
        router_url=args.router_url,
        fleet_manifest=args.fleet_manifest,
        use_mesh=not args.no_mesh,
        metrics_port=args.metrics_port,
        incident_dir=_incident_dir(args),
    )
    trainer = ContinuousTrainer(cfg)
    print(f"[info] Continuous trainer: app={app_name!r} "
          f"min_delta={cfg.min_delta_events} lease={trainer.lease.path}")
    outcomes = trainer.run(max_cycles=args.max_cycles)
    for rec in outcomes[-10:]:
        print(f"[train] {rec['outcome']}"
              + (f" gen={rec['generation']}" if rec["generation"] else ""))
    print(f"[info] Continuous trainer stopped after {len(outcomes)} cycles.")


def _http_json(url: str, *, method: str = "GET",
               body: Optional[dict] = None, timeout: float = 10.0) -> dict:
    """GET/POST JSON over urllib (jax-free ops path). An HTTP error
    with a JSON body comes back as that body plus ``_status``, so
    callers can show the replica's own refusal reason instead of a
    stack trace; transport errors still raise."""
    import urllib.error
    import urllib.request

    data = None
    headers = {}
    if body is not None:
        data = json.dumps(body).encode("utf-8")
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            doc = json.loads(e.read() or b"{}")
        except ValueError:
            doc = {}
        doc["_status"] = e.code
        return doc


def _replica_urls(args: argparse.Namespace) -> List[str]:
    """--url (repeatable) plus manifest lines (router format: first
    token is the URL, ``variants=`` annotations ignored here)."""
    urls = list(args.url or [])
    if getattr(args, "manifest", None):
        try:
            with open(args.manifest, "r", encoding="utf-8") as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln or ln.startswith("#"):
                        continue
                    u = ln.split()[0]
                    urls.append(u if "//" in u else "http://" + u)
        except OSError as e:
            _die(f"cannot read manifest {args.manifest!r}: {e}")
    return urls


def cmd_variants(args: argparse.Namespace) -> None:
    """Operate the live variant split (jax-free — runs on an ops box).
    ``status`` shows each replica's resident arms with warmup state and
    online score; ``set-weights`` re-splits traffic fleet-wide with
    probe-then-apply semantics: every replica must report every named
    arm serving BEFORE any replica's weights change, so a typo'd arm or
    a half-warmed challenger can't blackhole traffic on part of the
    fleet."""
    urls = _replica_urls(args)
    if not urls:
        _die("no replicas: pass --url (repeatable) or --manifest FILE")
    if args.variants_cmd == "status":
        out = {}
        for u in urls:
            base = u.rstrip("/")
            try:
                out[base] = _http_json(f"{base}/variants",
                                       timeout=args.timeout)
            except Exception as e:  # noqa: BLE001 — per-replica verdict
                out[base] = {"error": f"{type(e).__name__}: {e}"}
        if args.json:
            print(json.dumps(out, indent=2, sort_keys=True))
            return
        for base, doc in out.items():
            if "variants" not in doc:
                why = doc.get("error") or f"HTTP {doc.get('_status')}"
                print(f"[variants] {base}: {why}")
                continue
            print(f"[variants] {base} default={doc['default']} "
                  f"salt={doc['salt']!r} epoch={doc['weightsEpoch']}")
            for name, arm in sorted(doc["variants"].items()):
                gen = arm.get("generation")
                on = arm.get("online") or {}
                rmse = on.get("onlineRmse")
                print(f"  {name:<16} "
                      f"gen={'?' if gen is None else gen}  "
                      f"state={arm['state']:<8} "
                      f"w={arm['weight']:g}"
                      f"→{arm['effectiveWeight']:.3f}  "
                      f"served={on.get('served', 0)} "
                      f"ctr={on.get('ctr', 0.0):.3f} "
                      f"rmse={'-' if rmse is None else f'{rmse:.4f}'}")
        return
    # set-weights: probe ALL replicas before writing ANY
    from predictionio_tpu.server.variants import parse_weights

    try:
        specs = parse_weights(args.weights)
    except ValueError as e:
        _die(str(e))
    if any(s.gen is not None for s in specs):
        _die("set-weights re-splits arms already resident — generation "
             "pins (name@N) belong to `pio deploy --variants`")
    weights = {s.name: s.weight for s in specs}
    probed: List[str] = []
    for u in urls:
        base = u.rstrip("/")
        try:
            doc = _http_json(f"{base}/variants", timeout=args.timeout)
        except Exception as e:  # noqa: BLE001
            _die(f"probe {base}/variants failed: {type(e).__name__}: {e} "
                 "(no weights were changed)")
        arms = doc.get("variants") or {}
        missing = sorted(n for n in weights
                         if (arms.get(n) or {}).get("state") != "ready")
        if missing:
            _die(f"{base}: arm(s) not serving: {', '.join(missing)} "
                 "(no weights were changed)")
        probed.append(base)
    failed = False
    for base in probed:
        doc = _http_json(f"{base}/variants/weights", method="POST",
                         body={"weights": weights}, timeout=args.timeout)
        if "_status" in doc:
            print(f"[variants] {base}: refused "
                  f"({doc.get('error') or doc['_status']})")
            failed = True
        else:
            print(f"[variants] {base}: weights applied "
                  f"(epoch {doc.get('weightsEpoch')})")
    if failed:
        raise SystemExit(1)


def cmd_models(args: argparse.Namespace) -> None:
    """Generation-aware model registry verbs. Operator writes carry no
    fencing token (``token=None`` bypasses the fence deliberately — the
    human outranks a wedged trainer); meta statuses are re-synced so a
    plain ``/reload`` lands on the chosen champion."""
    from predictionio_tpu.storage.models import model_registry

    st = get_storage()
    reg = model_registry(st)
    if args.models_cmd == "list":
        doc = {"championGeneration": (reg.champion() or {}).get("gen"),
               "fenceToken": reg.fence_token(),
               "generations": reg.generations()}
        if args.replica_url:
            # residency column: which generations each serving replica
            # actually holds in HBM right now (reads /health, so a
            # not-ready 503 still yields the variants block)
            doc["variants"] = {}
            for u in args.replica_url:
                base = u.rstrip("/")
                try:
                    h = _http_json(f"{base}/health", timeout=5.0)
                    doc["variants"][base] = h.get("variants") or {}
                except Exception as e:  # noqa: BLE001
                    doc["variants"][base] = {
                        "error": f"{type(e).__name__}: {e}"}
        if args.json:
            print(json.dumps(doc, indent=2, sort_keys=True))
            return
        champ = doc["championGeneration"]
        print(f"[models] champion=gen-{champ:06d}" if champ is not None
              else "[models] champion=(none)")
        print(f"[models] fence token={doc['fenceToken']}")
        for e in doc["generations"]:
            mark = " *champion*" if e["gen"] == champ else ""
            print(f"  gen-{e['gen']:06d}  {e['status']:<12} "
                  f"instance={e['instance_id']}  "
                  f"sha256={e['sha256'][:12]}…{mark}")
        for base, snap in (doc.get("variants") or {}).items():
            arms = snap.get("variants") if isinstance(snap, dict) else None
            if not arms:
                why = (snap.get("error") or "no variant set resident"
                       if isinstance(snap, dict) else snap)
                print(f"  replica {base}: {why}")
                continue
            residency = ", ".join(
                (f"{n}=gen-{a['generation']:06d}[{a['state']}]"
                 if a.get("generation") is not None
                 else f"{n}=?[{a['state']}]")
                for n, a in sorted(arms.items()))
            print(f"  replica {base}: {residency}")
        return
    if args.models_cmd == "promote":
        try:
            entry = reg.promote(args.generation)
        except KeyError as e:
            _die(str(e))
        reg.sync_meta(st.meta)
        print(f"[models] promoted gen-{entry['gen']:06d} "
              f"(instance {entry['instance_id']}). "
              "GET /reload on each replica (or `pio router reload "
              "--rolling`) to swap serving onto it.")
        return
    if args.models_cmd == "rollback":
        try:
            entry = reg.rollback()
        except LookupError as e:
            _die(str(e))
        reg.sync_meta(st.meta)
        print(f"[models] rolled back to gen-{entry['gen']:06d} "
              f"(instance {entry['instance_id']}). "
              "GET /reload on each replica (or `pio router reload "
              "--rolling`) to swap serving onto it.")


def _human_bytes(n: Optional[int]) -> str:
    if n is None:
        return "?"
    v = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if v < 1024 or unit == "TiB":
            return f"{v:.1f} {unit}" if unit != "B" else f"{int(v)} B"
        v /= 1024
    raise AssertionError


def cmd_index(args: argparse.Namespace) -> None:
    """ANN retrieval-index status for the deployed (latest COMPLETED)
    engine instance: geometry, sizes, HBM estimate, build time, digest
    verdict. Reads only the on-disk artifact manifest + sidecar
    (jax-free — this verb must work on an ops box with no accelerator
    stack), so a memory-backed model store has nothing to show."""
    import hashlib
    from datetime import datetime, timezone

    from predictionio_tpu.utils.integrity import DIGEST_SUFFIX

    st = get_storage()
    iid = args.engine_instance_id
    if not iid:
        latest = next((ei for ei in st.meta.list_engine_instances()
                       if ei.status == "COMPLETED"), None)
        if latest is None:
            _die("no COMPLETED engine instance found "
                 "(train one, or pass --engine-instance-id)")
        iid = latest.id
    instance_dir = st.models.model_dir(iid)
    if instance_dir is None:
        _die(f"model store {type(st.models).__name__} has no filesystem "
             "directory — ANN index manifests live beside model.bin "
             "(LOCALFS)")
    found = []
    for algo in sorted(os.listdir(instance_dir)):
        algo_dir = os.path.join(instance_dir, algo)
        man_path = os.path.join(algo_dir, "ann_index.json")
        if not os.path.isfile(man_path):
            continue
        try:
            with open(man_path, "r", encoding="utf-8") as f:
                man = json.load(f)
        except (OSError, ValueError) as e:
            found.append({"algorithm": algo, "digest_status": "corrupt",
                          "detail": f"unreadable manifest: {e}"})
            continue
        blob_path = os.path.join(algo_dir, "ann_index.bin")
        digest_status = "missing-blob"
        if os.path.exists(blob_path):
            with open(blob_path, "rb") as f:
                actual = hashlib.sha256(f.read()).hexdigest()
            side = None
            try:
                with open(blob_path + DIGEST_SUFFIX, "r",
                          encoding="ascii") as f:
                    side = f.read().strip()
            except OSError:
                pass
            if actual == man.get("sha256") and (side is None
                                                or side == actual):
                digest_status = ("verified" if side is not None
                                 else "unchecksummed")
            else:
                digest_status = "MISMATCH"
        entry = {"algorithm": algo, "digest_status": digest_status,
                 **{k: man.get(k) for k in (
                     "m", "k", "dsub", "dim", "n_items", "code_bytes",
                     "codebook_bytes", "rotation_bytes",
                     "hbm_estimate_bytes", "shards",
                     "build_sec", "built_unix", "sha256")}}
        # per-shard layout math from the manifest alone (ann package
        # root is jax-free by design — safe on an ops box): size a
        # candidate serving mesh before any deploy touches a chip
        want_shards = int(getattr(args, "shards", 0) or 0) \
            or int(man.get("shards") or 0)
        if want_shards > 1 and man.get("n_items") is not None:
            from predictionio_tpu.ann.index import shard_view

            entry["shard_view"] = shard_view(man, want_shards)
        found.append(entry)
    doc = {"engineInstanceId": iid, "instanceDir": instance_dir,
           "indexes": found}
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return
    print(f"[index] engine instance {iid}")
    if not found:
        print("[index] no ANN index artifacts (exact retrieval; enable "
              "with \"ann\": true in engine.json algorithm params)")
        return
    for ix in found:
        print(f"[index] algorithm {ix['algorithm']!r}: "
              f"status={ix['digest_status']}")
        if ix.get("detail"):
            print(f"        {ix['detail']}")
            continue
        if ix.get("m") is None:
            continue
        print(f"        geometry   M={ix['m']} K={ix['k']} "
              f"dsub={ix['dsub']} (dim {ix['dim']})")
        print(f"        corpus     {ix['n_items']:,} items, "
              f"codes {_human_bytes(ix['code_bytes'])}, "
              f"codebooks {_human_bytes(ix['codebook_bytes'])}")
        print(f"        HBM est.   {_human_bytes(ix['hbm_estimate_bytes'])} "
              "(codes + codebooks + re-rank floats)")
        sv = ix.get("shard_view")
        if sv:
            print(f"        sharded    {sv['shards']}-way mesh: "
                  f"{sv['rows_per_shard']:,} rows/device "
                  f"({sv['padded_items'] - ix['n_items']} pad), "
                  f"codes {_human_bytes(sv['code_bytes_per_shard'])}/dev, "
                  f"rerank {_human_bytes(sv['rerank_bytes_per_shard'])}/dev")
            print(f"        HBM/device {_human_bytes(sv['hbm_per_device_bytes'])} "
                  f"(+ {_human_bytes(sv['replicated_bytes'])} replicated "
                  "codebooks/rotation)")
        built = ix.get("built_unix")
        when = (datetime.fromtimestamp(built, timezone.utc)
                .strftime("%Y-%m-%d %H:%M:%SZ") if built else "?")
        print(f"        built      {when} in {ix.get('build_sec', '?')}s, "
              f"sha256 {str(ix.get('sha256'))[:12]}…")


def _print_leaderboard(doc: dict, as_json: bool) -> None:
    from predictionio_tpu.storage import leaderboard as lb

    if as_json:
        print(json.dumps(doc, indent=2))
        return
    print(f"[leaderboard] instance={doc.get('instanceId')} "
          f"metric={doc.get('metric')} mode={doc.get('mode')} "
          f"grid={doc.get('gridSize')} digest={lb.digest(doc)}")
    if doc.get("mode") == "distributed":
        print(f"[leaderboard] buckets={doc.get('buckets')} "
              f"compiles={doc.get('compiles')} "
              f"dispatches={doc.get('dispatches')} "
              f"shards={doc.get('shards')} "
              f"wall={doc.get('wallSeconds', 0):.3f}s "
              f"device={doc.get('deviceSeconds', 0):.3f}s")
    for e in doc.get("entries", []):
        score = e.get("score")
        folds = e.get("foldScores") or []
        fold_s = (" folds=[" + ", ".join(
            "nan" if s is None else f"{s:.4f}" for s in folds) + "]"
            if folds else "")
        algos = (e.get("engineParams") or {}).get("algorithmsParams") or []
        algo_s = "; ".join(
            f"{a.get('name')}:{json.dumps(a.get('params'), sort_keys=True, default=str)}"
            for a in algos)
        print(f"  #{e['rank']:<3} cand {e['index']:<3} "
              f"score={'nan' if score is None else f'{score:.6f}'}"
              f"{fold_s}  {algo_s}")


def _eval_leaderboard(args: argparse.Namespace) -> None:
    """`pio eval leaderboard [instance_id]` — inspect a persisted sweep
    leaderboard. Pure artifact read (jax-free ops path): no jax import,
    no engine code."""
    from predictionio_tpu.storage import leaderboard as lb

    home = get_storage().config.home
    iid = args.engine_params_generator  # optional positional, reused
    doc = lb.read(home, iid) if iid else lb.latest(home)
    if doc is None:
        _die("no leaderboard found"
             + (f" for instance {iid}" if iid else
                f" under {lb.leaderboard_dir(home)}; run `pio eval "
                "--distributed` (or any eval) first"))
    _print_leaderboard(doc, args.json)


def cmd_eval(args: argparse.Namespace) -> None:
    if args.evaluation == "leaderboard":
        _eval_leaderboard(args)
        return
    from predictionio_tpu.controller.evaluation import Evaluation, EngineParamsGenerator
    from predictionio_tpu.core.workflow import run_evaluation

    if not args.engine_params_generator:
        _die("pio eval needs an engine params generator (module:attr)")
    sys.path.insert(0, os.path.abspath(args.engine_dir))
    ev_obj = _resolve(args.evaluation)
    evaluation: Evaluation = ev_obj() if isinstance(ev_obj, type) else ev_obj
    gen_obj = _resolve(args.engine_params_generator)
    generator: EngineParamsGenerator = gen_obj() if isinstance(gen_obj, type) else gen_obj
    instance_id, result = run_evaluation(
        evaluation, generator.engine_params_list,
        verbose=args.verbose,
        evaluation_class=args.evaluation,
        generator_class=args.engine_params_generator,
        distributed=args.distributed,
        sweep_shards=args.sweep_shards,
    )
    print(f"[info] Evaluation completed: instance {instance_id}")
    metric = evaluation.metric
    assert metric is not None
    for i, (_, score, _) in enumerate(result.candidates):
        mark = " *best*" if i == result.best_index else ""
        print(f"  candidate {i}: {metric.header} = {score:.6f}{mark}")
    from predictionio_tpu.storage import leaderboard as lb

    doc = lb.read(get_storage().config.home, instance_id)
    if doc is not None:
        _print_leaderboard(doc, args.json)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(result.to_json())
        print(f"[info] wrote {args.output}")


def cmd_evals(args: argparse.Namespace) -> None:
    """Evaluation-instance inspection (jax-free ops path, like
    `pio models`/`pio slo`): list past grid searches, explain a dead
    one (the FAILED row carries the exception), surface leaderboards."""
    from predictionio_tpu.storage import leaderboard as lb

    st = get_storage()
    home = st.config.home
    if args.evals_cmd == "list":
        rows = []
        for vi in st.meta.list_evaluation_instances():
            rows.append({
                "id": vi.id,
                "status": vi.status,
                "evaluationClass": vi.evaluation_class,
                "startTime": str(vi.start_time) if vi.start_time else None,
                "endTime": str(vi.end_time) if vi.end_time else None,
                "results": vi.evaluator_results or "",
                "hasLeaderboard": os.path.exists(
                    lb.leaderboard_path(home, vi.id)),
            })
        if args.json:
            print(json.dumps({"evaluations": rows}, indent=2))
            return
        if not rows:
            print("[evals] no evaluation instances")
            return
        for r in rows:
            mark = " +leaderboard" if r["hasLeaderboard"] else ""
            print(f"  {r['id']}  {r['status']:<14} "
                  f"{r['evaluationClass']:<24} {r['results']}{mark}")
        return
    vi = st.meta.get_evaluation_instance(args.instance_id)
    if vi is None:
        _die(f"no evaluation instance {args.instance_id!r}")
    doc = {
        "id": vi.id,
        "status": vi.status,
        "evaluationClass": vi.evaluation_class,
        "generatorClass": vi.engine_params_generator_class,
        "startTime": str(vi.start_time) if vi.start_time else None,
        "endTime": str(vi.end_time) if vi.end_time else None,
        # EVALCOMPLETED: the best-candidate summary. FAILED: the
        # recorded exception type/message — the whole point of the
        # verb, a dead sweep explains itself here
        "results": vi.evaluator_results or "",
        "resultsJson": (json.loads(vi.evaluator_results_json)
                        if vi.evaluator_results_json else None),
        "leaderboard": lb.read(home, vi.id),
    }
    if args.json:
        print(json.dumps(doc, indent=2, default=str))
        return
    print(f"[evals] {doc['id']}  status={doc['status']}")
    print(f"[evals] class={doc['evaluationClass']} "
          f"generator={doc['generatorClass'] or '-'}")
    print(f"[evals] start={doc['startTime']} end={doc['endTime']}")
    if doc["results"]:
        print(f"[evals] results: {doc['results']}")
    if doc["leaderboard"] is not None:
        _print_leaderboard(doc["leaderboard"], False)


def cmd_daemon(args: argparse.Namespace) -> None:
    from predictionio_tpu.tools.supervise import Supervisor, normalize_command

    cmd = normalize_command(args.command)
    if not cmd:
        _die("pio daemon: no command given")
    sup = Supervisor(cmd, health_url=args.health_url,
                     health_interval=args.health_interval,
                     health_grace=args.health_grace,
                     max_restarts=args.max_restarts,
                     restart_window=args.restart_window,
                     term_grace=args.term_grace,
                     pidfile=args.pidfile)
    raise SystemExit(sup.run())


def cmd_batchpredict(args: argparse.Namespace) -> None:
    from predictionio_tpu.core.batchpredict import run_batch_predict
    from predictionio_tpu.core.workflow import prepare_deploy

    variant = _load_variant_file(args.engine_dir, args.variant)
    factory = variant.get("engineFactory") or _die("engine.json missing engineFactory")
    sys.path.insert(0, os.path.abspath(args.engine_dir))
    deployed = prepare_deploy(engine_factory=factory,
                              instance_id=args.engine_instance_id,
                              variant_id=str(variant.get("id", "")))
    with open(args.input, "r", encoding="utf-8") as src, \
         open(args.output, "w", encoding="utf-8") as out:
        n = run_batch_predict(deployed, src, out,
                              batch_size=args.batch_size,
                              shards=getattr(args, "shards", 0))
    print(f"[info] Batch predicted {n} queries → {args.output}")


# -- export / import / status / dashboard -------------------------------------


def _app_id_for(args: argparse.Namespace) -> int:
    meta = get_storage().meta
    if args.appid is not None:
        return args.appid
    if args.app_name:
        app = meta.get_app_by_name(args.app_name) or _die(f"no app {args.app_name!r}")
        return app.id
    _die("need --appid or --app-name")
    raise AssertionError


def cmd_export(args: argparse.Namespace) -> None:
    from predictionio_tpu.tools.export_import import export_events

    app_id = _app_id_for(args)
    with open(args.output, "w", encoding="utf-8") as f:
        n = export_events(app_id, f)
    print(f"[info] Exported {n} events to {args.output}")


def cmd_import(args: argparse.Namespace) -> None:
    from predictionio_tpu.tools.export_import import import_events

    app_id = _app_id_for(args)
    with open(args.input, "r", encoding="utf-8") as f:
        n = import_events(app_id, f)
    print(f"[info] Imported {n} events.")


def cmd_status(args: argparse.Namespace) -> None:
    st = get_storage()
    print(f"[info] predictionio_tpu {__version__}")
    try:
        backends = st.verify()
    except Exception as e:
        _die(f"storage connectivity FAILED: {e}")
    for repo, backend in backends.items():
        print(f"[info] {repo}: {backend} (ok)")
    try:
        import jax

        devs = jax.devices()
        print(f"[info] jax devices: {[str(d) for d in devs]}")
    except Exception as e:  # pragma: no cover
        print(f"[warn] jax unavailable: {e}")
    print("[info] status: all systems go")


def cmd_fsck(args: argparse.Namespace) -> None:
    """Offline integrity scan of every persisted artifact under the
    storage home. Exit codes: 0 = clean, 1 = operational error, 2 =
    corruption present (unrepaired), 3 = corruption found and repaired
    — distinct codes so a cron wrapper can page on 2 but merely log 3."""
    from predictionio_tpu.data.pel_integrity import fsck_home
    from predictionio_tpu.storage.registry import StorageConfig

    home = args.home or StorageConfig.from_env().home
    if not os.path.isdir(home):
        _die(f"storage home not found: {home}")
    try:
        report = fsck_home(home, repair=args.repair)
    except OSError as e:
        _die(f"fsck failed: {e}")
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for a in report["artifacts"]:
            name = os.path.basename(str(a["path"]))
            extra = ""
            if a["artifact"] == "eventlog":
                extra = (f" v{a['version']} records={a['records']}"
                         f" corrupt={a['corrupt']}")
                if a["torn_offset"] is not None:
                    extra += f" torn@{a['torn_offset']}"
                if a["quarantine"]:
                    extra += f" quarantined→{a['quarantine']}"
            elif a["artifact"] == "segment":
                extra = f" state={a.get('state')} records={a.get('records')}"
                if a.get("cols_status"):
                    extra += f" cols={a['cols_status']}"
                if a.get("detail"):
                    extra += f" ({a['detail']})"
            print(f"[fsck] {a['artifact']:<9} {name}: {a['status']}{extra}")
        for q in report["quarantines"]:
            print(f"[fsck] quarantine sidecar: {q}")
        print(f"[fsck] checked={report['checked']} clean={report['clean']} "
              f"corrupt={report['corrupt']} repaired={report['repaired']} "
              f"unchecksummed={report['unchecksummed']} "
              f"cold={report.get('cold', 0)}")
    if report["corrupt"]:
        raise SystemExit(2)
    if report["repaired"]:
        raise SystemExit(3)


def cmd_lint(args: argparse.Namespace) -> None:
    """Static invariant analysis over the predictionio_tpu tree
    (stdlib ast only — runs on a jax-less ops box / CI path). Exits 0
    when every finding is baselined or suppressed, 1 otherwise."""
    from predictionio_tpu.analysis.runner import run_lint

    try:
        report = run_lint(
            root=args.root,
            rules=args.rule or None,
            baseline=args.baseline,
            use_baseline=not args.no_baseline,
        )
    except ValueError as e:
        _die(str(e))
    if args.json:
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        for f in report.findings:
            print(f.render())
        print(f"[lint] rules={','.join(report.rules)} "
              f"files={report.files} findings={len(report.findings)} "
              f"baselined={len(report.baselined)} "
              f"suppressed={report.suppressed} "
              f"({report.duration_s:.2f}s)")
        for key in report.stale_baseline:
            print(f"[lint] warning: stale baseline entry (no longer "
                  f"fires): {key}")
    if not report.ok:
        raise SystemExit(1)


def cmd_segments(args: argparse.Namespace) -> None:
    """Operate the partitioned event log: show segment layout, force a
    rollover, compact sealed segments into columnar sidecars, or ship
    them to the cold tier (PIO_SEGMENT_COLD)."""
    import re as _re

    store = get_storage().events
    if not hasattr(store, "namespaces") or not hasattr(store, "_dir"):
        _die("pio segments requires the EVENTLOG backend "
             f"(configured backend: {type(store).__name__})")
    # open every namespace present on disk, not just ones touched in
    # this process
    names = sorted(os.listdir(store._dir)) if os.path.isdir(store._dir) else []
    for fn in names:
        m = _re.match(r"^events_(\d+)(?:_(\d+))?\.pel$", fn)
        if m:
            store._ns(int(m.group(1)),
                      int(m.group(2)) if m.group(2) else None)
    namespaces = store.namespaces()
    if not namespaces:
        print("[segments] no event-log namespaces found")
        return
    acted = {"rolled": 0, "compacted": 0, "shipped": 0}
    report = []
    for ns in namespaces:
        if args.action == "roll":
            if ns.roll():
                acted["rolled"] += 1
        elif args.action == "compact":
            for seg in list(ns.sealed):
                if seg.meta.cols is None and seg.meta.records:
                    try:
                        ns.compact(seg)
                        acted["compacted"] += 1
                    except (IOError, OSError) as e:
                        print(f"[segments] compact {seg.meta.file}: {e}")
        elif args.action == "ship":
            from predictionio_tpu.utils.integrity import IntegrityError

            for seg in list(ns.sealed):
                if seg.meta.state == "sealed":
                    try:
                        if ns.ship(seg, verify=getattr(args, "verify",
                                                       False)):
                            acted["shipped"] += 1
                    except (IOError, OSError, IntegrityError) as e:
                        print(f"[segments] ship {seg.meta.file}: {e}")
        active_bytes = (os.path.getsize(ns.base_path)
                        if os.path.exists(ns.base_path) else 0)
        segs = [s.meta.to_dict() for s in ns.sealed]
        report.append({"namespace": ns.namespace_tag(),
                       "active_bytes": active_bytes,
                       "sealed": segs})
    if args.json:
        print(json.dumps({"namespaces": report, **acted},
                         indent=2, sort_keys=True))
        return
    for entry in report:
        segs = entry["sealed"]
        compacted = sum(1 for s in segs if s["cols"])
        cold = sum(1 for s in segs if s["state"] == "cold")
        print(f"[segments] {entry['namespace']}: "
              f"{len(segs)} sealed ({compacted} compacted, {cold} cold), "
              f"active {entry['active_bytes']} B")
        for s in segs:
            marks = "".join((
                "C" if s["cols"] else "-",
                "S" if s["state"] == "cold" else "-",
                "#" if s["sha256"] else "-",
            ))
            print(f"[segments]   {s['file']} [{marks}] "
                  f"records={s['records']} bytes={s['bytes']}")
    if args.action != "status":
        print(f"[segments] rolled={acted['rolled']} "
              f"compacted={acted['compacted']} shipped={acted['shipped']}")


def cmd_failover(args: argparse.Namespace) -> None:
    """Event-plane failover (jax-free): ``--target URL`` promotes a
    follower by hand (POST /repl/promote — refused while the current
    leader's lease is live, so it cannot split-brain); ``--drill``
    runs the kill -9 harness from server/repl_server.py and prints the
    proof document as one JSON line."""
    if args.target:
        from predictionio_tpu.server.repl_server import FollowerClient

        doc = FollowerClient(args.target, timeout=args.timeout).promote()
        print(json.dumps(doc, indent=2 if args.json else None,
                         sort_keys=True))
        if doc.get("role") != "leader":
            sys.exit(1)
        return
    if not args.drill:
        _die("pio failover needs --drill or --target URL")
    import tempfile

    from predictionio_tpu.server.repl_server import run_failover_drill

    base = args.dir or tempfile.mkdtemp(prefix="pio-failover-")
    proof = run_failover_drill(
        base, events=args.events, kill_after=args.kill_after,
        lease_ttl=args.lease_ttl,
        log=lambda s: print(f"[failover] {s}", file=sys.stderr))
    print(json.dumps(proof, indent=2 if args.json else None,
                     sort_keys=True))
    if not proof.get("ok"):
        sys.exit(3)


def cmd_trace(args: argparse.Namespace) -> None:
    """Tail/grep the span JSONL export written by servers running with
    ``--tracing``. Filters compose; ``--tree`` re-assembles whole traces
    into the same indented view the slow-query log prints."""
    from predictionio_tpu.storage.registry import StorageConfig
    from predictionio_tpu.utils import tracing

    path = args.file or tracing.default_trace_path(
        StorageConfig.from_env().home)
    # include the rotated predecessor so recent history survives rotation
    paths = [p for p in (path + ".1", path) if os.path.exists(p)]
    if not paths:
        _die(f"no trace file at {path} (start a server with --tracing)")
    spans: List[Dict[str, Any]] = []
    for fp in paths:
        with open(fp, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    spans.append(json.loads(line))
                except ValueError:
                    continue  # torn tail from a live writer

    def keep(s: Dict[str, Any]) -> bool:
        if args.trace_id and s.get("traceId") != args.trace_id:
            return False
        if args.errors_only and s.get("status") != "error":
            return False
        if args.min_ms and s.get("durationUs", 0) < args.min_ms * 1000:
            return False
        if args.grep and args.grep not in json.dumps(s, sort_keys=True):
            return False
        return True

    spans = [s for s in spans if keep(s)]
    if not spans:
        print("[info] no spans matched")
        return
    if args.tree:
        by_trace: Dict[str, List[Dict[str, Any]]] = {}
        order: List[str] = []
        for s in spans:
            tid = str(s.get("traceId", "?"))
            if tid not in by_trace:
                by_trace[tid] = []
                order.append(tid)
            by_trace[tid].append(s)
        for tid in order[-args.limit:]:
            print(f"trace {tid}:")
            print(tracing.render_trace_tree(by_trace[tid]))
    else:
        for s in spans[-args.limit:]:
            print(json.dumps(s, sort_keys=True))


def cmd_incidents(args: argparse.Namespace) -> None:
    """Browse the incident flight recorder's bundles (jax-free — runs
    on an ops box against a copied store just as well)."""
    from predictionio_tpu.storage.registry import StorageConfig
    from predictionio_tpu.utils import incidents as incmod

    root = args.dir or incmod.default_incident_dir(
        StorageConfig.from_env().home)
    store = incmod.IncidentStore(root)
    if args.inc_cmd == "list":
        rows = store.list_bundles()
        if args.json:
            print(json.dumps(rows, indent=2, sort_keys=True))
            return
        if not rows:
            print(f"[info] no incident bundles under {root}")
            return
        print(f"{'ID':<38}{'PROC':<9}{'TRIGGERS':<28}SLOS / ARMED FAULTS")
        for r in rows:
            if r.get("incomplete"):
                print(f"{r['id']:<38}{'?':<9}(incomplete: no manifest)")
                continue
            trig = ",".join(r.get("triggers") or [r.get("trigger") or "?"])
            tail = "  ".join((r.get("sloFastBurning") or [])
                             + [f"fault:{s}" for s in r.get("faults") or []])
            print(f"{r['id']:<38}{r.get('process') or '?':<9}"
                  f"{trig:<28}{tail}")
        return
    if args.inc_cmd == "show":
        iid = args.id or (store.ids() or [None])[0]
        if not iid:
            _die(f"no incident bundles under {root}")
        bundle = store.load_bundle(iid)
        if bundle is None:
            _die(f"incident {iid!r} not found (or incomplete) under {root}")
        if args.json:
            print(json.dumps(bundle, indent=2, sort_keys=True))
            return
        m = bundle["manifest"]
        print(f"incident {iid}  process={m.get('process')}  "
              f"at={m.get('capturedAt')}")
        for t in m.get("triggers", []):
            print(f"  trigger {t.get('trigger')}  "
                  f"detail={json.dumps(t.get('detail') or {}, sort_keys=True)}")
        if m.get("sloFastBurning"):
            print(f"  fast-burning SLOs: {', '.join(m['sloFastBurning'])}")
        if m.get("faults"):
            print(f"  armed fault sites: {', '.join(sorted(m['faults']))}")
        ex = m.get("exemplars") or []
        if ex:
            print(f"  pinned exemplars: {len(ex)} "
                  f"(worst {ex[0].get('valueMs')}ms in "
                  f"{ex[0].get('series')}, trace {ex[0].get('traceId')})")
        print(f"  files: {', '.join(m.get('files', []))}")
        return
    removed = store.prune(args.retain)
    print(f"[info] removed {len(removed)} bundle(s); "
          f"{len(store.ids())} retained under {root}")


def cmd_doctor(args: argparse.Namespace) -> None:
    """Ranked findings from a captured incident bundle or the live
    fleet (jax-free). Exit 0 = clean, 1 = warnings, 2 = firing
    evidence — scriptable straight into the paging runbook."""
    from predictionio_tpu.utils import incidents as incmod

    if args.incident:
        from predictionio_tpu.storage.registry import StorageConfig

        root = args.dir or incmod.default_incident_dir(
            StorageConfig.from_env().home)
        store = incmod.IncidentStore(root)
        iid = args.incident
        if iid == "latest":
            ids = store.ids()
            if not ids:
                _die(f"no incident bundles under {root}")
            iid = ids[0]
        bundle = store.load_bundle(iid)
        if bundle is None:
            _die(f"incident {iid!r} not found (or incomplete) under {root}")
        findings = incmod.diagnose(bundle)
        header = (f"doctor — incident {iid} "
                  f"(process={bundle['manifest'].get('process')})")
    else:
        base = args.url.rstrip("/")
        try:
            slo_doc = _http_json(f"{base}/slo/status", timeout=args.timeout)
            health_doc = _http_json(f"{base}/health", timeout=args.timeout)
            top_doc = _http_json(f"{base}/top?window=5m",
                                 timeout=args.timeout)
        except Exception as e:  # noqa: BLE001 — ops verb, readable failure
            _die(f"live diagnosis against {base} failed: "
                 f"{type(e).__name__}: {e}")
        findings = incmod.diagnose_live(slo_doc, health_doc, top_doc)
        header = f"doctor — live fleet at {base}"
    code = incmod.exit_code(findings)
    results = None
    if args.act:
        # remediation engine: map findings onto conf/remediations.json
        # playbooks. Without --yes this is a pure dry run — the full
        # plan prints, NOTHING executes.
        from predictionio_tpu.server.remediate import (
            OpsActuator,
            RemediationEngine,
            load_playbooks,
        )
        from predictionio_tpu.storage.registry import StorageConfig

        home = StorageConfig.from_env().home
        engine = RemediationEngine(
            OpsActuator(url=None if args.incident else args.url,
                        home=home, timeout=args.timeout),
            load_playbooks(args.remediations),
            lock_path=os.path.join(home, "remediation.lock"))
        results = engine.execute(engine.plan(findings), yes=args.yes)
    if args.json:
        out = {"findings": findings, "exit": code}
        if results is not None:
            out["remediation"] = results
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        print(header)
        if not findings:
            print("  no findings — clean bill of health")
        labels = {2: "FIRING", 1: "warn", 0: "info"}
        for f in findings:
            print(f"  [{labels[f['severity']]:<6}] {f['title']}")
            print(f"           {f['evidence']}")
        if results is not None:
            mode = ("EXECUTED" if args.yes else
                    "DRY RUN — pass --yes to execute")
            print(f"remediation plan ({mode}):")
            if not results:
                print("  nothing to do — no finding matches a playbook")
            for r in results:
                print(f"  [{r['result']:<11}] {r['playbook']}: "
                      f"{r['action']} -> {r['target']}")
                if r.get("detail"):
                    print(f"               {r['detail']}")
    raise SystemExit(code)


def cmd_dashboard(args: argparse.Namespace) -> None:
    from predictionio_tpu.tools.dashboard import Dashboard

    print(f"[info] Dashboard on {args.ip}:{args.port}")
    Dashboard(host=args.ip, port=args.port).run()


def cmd_template(args: argparse.Namespace) -> None:
    from predictionio_tpu.templates import TEMPLATES

    if args.tpl_cmd == "list":
        for name, mod in TEMPLATES.items():
            print(f"{name:<26} {mod}")
        return
    name, dest = args.name, args.dir
    if name not in TEMPLATES:
        _die(f"unknown template {name!r}; see `pio template list`")
    try:
        mod = importlib.import_module(TEMPLATES[name])
    except ImportError as e:
        _die(f"template {name!r} is not available: {e}")
    os.makedirs(dest, exist_ok=True)
    src = os.path.join(os.path.dirname(mod.__file__), "engine.json")
    dst = os.path.join(dest, "engine.json")
    if os.path.exists(src):
        import shutil
        shutil.copyfile(src, dst)
    else:
        with open(dst, "w", encoding="utf-8") as f:
            json.dump({"id": "default", "engineFactory": TEMPLATES[name] + ":engine_factory"},
                      f, indent=2)
    print(f"[info] Created engine dir {dest} from template {name!r}. "
          f"Edit {dst} (set appName) and run `pio train`.")


def cmd_adminserver(args: argparse.Namespace) -> None:
    from predictionio_tpu.tools.admin import AdminServer

    print(f"[info] Admin server on {args.ip}:{args.port}")
    AdminServer(host=args.ip, port=args.port).run()


def cmd_build(args: argparse.Namespace) -> None:
    """Validate an engine dir: engine.json parses, factory imports, params
    bind. The reference's `pio build` compiles Scala; Python needs no
    compile step, so build = static validation (same gate in the verb
    sequence build → train → deploy)."""
    variant = _load_variant_file(args.engine_dir, args.variant)
    factory = variant.get("engineFactory") or _die("engine.json missing engineFactory")
    sys.path.insert(0, os.path.abspath(args.engine_dir))
    from predictionio_tpu.controller.engine import EngineFactory

    try:
        engine = EngineFactory.create(factory)
        engine.params_from_variant(variant)
    except Exception as e:
        _die(f"engine validation failed: {e}")
    print(f"[info] Engine {factory} is valid. Ready for `pio train`.")


def cmd_run(args: argparse.Namespace) -> None:
    """Run an arbitrary `module:callable` inside the framework env
    (reference: `pio run` submits a main class through spark-submit)."""
    from predictionio_tpu.utils.imports import resolve_spec

    sys.path.insert(0, os.path.abspath(args.engine_dir))
    fn = resolve_spec(args.main)
    rv = fn(*args.args)
    if rv is not None:
        print(rv)


def cmd_shell(args: argparse.Namespace) -> None:
    """Interactive REPL with the framework pre-loaded (reference:
    `pio-shell --with-pyspark` opens a REPL with a live SparkSession
    and PIO on the classpath; here the session analogue is the storage
    + pypio bridge, initialized before the prompt)."""
    import code

    import predictionio_tpu
    from predictionio_tpu.data import store

    local = {
        "predictionio_tpu": predictionio_tpu,
        "storage": get_storage(),
        "store": store,
    }
    # pypio preloaded and initialized, like the reference shell's ready
    # SparkSession — find_events()/pd DataFrames work at the prompt
    pypio_line = "pypio unavailable (import failed)"
    try:
        import pypio

        pypio.init()
        local["pypio"] = pypio
        pypio_line = ("pypio (initialized: pypio.find_events('<app>') "
                      "-> DataFrame)")
    except Exception as e:  # noqa: BLE001 — shell must still open
        pypio_line = f"pypio unavailable ({e})"
    banner = (f"predictionio_tpu {__version__} shell\n"
              "preloaded: predictionio_tpu, storage (Storage), store "
              f"(PEventStore/LEventStore API), {pypio_line}\n"
              'try: store.find("MyApp1", limit=3)')
    code.interact(banner=banner, local=local)


# -- parser -------------------------------------------------------------------


def _add_observability_flags(sp: argparse.ArgumentParser) -> None:
    """Tracing/access-log flags shared by ``eventserver`` and ``deploy``."""
    sp.add_argument("--tracing", action="store_true",
                    help="request-scoped tracing: root span per request, "
                         "child spans through ingest/serving/storage, "
                         "ring-buffered for /traces and exported to a "
                         "span JSONL file (see `pio trace`)")
    sp.add_argument("--trace-sample", type=float, default=1.0,
                    help="probability a trace is exported to the JSONL "
                         "file; errors and slow spans always export "
                         "(ring buffer + /traces see every span)")
    sp.add_argument("--trace-file",
                    help="span JSONL path (default: "
                         "<home>/traces/spans.jsonl; '' = ring only)")
    sp.add_argument("--slow-query-ms", type=float, default=0.0,
                    help="log the full span tree of any request slower "
                         "than this, regardless of sampling "
                         "(0 = disabled)")
    sp.add_argument("--access-log", action="store_true",
                    help="one structured JSON line per request (method, "
                         "path, status, duration, trace id) on the "
                         "'pio.access' logger")


def _add_incident_flags(sp: argparse.ArgumentParser) -> None:
    """Incident flight-recorder flags shared by the long-lived server
    verbs (eventserver/deploy/router serve/train --continuous)."""
    sp.add_argument("--incident-dir", default="auto", metavar="PATH",
                    help="incident-bundle store directory (default: "
                         "<storage home>/incidents)")
    sp.add_argument("--no-incidents", action="store_true",
                    help="disable automatic postmortem capture")


def _incident_dir(args: argparse.Namespace) -> Optional[str]:
    return None if args.no_incidents else args.incident_dir


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pio", description="TPU-native PredictionIO")
    p.add_argument("--version", action="version", version=__version__)
    sub = p.add_subparsers(dest="cmd", required=True)

    ap = sub.add_parser("app", aliases=["apps"],
                        help="manage apps, channels, and QoS quotas")
    aps = ap.add_subparsers(dest="app_cmd", required=True)
    x = aps.add_parser("new"); x.add_argument("name")
    x.add_argument("--description"); x.add_argument("--access-key")
    aps.add_parser("list")
    x = aps.add_parser("show"); x.add_argument("name")
    x = aps.add_parser("delete"); x.add_argument("name")
    x = aps.add_parser("data-delete"); x.add_argument("name")
    x.add_argument("--channel")
    x = aps.add_parser("channel-new"); x.add_argument("name"); x.add_argument("channel")
    x = aps.add_parser("channel-delete"); x.add_argument("name"); x.add_argument("channel")
    x = aps.add_parser(
        "quota",
        help="show or set per-app QoS overrides (quotas.json; "
             "hot-reloaded by every server within ~1s)")
    x.add_argument("name", help="app name (overrides key on the app id)")
    x.add_argument("--rate", type=float,
                   help="sustained ingest events/second (0 = unlimited)")
    x.add_argument("--burst", type=float,
                   help="ingest bucket depth (0 = rate for 1s, min 1)")
    x.add_argument("--weight", type=float,
                   help="weighted share of engine-server inflight and of "
                        "the router retry budget at saturation")
    x.add_argument("--writer-shards", type=int,
                   help="ACTIVE-segment writer shards for this app's "
                        "event namespaces (hot-partition relief)")
    x.add_argument("--deadline-ms", type=float,
                   help="router deadline cap for this app's queries "
                        "(0 = router default)")
    x.add_argument("--clear", action="append", metavar="FIELD",
                   choices=["rate", "burst", "weight", "writer-shards",
                            "deadline-ms"],
                   help="drop one override, back to the fleet default "
                        "(repeatable)")
    x.add_argument("--quotas-file",
                   help="explicit quotas.json path (default: "
                        "<storage home>/quotas.json)")
    ap.set_defaults(fn=cmd_app)

    ak = sub.add_parser("accesskey", help="manage access keys")
    aks = ak.add_subparsers(dest="ak_cmd", required=True)
    x = aks.add_parser("new"); x.add_argument("app_name"); x.add_argument("--events")
    x = aks.add_parser("list"); x.add_argument("app_name", nargs="?")
    x = aks.add_parser("delete"); x.add_argument("key")
    ak.set_defaults(fn=cmd_accesskey)

    es = sub.add_parser("eventserver", help="start the event server")
    es.add_argument("--ip", default="0.0.0.0")
    es.add_argument("--port", type=int, default=7070)
    es.add_argument("--stats", action="store_true")
    es.add_argument("--ingest-batching", action="store_true",
                    help="group-commit concurrent single-event POSTs "
                         "into one storage commit per (app, channel); "
                         "201 is still acked only after the commit")
    es.add_argument("--ingest-max-batch", type=int, default=512,
                    help="max events per group commit")
    es.add_argument("--ingest-queue-depth", type=int, default=4096,
                    help="pending-event limit before POSTs get 429 + "
                         "Retry-After backpressure")
    es.add_argument("--durable-acks", action="store_true",
                    help="fsync storage before acking 201 (survives "
                         "power loss, not just process death); group "
                         "commit amortizes the sync per batch")
    es.add_argument("--segment-maintenance", action="store_true",
                    help="background compaction of sealed event-log "
                         "segments into columnar sidecars, plus "
                         "cold-tier shipping when PIO_SEGMENT_COLD "
                         "is configured (EVENTLOG backend only)")
    es.add_argument("--auth-cache-ttl", type=float, default=30.0,
                    help="access-key/channel auth cache TTL seconds "
                         "(0 disables; in-process key mutations "
                         "invalidate immediately regardless)")
    es.add_argument("--tenant-quotas", metavar="PATH", default=None,
                    help="per-app QoS policy file (default: "
                         "<storage home>/quotas.json, managed by "
                         "'pio app quota'; hot-reloaded)")
    es.add_argument("--lease-home", metavar="DIR", default=None,
                    help="shared directory holding the event-plane "
                         "leader lease; setting it turns on the "
                         "replicated event plane (leader election with "
                         "fencing tokens, follower streaming — "
                         "docs/operations.md \"Event-plane HA\")")
    es.add_argument("--advertise-url", metavar="URL", default=None,
                    help="base URL peers and redirected clients reach "
                         "THIS node at (default: http://<ip>:<port>; "
                         "also the lease owner identity)")
    es.add_argument("--replicate-to", action="append", metavar="URL",
                    help="follower base URL to stream the event log to "
                         "when this node leads (repeatable; a node "
                         "never replicates to its own advertise URL)")
    es.add_argument("--lease-ttl", type=float, default=2.0,
                    help="event-plane lease TTL seconds: a leader that "
                         "stops heartbeating is superseded after this "
                         "(promotion latency trades against false "
                         "failover on GC/IO stalls)")
    _add_observability_flags(es)
    _add_incident_flags(es)
    es.set_defaults(fn=cmd_eventserver)

    tr = sub.add_parser("train", help="train an engine")
    tr.add_argument("--engine-dir", default=".")
    tr.add_argument("-e", "--variant", help="path to engine.json")
    tr.add_argument("--batch", help="batch label")
    tr.add_argument("-v", "--verbose", action="count", default=0)
    tr.add_argument("--no-mesh", action="store_true",
                    help="single-device training (skip mesh construction)")
    tr.add_argument("--resume", action="store_true",
                    help="resume an interrupted train from its latest "
                         "mid-train checkpoint")
    tr.add_argument("--no-scan-cache", action="store_true",
                    help="bypass the columnar snapshot cache and rescan "
                         "the full event log")
    tr.add_argument("--scan-workers", type=int,
                    help="parallel segment scans per training read "
                         "(default: PIO_SCAN_WORKERS)")
    tr.add_argument("--read-from", choices=("leader", "follower", "any"),
                    default="leader",
                    help="which event-plane node training reads come "
                         "from: 'follower' trains off a replicated "
                         "home (--replica-home / PIO_REPL_REPLICA_HOME) "
                         "so scans never contend with leader ingest; "
                         "'any' prefers the replica when present and "
                         "falls back to the leader")
    tr.add_argument("--replica-home", metavar="DIR",
                    help="storage home of a replicated follower to "
                         "train from (default: PIO_REPL_REPLICA_HOME)")
    tr.add_argument("--continuous", action="store_true",
                    help="run the supervised continuous-training loop: "
                         "single-writer lease with fencing tokens, "
                         "watermark-triggered delta trains (resumable "
                         "after kill -9), guardrail-gated promotion "
                         "through the model registry, /reload push, and "
                         "a live-metrics bake window with automatic "
                         "rollback (docs/operations.md)")
    tr.add_argument("--app", help="app whose events drive the loop "
                                  "(default: variant datasource appName)")
    tr.add_argument("--channel", help="optional event channel")
    tr.add_argument("--min-delta-events", type=int, default=1,
                    help="train only when at least this many new events "
                         "arrived since the last completed cycle")
    tr.add_argument("--poll-interval", type=float, default=5.0,
                    help="seconds between watermark polls when idle")
    tr.add_argument("--lease-ttl", type=float, default=30.0,
                    help="trainer lease TTL seconds; a trainer that "
                         "stops heartbeating is supersedable after this")
    tr.add_argument("--retain", type=int, default=5,
                    help="registry generations kept beyond the champion")
    tr.add_argument("--guardrail-holdout", type=int, default=200,
                    help="newest-N feedback events scored champion vs "
                         "candidate before promotion")
    tr.add_argument("--guardrail-max-regress", type=float, default=0.10,
                    help="refuse candidates whose holdout RMSE is worse "
                         "than the champion's by more than this fraction")
    tr.add_argument("--guardrail-min-events", type=int, default=10,
                    help="below this many scoreable holdout pairs the "
                         "gate passes trivially")
    tr.add_argument("--gate", choices=("offline", "online", "both", "eval"),
                    default="offline",
                    help="promotion gate mode: 'offline' scores the "
                         "candidate on held-out feedback (default); "
                         "'online' judges the CHALLENGER arm's accrued "
                         "live metrics (pio_variant_online_rmse, fed by "
                         "real traffic on a --variants replica) against "
                         "the champion's; 'both' requires both to pass; "
                         "'eval' consults the latest persisted `pio eval` "
                         "sweep leaderboard and refuses candidates the "
                         "sweep ranked below the current champion")
    tr.add_argument("--eval-leaderboard-max-age", type=float, default=0.0,
                    help="with --gate eval: leaderboards older than this "
                         "many seconds are considered stale and the gate "
                         "passes trivially (0 = never stale)")
    tr.add_argument("--online-challenger", default="challenger",
                    help="variant name whose accrued online RMSE the "
                         "online gate judges")
    tr.add_argument("--online-champion", default="champion",
                    help="variant name serving as the online baseline")
    tr.add_argument("--online-min-pairs", type=int, default=20,
                    help="below this many fleet-wide online rated pairs "
                         "the online gate passes trivially")
    tr.add_argument("--online-max-regress", type=float, default=None,
                    help="online gate regression tolerance (default: "
                         "--guardrail-max-regress)")
    tr.add_argument("--bake-seconds", type=float, default=0.0,
                    help="watch live serving metrics for this long after "
                         "promotion and auto-roll-back on regression "
                         "(0 = no bake window)")
    tr.add_argument("--bake-error-rate", type=float, default=0.01,
                    help="bake: roll back when the 5xx fraction over the "
                         "window exceeds this")
    tr.add_argument("--bake-p95-ratio", type=float, default=2.0,
                    help="bake: roll back when window p95 exceeds the "
                         "pre-swap baseline by this factor")
    tr.add_argument("--reload-url", action="append",
                    help="engine-server base URL to /reload and scrape "
                         "(repeatable)")
    tr.add_argument("--router-url",
                    help="fleet-router base URL; promotion then pushes "
                         "POST /router/reload?rolling=1 instead of "
                         "direct /reload calls")
    tr.add_argument("--fleet-manifest",
                    help="router manifest file; its replica URLs are "
                         "used for direct reload + bake scraping")
    tr.add_argument("--max-cycles", type=int,
                    help="stop after N wake cycles (smoke/testing; "
                         "default: run until SIGTERM)")
    tr.add_argument("--metrics-port", type=int, default=None,
                    help="continuous mode: serve /metrics, "
                         "/metrics/history and /health on this port so "
                         "the router federates the trainer (manifest "
                         "'observe=1' line); 0 = ephemeral, unset = "
                         "no listener")
    _add_incident_flags(tr)
    tr.set_defaults(fn=cmd_train)

    dp = sub.add_parser("deploy", help="serve the latest trained instance")
    dp.add_argument("--engine-dir", default=".")
    dp.add_argument("-e", "--variant")
    dp.add_argument("--ip", default="0.0.0.0")
    dp.add_argument("--port", type=int, default=8000)
    dp.add_argument("--engine-instance-id")
    dp.add_argument("--feedback", action="store_true")
    dp.add_argument("--feedback-url",
                    help="Event Server base URL (e.g. http://host:7070); "
                         "feedback then posts through its authenticated "
                         "HTTP API instead of writing storage directly")
    dp.add_argument("--feedback-accesskey",
                    help="access key for --feedback-url")
    dp.add_argument("--feedback-channel",
                    help="optional channel name for feedback events")
    dp.add_argument("--batching", action="store_true",
                    help="micro-batch concurrent queries into one dispatch")
    dp.add_argument("--batch-max", type=int, default=64)
    dp.add_argument("--batch-wait-ms", type=float, default=0.0,
                    help="opt-in batch-formation wait; 0 = drain-only "
                         "continuous batching (default)")
    dp.add_argument("--aot-buckets", default=None,
                    help="AOT-compile the serving program for a ladder of "
                         "padded batch buckets at deploy time: 'auto' = "
                         "geometric 1,2,4,..,batch-max; or an explicit "
                         "comma list e.g. '1,4,16,64' (its largest bucket "
                         "becomes the effective batch max). /health stays "
                         "not-ready until the ladder is compiled; unset = "
                         "no AOT warmup (shapes compile on first use)")
    dp.add_argument("--aot-topk", type=int, default=16,
                    help="top-k width to warm the AOT ladder at (serving "
                         "k is bucketed up to this program shape)")
    dp.add_argument("--query-timeout-ms", type=float, default=0.0,
                    help="per-request deadline for /queries.json; a query "
                         "still running at the deadline returns 504 "
                         "(0 = no deadline)")
    dp.add_argument("--max-inflight", type=int, default=0,
                    help="concurrent query cap; excess requests are shed "
                         "immediately with 503 + Retry-After "
                         "(0 = unlimited)")
    dp.add_argument("--variants", default=None, metavar="SPEC",
                    help="multi-model serving: keep several registry "
                         "generations resident and split traffic by a "
                         "deterministic sticky hash, e.g. "
                         "'champion:9,challenger:1' (name[@gen]:weight; "
                         "'champion' = registry champion, an unpinned "
                         "other name = newest non-champion generation). "
                         "The first arm is the default and absorbs a "
                         "failed arm's weight. See docs/operations.md "
                         "'Running a challenger'")
    dp.add_argument("--variant-salt", default="pio",
                    help="salt for the sticky split hash; change it to "
                         "reshuffle which entities land on which arm")
    dp.add_argument("--tenant-quotas", metavar="PATH", default=None,
                    help="per-app QoS policy file driving weighted-fair "
                         "admission under --max-inflight (default: "
                         "<storage home>/quotas.json; hot-reloaded)")
    _add_observability_flags(dp)
    _add_incident_flags(dp)
    dp.set_defaults(fn=cmd_deploy)

    rt = sub.add_parser(
        "router",
        help="fleet router: one endpoint over N engine-server replicas")
    rts = rt.add_subparsers(dest="router_cmd", required=True)
    x = rts.add_parser("serve", help="start the router")
    x.add_argument("--replicas",
                   help="comma-separated replica URLs (host:port or "
                        "http://host:port)")
    x.add_argument("--manifest",
                   help="file with one replica URL per line, re-read on "
                        "mtime change (# comments ok)")
    x.add_argument("--ip", default="0.0.0.0")
    x.add_argument("--port", type=int, default=8100)
    x.add_argument("--health-interval", type=float, default=1.0,
                   help="seconds between active /health probe rounds")
    x.add_argument("--retry-budget", type=float, default=0.1,
                   help="retry/hedge tokens earned per live request; "
                        "bounds retries to this fraction of traffic")
    x.add_argument("--no-hedge", action="store_true",
                   help="disable tail-latency hedging of /queries.json")
    x.add_argument("--hedge-min-ms", type=float, default=20.0,
                   help="hedge delay floor (used until enough latency "
                        "samples exist for a p95)")
    x.add_argument("--deadline-ms", type=float, default=10000.0,
                   help="default end-to-end budget per client request "
                        "(an inbound X-PIO-Deadline-Ms only tightens it)")
    x.add_argument("--per-try-timeout-ms", type=float, default=0.0,
                   help="cap any single replica attempt (0 = the "
                        "remaining deadline)")
    x.add_argument("--drain-timeout", type=float, default=30.0,
                   help="rolling reload: max seconds to wait for a "
                        "replica's in-flight requests to finish")
    x.add_argument("--ready-timeout", type=float, default=120.0,
                   help="rolling reload: max seconds for /reload + "
                        "AOT re-warm readiness per replica")
    x.add_argument("--tenant-quotas", metavar="PATH", default=None,
                   help="per-app QoS policy file driving per-tenant "
                        "retry/hedge budgets and deadline caps "
                        "(default: <storage home>/quotas.json; "
                        "hot-reloaded)")
    x.add_argument("--slo-config", metavar="PATH", default=None,
                   help="SLO objectives file for the burn-rate engine "
                        "(default: ./conf/slo.json if present, else the "
                        "built-in prober objectives)")
    x.add_argument("--scrape-interval", type=float, default=10.0,
                   help="seconds between metrics-history scrape ticks "
                        "(local registry + fleet federation + SLO "
                        "evaluation)")
    x.add_argument("--probe-interval", type=float, default=2.0,
                   help="seconds between synthetic canary probes "
                        "(X-PIO-Probe queries feeding the SLO series; "
                        "0 disables the prober)")
    x.add_argument("--pool-spawn", metavar="CMD",
                   help="own the replica fleet: spawn each replica with "
                        "this command ('{port}' substituted), supervise "
                        "it, and rewrite --manifest on membership "
                        "changes (enables the autoscaler and POST "
                        "/pool/* endpoints)")
    x.add_argument("--min-replicas", type=int, default=1,
                   help="pool floor: replicas started at boot and the "
                        "scale-down limit")
    x.add_argument("--max-replicas", type=int, default=4,
                   help="pool ceiling: the autoscaler never scales past "
                        "this")
    x.add_argument("--autoscale-interval", type=float, default=5.0,
                   help="seconds between autoscaler control ticks")
    x.add_argument("--no-autoscale", action="store_true",
                   help="own the pool but hold the fleet size fixed "
                        "(manual scaling via POST /pool/add|remove)")
    x.add_argument("--remediations", metavar="PATH", default=None,
                   help="remediation playbooks for the auto-remediator "
                        "(default: ./conf/remediations.json if present, "
                        "else built-ins)")
    _add_observability_flags(x)
    _add_incident_flags(x)
    x = rts.add_parser("status", help="replica states from a running router")
    x.add_argument("--url", default="http://localhost:8100")
    x.add_argument("--timeout", type=float, default=10.0)
    x = rts.add_parser("reload", help="reload the fleet through the router")
    x.add_argument("--url", default="http://localhost:8100")
    x.add_argument("--rolling", action="store_true",
                   help="drain + reload + re-warm one replica at a time "
                        "(zero-downtime); default reloads all at once")
    x.add_argument("--timeout", type=float, default=600.0)
    rt.set_defaults(fn=cmd_router)

    ud = sub.add_parser("undeploy", help="stop a running engine server")
    ud.add_argument("--ip", default="127.0.0.1")
    ud.add_argument("--port", type=int, default=8000)
    ud.set_defaults(fn=cmd_undeploy)

    ev = sub.add_parser("eval", help="hyperparameter evaluation (grid search)")
    ev.add_argument("evaluation",
                    help="module:attr of the Evaluation, or the literal "
                         "'leaderboard' to inspect a persisted sweep "
                         "leaderboard (no engine code loaded)")
    ev.add_argument("engine_params_generator", nargs="?", default=None,
                    help="module:attr of the generator (after "
                         "'leaderboard': an optional evaluation instance "
                         "id, default latest)")
    ev.add_argument("--engine-dir", default=".")
    ev.add_argument("-v", "--verbose", action="count", default=0)
    ev.add_argument("--output", help="write full results JSON here")
    ev.add_argument("--distributed", action="store_true",
                    help="run the grid as vmapped sweep programs: one "
                         "compile per program geometry bucket instead of "
                         "one train per candidate per fold")
    ev.add_argument("--sweep-shards", type=int, default=0,
                    help="additionally shard_map each vmapped sweep over "
                         "this many devices (0 = single-device vmap)")
    ev.add_argument("--json", action="store_true",
                    help="print the leaderboard document as JSON")
    ev.set_defaults(fn=cmd_eval)

    evs = sub.add_parser(
        "evals", help="inspect past evaluation instances (jax-free)")
    evsub = evs.add_subparsers(dest="evals_cmd", required=True)
    evl = evsub.add_parser("list", help="list evaluation instances")
    evl.add_argument("--json", action="store_true")
    evw = evsub.add_parser(
        "show", help="one instance: status, results/error, leaderboard")
    evw.add_argument("instance_id")
    evw.add_argument("--json", action="store_true")
    evs.set_defaults(fn=cmd_evals)

    bp = sub.add_parser("batchpredict", help="bulk predictions from a JSONL file")
    bp.add_argument("--engine-dir", default=".")
    bp.add_argument("-e", "--variant")
    bp.add_argument("--input", required=True)
    bp.add_argument("--output", required=True)
    bp.add_argument("--engine-instance-id")
    bp.add_argument("--batch-size", type=int, default=1024)
    bp.add_argument("--shards", type=int, default=0,
                    help="serve ANN-indexed engines over an N-way "
                         "item-sharded retrieval mesh (needs >= N "
                         "devices; docs/perf.md \"Sharded retrieval\")")
    bp.set_defaults(fn=cmd_batchpredict)

    ex = sub.add_parser("export", help="export events to JSONL")
    ex.add_argument("--appid", type=int)
    ex.add_argument("--app-name")
    ex.add_argument("--output", required=True)
    ex.set_defaults(fn=cmd_export)

    im = sub.add_parser("import", help="import events from JSONL")
    im.add_argument("--appid", type=int)
    im.add_argument("--app-name")
    im.add_argument("--input", required=True)
    im.set_defaults(fn=cmd_import)

    stp = sub.add_parser("status", help="check storage + device connectivity")
    stp.set_defaults(fn=cmd_status)

    fs = sub.add_parser(
        "fsck",
        help="verify integrity of eventlog segments, snapshot cache, "
             "model blobs, ANN index blobs, and the model registry "
             "(exit 0 clean / 2 corrupt / 3 repaired)")
    fs.add_argument("--home", help="storage home to scan "
                                   "(default: PIO_HOME / ~/.pio_store)")
    fs.add_argument("--repair", action="store_true",
                    help="quarantine torn eventlog tails (copied to a "
                         ".quarantine-<offset> sidecar, then truncated), "
                         "delete corrupt snapshots, delete orphaned "
                         "registry generation dirs, and rewrite registry "
                         "sha256 sidecars from the manifest; corrupt "
                         "model blobs are reported only")
    fs.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON document")
    fs.set_defaults(fn=cmd_fsck)

    md = sub.add_parser(
        "models",
        help="generation-aware model registry: list the promotion "
             "history, promote a generation, or roll back the champion "
             "(continuous-training loop, docs/operations.md)")
    mds = md.add_subparsers(dest="models_cmd", required=True)
    x = mds.add_parser("list", help="generations, statuses, champion, "
                                    "fence token")
    x.add_argument("--json", action="store_true",
                   help="emit the registry state as one JSON document")
    x.add_argument("--replica-url", action="append", metavar="URL",
                   help="also show which generations this serving "
                        "replica holds resident (repeatable; reads the "
                        "replica's /health variants block)")
    x = mds.add_parser("promote",
                       help="move the champion pointer to a generation "
                            "(then /reload the fleet to swap serving)")
    x.add_argument("generation", type=int)
    x = mds.add_parser("rollback",
                       help="demote the champion and restore the most "
                            "recently promoted retired generation")
    md.set_defaults(fn=cmd_models)

    vt = sub.add_parser(
        "variants",
        help="multi-model serving: show resident variant sets or "
             "re-weight the live traffic split across the fleet "
             "(probe-then-apply; jax-free — docs/operations.md "
             "\"Running a challenger\")")
    vts = vt.add_subparsers(dest="variants_cmd", required=True)
    x = vts.add_parser("status",
                       help="resident arms, weights, warmup state and "
                            "online score, per replica")
    x.add_argument("--url", action="append", metavar="URL",
                   help="replica base URL, e.g. http://h:8000 "
                        "(repeatable)")
    x.add_argument("--manifest",
                   help="fleet manifest file (router format, one "
                        "replica per line)")
    x.add_argument("--json", action="store_true")
    x.add_argument("--timeout", type=float, default=10.0)
    x = vts.add_parser(
        "set-weights",
        help="re-split live traffic across already-resident arms; every "
             "replica is probed for every named arm BEFORE any replica "
             "is changed")
    x.add_argument("weights", metavar="SPEC",
                   help='e.g. "champion:8,challenger:2" — same grammar '
                        "as deploy --variants, minus generation pins")
    x.add_argument("--url", action="append", metavar="URL",
                   help="replica base URL (repeatable)")
    x.add_argument("--manifest",
                   help="fleet manifest file (router format)")
    x.add_argument("--timeout", type=float, default=10.0)
    vt.set_defaults(fn=cmd_variants)

    ix = sub.add_parser(
        "index",
        help="ANN retrieval index: geometry (M, K, corpus size, code "
             "bytes, HBM estimate), build time, and digest status of "
             "the deployed model's PQ index — reads the artifact "
             "manifest only, jax-free (docs/perf.md \"Approximate "
             "retrieval\")")
    ixs = ix.add_subparsers(dest="index_cmd", required=True)
    x = ixs.add_parser("status",
                       help="inspect the latest COMPLETED instance's "
                            "ann_index.json manifests")
    x.add_argument("--engine-instance-id",
                   help="inspect this instance instead of the latest "
                        "COMPLETED one")
    x.add_argument("--json", action="store_true",
                   help="emit the full report as one JSON document")
    x.add_argument("--shards", type=int, default=0,
                   help="also print the per-shard layout (rows, code "
                        "bytes, per-device HBM) for an N-way serving "
                        "mesh — pure manifest math, still jax-free")
    ix.set_defaults(fn=cmd_index)

    sg = sub.add_parser(
        "segments",
        help="inspect/operate the partitioned event log (EVENTLOG "
             "backend): status, force rollover, compact, cold-tier ship")
    sg.add_argument("action", nargs="?", default="status",
                    choices=("status", "roll", "compact", "ship"))
    sg.add_argument("--json", action="store_true",
                    help="emit the full segment report as JSON")
    sg.add_argument("--verify", action="store_true",
                    help="ship: re-fetch every uploaded object from the "
                         "cold tier and compare sha256 before trusting "
                         "it; a mismatch deletes the cold copy, keeps "
                         "the local file, and fails the ship")
    sg.set_defaults(fn=cmd_segments)

    fo = sub.add_parser(
        "failover",
        help="event-plane failover: promote a follower by hand "
             "(--target) or run the kill -9 drill (--drill) that "
             "proves zero acked loss, sub-second promotion, "
             "stale-epoch refusal, fsck-clean logs, and one coalesced "
             "incident bundle (jax-free)")
    fo.add_argument("--drill", action="store_true",
                    help="spawn a leader+follower pair, ingest through "
                         "the follower's 307 redirect, kill -9 the "
                         "leader mid-stream, and print the proof "
                         "document as one JSON line (exit 3 if any "
                         "proof fails)")
    fo.add_argument("--target", metavar="URL",
                    help="follower base URL to promote now (POST "
                         "/repl/promote; refused while the current "
                         "leader's lease is live)")
    fo.add_argument("--dir", metavar="PATH",
                    help="drill working directory (default: a fresh "
                         "temp dir; kept afterward for inspection)")
    fo.add_argument("--events", type=int, default=120,
                    help="drill: total events to ingest")
    fo.add_argument("--kill-after", type=int, default=40,
                    help="drill: kill -9 the leader after this many "
                         "acked events")
    fo.add_argument("--lease-ttl", type=float, default=0.35,
                    help="drill: event-plane lease TTL seconds "
                         "(promotion must still land under 1s "
                         "including the expiry wait)")
    fo.add_argument("--timeout", type=float, default=10.0,
                    help="--target: HTTP timeout seconds")
    fo.add_argument("--json", action="store_true",
                    help="pretty-print the proof document instead of "
                         "one line")
    fo.set_defaults(fn=cmd_failover)

    tc = sub.add_parser(
        "trace",
        help="tail/grep exported trace spans (JSONL written by servers "
             "started with --tracing)")
    tc.add_argument("--file", help="span JSONL path "
                                   "(default: <home>/traces/spans.jsonl)")
    tc.add_argument("--trace-id", help="only spans of this trace id")
    tc.add_argument("--min-ms", type=float, default=0.0,
                    help="only spans at least this many ms long")
    tc.add_argument("--errors-only", action="store_true",
                    help="only spans that finished in error")
    tc.add_argument("--grep", help="substring filter over the span JSON")
    tc.add_argument("--tree", action="store_true",
                    help="group by trace and render indented span trees")
    tc.add_argument("--limit", type=int, default=50,
                    help="print at most the newest N spans (or traces "
                         "with --tree)")
    tc.set_defaults(fn=cmd_trace)

    dm = sub.add_parser(
        "daemon",
        help="supervise a server verb: crash restart with backoff, "
             "health checks, pidfile (MasterActor-grade supervision)")
    dm.add_argument("--pidfile")
    dm.add_argument("--health-url")
    dm.add_argument("--health-interval", type=float, default=5.0)
    dm.add_argument("--health-grace", type=float, default=30.0)
    dm.add_argument("--max-restarts", type=int, default=10)
    dm.add_argument("--restart-window", type=float, default=600.0)
    dm.add_argument("--term-grace", type=float, default=10.0,
                    help="seconds between SIGTERM and SIGKILL when "
                         "stopping the child; give the continuous "
                         "trainer enough to finish its cycle and "
                         "release the lease cleanly")
    dm.add_argument("command", nargs=argparse.REMAINDER)
    dm.set_defaults(fn=cmd_daemon)

    db = sub.add_parser("dashboard", help="evaluation results dashboard")
    db.add_argument("--ip", default="0.0.0.0")
    db.add_argument("--port", type=int, default=9000)
    db.set_defaults(fn=cmd_dashboard)

    tp = sub.add_parser("template", help="engine templates")
    tps = tp.add_subparsers(dest="tpl_cmd", required=True)
    tps.add_parser("list")
    x = tps.add_parser("new"); x.add_argument("name"); x.add_argument("dir")
    tp.set_defaults(fn=cmd_template)

    ad = sub.add_parser("adminserver", help="REST admin API")
    ad.add_argument("--ip", default="0.0.0.0")
    ad.add_argument("--port", type=int, default=7071)
    ad.set_defaults(fn=cmd_adminserver)

    bd = sub.add_parser("build", help="validate an engine dir")
    bd.add_argument("--engine-dir", default=".")
    bd.add_argument("-e", "--variant")
    bd.set_defaults(fn=cmd_build)

    rn = sub.add_parser("run", help="run a module:callable in the framework env")
    rn.add_argument("main", help="module:callable")
    rn.add_argument("args", nargs="*")
    rn.add_argument("--engine-dir", default=".")
    rn.set_defaults(fn=cmd_run)

    sh = sub.add_parser("shell", help="interactive framework REPL")
    sh.set_defaults(fn=cmd_shell)

    ln = sub.add_parser(
        "lint",
        help="static invariant analysis: trace-safety (PL01), jax-free "
             "import closure (PL02), lock discipline (PL03), "
             "registry/docs closure (PL04), resilience hygiene (PL05) "
             "— stdlib ast only, jax-free (docs/development.md)")
    ln.add_argument("--json", action="store_true",
                    help="emit the full report as one JSON document")
    ln.add_argument("--rule", action="append", metavar="RULE",
                    help="run only this rule family, e.g. PL03 "
                         "(repeatable; default: all)")
    ln.add_argument("--baseline", metavar="PATH",
                    help="baseline file of reviewed, accepted findings "
                         "(default: conf/lint-baseline.json)")
    ln.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings too (review mode)")
    ln.add_argument("--root", metavar="DIR",
                    help="repo root to analyze (default: the tree this "
                         "package was loaded from)")
    ln.set_defaults(fn=cmd_lint)

    sp = sub.add_parser(
        "slo", help="SLO burn-rate status from a running router")
    sps = sp.add_subparsers(dest="slo_cmd", required=True)
    x = sps.add_parser("status", help="print burn rates per SLO "
                                      "(exit 1 while fast-burning)")
    x.add_argument("--url", default="http://localhost:8100",
                   help="router base URL")
    x.add_argument("--json", action="store_true",
                   help="raw /slo/status JSON instead of the table")
    x.add_argument("--timeout", type=float, default=10.0)
    x.set_defaults(fn=cmd_slo)

    tp = sub.add_parser(
        "top", help="live fleet view from a running router "
                    "(QPS, latency, variants, tenants, SLOs, replicas)")
    tp.add_argument("--url", default="http://localhost:8100",
                    help="router base URL")
    tp.add_argument("--window", default="1m",
                    help="rate/quantile window over federated history "
                         "(e.g. 30s, 1m, 5m)")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds")
    tp.add_argument("--once", action="store_true",
                    help="render one frame and exit (no screen clear)")
    tp.add_argument("--json", action="store_true",
                    help="raw /top JSON once and exit")
    tp.add_argument("--watch", type=float, default=0.0, metavar="N",
                    help="redraw every N seconds (overrides --interval "
                         "and --once; ctrl-C exits)")
    tp.add_argument("--timeout", type=float, default=10.0)
    tp.set_defaults(fn=cmd_top)

    ic = sub.add_parser(
        "incidents",
        help="browse incident flight-recorder bundles (postmortems)")
    ics = ic.add_subparsers(dest="inc_cmd", required=True)
    x = ics.add_parser("list", help="resident bundles, newest first")
    x.add_argument("--dir", metavar="PATH",
                   help="incident store (default: "
                        "<storage home>/incidents)")
    x.add_argument("--json", action="store_true",
                   help="summary rows as JSON")
    x.set_defaults(fn=cmd_incidents)
    x = ics.add_parser("show",
                       help="one bundle's manifest (default: newest)")
    x.add_argument("id", nargs="?",
                   help="bundle id from 'pio incidents list'")
    x.add_argument("--dir", metavar="PATH",
                   help="incident store (default: "
                        "<storage home>/incidents)")
    x.add_argument("--json", action="store_true",
                   help="the full bundle (manifest + parsed files) as "
                        "JSON")
    x.set_defaults(fn=cmd_incidents)
    x = ics.add_parser("prune",
                       help="drop the oldest bundles beyond --retain")
    x.add_argument("--retain", type=int, default=20,
                   help="bundles to keep (newest first)")
    x.add_argument("--dir", metavar="PATH",
                   help="incident store (default: "
                        "<storage home>/incidents)")
    x.set_defaults(fn=cmd_incidents)

    dr = sub.add_parser(
        "doctor",
        help="ranked findings from an incident bundle or the live "
             "fleet (exit 0 clean / 1 warn / 2 firing)")
    dr.add_argument("--incident", metavar="ID",
                    help="diagnose a captured bundle ('latest' = "
                         "newest) instead of the live fleet")
    dr.add_argument("--dir", metavar="PATH",
                    help="incident store for --incident (default: "
                         "<storage home>/incidents)")
    dr.add_argument("--url", default="http://localhost:8100",
                    help="router base URL for live diagnosis")
    dr.add_argument("--json", action="store_true",
                    help="findings + exit code as JSON")
    dr.add_argument("--timeout", type=float, default=10.0)
    dr.add_argument("--act", action="store_true",
                    help="map findings onto conf/remediations.json "
                         "playbooks and print the remediation plan "
                         "(dry run: NOTHING executes without --yes)")
    dr.add_argument("--yes", action="store_true",
                    help="with --act: actually execute the plan "
                         "(rate-limited, target-verified, one "
                         "remediation in flight)")
    dr.add_argument("--remediations", metavar="PATH", default=None,
                    help="playbook file for --act (default: "
                         "./conf/remediations.json if present, else "
                         "built-ins)")
    dr.set_defaults(fn=cmd_doctor)

    vp = sub.add_parser("version")
    vp.set_defaults(fn=lambda a: print(__version__))
    return p


# verbs whose command path (or user engine code under it) imports jax —
# the others must not pay jax import cost at CLI startup
_JAX_VERBS = {"train", "deploy", "eval", "batchpredict", "status", "run",
              "shell", "build"}


def main(argv: Optional[List[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    # Restrict jax to a specific platform before any backend init. The
    # env-var route (JAX_PLATFORMS) is not reliable here: this image's
    # sitecustomize registers the tunneled-TPU plugin at interpreter
    # startup regardless, so the config knob is the only effective one.
    # Used by the integration harness (tests/scenarios) to force CPU.
    platforms = os.environ.get("PIO_JAX_PLATFORMS")
    if platforms and args.cmd in _JAX_VERBS:
        import jax

        jax.config.update("jax_platforms", platforms)
    args.fn(args)


if __name__ == "__main__":
    main()
