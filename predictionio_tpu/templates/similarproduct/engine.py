"""Similar Product template: item-to-item similarity from ALS factors.

Behavioral equivalent of the reference's similar-product template
(reference: [U] examples/scala-parallel-similarproduct/ — "view" events
→ implicit ALS; query = list of liked items → top-K cosine-similar
items, with category/whitelist/blacklist filters; SURVEY.md §2c).

    POST /queries.json {"items": ["i1", "i3"], "num": 4,
                        "categories": ["c1"], "blackList": ["i5"]}
    → {"itemScores": [{"item": "i2", "score": 0.87}, ...]}
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    AverageMetric,
    DataSource,
    Engine,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    FirstServing,
    IdentityPreparator,
    WorkflowContext,
)
from predictionio_tpu.data import store as event_store
from predictionio_tpu.models.als import (
    ALSParams,
    RatingsCOO,
    als_train,
    similar_items,
)
from predictionio_tpu.utils.bimap import BiMap


@dataclass
class DataSourceParams:
    app_name: str = ""
    event_names: List[str] = field(default_factory=lambda: ["view"])


@dataclass
class TrainingData:
    """Columnar, index-mapped view events (streaming read — see
    ``data/pipeline.read_interactions``; O(chunk + vocab) transient
    host memory, event ORDER preserved for the last-view eval split).
    ``views`` materializes (user, item) string pairs lazily for
    small-data consumers."""

    user_idx: np.ndarray   # int32 [n], event order
    item_idx: np.ndarray   # int32 [n]
    user_ids: BiMap
    item_ids: BiMap
    item_categories: Dict[str, List[str]]  # from $set item properties

    @property
    def n(self) -> int:
        return int(self.user_idx.shape[0])

    @property
    def views(self) -> List[tuple]:
        u_inv = self.user_ids.inverse()
        i_inv = self.item_ids.inverse()
        return [(u_inv[int(u)], i_inv[int(i)])
                for u, i in zip(self.user_idx, self.item_idx)]

    def subset(self, mask: np.ndarray) -> "TrainingData":
        """Rows where ``mask`` holds, vocabularies trimmed (eval-fold
        cold-entity rule — see ``data/pipeline.subset_columnar``)."""
        from predictionio_tpu.data.pipeline import subset_columnar

        uu, ii, u_ids, i_ids = subset_columnar(
            mask, self.user_idx, self.item_idx,
            self.user_ids, self.item_ids)
        return TrainingData(uu, ii, u_ids, i_ids, self.item_categories)


class SimilarProductDataSource(DataSource):
    ParamsClass = DataSourceParams

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        from predictionio_tpu.data.store import read_training_interactions

        p: DataSourceParams = self.params
        data = read_training_interactions(
            p.app_name, entity_type="user", target_entity_type="item",
            event_names=p.event_names, storage=ctx.storage)
        uu, ii, _ones = data.arrays()
        if uu.size == 0:
            raise ValueError("no view events found; import events before training")
        cats = {
            entity_id: list(props.get("categories") or [])
            for entity_id, props in event_store.aggregate_properties(
                p.app_name, "item", storage=ctx.storage).items()
        }
        return TrainingData(uu, ii, data.user_ids, data.item_ids, cats)

    def read_eval(self, ctx: WorkflowContext):
        """Item-to-item retrieval protocol: each user's LAST viewed
        item is held out; the query carries the user's remaining items
        and the held-out one must rank in the top-k similars."""
        td = self.read_training(ctx)
        n_u = len(td.user_ids)
        counts = np.bincount(td.user_idx, minlength=n_u)
        last_row = np.full(n_u, -1, np.int64)
        last_row[td.user_idx] = np.arange(td.n)  # later rows overwrite
        held = np.sort(last_row[(last_row >= 0) & (counts >= 3)])
        if held.size == 0:
            raise ValueError("no user has >= 3 views to hold one out")
        keep_mask = np.ones(td.n, bool)
        keep_mask[held] = False
        u_inv = td.user_ids.inverse()
        i_inv = td.item_ids.inverse()
        held_users = set(td.user_idx[held].tolist())
        by_user: Dict[int, List[str]] = {}
        for u, i in zip(td.user_idx[keep_mask].tolist(),
                        td.item_idx[keep_mask].tolist()):
            if u in held_users:
                by_user.setdefault(u, []).append(i_inv[i])
        qa = [({"items": by_user[int(td.user_idx[j])], "num": 10},
               i_inv[int(td.item_idx[j])]) for j in held]
        return [(td.subset(keep_mask), {"fold": 0}, qa)]


@dataclass
class ALSAlgorithmParams:
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None
    # -- approximate item-to-item retrieval (predictionio_tpu/ann):
    # builds the PQ index over the NORMALIZED item factors at train
    # time, so the ADC scan + exact re-rank computes cosine directly.
    # engine.json spelling: ann, annM, annK, annShortlist, annShards.
    ann: bool = False
    ann_m: int = 5            # subspaces (must divide rank)
    ann_k: int = 256          # centroids per subspace
    ann_shortlist: int = 128  # k′ re-rank candidates
    ann_shards: int = 0       # serving-mesh width hint (> 1 = sharded)


class SimilarProductModel:
    def __init__(self, V: np.ndarray, item_ids: BiMap,
                 item_categories: Dict[str, List[str]],
                 ann_index=None, ann_shortlist: int = 128,
                 ann_shards: int = 0) -> None:
        self.V = V
        self.item_ids = item_ids
        self._inv = item_ids.inverse()
        self.item_categories = item_categories
        self.ann_index = ann_index
        self.ann_shortlist = ann_shortlist
        self.ann_shards = ann_shards
        self._Vn: Optional[np.ndarray] = None
        self._scorer = None

    def _normalized(self) -> np.ndarray:
        if self._Vn is None:
            norms = np.linalg.norm(self.V, axis=1, keepdims=True)
            self._Vn = (self.V / np.maximum(norms, 1e-12)).astype(
                np.float32)
        return self._Vn

    def _device_scorer(self):
        """Lazy ANN scorer over the normalized corpus with itself as
        the query table: ``U[i] · V[j] = cos(v_i, v_j)``, so a
        single-liked-item query is ONE ADC-shortlist dispatch — the
        same serving program (sharded or not) as the user-to-item
        templates. Multi-item queries keep the host mean-direction
        path (`models/als.similar_items`)."""
        if self.ann_index is None:
            return None
        from predictionio_tpu.ann import maybe_ann_scorer

        Vn = self._normalized()
        s = maybe_ann_scorer(Vn, Vn, self.ann_index, self._scorer,
                             shortlist=self.ann_shortlist,
                             shards=self.ann_shards)
        if s is not None:
            self._scorer = s
        return s

    def query(self, items: List[str], num: int,
              categories: Optional[List[str]] = None,
              white_list: Optional[List[str]] = None,
              black_list: Optional[List[str]] = None) -> List[Dict[str, Any]]:
        idxs = np.asarray([self.item_ids[i] for i in items
                           if i in self.item_ids], np.int32)
        if idxs.size == 0:
            return []
        # over-fetch so post-filters still fill `num`
        fetch = min(len(self.item_ids), num + idxs.size + 50)
        scorer = self._device_scorer() if idxs.size == 1 else None
        if scorer is not None:
            top, scores = scorer.recommend(int(idxs[0]), fetch,
                                           exclude=idxs)
        else:
            top, scores = similar_items(self.V, idxs, fetch)
        cats = set(categories or [])
        white = set(white_list or [])
        black = set(black_list or [])
        out = []
        for i, s in zip(top, scores):
            item = self._inv[int(i)]
            if white and item not in white:
                continue
            if item in black:
                continue
            if cats and not cats.intersection(self.item_categories.get(item, [])):
                continue
            out.append({"item": item, "score": float(s)})
            if len(out) >= num:
                break
        return out


class ALSAlgorithm(Algorithm):
    ParamsClass = ALSAlgorithmParams

    def sanity_check(self, data: TrainingData) -> None:
        if data.n == 0:
            raise ValueError("empty view data")

    @staticmethod
    def _to_coo(pd: TrainingData) -> RatingsCOO:
        # repeat-view counts by linearized (user, item) pair — the
        # vectorized Counter (no per-event Python objects)
        n_items = len(pd.item_ids)
        lin = pd.user_idx.astype(np.int64) * n_items + pd.item_idx
        uniq, cnt = np.unique(lin, return_counts=True)
        return RatingsCOO((uniq // n_items).astype(np.int32),
                          (uniq % n_items).astype(np.int32),
                          cnt.astype(np.float32),
                          len(pd.user_ids), n_items)

    @staticmethod
    def _als_params(p: ALSAlgorithmParams) -> ALSParams:
        return ALSParams(rank=p.rank, iterations=p.num_iterations,
                         reg=p.lambda_, implicit=True, alpha=p.alpha,
                         seed=0 if p.seed is None else p.seed)

    @staticmethod
    def _maybe_index(V: np.ndarray, p: ALSAlgorithmParams):
        """PQ index over the NORMALIZED factors (cosine = inner
        product there); None when ANN is off or the rank doesn't split
        into ``ann_m`` subspaces."""
        if not p.ann:
            return None
        from predictionio_tpu import ann

        norms = np.linalg.norm(V, axis=1, keepdims=True)
        Vn = (V / np.maximum(norms, 1e-12)).astype(np.float32)
        return ann.build_index(
            Vn, p.ann_m, min(p.ann_k, max(2, V.shape[0])),
            shards=(int(p.ann_shards) if p.ann_shards
                    and int(p.ann_shards) > 1 else None))

    @classmethod
    def train_many(cls, ctx: WorkflowContext, pd: TrainingData,
                   params_list) -> List[SimilarProductModel]:
        """Grid fan-out: one COO + prepared layout for every candidate;
        lambda/alpha-only candidates share a compiled executable
        (models/als.als_train_many)."""
        from predictionio_tpu.models.als import als_train_many

        coo = cls._to_coo(pd)
        results = als_train_many(
            coo, [cls._als_params(p) for p in params_list], mesh=ctx.mesh)
        return [SimilarProductModel(V, pd.item_ids, pd.item_categories,
                                    ann_index=cls._maybe_index(V, p),
                                    ann_shortlist=p.ann_shortlist,
                                    ann_shards=p.ann_shards)
                for p, (_, V) in zip(params_list, results)]

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> SimilarProductModel:
        p: ALSAlgorithmParams = self.params
        _, V = als_train(self._to_coo(pd), self._als_params(p),
                         mesh=ctx.mesh)
        return SimilarProductModel(V, pd.item_ids, pd.item_categories,
                                   ann_index=self._maybe_index(V, p),
                                   ann_shortlist=p.ann_shortlist,
                                   ann_shards=p.ann_shards)

    def predict(self, model: SimilarProductModel, query: Dict[str, Any]) -> Dict[str, Any]:
        return {"itemScores": model.query(
            [str(i) for i in query.get("items", [])],
            int(query.get("num", 10)),
            query.get("categories"),
            query.get("whiteList"),
            query.get("blackList"),
        )}

    def save_model(self, model: SimilarProductModel, instance_dir: Optional[str]) -> bytes:
        buf = io.BytesIO()
        np.savez_compressed(buf, V=model.V)
        d = {
            "npz": buf.getvalue(),
            "item_ids": model.item_ids.to_dict(),
            "cats": model.item_categories,
            "ann_shortlist": model.ann_shortlist,
            "ann_shards": model.ann_shards,
        }
        # same persistence contract as the twotower template: wire
        # bytes inside the blob, plus the fsck-auditable sidecar
        # layout when the model store has a real directory
        if model.ann_index is not None:
            from predictionio_tpu import ann

            d["ann_index"] = model.ann_index.to_bytes()
            if instance_dir:
                ann.save_index(model.ann_index, instance_dir)
        return pickle.dumps(d)

    def load_model(self, blob: Optional[bytes], instance_dir: Optional[str]) -> SimilarProductModel:
        assert blob is not None
        d = pickle.loads(blob)
        arrs = np.load(io.BytesIO(d["npz"]))
        ann_index = None
        if instance_dir:
            from predictionio_tpu import ann

            ann_index = ann.load_index(instance_dir)
        if ann_index is None and d.get("ann_index") is not None:
            from predictionio_tpu.ann import PQIndex

            ann_index = PQIndex.from_bytes(d["ann_index"])
        return SimilarProductModel(arrs["V"], BiMap(d["item_ids"]),
                                   d["cats"], ann_index=ann_index,
                                   ann_shortlist=d.get("ann_shortlist", 128),
                                   ann_shards=d.get("ann_shards", 0))


def engine_factory() -> Engine:
    return Engine(
        data_source_cls=SimilarProductDataSource,
        preparator_cls=IdentityPreparator,
        algorithm_cls_map={"als": ALSAlgorithm},
        serving_cls=FirstServing,
    )


# -- evaluation (pio eval out of the box) -------------------------------------


class HitRateAtK(AverageMetric):
    def __init__(self, k: int = 10) -> None:
        self.k = k

    def calculate_one(self, query, predicted, actual) -> float:
        items = [s["item"] for s in predicted.get("itemScores", [])][: self.k]
        return 1.0 if actual in items else 0.0

    @property
    def header(self) -> str:
        return f"HitRate@{self.k}"


class SPEvaluation(Evaluation):
    engine_factory = staticmethod(engine_factory)
    metric = HitRateAtK(10)


class DefaultGrid(EngineParamsGenerator):
    """Rank candidates; app via $PIO_EVAL_APP_NAME."""

    @property
    def engine_params_list(self):
        import os

        app = os.environ.get("PIO_EVAL_APP_NAME", "MyApp1")
        return [EngineParams(
            data_source_params=DataSourceParams(app_name=app),
            algorithms_params=[("als", ALSAlgorithmParams(rank=r))])
            for r in (8, 16)]
