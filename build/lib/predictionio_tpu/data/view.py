"""Batch views over the event stream (legacy helper layer).

Reference: [U] data/.../view/{LBatchView,PBatchView}.scala (unverified,
SURVEY.md §2a — largely deprecated by 0.14 but part of the public
surface). A view materializes one pass over an app's events and offers
the common folds: full property aggregation per entity type and
event grouping by entity/name. The L/P split collapses here — the same
view serves both; heavy per-event math belongs in jitted code over the
arrays a DataSource builds, not in this host-side helper.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict, List, Optional, Tuple

from predictionio_tpu.data.event import Event, PropertyMap, aggregate_properties
from predictionio_tpu.data.store import resolve_app_channel
from predictionio_tpu.storage.registry import Storage, get_storage


class BatchView:
    """One materialized scan of an (app, channel) namespace."""

    def __init__(
        self,
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        storage: Optional[Storage] = None,
    ) -> None:
        st = storage or get_storage()
        app_id, channel_id = resolve_app_channel(app_name, channel_name, st)
        self.events: List[Event] = list(st.events.find(
            app_id, channel_id, start_time=start_time, until_time=until_time))

    def aggregate_properties(self, entity_type: str) -> Dict[str, PropertyMap]:
        """Folded ``$set/$unset/$delete`` snapshot per entity of the type
        (reference: LBatchView.aggregateProperties)."""
        return aggregate_properties(
            e for e in self.events if e.entity_type == entity_type)

    def group_by_entity(
        self, entity_type: Optional[str] = None,
        event_names: Optional[List[str]] = None,
    ) -> Dict[str, List[Event]]:
        """Events per entity id, insertion order preserved
        (reference: events-by-entity grouping in LBatchView)."""
        out: Dict[str, List[Event]] = {}
        for e in self.events:
            if entity_type is not None and e.entity_type != entity_type:
                continue
            if event_names is not None and e.event not in event_names:
                continue
            out.setdefault(e.entity_id, []).append(e)
        return out

    def count_by_event(self) -> Dict[str, int]:
        """Event-name histogram (the /stats.json shape)."""
        out: Dict[str, int] = {}
        for e in self.events:
            out[e.event] = out.get(e.event, 0) + 1
        return out

    def pairs(
        self, event_names: Optional[List[str]] = None,
    ) -> List[Tuple[str, str]]:
        """(entityId, targetEntityId) interaction pairs — the shape every
        recommender DataSource wants."""
        return [
            (e.entity_id, e.target_entity_id)
            for e in self.events
            if e.target_entity_id is not None
            and (event_names is None or e.event in event_names)
        ]
