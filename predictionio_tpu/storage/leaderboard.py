"""Versioned sweep leaderboards — the artifact `pio eval` persists next
to the EvaluationInstance row and everything downstream consumes: the
trainer's ``--gate eval`` promotion guardrail, the jax-free
``pio evals`` / ``pio eval leaderboard`` inspection verbs, and
profile_eval.py's proof digest.

Deliberately stdlib-only (json/math/hashlib): the inspection verbs run
on ops boxes with no jax installed (PL02), so this module must never
import jax — or anything that does.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Sequence

LEADERBOARD_VERSION = 1


def leaderboard_dir(home: str) -> str:
    return os.path.join(home, "leaderboards")


def leaderboard_path(home: str, instance_id: str) -> str:
    return os.path.join(leaderboard_dir(home), f"{instance_id}.json")


def _key(score: float, higher_is_better: bool) -> float:
    # mirrors controller.evaluation.ranking_key without importing it
    # (that module's closure is not jax-free); NaN ranks last
    if score is None or math.isnan(score):
        return -math.inf
    return score if higher_is_better else -score


def rank_candidates(scores: Sequence[float],
                    higher_is_better: bool) -> List[int]:
    """rank (0 = best) per candidate index. Stable: equal scores keep
    candidate order, matching MetricEvaluator's first-argmax ``max``."""
    order = sorted(range(len(scores)),
                   key=lambda i: (-_key(scores[i], higher_is_better), i))
    ranks = [0] * len(scores)
    for r, i in enumerate(order):
        ranks[i] = r
    return ranks


def build(instance_id: str, metric_header: str, higher_is_better: bool,
          engine_params_json: Sequence[Dict[str, Any]],
          scores: Sequence[float],
          fold_scores: Optional[Sequence[Sequence[float]]] = None,
          mode: str = "serial", stats: Optional[Dict[str, Any]] = None,
          ) -> Dict[str, Any]:
    """Assemble the versioned leaderboard document. ``entries`` are
    ordered by rank (best first); per-candidate ``index`` preserves the
    generator's candidate order for parity checks against the serial
    result."""
    ranks = rank_candidates(scores, higher_is_better)
    entries = [{
        "rank": ranks[i],
        "index": i,
        "score": None if math.isnan(scores[i]) else float(scores[i]),
        "foldScores": [None if math.isnan(s) else float(s)
                       for s in (fold_scores[i] if fold_scores else [])],
        "engineParams": engine_params_json[i],
    } for i in range(len(scores))]
    entries.sort(key=lambda e: e["rank"])
    doc = {
        "version": LEADERBOARD_VERSION,
        "instanceId": instance_id,
        "metric": metric_header,
        "higherIsBetter": bool(higher_is_better),
        "mode": mode,
        "gridSize": len(scores),
        "createdAt": time.time(),
        "entries": entries,
    }
    doc.update(stats or {})
    return doc


def write(home: str, doc: Dict[str, Any]) -> str:
    """Atomic write (tmp + rename) so a concurrent gate read never sees
    a torn leaderboard."""
    d = leaderboard_dir(home)
    os.makedirs(d, exist_ok=True)
    path = leaderboard_path(home, doc["instanceId"])
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2, default=str)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def read(home: str, instance_id: str) -> Optional[Dict[str, Any]]:
    path = leaderboard_path(home, instance_id)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def latest(home: str) -> Optional[Dict[str, Any]]:
    """Newest leaderboard by createdAt (mtime tiebreak) under ``home``."""
    d = leaderboard_dir(home)
    if not os.path.isdir(d):
        return None
    best: Optional[Dict[str, Any]] = None
    for name in os.listdir(d):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if best is None or doc.get("createdAt", 0) > best.get("createdAt", 0):
            best = doc
    return best


def digest(doc: Dict[str, Any]) -> str:
    """Stable content digest over (rank, engineParams) — the proof line
    identity: serial and distributed runs that rank the same grid the
    same way share a digest regardless of timing fields."""
    payload = [(e["rank"], e["engineParams"]) for e in doc["entries"]]
    blob = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _norm_algo_params(algorithms_params: Any) -> str:
    return json.dumps(algorithms_params, sort_keys=True, default=str)


def candidate_rank_for(doc: Dict[str, Any],
                       algorithms_params: Any) -> Optional[int]:
    """Rank of the entry whose ``algorithmsParams`` match (normalized
    JSON equality), or None when the grid never swept those params —
    the gate treats that as unscoreable and passes trivially."""
    want = _norm_algo_params(algorithms_params)
    for e in doc.get("entries", []):
        got = _norm_algo_params(e.get("engineParams", {})
                                .get("algorithmsParams"))
        if got == want:
            return int(e["rank"])
    return None
