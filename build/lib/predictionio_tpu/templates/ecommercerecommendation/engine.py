"""E-Commerce Recommendation template: implicit ALS + live business rules.

Behavioral equivalent of the reference's e-commerce template (reference:
[U] examples/scala-parallel-ecommercerecommendation/ — implicit ALS on
view/buy events; at query time: exclude items the user has seen (read
LIVE from the event store), exclude globally unavailable items (a
``constraint`` entity's ``$set`` events, read live so ops can flip
availability without retraining), category filter, white/black lists,
and a popularity fallback for unknown/cold-start users; SURVEY.md §2c).

    POST /queries.json {"user": "u1", "num": 4, "categories": ["c1"],
                        "whiteList": [], "blackList": ["i3"]}
    → {"itemScores": [{"item": "i2", "score": 1.2}, ...]}

The live lookups run host-side around the resident-factor scoring —
serving-time business rules stay out of the compiled path.
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    AverageMetric,
    DataSource,
    Engine,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    FirstServing,
    IdentityPreparator,
    WorkflowContext,
)
from predictionio_tpu.data import store as event_store
from predictionio_tpu.models.als import ALSParams, RatingsCOO, als_train, recommend
from predictionio_tpu.utils.bimap import BiMap


@dataclass
class DataSourceParams:
    app_name: str = ""
    event_names: List[str] = field(default_factory=lambda: ["view", "buy"])


@dataclass
class TrainingData:
    """Columnar, index-mapped (user, item, weight) interactions
    (streaming read — ``data/pipeline.read_interactions``; O(chunk +
    vocab) transient host memory, event order preserved for the
    leave-one-out eval split). ``interactions`` materializes string
    tuples lazily for small-data consumers."""

    app_name: str
    user_idx: np.ndarray   # int32 [n], event order
    item_idx: np.ndarray   # int32 [n]
    weight: np.ndarray     # float32 [n] (buys count harder)
    user_ids: BiMap
    item_ids: BiMap
    item_categories: Dict[str, List[str]]

    @property
    def n(self) -> int:
        return int(self.user_idx.shape[0])

    @property
    def interactions(self) -> List[tuple]:
        u_inv = self.user_ids.inverse()
        i_inv = self.item_ids.inverse()
        return [(u_inv[int(u)], i_inv[int(i)], float(w))
                for u, i, w in zip(self.user_idx, self.item_idx,
                                   self.weight)]

    def subset(self, mask: np.ndarray) -> "TrainingData":
        """Rows where ``mask`` holds, vocabularies trimmed (eval-fold
        cold-entity rule — see ``data/pipeline.subset_columnar``)."""
        from predictionio_tpu.data.pipeline import subset_columnar

        uu, ii, u_ids, i_ids, ww = subset_columnar(
            mask, self.user_idx, self.item_idx,
            self.user_ids, self.item_ids, self.weight)
        return TrainingData(self.app_name, uu, ii, ww, u_ids, i_ids,
                            self.item_categories)


class ECommDataSource(DataSource):
    ParamsClass = DataSourceParams

    def read_eval(self, ctx: WorkflowContext):
        """Leave-one-out over interactions: each user's LAST pair is
        held out and must be retrieved by the plain user query. Eval
        candidates must set ``unseenOnly: false`` — live seen-item
        exclusion reads the event store, which still contains the
        held-out event."""
        td = self.read_training(ctx)
        n_u = len(td.user_ids)
        counts = np.bincount(td.user_idx, minlength=n_u)
        last_row = np.full(n_u, -1, np.int64)
        last_row[td.user_idx] = np.arange(td.n)  # later rows overwrite
        held = np.sort(last_row[(last_row >= 0) & (counts >= 2)])
        if held.size == 0:
            raise ValueError("no user has >= 2 interactions to hold out")
        keep_mask = np.ones(td.n, bool)
        keep_mask[held] = False
        u_inv = td.user_ids.inverse()
        i_inv = td.item_ids.inverse()
        qa = [({"user": u_inv[int(td.user_idx[j])], "num": 10},
               i_inv[int(td.item_idx[j])]) for j in held]
        return [(td.subset(keep_mask), {"fold": 0}, qa)]

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        from predictionio_tpu.data.store import read_training_interactions

        p: DataSourceParams = self.params
        data = read_training_interactions(
            p.app_name, entity_type="user", target_entity_type="item",
            event_names=p.event_names,
            value_spec={"buy": 4.0}, default_spec=1.0,
            storage=ctx.storage)
        uu, ii, ww = data.arrays()
        if uu.size == 0:
            raise ValueError("no view/buy events found")
        cats = {
            entity_id: list(props.get("categories") or [])
            for entity_id, props in event_store.aggregate_properties(
                p.app_name, "item", storage=ctx.storage).items()
        }
        return TrainingData(p.app_name, uu, ii, ww,
                            data.user_ids, data.item_ids, cats)


@dataclass
class ECommAlgorithmParams:
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None
    # live-rule knobs (reference: unseenOnly, seenEvents, similarEvents)
    unseen_only: bool = True
    seen_events: List[str] = field(default_factory=lambda: ["view", "buy"])


class ECommModel:
    def __init__(self, U: np.ndarray, V: np.ndarray, user_ids: BiMap,
                 item_ids: BiMap, item_categories: Dict[str, List[str]],
                 popularity: np.ndarray, app_name: str,
                 params: "ECommAlgorithmParams") -> None:
        self.U = U
        self.V = V
        self.user_ids = user_ids
        self.item_ids = item_ids
        self._inv = item_ids.inverse()
        self.item_categories = item_categories
        self.popularity = popularity  # per item index, for cold start
        self.app_name = app_name
        self.params = params
        self._scorer = None

    def _device_scorer(self):
        """Lazy device-resident scorer for production-size catalogs
        (shared policy: ``models/als.maybe_resident_scorer``)."""
        from predictionio_tpu.models.als import maybe_resident_scorer

        self._scorer = maybe_resident_scorer(self.U, self.V, self._scorer)
        return self._scorer

    # -- live lookups (host-side, storage at serving time) --------------------

    def _seen_items(self, user: str, storage) -> Set[str]:
        if not self.params.unseen_only:
            return set()
        evs = event_store.find_by_entity(
            self.app_name, "user", user,
            event_names=self.params.seen_events,
            target_entity_type="item", limit=None, storage=storage)
        return {e.target_entity_id for e in evs if e.target_entity_id}

    def _unavailable_items(self, storage) -> Set[str]:
        """Latest $set on the 'constraint' entity 'unavailableItems'
        (reference behavior: ops toggle availability live)."""
        snap = event_store.aggregate_properties(self.app_name, "constraint",
                                                storage=storage)
        pm = snap.get("unavailableItems")
        if pm is None:
            return set()
        return set(pm.get("items") or [])

    def query(self, user: str, num: int,
              categories: Optional[List[str]] = None,
              white_list: Optional[List[str]] = None,
              black_list: Optional[List[str]] = None,
              storage=None) -> List[Dict[str, Any]]:
        banned = self._unavailable_items(storage) | set(black_list or [])
        banned |= self._seen_items(user, storage)
        cats = set(categories or [])
        white = set(white_list or [])

        uidx = self.user_ids.get(user)
        if uidx is not None:
            fetch = min(len(self.item_ids), num + len(banned) + 50)
            scorer = self._device_scorer()
            if scorer is not None:
                top, scores = scorer.recommend(uidx, fetch)
            else:
                top, scores = recommend(self.U, self.V, uidx, fetch)
            ranked = [(self._inv[int(i)], float(s)) for i, s in zip(top, scores)]
        else:
            # cold start: popularity fallback (reference behavior)
            order = np.argsort(-self.popularity)
            ranked = [(self._inv[int(i)], float(self.popularity[i]))
                      for i in order]

        out = []
        for item, score in ranked:
            if item in banned:
                continue
            if white and item not in white:
                continue
            if cats and not cats.intersection(self.item_categories.get(item, [])):
                continue
            out.append({"item": item, "score": score})
            if len(out) >= num:
                break
        return out


class ECommAlgorithm(Algorithm):
    ParamsClass = ECommAlgorithmParams

    def sanity_check(self, data: TrainingData) -> None:
        if data.n == 0:
            raise ValueError("empty interactions")

    @staticmethod
    def _to_coo(pd: TrainingData) -> RatingsCOO:
        # weight aggregation by linearized (user, item) pair — the
        # vectorized Counter (no per-event Python objects)
        n_items = len(pd.item_ids)
        lin = pd.user_idx.astype(np.int64) * n_items + pd.item_idx
        uniq, inv = np.unique(lin, return_inverse=True)
        vv = np.bincount(inv, weights=pd.weight).astype(np.float32)
        return RatingsCOO((uniq // n_items).astype(np.int32),
                          (uniq % n_items).astype(np.int32), vv,
                          len(pd.user_ids), n_items)

    @staticmethod
    def _als_params(p: ECommAlgorithmParams) -> ALSParams:
        return ALSParams(rank=p.rank, iterations=p.num_iterations,
                         reg=p.lambda_, implicit=True, alpha=p.alpha,
                         seed=0 if p.seed is None else p.seed)

    def _model(self, pd: TrainingData, coo: RatingsCOO, U, V,
               p: ECommAlgorithmParams) -> ECommModel:
        popularity = np.bincount(coo.item_idx, weights=coo.rating,
                                 minlength=len(pd.item_ids))
        return ECommModel(U, V, pd.user_ids, pd.item_ids,
                          pd.item_categories,
                          popularity.astype(np.float32), pd.app_name, p)

    @classmethod
    def train_many(cls, ctx: WorkflowContext, pd: TrainingData,
                   params_list) -> List[ECommModel]:
        """Grid fan-out: one COO + prepared layout for every candidate;
        lambda/alpha-only candidates share a compiled executable
        (models/als.als_train_many)."""
        from predictionio_tpu.models.als import als_train_many

        coo = cls._to_coo(pd)
        results = als_train_many(
            coo, [cls._als_params(p) for p in params_list], mesh=ctx.mesh)
        return [cls(p)._model(pd, coo, U, V, p)
                for p, (U, V) in zip(params_list, results)]

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> ECommModel:
        p: ECommAlgorithmParams = self.params
        coo = self._to_coo(pd)
        U, V = als_train(coo, self._als_params(p), mesh=ctx.mesh)
        return self._model(pd, coo, U, V, p)

    def predict(self, model: ECommModel, query: Dict[str, Any]) -> Dict[str, Any]:
        return {"itemScores": model.query(
            str(query["user"]),
            int(query.get("num", 10)),
            query.get("categories"),
            query.get("whiteList"),
            query.get("blackList"),
            storage=self.serving_storage,  # live rules read the deploy Storage
        )}

    def save_model(self, model: ECommModel, instance_dir: Optional[str]) -> bytes:
        buf = io.BytesIO()
        np.savez_compressed(buf, U=model.U, V=model.V, pop=model.popularity)
        return pickle.dumps({
            "npz": buf.getvalue(),
            "user_ids": model.user_ids.to_dict(),
            "item_ids": model.item_ids.to_dict(),
            "cats": model.item_categories,
            "app_name": model.app_name,
            "params": self.params,
        })

    def load_model(self, blob: Optional[bytes], instance_dir: Optional[str]) -> ECommModel:
        assert blob is not None
        d = pickle.loads(blob)
        arrs = np.load(io.BytesIO(d["npz"]))
        return ECommModel(arrs["U"], arrs["V"], BiMap(d["user_ids"]),
                          BiMap(d["item_ids"]), d["cats"], arrs["pop"],
                          d["app_name"], d["params"])


def engine_factory() -> Engine:
    return Engine(
        data_source_cls=ECommDataSource,
        preparator_cls=IdentityPreparator,
        algorithm_cls_map={"ecomm": ECommAlgorithm},
        serving_cls=FirstServing,
    )


# -- evaluation (pio eval out of the box) -------------------------------------


class HitRateAtK(AverageMetric):
    def __init__(self, k: int = 10) -> None:
        self.k = k

    def calculate_one(self, query, predicted, actual) -> float:
        items = [s["item"] for s in predicted.get("itemScores", [])][: self.k]
        return 1.0 if actual in items else 0.0

    @property
    def header(self) -> str:
        return f"HitRate@{self.k}"


class ECommEvaluation(Evaluation):
    engine_factory = staticmethod(engine_factory)
    metric = HitRateAtK(10)


class DefaultGrid(EngineParamsGenerator):
    """rank/alpha candidates; unseenOnly stays FALSE for eval (see
    read_eval); app via $PIO_EVAL_APP_NAME."""

    @property
    def engine_params_list(self):
        import os

        app = os.environ.get("PIO_EVAL_APP_NAME", "MyApp1")
        return [EngineParams(
            data_source_params=DataSourceParams(app_name=app),
            algorithms_params=[("ecomm", ECommAlgorithmParams(
                rank=r, num_iterations=10, alpha=a, unseen_only=False))])
            for r in (8, 16) for a in (1.0,)]
