"""Scale test: embedded index (ELASTICSEARCH-equivalent) + CCO path at
UR-realistic size (VERDICT r3 #7).

Generates a synthetic Universal-Recommender-shaped workload —
default 1M view/buy events, 100k items, 50k users, zipf-ish item
popularity — and measures:

- event ingest into ``ESEventStore`` (docs/sec, WAL bytes, compaction
  count and cost),
- durable-restart replay time (the WAL read path),
- event-store query latency (event-name filtered find, entity find),
- raw index search latency (terms query over indicator fields),
- CCO indicator train time at this catalog size (the sparse
  co-occurrence path — the dense (n_a, n_b) C would be 40 GB here)
  plus device top-k share, and the indicator-index build.

Usage::

    python profile_indexed.py [--events 1000000] [--items 100000]
                              [--users 50000] [--platform cpu]

Prints ONE JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import tempfile
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--events", type=int, default=1_000_000)
    ap.add_argument("--items", type=int, default=100_000)
    ap.add_argument("--users", type=int, default=50_000)
    ap.add_argument("--platform", default="cpu")
    args = ap.parse_args()

    from profile_common import resolve_platform

    resolve_platform(args.platform)

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.models.cco import CCOParams, cco_indicators
    from predictionio_tpu.storage.indexed import (ESEventStore,
                                                  IndexedStorageClient)

    root = tempfile.mkdtemp(prefix="pio_index_scale_")
    out = {"events": args.events, "items": args.items, "users": args.users}
    try:
        rng = np.random.default_rng(0)
        # zipf-ish popularity: heavy head like a real catalog
        item_pop = rng.zipf(1.3, args.events) % args.items
        users = rng.integers(0, args.users, args.events)
        is_buy = rng.random(args.events) < 0.3

        client = IndexedStorageClient(root)
        store = ESEventStore(client)
        app_id = 1

        t0 = time.perf_counter()
        batch = []
        for n in range(args.events):
            batch.append(Event(
                event="buy" if is_buy[n] else "view",
                entity_type="user", entity_id=str(int(users[n])),
                target_entity_type="item",
                target_entity_id=str(int(item_pop[n]))))
            if len(batch) == 20_000:
                store.insert_batch(batch, app_id)
                batch = []
        if batch:
            store.insert_batch(batch, app_id)
        ingest_sec = time.perf_counter() - t0
        idx = client.index(store._name(app_id, None))
        wal_bytes = os.path.getsize(idx._path)
        out["ingest"] = {
            "sec": round(ingest_sec, 2),
            "events_per_sec": round(args.events / ingest_sec),
            "wal_mb": round(wal_bytes / 1e6, 1),
        }

        # durable restart: replay cost of the WAL read path
        client.close()
        t0 = time.perf_counter()
        client = IndexedStorageClient(root)
        store = ESEventStore(client)
        n_docs = len(client.index(store._name(app_id, None)))
        out["replay"] = {"sec": round(time.perf_counter() - t0, 2),
                         "docs": n_docs}

        # query latency (warm): filtered find + entity find
        def bench(fn, iters=50):
            fn()
            lat = np.empty(iters)
            for i in range(iters):
                t = time.perf_counter()
                fn()
                lat[i] = time.perf_counter() - t
            return round(float(np.percentile(lat, 50) * 1e3), 2)

        out["query_ms"] = {
            "find_by_event_limit100": bench(
                lambda: list(store.find(app_id, event_names=["buy"],
                                        limit=100))),
            "find_by_entity": bench(
                lambda: list(store.find(app_id, entity_type="user",
                                        entity_id="42", limit=100))),
        }

        # CCO at this catalog size (sparse path: dense C would be
        # items² × 4B = 40 GB at the default geometry)
        uu = users.astype(np.int32)
        ii = item_pop.astype(np.int32)
        prim = (uu[is_buy], ii[is_buy])
        sec = (uu, ii)
        t0 = time.perf_counter()
        indicators = cco_indicators(
            prim, {"buy": prim, "view": sec}, args.users, args.items,
            {"buy": args.items, "view": args.items},
            CCOParams(max_indicators_per_item=50))
        cco_sec = time.perf_counter() - t0
        out["cco"] = {
            "sec": round(cco_sec, 2),
            "nnz_primary": int(prim[0].size),
            "indicators_per_item": int(
                np.isfinite(indicators["buy"][1]).sum(1).mean()),
        }

        # indicator index build (the trained-model → queryable-index
        # step the reference does into Elasticsearch)
        from predictionio_tpu.storage.indexed import index_indicators
        from predictionio_tpu.utils.bimap import BiMap

        t0 = time.perf_counter()
        index_indicators(client, "ur_indicators", indicators,
                         item_ids=BiMap({str(i): i
                                         for i in range(args.items)}))
        out["index_indicators_sec"] = round(time.perf_counter() - t0, 2)
        ind_idx = client.index("ur_indicators")
        out["indicator_search_ms"] = bench(
            lambda: ind_idx.search(
                should=[("buy", str(int(ii[0])), 1.0)], size=50))
        client.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps({"metric": "indexed_cco_scale", **out}))


if __name__ == "__main__":
    main()
