"""AOT-bucketed serving executables: the deploy-time warmup layer.

Upstream PredictionIO serves its first query the instant ``pio deploy``
binds the port (akka-http → ``predictBase``, SURVEY.md §3.2) because
Spark ships pre-built JVM bytecode. The JAX port instead pays a full
XLA trace+compile the first time the serving program meets a NEW batch
shape — so first queries, rare batch sizes, and every probe-then-swap
``/reload`` eat a multi-second latency cliff on the hot path.

This module removes the cliff by construction:

- :class:`BucketLadder` — a geometric ladder of padded batch buckets
  (default 1, 2, 4, … max_batch; ``pio deploy --aot-buckets`` overrides).
  Every collected micro-batch is snapped UP to the nearest bucket and
  padded with masked rows, so the set of batch shapes that can ever
  reach the device is finite and known at deploy time.
- :class:`ExecutableCache` — a process-wide cache of AOT-compiled
  (``jax.jit(...).lower(...).compile()``) serving executables keyed by
  program geometry. Sharing by geometry means a probe-then-swap
  ``/reload`` of a same-shape candidate is pure cache hits: the swap
  causes ZERO compiles on the first post-swap query. The underlying
  XLA compile additionally lands in the persistent on-disk cache
  (``utils/compilecache``), so restarts warm-start from disk.
- :class:`AOTWarmup` — deploy-time orchestration: walks the deployed
  engine's algorithms, asks each (duck-typed ``aot_warm`` hook) to
  compile its serving program for every ladder bucket, and exposes
  progress for ``/health`` (``not-ready`` until the serving bucket set
  is compiled).
- ``PAD`` — the sentinel the :class:`~predictionio_tpu.server.batching.
  MicroBatcher` pads collected batches with; padded rows are masked on
  device and sliced off the fan-out, with a parity guarantee (padded
  results bitwise-identical to unpadded execution — tests/
  test_aot_serving.py).

Per-bucket device-program latency lands in the
``pio_predict_device_seconds{bucket,path}`` histogram — the tracked
serving metric (``predict_p50_device_ms``) while the accelerator
tunnel is down (ROADMAP item 5; bench.py + profile_serving.py --aot).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.utils.metrics import REGISTRY

# -- padding sentinel ---------------------------------------------------------


class _PadQuery:
    """Sentinel appended by the MicroBatcher to fill a batch up to its
    bucket. Engine layers must never serve it: its result slot is
    sliced off before the fan-out. Singleton so ``q is PAD`` works
    across modules."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "<PAD>"


PAD = _PadQuery()


def is_pad(query: Any) -> bool:
    return query is PAD


def strip_pads(queries: Sequence[Any]) -> Tuple[List[Any], List[int]]:
    """Split a padded batch into (real queries, their original
    positions). The complement positions are PAD slots."""
    real, pos = [], []
    for i, q in enumerate(queries):
        if q is not PAD:
            real.append(q)
            pos.append(i)
    return real, pos


# -- the bucket ladder --------------------------------------------------------


class BucketLadder:
    """A sorted ladder of padded batch buckets.

    ``snap(n)`` returns the smallest bucket ≥ n — the batch shape the
    dispatch will actually run at. The largest bucket doubles as the
    serving ``max_batch``: the MicroBatcher never collects more.
    """

    def __init__(self, buckets: Sequence[int]) -> None:
        cleaned = sorted({int(b) for b in buckets if int(b) >= 1})
        if not cleaned:
            raise ValueError("bucket ladder needs at least one bucket >= 1")
        self.buckets: Tuple[int, ...] = tuple(cleaned)

    @classmethod
    def geometric(cls, max_batch: int, base: int = 2) -> "BucketLadder":
        """1, base, base², … up to (and always including) max_batch."""
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        buckets = []
        b = 1
        while b < max_batch:
            buckets.append(b)
            b *= base
        buckets.append(max_batch)
        return cls(buckets)

    @classmethod
    def parse(cls, spec: Optional[str], max_batch: int) -> "BucketLadder":
        """``--aot-buckets`` grammar: ``auto`` (or empty) → geometric
        ladder up to ``max_batch``; else a comma-separated explicit
        ladder, e.g. ``1,2,4,8,16,32,64``. An explicit ladder defines
        its own max batch (its largest bucket)."""
        if not spec or spec.strip().lower() == "auto":
            return cls.geometric(max_batch)
        try:
            buckets = [int(tok) for tok in spec.split(",") if tok.strip()]
        except ValueError as e:
            raise ValueError(f"bad --aot-buckets spec {spec!r}: {e}") from None
        return cls(buckets)

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def snap(self, n: int) -> int:
        """Smallest bucket ≥ n (n > max_batch snaps to max_batch —
        callers cap collection at max_batch, so this is defensive)."""
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def __iter__(self):
        return iter(self.buckets)

    def __len__(self) -> int:
        return len(self.buckets)

    def __repr__(self) -> str:
        return f"BucketLadder({list(self.buckets)})"


# -- process-wide executable cache -------------------------------------------


class ExecutableCache:
    """AOT-compiled serving executables keyed by program geometry.

    The key must capture EVERYTHING that selects a distinct XLA
    program (shapes, statics, platform) — value arrays are passed at
    call time, so executables are safely shared across model instances
    with the same geometry. That sharing is what makes a same-geometry
    ``/reload`` compile-free: the candidate's warmup is pure hits.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._programs: Dict[Tuple, Any] = {}
        self._m_lookups = REGISTRY.counter(
            "pio_aot_cache_lookups_total",
            "AOT executable-cache lookups", ("result",))
        self._m_compile_s = REGISTRY.histogram(
            "pio_aot_compile_seconds",
            "Wall time of cold AOT lower+compile",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0))

    def get(self, key: Tuple) -> Optional[Any]:
        with self._lock:
            return self._programs.get(key)

    def get_or_compile(self, key: Tuple, build: Callable[[], Any]) -> Any:
        """Return the cached executable for ``key``, compiling (and
        recording cold-compile wall time) on first use. ``build`` runs
        outside the lock — XLA compiles can take seconds and must not
        serialize unrelated lookups; a racing double-compile is benign
        (last write wins, both executables are equivalent)."""
        with self._lock:
            prog = self._programs.get(key)
        if prog is not None:
            self._m_lookups.inc(("hit",))
            return prog
        t0 = time.perf_counter()
        prog = build()
        self._m_compile_s.observe(time.perf_counter() - t0)
        self._m_lookups.inc(("compile",))
        with self._lock:
            self._programs.setdefault(key, prog)
            return self._programs[key]

    def counts(self) -> Dict[str, int]:
        """{"hit": n, "compile": m} — the zero-compile assertions in
        tests and the ``--aot`` profile read this."""
        vals = self._m_lookups._values
        return {k[0]: int(v) for k, v in vals.items()}

    def clear(self) -> None:
        with self._lock:
            self._programs.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._programs)


#: process-wide cache — all scorers/models share it so reloads and
#: repeated deploys in one process never recompile a known geometry
EXECUTABLES = ExecutableCache()


# -- per-bucket device latency ------------------------------------------------

#: the tracked serving metric (ROADMAP item 5): device-program latency
#: per padded batch bucket. ``path`` = aot (precompiled executable) |
#: jit (fell back to jax.jit dispatch — counts a warmup gap).
DEVICE_LATENCY = REGISTRY.histogram(
    "pio_predict_device_seconds",
    "Serving device-program latency (dispatch + packed fetch) per bucket",
    labelnames=("bucket", "path"))

_DISPATCHES = REGISTRY.counter(
    "pio_aot_dispatch_total",
    "Serving device dispatches", ("bucket", "path"))


def record_device_latency(bucket: int, seconds: float, path: str,
                          trace_exemplar: Optional[str] = None) -> None:
    labels = (str(bucket), path)
    DEVICE_LATENCY.observe(seconds, labels, exemplar=trace_exemplar)
    _DISPATCHES.inc(labels)


#: sharded-ANN serving layout (ann/scorer.ShardedANNScorer): shard
#: count of the serving mesh, padded item rows resident per device,
#: and the (k′ · shards) width of the distributed top-k merge — the
#: three numbers that size per-device HBM and the collective
#: (docs/observability.md; `pio index status --shards` predicts them
#: from the manifest alone).
ANN_SHARDS = REGISTRY.gauge(
    "pio_ann_shard_count",
    "Item shards in the sharded ANN serving mesh (0 = unsharded)")
ANN_SHARD_ITEMS = REGISTRY.gauge(
    "pio_ann_shard_items_per_device",
    "Padded item rows resident per device under sharded ANN serving")
ANN_SHARD_MERGE = REGISTRY.gauge(
    "pio_ann_shard_merge_candidates",
    "Distributed shortlist-merge width (k' x shards) per query row")


def record_shard_layout(shards: int, items_per_device: int,
                        shortlist: int) -> None:
    """Publish the sharded-ANN serving layout (called once per scorer
    construction, not per dispatch — layout only changes on /reload)."""
    ANN_SHARDS.set(shards)
    ANN_SHARD_ITEMS.set(items_per_device)
    ANN_SHARD_MERGE.set(shortlist * shards)


def device_p50_ms_by_bucket(path: str = "aot") -> Dict[str, float]:
    """Approximate per-bucket p50 (ms) from the histogram buckets —
    the ``predict_p50_device_ms`` series bench.py / profile_serving.py
    report. Median taken at the first bucket whose cumulative count
    crosses half the total (upper-bound estimate). ``path`` selects the
    dispatch path: ``"aot"`` = exact precompiled serving, ``"ann"`` =
    precompiled ADC-shortlist serving (predictionio_tpu/ann) — bench.py
    reads both to report the ANN-vs-exact per-bucket story."""
    out: Dict[str, float] = {}
    with DEVICE_LATENCY._lock:
        items = {k: list(c) for k, c in DEVICE_LATENCY._counts.items()}
    for key, counts in items.items():
        total = sum(counts)
        if not total or key[1] != path:
            continue
        half, cum = total / 2.0, 0
        p50 = DEVICE_LATENCY.buckets[-1]
        for b, c in zip(DEVICE_LATENCY.buckets, counts):
            cum += c
            if cum >= half:
                p50 = b
                break
        out[key[0]] = p50 * 1e3
    return out


# -- deploy-time warmup orchestration ----------------------------------------


class AOTWarmup:
    """Compiles the deployed engine's serving programs for every ladder
    bucket, tracking progress for ``/health``.

    States: ``idle`` (never started) → ``warming`` → ``ready`` |
    ``failed``. A deploy with AOT enabled reports ``not-ready`` until
    ``ready``; a reload warms the CANDIDATE through :meth:`warm_sync`
    before the swap, so the post-swap first query runs a precompiled
    bucket executable.

    Algorithms opt in by implementing ``aot_warm(model, ladder, ks)``
    → dict with ``compiled``/``cached`` counts (duck-typed — see
    ``controller/components.Algorithm.aot_warm``). Engines whose
    algorithms serve host-side (no device program) warm instantly.
    """

    def __init__(self, ladder: BucketLadder,
                 ks: Sequence[int] = (16,)) -> None:
        self.ladder = ladder
        self.ks = tuple(ks)
        self.state = "idle"
        self.error: Optional[str] = None
        self.compiled = 0
        self.cached = 0
        self.total_targets = 0
        self.wall_sec = 0.0
        self._started_at = 0.0
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._m_state = REGISTRY.gauge(
            "pio_aot_warmup_ready",
            "1 once the serving bucket ladder is fully compiled")
        self._m_state.set(0)
        self._m_warm_s = REGISTRY.gauge(
            "pio_aot_warmup_seconds", "Wall time of the last warmup pass")

    # -- sync core ----------------------------------------------------------

    def warm_sync(self, deployed: Any) -> Dict[str, Any]:
        """Warm every algorithm of ``deployed`` across the ladder; runs
        in the caller's thread (deploy startup uses :meth:`start`; the
        reload path calls this directly pre-swap). Raises on failure —
        a candidate whose serving program will not compile must never
        be swapped live."""
        from predictionio_tpu.utils import tracing

        t0 = time.perf_counter()
        with self._lock:
            self._started_at = t0
        compiled = cached = targets = 0
        with tracing.span("serving.aot_warmup",
                          buckets=len(self.ladder), ks=len(self.ks)):
            for name, algo in getattr(deployed, "algorithms", []):
                model = deployed.models[
                    [n for n, _ in deployed.algorithms].index(name)]
                hook = getattr(algo, "aot_warm", None)
                if hook is None:
                    continue
                stats = hook(model, self.ladder, self.ks) or {}
                compiled += int(stats.get("compiled", 0))
                cached += int(stats.get("cached", 0))
                targets += int(stats.get("targets", 0))
        wall = time.perf_counter() - t0
        with self._lock:
            self.compiled, self.cached = compiled, cached
            self.total_targets = targets
            self.wall_sec = wall
        self._m_warm_s.set(wall)
        return {"compiled": compiled, "cached": cached,
                "targets": targets, "wall_sec": wall}

    # -- background lifecycle -----------------------------------------------

    def start(self, deployed: Any) -> None:
        """Kick off (or restart) the deploy-time warmup in a daemon
        thread; ``/health`` turns ``ready`` when it completes."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self.state = "warming"
            self.error = None
            self._m_state.set(0)
            self._thread = threading.Thread(
                target=self._run, args=(deployed,),
                name="pio-aot-warmup", daemon=True)
            self._thread.start()

    def _run(self, deployed: Any) -> None:
        try:
            self.warm_sync(deployed)
        except Exception as e:  # noqa: BLE001 — surfaced via /health
            with self._lock:
                self.state = "failed"
                self.error = f"{type(e).__name__}: {e}"
            return
        with self._lock:
            self.state = "ready"
        self._m_state.set(1)

    def mark_ready(self) -> None:
        """Record a successful synchronous warm (the /reload pre-swap
        path calls :meth:`warm_sync` directly, with no background
        thread to flip the state)."""
        with self._lock:
            self.state = "ready"
        self._m_state.set(1)

    def wait(self, timeout: Optional[float] = None) -> bool:
        t = self._thread
        if t is not None:
            t.join(timeout)
        return self.state in ("ready", "failed")

    @property
    def ready(self) -> bool:
        return self.state == "ready"

    def retry_after(self) -> float:
        """Seconds a not-ready client should wait before re-probing:
        the last pass's wall time minus what has already elapsed of the
        current one (floored at 0.5 s so pollers don't spin), or the
        full estimate when no pass is in flight. 0 once settled."""
        with self._lock:
            if self.state in ("ready", "failed"):
                return 0.0
            est = self.wall_sec if self.wall_sec > 0 else 5.0
            if self.state == "warming" and self._started_at > 0:
                elapsed = time.perf_counter() - self._started_at
                return max(0.5, est - elapsed)
            return est

    def progress(self) -> Dict[str, Any]:
        """The ``/health`` warmup block."""
        with self._lock:
            return {
                "state": self.state,
                "buckets": list(self.ladder.buckets),
                "ks": list(self.ks),
                "compiled": self.compiled,
                "cached": self.cached,
                "targets": self.total_targets,
                "wallSec": round(self.wall_sec, 3),
                **({"error": self.error} if self.error else {}),
            }

    def release(self) -> None:
        """Drop the warmup thread reference (server shutdown). The
        process-wide :data:`EXECUTABLES` cache intentionally survives —
        a supervisor-restarted server in the same process re-warms from
        it for free."""
        with self._lock:
            self._thread = None
            self.state = "idle"
            self._m_state.set(0)
