"""Segmented, tiered event-log namespaces for the EVENTLOG backend.

Upstream PredictionIO scaled its event store by partitioning across
HBase regions; this is the repo-native equivalent for the C++ log
engine. Each (app, channel) namespace is an ACTIVE segment — the plain
``events_<app>[_<ch>].pel`` file, identical to the pre-segment layout,
receiving group-commit appends under one per-namespace writer lock —
plus zero or more SEALED segments under ``events_<app>[_<ch>].peld/``:

    events_1.pel                  active segment (engine wire format)
    events_1.peld/
        segments.json             manifest (atomic-replace writes)
        seg-000000.pel            sealed segment, immutable
        seg-000000.cols.npz       columnar compaction sidecar
        seg-000000.ids.bf         live-id filter (ship-time fetch guard)
        seg-000001.pel            ...

A legacy single-file log therefore IS a valid namespace (its lone
active segment); the first write that crosses the rollover threshold
migrates it in place — rename into the directory as the next sealed
segment, reopen a fresh active file. Rollover reuses the old active
handle as the sealed read handle (the engine reads through the open
fd, so the rename is invisible to it) — no close/reopen race, no
re-index of a file we just finished writing.

Sealed segments are immutable except for tombstones (cross-segment
overwrite/delete propagation), which re-seal the metadata and drop the
sidecar. Compaction scans a sealed segment once through the native
extended columnar scan and persists the result as an npz sidecar, so
training scans read it back without record-by-record decode; shipment
moves the sealed frame file to a cold tier (``storage/remote.py``)
keyed by the manifest's sha256, which the fetch path re-verifies — a
corrupt cold blob is refused (:class:`IntegrityError`), never served.

Scan fan-out: segments whose creationTime bounds fall entirely outside
the requested window are pruned (the snapshot cache's watermark becomes
a per-segment watermark), the rest scan on a thread pool (the engine
releases the GIL inside native calls) in bounded windows, and
:func:`~predictionio_tpu.data.pipeline.merge_columnar_segments`
restores the global (eventTime, creationTime, seq) order.
"""

from __future__ import annotations

import ctypes
import hashlib
import io
import json
import logging
import os
import struct
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.utils import faults, tracing
from predictionio_tpu.utils.atomic_write import (
    atomic_write_bytes,
    atomic_write_text,
)
from predictionio_tpu.utils.integrity import (
    INTEGRITY_FAILED,
    INTEGRITY_VERIFIED,
    IntegrityError,
    sha256_hex,
)
from predictionio_tpu.utils.metrics import REGISTRY

logger = logging.getLogger("pio.segments")

SEG_DIR_SUFFIX = ".peld"
MANIFEST_NAME = "segments.json"
MANIFEST_SCHEMA = 1
COLS_SUFFIX = ".cols.npz"
IDF_SUFFIX = ".ids.bf"
FAULT_SEGMENT = "data.corrupt.segment"
DEFAULT_SEGMENT_BYTES = 64 * 1024 * 1024
_UNBOUNDED_LO = -(2**62)
_UNBOUNDED_HI = 2**62

SEG_ROLLS = REGISTRY.counter(
    "pio_segment_rolls_total", "Active segments sealed (rollovers)")
SEG_COMPACTIONS = REGISTRY.counter(
    "pio_segment_compactions_total", "Sealed segments compacted to columnar")
SEG_SHIPPED = REGISTRY.counter(
    "pio_segment_shipped_total", "Sealed segments shipped to the cold tier")
SEG_SHIP_VERIFY = REGISTRY.counter(
    "pio_segment_ship_verify_total",
    "Post-ship cold-tier read-back digest checks by result", ("result",))
SEG_FETCHES = REGISTRY.counter(
    "pio_segment_fetches_total", "Cold segments fetched back on demand")
SEG_MAINT_ERRORS = REGISTRY.counter(
    "pio_segment_maintenance_errors_total",
    "Errors contained by segment maintenance sweeps")


def segment_bytes_threshold() -> int:
    """Rollover threshold; ``PIO_SEGMENT_BYTES=0`` disables rollover."""
    try:
        return int(os.environ.get("PIO_SEGMENT_BYTES",
                                  DEFAULT_SEGMENT_BYTES))
    except ValueError:
        return DEFAULT_SEGMENT_BYTES


def scan_workers_default() -> int:
    try:
        w = int(os.environ.get("PIO_SCAN_WORKERS", "0"))
    except ValueError:
        w = 0
    if w > 0:
        return w
    # IO overlap pays even on one core, so floor at 2
    return max(2, min(8, os.cpu_count() or 1))


def _file_sha256(path: str) -> str:
    d = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            d.update(chunk)
    return d.hexdigest()


@dataclass
class SegMeta:
    """One sealed segment's manifest entry."""

    id: int
    file: str
    state: str                      # "sealed" | "cold"
    records: int
    bytes: int
    min_creation_us: Optional[int]
    max_creation_us: Optional[int]
    sha256: Optional[str]           # None until finalized (lazy, off the
    version: int                    # group-commit path)
    cols: Optional[dict] = None     # {"file","sha256","value_keys":[...]}
    remote_key: Optional[str] = None
    idf: Optional[dict] = None      # {"file","sha256","k","n"} id filter

    def to_dict(self) -> dict:
        return {
            "id": self.id, "file": self.file, "state": self.state,
            "records": self.records, "bytes": self.bytes,
            "min_creation_us": self.min_creation_us,
            "max_creation_us": self.max_creation_us,
            "sha256": self.sha256, "version": self.version,
            "cols": self.cols, "remote_key": self.remote_key,
            "idf": self.idf,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "SegMeta":
        return cls(
            id=int(d["id"]), file=str(d["file"]), state=str(d["state"]),
            records=int(d["records"]), bytes=int(d["bytes"]),
            min_creation_us=d.get("min_creation_us"),
            max_creation_us=d.get("max_creation_us"),
            sha256=d.get("sha256"), version=int(d.get("version", 2)),
            cols=d.get("cols"), remote_key=d.get("remote_key"),
            idf=d.get("idf"),
        )


class Segment:
    """Runtime state for one sealed segment: manifest row + (lazy)
    engine handle. The handle, once open, stays open for the namespace
    lifetime — in-flight scans on other threads may hold it. ``gen``
    counts mutations (tombstone re-seals): slow paths that scan outside
    the lock snapshot it and abort their commit when it moved."""

    __slots__ = ("meta", "handle", "gen", "idf")

    def __init__(self, meta: SegMeta, handle: Optional[int] = None) -> None:
        self.meta = meta
        self.handle = handle
        self.gen = 0
        self.idf = None        # cached IdFilter | False (known absent)


# ---------------- extended native scan plumbing ---------------------------


@dataclass
class SegBlock:
    """Parsed pel_scan_columnar_ex blob: ColumnarEvents columns plus a
    creationTime column and entity/target TYPE columns."""

    times: "object"
    creation: "object"
    values: Dict[str, "object"]      # value_key → f64[n]
    ent_idx: "object"
    tgt_idx: "object"
    name_idx: "object"
    etype_idx: "object"
    ttype_idx: "object"
    ents: List[str]
    tgts: List[str]
    names: List[str]
    etypes: List[str]
    ttypes: List[str]
    nbytes: int


def _scan_ex(lib, h: int, start_us: int, until_us: int,
             created_after_us: int, created_until_us: int,
             entity_type: Optional[str], target_entity_type: Optional[str],
             event_names: Optional[Sequence[str]],
             value_keys: Optional[Sequence[str]]) -> Optional[bytes]:
    """Run the extended scan; None = engine declined (vocab overflow)."""
    out = ctypes.c_void_p()
    n = lib.pel_scan_columnar_ex(
        h, start_us, until_us, created_after_us, created_until_us,
        entity_type.encode() if entity_type is not None else None,
        target_entity_type.encode()
        if target_entity_type is not None else None,
        "\n".join(event_names).encode()
        if event_names is not None else None,
        "\n".join(value_keys).encode() if value_keys else None,
        ctypes.byref(out))
    if n == -2:
        return None
    if n < 0:
        raise IOError("segment columnar scan failed")
    try:
        return ctypes.string_at(out, n)
    finally:
        lib.pel_free(out)


def parse_scan_ex_blob(buf: bytes,
                       value_keys: Sequence[str]) -> SegBlock:
    import struct

    import numpy as np

    n, n_ent, n_tgt, n_nam, n_et, n_tt, n_keys = struct.unpack_from(
        "<QQQQQQQ", buf, 0)
    assert n_keys == len(value_keys), "value-key count mismatch"
    off = 56
    times = np.frombuffer(buf, "<i8", n, off); off += 8 * n
    creation = np.frombuffer(buf, "<i8", n, off); off += 8 * n
    values = {}
    for k in value_keys:
        values[k] = np.frombuffer(buf, "<f8", n, off); off += 8 * n
    ent_idx = np.frombuffer(buf, "<u4", n, off); off += 4 * n
    off += -off % 8
    tgt_idx = np.frombuffer(buf, "<u4", n, off); off += 4 * n
    off += -off % 8
    name_idx = np.frombuffer(buf, "<u2", n, off); off += 2 * n
    off += -off % 8
    etype_idx = np.frombuffer(buf, "<u2", n, off); off += 2 * n
    off += -off % 8
    ttype_idx = np.frombuffer(buf, "<u2", n, off); off += 2 * n
    off += -off % 8

    u32 = struct.Struct("<I")

    def table(off: int, count: int):
        strs = []
        for _ in range(count):
            (sl,) = u32.unpack_from(buf, off)
            off += 4
            strs.append(buf[off:off + sl].decode("utf-8"))
            off += sl
        return strs, off + (-off % 8)

    names_t, off = table(off, n_nam)
    ents_t, off = table(off, n_ent)
    tgts_t, off = table(off, n_tgt)
    etypes_t, off = table(off, n_et)
    ttypes_t, off = table(off, n_tt)
    return SegBlock(times=times, creation=creation, values=values,
                    ent_idx=ent_idx, tgt_idx=tgt_idx, name_idx=name_idx,
                    etype_idx=etype_idx, ttype_idx=ttype_idx,
                    ents=ents_t, tgts=tgts_t, names=names_t,
                    etypes=etypes_t, ttypes=ttypes_t, nbytes=len(buf))


def block_to_cols(block: SegBlock, value_key: Optional[str]):
    import numpy as np

    from predictionio_tpu.data.pipeline import ColumnarEvents

    if value_key is not None:
        values = block.values[value_key]
    else:
        values = np.full(block.times.shape[0], np.nan)
    return ColumnarEvents(
        entity_idx=block.ent_idx, target_idx=block.tgt_idx,
        name_idx=block.name_idx, values=values, times_us=block.times,
        entity_ids=block.ents, target_ids=block.tgts, names=block.names)


# ---------------- columnar compaction sidecars ----------------------------


def sidecar_bytes(block: SegBlock, value_keys: Sequence[str]) -> bytes:
    """Serialize a wildcard-scan block as an npz sidecar (no pickle)."""
    import numpy as np

    def tab(strs: List[str]):
        return np.asarray(strs, dtype=str) if strs else np.asarray(
            [], dtype="<U1")

    arrays = {
        "times": block.times, "creation": block.creation,
        "ent_idx": block.ent_idx, "tgt_idx": block.tgt_idx,
        "name_idx": block.name_idx, "etype_idx": block.etype_idx,
        "ttype_idx": block.ttype_idx,
        "ent_tab": tab(block.ents), "tgt_tab": tab(block.tgts),
        "name_tab": tab(block.names), "etype_tab": tab(block.etypes),
        "ttype_tab": tab(block.ttypes),
        "value_keys": tab(list(value_keys)),
    }
    for i, k in enumerate(value_keys):
        arrays[f"val_{i}"] = block.values[k]
    bio = io.BytesIO()
    import numpy as _np
    _np.savez(bio, **arrays)
    return bio.getvalue()


def load_sidecar(path: str, expected_sha: str) -> Tuple[dict, int]:
    """Read + digest-verify a compaction sidecar. The sidecar is a
    cache of the raw segment, never authoritative — callers treat any
    failure here as a miss and fall back to the raw frame scan."""
    import numpy as np

    with open(path, "rb") as f:
        blob = f.read()
    if sha256_hex(blob) != expected_sha:
        INTEGRITY_FAILED.inc(("segment_cols",))
        raise IntegrityError(f"segment sidecar digest mismatch: {path}")
    npz = np.load(io.BytesIO(blob), allow_pickle=False)
    return {k: npz[k] for k in npz.files}, len(blob)


def sidecar_scan(sc: dict, start_us: int, until_us: int,
                 created_after_us: int, created_until_us: int,
                 entity_type: Optional[str],
                 target_entity_type: Optional[str],
                 event_names: Optional[Sequence[str]],
                 value_key: Optional[str]):
    """Serve one scan_columnar filter set from a loaded sidecar, or
    None when it cannot (value_key the compaction did not extract).
    Vocabularies are renumbered to first-seen order of the FILTERED
    rows — identical to what a native scan of the raw segment with the
    same filters would build."""
    import numpy as np

    from predictionio_tpu.data.pipeline import (
        ColumnarEvents,
        _reindex_first_seen,
    )

    vkeys = [str(s) for s in sc["value_keys"]]
    if value_key is not None and value_key not in vkeys:
        return None
    times = sc["times"]
    creation = sc["creation"]
    mask = np.ones(times.shape[0], bool)
    if start_us > _UNBOUNDED_LO:
        mask &= times >= start_us
    if until_us < _UNBOUNDED_HI:
        mask &= times < until_us
    if created_after_us > _UNBOUNDED_LO:
        mask &= creation > created_after_us
    if created_until_us < _UNBOUNDED_HI:
        mask &= creation <= created_until_us

    def type_mask(filter_val: Optional[str], tab_key: str, idx_key: str):
        nonlocal mask
        if filter_val is None:
            return
        tab = sc[tab_key].tolist()
        if filter_val in tab:
            mask &= sc[idx_key] == tab.index(filter_val)
        else:
            mask &= False

    type_mask(entity_type, "etype_tab", "etype_idx")
    type_mask(target_entity_type, "ttype_tab", "ttype_idx")
    if event_names is not None:
        allowed_set = set(event_names)
        tab = sc["name_tab"].tolist()
        allowed = np.asarray([s in allowed_set for s in tab], bool)
        if tab:
            mask &= allowed[sc["name_idx"]]
        # empty name table ⇒ empty segment scan; mask already matches
    if mask.all():
        # nothing filtered: the compaction already stored first-seen
        # vocabularies, so the block passes through untouched (numpy
        # <U tables — the segment merge normalizes to lists)
        if value_key is not None:
            values = sc[f"val_{vkeys.index(value_key)}"]
        else:
            values = np.full(times.shape[0], np.nan)
        cols = ColumnarEvents(
            entity_idx=sc["ent_idx"], target_idx=sc["tgt_idx"],
            name_idx=sc["name_idx"], values=values, times_us=times,
            entity_ids=sc["ent_tab"], target_ids=sc["tgt_tab"],
            names=sc["name_tab"])
        return cols, creation
    times_f = times[mask]
    creation_f = creation[mask]
    if value_key is not None:
        values = sc[f"val_{vkeys.index(value_key)}"][mask]
    else:
        values = np.full(times_f.shape[0], np.nan)
    e_idx, e_tab = _reindex_first_seen(
        sc["ent_idx"][mask], sc["ent_tab"].tolist(), np.uint32)
    t_idx, t_tab = _reindex_first_seen(
        sc["tgt_idx"][mask], sc["tgt_tab"].tolist(), np.uint32)
    n_idx, n_tab = _reindex_first_seen(
        sc["name_idx"][mask], sc["name_tab"].tolist(), np.uint16)
    cols = ColumnarEvents(
        entity_idx=e_idx, target_idx=t_idx, name_idx=n_idx,
        values=values, times_us=times_f,
        entity_ids=e_tab, target_ids=t_tab, names=n_tab)
    return cols, creation_f


# ---------------- id membership filters -----------------------------------


class IdFilter:
    """Bloom filter over a sealed segment's live event ids, persisted
    at ship time so the synchronous write path can prove "this id is
    not in that cold segment" without fetching the frame file back
    from the tier. A false positive costs one extra fetch; false
    negatives cannot happen, so a miss is always safe to skip."""

    __slots__ = ("bits", "k", "m")

    BITS_PER_ID = 12
    K = 7                           # ~0.3% false positives at 12 b/id

    def __init__(self, bits: bytes, k: int) -> None:
        self.bits = bits
        self.k = k
        self.m = len(bits) * 8

    @staticmethod
    def _hashes(id_: bytes) -> Tuple[int, int]:
        d = hashlib.blake2b(id_, digest_size=16).digest()
        # double hashing: h1 + i*h2 — h2 forced odd so strides cover m
        return (int.from_bytes(d[:8], "little"),
                int.from_bytes(d[8:], "little") | 1)

    @classmethod
    def build(cls, ids: Sequence[bytes]) -> "IdFilter":
        m = max(1024, len(ids) * cls.BITS_PER_ID)
        m += -m % 8
        bits = bytearray(m // 8)
        for id_ in ids:
            h1, h2 = cls._hashes(id_)
            for i in range(cls.K):
                b = (h1 + i * h2) % m
                bits[b >> 3] |= 1 << (b & 7)
        return cls(bytes(bits), cls.K)

    def __contains__(self, id_: str) -> bool:
        h1, h2 = self._hashes(id_.encode())
        for i in range(self.k):
            b = (h1 + i * h2) % self.m
            if not (self.bits[b >> 3] >> (b & 7)) & 1:
                return False
        return True


# ---------------- cold tier -----------------------------------------------


def cold_tier():
    """The configured segment cold tier, or None (lazy import — the
    remote module pulls in breaker/retry plumbing)."""
    from predictionio_tpu.storage.remote import segment_cold_tier

    return segment_cold_tier()


# ---------------- namespace -----------------------------------------------


class LogNamespace:
    """One (app, channel) namespace: active engine handle + sealed
    segment list + manifest. All mutation happens under ``lock`` (the
    per-namespace writer lock); readers snapshot the handle/segment
    list under the lock and then run lock-free — handles are never
    closed while the namespace lives."""

    def __init__(self, lib, base_path: str, fmt: int) -> None:
        self._lib = lib
        self.base_path = base_path
        root, _ext = os.path.splitext(base_path)
        self.dir_path = root + SEG_DIR_SUFFIX
        self.fmt = fmt
        self.lock = threading.RLock()
        self.sealed: List[Segment] = []
        self.next_id = 0
        self.last_scan: Optional[dict] = None
        # handles swapped out of service (wipe, cold re-materialize):
        # lock-free readers may still hold them, so they are parked
        # here and only closed when the namespace itself closes
        self._retired: List[int] = []
        self._load_manifest()
        self.h = lib.pel_open_ex(base_path.encode(), fmt)
        if not self.h:
            raise IOError(f"cannot open event log {base_path}")

    # -- manifest ----------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.dir_path, MANIFEST_NAME)

    def _load_manifest(self) -> None:
        try:
            with open(self.manifest_path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except FileNotFoundError:
            return
        except (OSError, json.JSONDecodeError) as e:
            raise IOError(
                f"unreadable segment manifest {self.manifest_path}: {e}"
            ) from e
        if doc.get("schema") != MANIFEST_SCHEMA:
            raise IOError(
                f"unknown segment manifest schema in {self.manifest_path}")
        self.sealed = [Segment(SegMeta.from_dict(d))
                       for d in doc.get("segments", [])]
        self.sealed.sort(key=lambda s: s.meta.id)
        ids = [s.meta.id for s in self.sealed]
        self.next_id = max([int(doc.get("next_id", 0))] +
                           [i + 1 for i in ids])

    def _write_manifest(self) -> None:
        doc = {
            "schema": MANIFEST_SCHEMA,
            "next_id": self.next_id,
            "segments": [s.meta.to_dict() for s in self.sealed],
        }
        os.makedirs(self.dir_path, exist_ok=True)
        atomic_write_text(self.manifest_path,
                          json.dumps(doc, indent=1, sort_keys=True))

    def seg_path(self, seg: Segment) -> str:
        return os.path.join(self.dir_path, seg.meta.file)

    def cols_path(self, seg: Segment) -> Optional[str]:
        if not seg.meta.cols:
            return None
        return os.path.join(self.dir_path, seg.meta.cols["file"])

    def idf_path(self, seg: Segment) -> Optional[str]:
        if not seg.meta.idf:
            return None
        return os.path.join(self.dir_path, seg.meta.idf["file"])

    # -- rollover ----------------------------------------------------------

    def maybe_roll(self, threshold_bytes: int) -> bool:
        """Seal the active segment when it crosses the size threshold.
        Called with appends quiesced (writer lock held by caller or
        taken here). The seal is cheap — rename + index-only bounds +
        manifest write; the content digest is deferred to
        :meth:`finalize` so group commits never pay a full-file hash."""
        if threshold_bytes <= 0:
            return False
        with self.lock:
            try:
                size = os.path.getsize(self.base_path)
            except OSError:
                return False
            if size < threshold_bytes:
                return False
            return self.roll()

    def roll(self) -> bool:
        """Unconditionally seal the active segment (no-op when empty)."""
        with self.lock:
            lib = self._lib
            h = self.h
            lib.pel_sync(h)
            try:
                if os.path.getsize(self.base_path) <= 8:
                    return False  # header-only / empty file
            except OSError:
                return False
            mn = ctypes.c_longlong(0)
            mx = ctypes.c_longlong(0)
            count = lib.pel_creation_bounds(
                h, ctypes.byref(mn), ctypes.byref(mx))
            ver = ctypes.c_longlong(2)
            lib.pel_info(h, ctypes.byref(ver), None, None, None)
            seg_id = self.next_id
            self.next_id += 1
            fname = f"seg-{seg_id:06d}.pel"
            os.makedirs(self.dir_path, exist_ok=True)
            dst = os.path.join(self.dir_path, fname)
            os.rename(self.base_path, dst)
            meta = SegMeta(
                id=seg_id, file=fname, state="sealed",
                records=int(count), bytes=os.path.getsize(dst),
                min_creation_us=int(mn.value) if count else None,
                max_creation_us=int(mx.value) if count else None,
                sha256=None, version=int(ver.value))
            # the old active handle becomes the sealed read handle: the
            # engine reads through the open fd, so the rename (and a
            # later cold-tier unlink) is invisible to it
            self.sealed.append(Segment(meta, handle=h))
            self._write_manifest()
            nh = lib.pel_open_ex(self.base_path.encode(), self.fmt)
            if not nh:
                raise IOError(
                    f"cannot reopen active segment {self.base_path}")
            self.h = nh
            SEG_ROLLS.inc()
            return True

    def finalize(self, seg: Segment) -> None:
        """Fill in the deferred content digest of a sealed segment."""
        with self.lock:
            if seg.meta.sha256 is not None:
                return
            path = self.seg_path(seg)
            if seg.handle is not None:
                self._lib.pel_sync(seg.handle)
            seg.meta.sha256 = _file_sha256(path)
            seg.meta.bytes = os.path.getsize(path)
            self._write_manifest()

    def finalize_all(self) -> None:
        for seg in list(self.sealed):
            if seg.meta.sha256 is None and seg.meta.state == "sealed":
                self.finalize(seg)

    # -- handles / locality ------------------------------------------------

    def handle_for(self, seg: Segment) -> int:
        with self.lock:
            if seg.handle is not None:
                return seg.handle
            self.ensure_local(seg)
            h = self._lib.pel_open_ex(self.seg_path(seg).encode(), self.fmt)
            if not h:
                raise IOError(f"cannot open segment {self.seg_path(seg)}")
            corrupt = ctypes.c_longlong(0)
            torn = ctypes.c_longlong(-1)
            self._lib.pel_info(h, None, ctypes.byref(corrupt),
                               ctypes.byref(torn), None)
            if corrupt.value > 0 or torn.value >= 0:
                # a sealed segment should never recover records; serve
                # what is readable but surface it — fsck flags it hard
                INTEGRITY_FAILED.inc(("segment",))
            seg.handle = h
            return h

    def ensure_local(self, seg: Segment) -> None:
        """Fetch a cold segment's frame file back from the tier,
        verifying the manifest digest; a mismatch refuses the segment."""
        path = self.seg_path(seg)
        if os.path.exists(path):
            return
        meta = seg.meta
        if meta.state != "cold" or not meta.remote_key:
            raise IOError(f"segment file missing: {path}")
        tier = cold_tier()
        if tier is None:
            raise IOError(
                f"segment {meta.file} is cold but no cold tier is "
                "configured (PIO_SEGMENT_COLD)")
        with tracing.span("storage.segment.fetch", key=meta.remote_key):
            blob = tier.get(meta.remote_key)
        if blob is None:
            raise IOError(
                f"cold tier has no object for segment {meta.file} "
                f"({meta.remote_key})")
        blob = faults.corrupt_bytes(FAULT_SEGMENT, blob)
        if meta.sha256 is None or sha256_hex(blob) != meta.sha256:
            INTEGRITY_FAILED.inc(("segment",))
            raise IntegrityError(
                f"cold segment {meta.file} failed digest verification "
                "— refusing to serve it")
        INTEGRITY_VERIFIED.inc(("segment",))
        atomic_write_bytes(path, blob)
        SEG_FETCHES.inc()

    def _mutable_handle(self, seg: Segment) -> int:
        """A handle safe to append tombstones through. A shipped
        segment's lingering read handle sits on an unlinked inode
        (:meth:`ship` removes the local path), so appends there would
        vanish when the handle closes. Re-materialize the authoritative
        cold copy first and open a fresh handle on it; the stale handle
        is parked, never closed — lock-free readers may still hold it."""
        with self.lock:
            if not os.path.exists(self.seg_path(seg)):
                self.ensure_local(seg)
                if seg.handle is not None:
                    self._retired.append(seg.handle)
                    seg.handle = None
            return self.handle_for(seg)

    # -- id membership filters ---------------------------------------------

    def build_id_filter(self, seg: Segment) -> Optional[dict]:
        """Build + persist the live-id filter for a segment about to go
        cold (index-only native walk, no payload IO). Best effort: the
        filter only short-circuits tombstone probes, so on any failure
        the segment ships without one and probes fall back to fetching."""
        try:
            h = self.handle_for(seg)
            out = ctypes.c_void_p()
            n = self._lib.pel_live_ids(h, ctypes.byref(out))
            if n < 0:
                return None
            try:
                buf = ctypes.string_at(out, n)
            finally:
                self._lib.pel_free(out)
            ids = []
            pos = 0
            while pos < len(buf):
                (ln,) = struct.unpack_from("<I", buf, pos)
                pos += 4
                ids.append(buf[pos:pos + ln])
                pos += ln
            f = IdFilter.build(ids)
            fname = seg.meta.file[:-len(".pel")] + IDF_SUFFIX
            atomic_write_bytes(os.path.join(self.dir_path, fname), f.bits)
            seg.idf = f
            return {"file": fname, "sha256": sha256_hex(f.bits),
                    "k": f.k, "n": len(ids)}
        except Exception:
            logger.warning("id-filter build failed for %s; cold "
                           "tombstone probes will fetch", seg.meta.file,
                           exc_info=True)
            return None

    def _load_id_filter(self, seg: Segment) -> Optional[IdFilter]:
        """The segment's persisted id filter (lazy, digest-verified),
        or None when absent/unreadable — callers then treat every id
        as a possible member (correct, just slower)."""
        if seg.idf is not None:
            return seg.idf or None      # False sentinel = known absent
        meta = seg.meta.idf
        path = self.idf_path(seg)
        if not meta or path is None:
            seg.idf = False
            return None
        try:
            with open(path, "rb") as f:
                blob = f.read()
            if sha256_hex(blob) != meta.get("sha256"):
                raise IntegrityError(
                    f"id-filter digest mismatch: {path}")
            seg.idf = IdFilter(blob, int(meta.get("k", IdFilter.K)))
        except (OSError, IntegrityError, ValueError):
            seg.idf = False
            return None
        return seg.idf

    # -- compaction --------------------------------------------------------

    def sample_value_keys(self, h: int, sample: int = 256) -> List[str]:
        """Pick the property keys worth extracting into value columns:
        explicit ``PIO_SEGMENT_VALUE_KEYS`` wins, else the most common
        top-level keys of a record sample (up to 4)."""
        env = os.environ.get("PIO_SEGMENT_VALUE_KEYS")
        if env is not None:
            return [k for k in (p.strip() for p in env.split(","))
                    if k][:8]
        from predictionio_tpu.data.filestore import deserialize_payload

        import struct as _struct

        out = ctypes.c_void_p()
        n = self._lib.pel_find(
            h, _UNBOUNDED_LO, _UNBOUNDED_HI, None, None, None, None,
            None, 0, sample, ctypes.byref(out))
        if n < 0:
            return []
        try:
            buf = ctypes.string_at(out, n)
        finally:
            self._lib.pel_free(out)
        counts: Dict[str, int] = {}
        pos = 0
        while pos < len(buf):
            (plen,) = _struct.unpack_from("<I", buf, pos)
            pos += 4
            try:
                e = deserialize_payload(buf, pos, plen)
            except Exception:
                pos += plen
                continue
            pos += plen
            for k in e.properties:
                counts[k] = counts.get(k, 0) + 1
        top = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return [k for k, _c in top[:4]]

    def compact(self, seg: Segment,
                value_keys: Optional[Sequence[str]] = None) -> bool:
        """Compact one sealed segment into its columnar sidecar."""
        with self.lock:
            if seg.meta.cols is not None or seg.meta.records == 0:
                return False
            gen = seg.gen
        h = self.handle_for(seg)
        keys = list(value_keys) if value_keys is not None \
            else self.sample_value_keys(h)
        blob = _scan_ex(self._lib, h, _UNBOUNDED_LO, _UNBOUNDED_HI,
                        _UNBOUNDED_LO, _UNBOUNDED_HI, None, None, None,
                        keys)
        if blob is None:
            return False  # vocab overflow: raw scans only
        block = parse_scan_ex_blob(blob, keys)
        data = sidecar_bytes(block, keys)
        fname = seg.meta.file[:-len(".pel")] + COLS_SUFFIX
        path = os.path.join(self.dir_path, fname)
        atomic_write_bytes(path, data)
        with self.lock:
            if seg.gen != gen:
                # the segment mutated (tombstone re-seal) while we
                # scanned outside the lock: committing would resurrect
                # deleted events from the stale snapshot — drop it and
                # let the next maintenance sweep recompact
                try:
                    os.unlink(path)
                except OSError:
                    pass
                return False
            self.finalize(seg)
            seg.meta.cols = {"file": fname, "sha256": sha256_hex(data),
                             "value_keys": keys}
            self._write_manifest()
        SEG_COMPACTIONS.inc()
        return True

    # -- cold tier ---------------------------------------------------------

    def namespace_tag(self) -> str:
        return os.path.splitext(os.path.basename(self.base_path))[0]

    def ship(self, seg: Segment, tier=None, verify: bool = False) -> bool:
        """Ship one sealed segment's frame file to the cold tier and
        drop the local copy (the compaction sidecar stays local, so
        warm scans never refetch).

        ``verify`` closes the silent-ship-corruption gap: after the
        put, re-fetch the object from the tier and compare its sha256
        against the manifest digest BEFORE trusting the cold copy and
        unlinking the local file. A mismatch (bit rot in flight, a
        lying proxy, an eventually-consistent tier serving a stale
        body) deletes the bad remote object, keeps the local file, and
        raises :class:`IntegrityError` — the segment stays ``sealed``
        and a later ship retries."""
        tier = tier or cold_tier()
        if tier is None:
            return False
        with self.lock:
            if seg.meta.state != "sealed":
                return False
            if seg.meta.cols is None:
                self.compact(seg)   # best effort; ship regardless
            self.finalize(seg)
            # live-id filter, persisted locally: the write path probes
            # it so tombstone misses never fetch the segment back
            if seg.meta.idf is None:
                seg.meta.idf = self.build_id_filter(seg)
            path = self.seg_path(seg)
        with open(path, "rb") as f:
            blob = f.read()
        if sha256_hex(blob) != seg.meta.sha256:
            raise IntegrityError(
                f"sealed segment {seg.meta.file} changed under us — "
                "refusing to ship")
        key = f"segments/{self.namespace_tag()}/{seg.meta.file}"
        with tracing.span("storage.segment.ship", key=key,
                          bytes=len(blob)):
            tier.put(key, blob)
        if verify:
            with tracing.span("storage.segment.ship_verify", key=key):
                back = tier.get(key)
                digest = sha256_hex(back) if back is not None else None
                if digest != seg.meta.sha256:
                    SEG_SHIP_VERIFY.inc(("mismatch",))
                    try:
                        tier.delete(key)
                    except Exception:  # noqa: BLE001 — best effort
                        pass
                    raise IntegrityError(
                        f"cold tier read-back of {key} does not match "
                        f"the manifest digest (got "
                        f"{digest[:12] if digest else 'nothing'}…, want "
                        f"{seg.meta.sha256[:12]}…) — keeping the local "
                        "copy, remote object deleted")
                SEG_SHIP_VERIFY.inc(("ok",))
        with self.lock:
            seg.meta.state = "cold"
            seg.meta.remote_key = key
            self._write_manifest()
            # an already-open handle keeps reading through its fd; new
            # opens fetch from the tier
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        SEG_SHIPPED.inc()
        return True

    # -- tombstones (cross-segment overwrite / delete) ---------------------

    def tombstone_sealed(self, ids: Sequence[str]) -> int:
        """Propagate deletes/overwrites into sealed segments. Each id
        lives in at most one segment (overwrites tombstone the old copy
        at insert time), so the walk stops at the first hit per id.
        Cold segments are probed through their shipped-time id filter
        first: a definite miss skips the segment entirely, so appends
        with brand-new client-supplied ids never fetch from the tier."""
        deleted = 0
        with self.lock:
            segs = list(self.sealed)
        remaining = list(ids)
        for seg in reversed(segs):
            if not remaining:
                break
            candidates = remaining
            if seg.meta.state == "cold":
                f = self._load_id_filter(seg)
                if f is not None:
                    candidates = [i for i in remaining if i in f]
                if not candidates:
                    continue        # definite miss: no fetch, no probe
            # cold segment with a possible hit: re-materialize the
            # frame file before any mutation (the lingering read handle
            # sits on an unlinked inode — appends there would be lost)
            h = self._mutable_handle(seg)
            hit = set()
            for id_ in candidates:
                b = id_.encode()
                r = self._lib.pel_delete(h, b, len(b))
                if r < 0:
                    raise IOError("segment tombstone append failed")
                if r:
                    hit.add(id_)
                    deleted += 1
            if hit:
                remaining = [i for i in remaining if i not in hit]
                self._reseal(seg)
        return deleted

    def _reseal(self, seg: Segment) -> None:
        """A sealed segment mutated (tombstones): refresh its metadata
        and drop the now-stale sidecar. The local frame file is the new
        authoritative copy — its digest is recorded in the manifest
        BEFORE the (now stale) cold-tier object is deleted, so at no
        point is the only surviving copy an unlinked inode or a
        remote object about to be removed."""
        with self.lock:
            h = seg.handle
            path = self.seg_path(seg)
            if h is None or not os.path.exists(path):
                raise IOError(
                    f"re-seal of {seg.meta.file} without a local frame "
                    "file — refusing to drop the authoritative copy")
            self._lib.pel_sync(h)
            mn = ctypes.c_longlong(0)
            mx = ctypes.c_longlong(0)
            count = self._lib.pel_creation_bounds(
                h, ctypes.byref(mn), ctypes.byref(mx))
            cols = self.cols_path(seg)
            if cols:
                try:
                    os.unlink(cols)
                except FileNotFoundError:
                    pass
            old_remote = seg.meta.remote_key
            seg.meta.state = "sealed"
            seg.meta.remote_key = None
            seg.meta.cols = None
            # the id filter stays: tombstones only remove ids, so the
            # persisted filter remains a superset — still sound
            seg.meta.records = int(count)
            seg.meta.min_creation_us = int(mn.value) if count else None
            seg.meta.max_creation_us = int(mx.value) if count else None
            seg.meta.sha256 = _file_sha256(path)
            seg.meta.bytes = os.path.getsize(path)
            seg.gen += 1
            self._write_manifest()
        # only now — local copy durable and its digest recorded — may
        # the stale cold object go (network IO, outside the lock)
        if old_remote:
            tier = cold_tier()
            if tier is not None:
                try:
                    tier.delete(old_remote)
                except Exception:
                    pass  # orphaned object is harmless: state says
                    # sealed, nothing fetches it, re-ship overwrites it

    # -- stats -------------------------------------------------------------

    def creation_stats(self, until_us: int) -> Tuple[int, Optional[int]]:
        with self.lock:
            segs = list(self.sealed)
            h = self.h
        total = 0
        max_c: Optional[int] = None
        for seg in segs:
            m = seg.meta
            if m.records == 0 or m.min_creation_us is None:
                continue
            if until_us >= (m.max_creation_us or 0):
                total += m.records
                if max_c is None or m.max_creation_us > max_c:
                    max_c = m.max_creation_us
            elif until_us < m.min_creation_us:
                continue
            else:
                sh = self.handle_for(seg)
                mo = ctypes.c_longlong(0)
                n = self._lib.pel_creation_stats(
                    sh, until_us, ctypes.byref(mo))
                if n > 0:
                    total += int(n)
                    if max_c is None or mo.value > max_c:
                        max_c = int(mo.value)
        mo = ctypes.c_longlong(0)
        n = self._lib.pel_creation_stats(h, until_us, ctypes.byref(mo))
        if n > 0:
            total += int(n)
            if max_c is None or mo.value > max_c:
                max_c = int(mo.value)
        return (total, max_c) if total else (0, None)

    # -- scan fan-out ------------------------------------------------------

    def scan_columnar(self, start_us: int, until_us: int,
                      created_after_us: int, created_until_us: int,
                      entity_type: Optional[str],
                      target_entity_type: Optional[str],
                      event_names: Optional[Sequence[str]],
                      value_key: Optional[str],
                      workers: int):
        """Multi-segment columnar scan: prune by per-segment creation
        bounds, scan survivors (sidecar first, raw frames otherwise) on
        a bounded thread-pool window, merge into global order."""
        from predictionio_tpu.data.pipeline import merge_columnar_segments

        return merge_columnar_segments(self.scan_blocks(
            start_us, until_us, created_after_us, created_until_us,
            entity_type, target_entity_type, event_names, value_key,
            workers))

    def scan_blocks(self, start_us: int, until_us: int,
                    created_after_us: int, created_until_us: int,
                    entity_type: Optional[str],
                    target_entity_type: Optional[str],
                    event_names: Optional[Sequence[str]],
                    value_key: Optional[str],
                    workers: int):
        """The scan fan-out as a ``(cols, creation)`` block generator,
        in segment order, WITHOUT the final merge — so a caller can
        chain several namespaces' streams (the writer-shard read path
        in ``data/filestore.py``) into ONE
        :func:`~predictionio_tpu.data.pipeline.merge_columnar_segments`
        call and still get a result identical to a single-file scan of
        the union. Scan stats (``last_scan``, trace attrs) are recorded
        when the generator is exhausted."""
        with self.lock:
            segs = list(self.sealed)
            active_h = self.h
        targets: List[Optional[Segment]] = []
        pruned = 0
        for seg in segs:
            m = seg.meta
            if (m.records == 0 or m.min_creation_us is None
                    or m.max_creation_us <= created_after_us
                    or m.min_creation_us > created_until_us):
                pruned += 1
                continue
            targets.append(seg)
        targets.append(None)  # the active segment, always scanned
        stats: List[dict] = [None] * len(targets)  # type: ignore

        value_keys = [value_key] if value_key is not None else []

        def scan_one(i: int, seg: Optional[Segment]):
            if seg is not None and seg.meta.cols is not None:
                vk = seg.meta.cols.get("value_keys", [])
                if value_key is None or value_key in vk:
                    try:
                        sc, nbytes = load_sidecar(
                            self.cols_path(seg), seg.meta.cols["sha256"])
                        served = sidecar_scan(
                            sc, start_us, until_us, created_after_us,
                            created_until_us, entity_type,
                            target_entity_type, event_names, value_key)
                        if served is not None:
                            cols, creation = served
                            stats[i] = {
                                "segment": seg.meta.id,
                                "source": "columnar",
                                "records": int(cols.n),
                                "bytes": int(nbytes)}
                            return cols, creation
                    except (OSError, IntegrityError, ValueError,
                            KeyError):
                        pass  # sidecar is a cache: fall back to frames
            h = active_h if seg is None else self.handle_for(seg)
            blob = _scan_ex(self._lib, h, start_us, until_us,
                            created_after_us, created_until_us,
                            entity_type, target_entity_type, event_names,
                            value_keys)
            if blob is None:
                return None, None  # vocab overflow → whole scan declines
            block = parse_scan_ex_blob(blob, value_keys)
            stats[i] = {
                "segment": -1 if seg is None else seg.meta.id,
                "source": "active" if seg is None else "raw",
                "records": int(block.times.shape[0]),
                "bytes": int(block.nbytes)}
            return block_to_cols(block, value_key), block.creation

        def blocks():
            # bounded fan-out window: at most `workers` segment scans
            # (and their blocks) in flight, results consumed in segment
            # order so peak memory stays O(result + window)
            if workers <= 1 or len(targets) == 1:
                for i, seg in enumerate(targets):
                    yield scan_one(i, seg)
                return
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=min(workers, len(targets))) as ex:
                pending = []
                idx = 0
                while pending or idx < len(targets):
                    while idx < len(targets) and len(pending) < workers:
                        pending.append(
                            ex.submit(scan_one, idx, targets[idx]))
                        idx += 1
                    fut = pending.pop(0)
                    yield fut.result()

        yield from blocks()
        seg_stats = [s for s in stats if s]
        self.last_scan = {
            "segments": len(targets), "pruned": pruned,
            "per_segment": seg_stats,
        }
        tracing.add_attrs(
            scan_segments=len(targets), scan_segments_pruned=pruned,
            scan_segment_detail=seg_stats)

    # -- lifecycle ---------------------------------------------------------

    def wipe(self) -> bool:
        with self.lock:
            if self._lib.pel_wipe(self.h) != 0:
                return False
            tier = cold_tier() if any(
                s.meta.state == "cold" for s in self.sealed) else None
            for seg in self.sealed:
                if seg.handle is not None:
                    # lock-free readers may hold a snapshot of this
                    # handle: park it (closed at namespace close),
                    # never free it out from under an in-flight scan
                    self._retired.append(seg.handle)
                    seg.handle = None
                for p in (self.seg_path(seg), self.cols_path(seg),
                          self.idf_path(seg)):
                    if p:
                        try:
                            os.unlink(p)
                        except FileNotFoundError:
                            pass
                if tier is not None and seg.meta.remote_key:
                    try:
                        tier.delete(seg.meta.remote_key)
                    except Exception:
                        pass
            self.sealed = []
            self.next_id = 0
            try:
                os.unlink(self.manifest_path)
                os.rmdir(self.dir_path)
            except OSError:
                pass
            return True

    def close(self) -> None:
        with self.lock:
            self._lib.pel_close(self.h)
            for seg in self.sealed:
                if seg.handle is not None:
                    self._lib.pel_close(seg.handle)
                    seg.handle = None
            for h in self._retired:
                self._lib.pel_close(h)
            self._retired = []

    def remove(self) -> None:
        with self.lock:
            self.close()
            try:
                os.unlink(self.base_path)
            except FileNotFoundError:
                pass
            import shutil

            shutil.rmtree(self.dir_path, ignore_errors=True)


# ---------------- background maintenance ----------------------------------


class SegmentMaintenance(threading.Thread):
    """Background compaction + cold-tier shipment for an EVENTLOG
    store. One sweep per interval: compact every sealed-uncompacted
    segment, then (when a tier is configured) ship all but the newest
    ``keep_local`` sealed segments. Errors are contained per segment —
    a bad segment never stops the sweep."""

    def __init__(self, store, interval: float = 30.0,
                 keep_local: int = 2) -> None:
        super().__init__(name="segment-maintenance", daemon=True)
        self._store = store
        self.interval = interval
        self.keep_local = max(0, keep_local)
        # NOT named _stop: Thread.join() calls the private Thread._stop
        # method internally, and shadowing it with an Event breaks join
        self._halt = threading.Event()
        self.sweeps = 0

    def run(self) -> None:
        while not self._halt.wait(self.interval):
            try:
                res = self.run_once()
                if res["errors"]:
                    logger.warning(
                        "segment maintenance sweep finished with %d "
                        "contained error(s): %s", res["errors"], res)
            except Exception:
                # systemic failure (bad tier config, permissions):
                # must be observable, not silently retried forever
                SEG_MAINT_ERRORS.inc()
                logger.exception("segment maintenance sweep failed")

    def run_once(self) -> dict:
        compacted = shipped = errors = 0
        tier = cold_tier()
        for ns in self._store.namespaces():
            with ns.lock:
                segs = list(ns.sealed)
            for seg in segs:
                try:
                    if (seg.meta.state == "sealed"
                            and seg.meta.cols is None
                            and seg.meta.records > 0):
                        if ns.compact(seg):
                            compacted += 1
                    elif seg.meta.state == "sealed":
                        ns.finalize(seg)
                except Exception:
                    errors += 1
                    SEG_MAINT_ERRORS.inc()
                    logger.warning("segment maintenance: compaction/"
                                   "finalize failed for %s",
                                   seg.meta.file, exc_info=True)
            if tier is not None:
                local = [s for s in segs if s.meta.state == "sealed"]
                for seg in local[:max(0, len(local) - self.keep_local)]:
                    try:
                        if ns.ship(seg, tier):
                            shipped += 1
                    except Exception:
                        errors += 1
                        SEG_MAINT_ERRORS.inc()
                        logger.warning("segment maintenance: ship "
                                       "failed for %s", seg.meta.file,
                                       exc_info=True)
        self.sweeps += 1
        return {"compacted": compacted, "shipped": shipped,
                "errors": errors}

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=5.0)
