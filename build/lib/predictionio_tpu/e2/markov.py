"""First-order Markov chain over an integer state space.

Reference: [U] e2/.../engine/MarkovChain.scala (unverified, SURVEY.md
§2a) — builds row-normalized transition probabilities from a sparse
count matrix and answers "top-K most likely next states".

TPU mapping: transition counting is a segment-sum over flattened
(from, to) pairs (``ops.segment.segment_sum``), normalization and the
top-K scan are jitted; the model keeps the dense (S, S) transition
matrix resident as a jax.Array when S is modest (item-to-item
navigation graphs), with a host dict fallback for very large sparse
spaces left to callers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np

from predictionio_tpu.ops.segment import segment_sum


@dataclass
class MarkovChainModel:
    """Row-stochastic transition matrix (rows with no observations are
    all-zero, matching the reference's sparse behavior)."""

    transitions: np.ndarray  # (S, S) float32
    n_states: int

    def transition_prob(self, from_state: int, to_state: int) -> float:
        return float(self.transitions[from_state, to_state])

    def predict_top_k(self, from_state: int, k: int) -> List[Tuple[int, float]]:
        """Top-K next states by probability (reference: MarkovChain
        top-K). Host-side numpy: a single (S,) row's top-k is µs work —
        a device dispatch per serving call would dominate it."""
        row = self.transitions[from_state]
        k = min(k, self.n_states)
        idx = np.argpartition(-row, k - 1)[:k]
        idx = idx[np.argsort(-row[idx], kind="stable")]
        return [(int(i), float(row[i])) for i in idx if row[i] > 0.0]


def markov_chain_train(
    pairs: Sequence[Tuple[int, int]], n_states: int,
) -> MarkovChainModel:
    """Count (from, to) transitions and row-normalize."""
    import jax.numpy as jnp

    if n_states <= 0:
        raise ValueError("n_states must be positive")
    if n_states > 46_340:
        # S*S must fit int32 (JAX x32 mode) — and a dense (S, S) f32
        # matrix past this point is >8 GB anyway; shard or sparsify
        # externally for larger state spaces
        raise ValueError(
            f"n_states={n_states} too large for the dense transition "
            "matrix (max 46340)")
    arr = np.asarray(pairs, np.int32).reshape(-1, 2)
    if arr.size and (arr.min() < 0 or arr.max() >= n_states):
        raise ValueError("state id out of range")
    flat = arr[:, 0].astype(np.int32) * n_states + arr[:, 1]
    counts = segment_sum(
        jnp.ones((len(flat),), jnp.float32), jnp.asarray(flat),
        n_states * n_states,
    ).reshape(n_states, n_states)
    row_tot = counts.sum(axis=1, keepdims=True)
    probs = jnp.where(row_tot > 0, counts / jnp.maximum(row_tot, 1.0), 0.0)
    return MarkovChainModel(np.asarray(probs, np.float32), n_states)
