"""Lint orchestration: run rule families over a project, apply inline
suppressions and the reviewed baseline, and shape the report the CLI
(and CI) consume.

Kept separate from the CLI so tests can call :func:`run_lint` on
fixture trees directly, and ``tests/test_faults_registry.py`` can call
the PL04 checker without going through argv.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence

from predictionio_tpu.analysis import (
    rules_jaxfree,
    rules_locks,
    rules_registry,
    rules_resilience,
    rules_trace,
)
from predictionio_tpu.analysis.core import Finding, Project, load_baseline

#: rule family id → checker. Adding a family = one module with a
#: ``check(project) -> list[Finding]`` plus one row here (and a
#: docs/development.md section — PL04 applies to us too).
RULES: Dict[str, Callable[[Project], List[Finding]]] = {
    "PL01": rules_trace.check,
    "PL02": rules_jaxfree.check,
    "PL03": rules_locks.check,
    "PL04": rules_registry.check,
    "PL05": rules_resilience.check,
}

DEFAULT_BASELINE = "conf/lint-baseline.json"


@dataclass
class LintReport:
    findings: List[Finding] = field(default_factory=list)  #: actionable
    baselined: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    stale_baseline: List[str] = field(default_factory=list)
    rules: List[str] = field(default_factory=list)
    files: int = 0
    duration_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> Dict:
        return {
            "ok": self.ok,
            "rules": self.rules,
            "files": self.files,
            "duration_s": round(self.duration_s, 3),
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "symbol": f.symbol, "key": f.key, "message": f.message}
                for f in self.findings
            ],
            "baselined": len(self.baselined),
            "suppressed": self.suppressed,
            "stale_baseline": self.stale_baseline,
        }


def default_root() -> Path:
    """The repo root: the directory holding the package dir (this file
    is ``<root>/predictionio_tpu/analysis/runner.py``)."""
    return Path(__file__).resolve().parents[2]


def run_lint(
    root: Optional[Path] = None,
    rules: Optional[Sequence[str]] = None,
    baseline: Optional[Path] = None,
    use_baseline: bool = True,
    package: str = "predictionio_tpu",
) -> LintReport:
    t0 = time.monotonic()
    root = Path(root) if root is not None else default_root()
    selected = list(rules) if rules else sorted(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule id(s): {unknown} "
                         f"(known: {sorted(RULES)})")

    project = Project(root, package=package)
    raw: List[Finding] = []
    for rule_id in selected:
        raw.extend(RULES[rule_id](project))

    report = LintReport(rules=selected, files=len(project.modules))

    by_path = {m.relpath: m for m in project.iter_modules()}
    visible: List[Finding] = []
    for f in raw:
        mod = by_path.get(f.path)
        if mod is not None and mod.is_suppressed(f.rule, f.line):
            report.suppressed += 1
        else:
            visible.append(f)

    accepted: Dict[str, str] = {}
    if use_baseline:
        path = Path(baseline) if baseline is not None \
            else root / DEFAULT_BASELINE
        if path.is_file():
            accepted = load_baseline(path)

    for f in visible:
        (report.baselined if f.key in accepted
         else report.findings).append(f)
    matched = {f.key for f in report.baselined}
    report.stale_baseline = sorted(k for k in accepted if k not in matched)

    report.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))
    report.duration_s = time.monotonic() - t0
    return report
