"""Multi-host distributed runtime: the communication-backend shell.

The reference's distributed story is Spark's driver/executor control
plane over netty RPC plus the shuffle service (SURVEY.md §2d P5/C1-C2).
The TPU-native equivalent is the JAX multi-controller model: one Python
process per host, rendezvoused over DCN by ``jax.distributed``, with
**no** driver/worker asymmetry inside compiled regions — collectives
ride ICI within a slice and DCN across slices. This module is the thin
shell around that: env-driven initialization, barriers, and the
host-local vs global device split that data loading needs.

Single-process runs (including CI and the 1-chip bench) skip
initialization entirely — every helper degrades to the trivial case.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional

_initialized = False


@dataclass
class DistributedConfig:
    """Rendezvous parameters, usually from the environment.

    Env (same spirit as the reference's PIO_* + Spark master env):
    ``PIO_COORDINATOR_ADDRESS`` (host:port of process 0),
    ``PIO_NUM_PROCESSES``, ``PIO_PROCESS_ID``. On Cloud TPU VMs all
    three are optional — jax.distributed auto-discovers from metadata.
    """

    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    @classmethod
    def from_env(cls) -> "DistributedConfig":
        e = os.environ

        def num(k: str) -> Optional[int]:
            return int(e[k]) if k in e else None

        return cls(
            coordinator_address=e.get("PIO_COORDINATOR_ADDRESS"),
            num_processes=num("PIO_NUM_PROCESSES"),
            process_id=num("PIO_PROCESS_ID"),
        )

    @property
    def requested(self) -> bool:
        return self.coordinator_address is not None


def _on_multihost_tpu() -> bool:
    """True when the Cloud-TPU environment itself announces multiple
    workers (auto-discovery then needs no PIO_* vars)."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hosts.split(",") if h.strip()]) > 1:
        return True
    return bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))


def initialize(config: Optional[DistributedConfig] = None) -> bool:
    """``jax.distributed.initialize``: explicitly when the PIO_* rendezvous
    vars are set, auto-discovered (argless) when the Cloud-TPU env
    announces a multi-host slice, otherwise a no-op. Idempotent.
    Returns True when running multi-process."""
    global _initialized
    import jax

    config = config or DistributedConfig.from_env()
    if _initialized:
        return jax.process_count() > 1
    if config.requested:
        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
        _initialized = True
    elif _on_multihost_tpu():
        jax.distributed.initialize()  # TPU-metadata auto-discovery
        _initialized = True
    return jax.process_count() > 1


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_coordinator() -> bool:
    return process_index() == 0


def local_devices() -> List:
    """Devices attached to THIS host (addressable)."""
    import jax

    return jax.local_devices()


def global_devices() -> List:
    import jax

    return jax.devices()


def barrier(name: str = "pio_barrier") -> None:
    """Cross-host sync point (no-op single-process)."""
    import jax

    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def broadcast_from_coordinator(pytree):
    """Replicate host-local data from process 0 to all hosts (the
    reference's torrent-broadcast analogue at the control-plane level)."""
    import jax

    if jax.process_count() <= 1:
        return pytree
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(pytree)


def broadcast_string(s: str, max_len: int = 256) -> str:
    """Broadcast a short string (e.g. the engine-instance id minted by
    the coordinator) to every process."""
    import jax
    import numpy as np

    if jax.process_count() <= 1:
        return s
    buf = np.zeros(max_len, np.uint8)
    raw = s.encode()
    if len(raw) > max_len:
        raise ValueError(f"string longer than {max_len} bytes")
    buf[: len(raw)] = np.frombuffer(raw, np.uint8)
    out = np.asarray(broadcast_from_coordinator(buf))
    return bytes(out).rstrip(b"\x00").decode()
