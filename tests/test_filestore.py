"""Native (C++) event-log engine specifics: durability, index rebuild,
and the native $set/$unset/$delete fold vs the Python reference fold."""

import datetime as dt
import json
import numpy as np

import pytest

from predictionio_tpu.data.event import Event, aggregate_properties, parse_event_time


def _t(s):
    return parse_event_time(s)


@pytest.fixture
def store(tmp_path):
    from predictionio_tpu.data.filestore import NativeEventLogStore

    try:
        s = NativeEventLogStore(str(tmp_path / "log"))  # builds the engine
    except RuntimeError as e:  # no g++ in this environment
        pytest.skip(str(e))
    yield s
    s.close()


APP = 1


def test_reopen_rebuilds_index(tmp_path, store):
    ids = store.insert_batch(
        [Event(event="rate", entity_type="user", entity_id=str(i),
               target_entity_type="item", target_entity_id="x",
               properties={"rating": float(i)},
               event_time=_t(f"2026-01-0{i+1}T00:00:00Z"))
         for i in range(3)],
        APP)
    store.delete(ids[1], APP)
    store.close()

    from predictionio_tpu.data.filestore import NativeEventLogStore

    s2 = NativeEventLogStore(str(tmp_path / "log"))
    evs = list(s2.find(APP))
    assert [e.event_id for e in evs] == [ids[0], ids[2]]
    assert s2.get(ids[1], APP) is None
    assert s2.get(ids[2], APP).properties == {"rating": 2.0}
    s2.close()


def test_overwrite_by_id(store):
    e = Event(event="$set", entity_type="user", entity_id="u",
              properties={"a": 1}, event_time=_t("2026-01-01T00:00:00Z"))
    eid = store.insert(e, APP)
    e2 = Event(event_id=eid, event="$set", entity_type="user", entity_id="u",
               properties={"a": 2}, event_time=_t("2026-01-01T00:00:00Z"))
    store.insert(e2, APP)
    evs = list(store.find(APP))
    assert len(evs) == 1 and evs[0].properties == {"a": 2}


def test_nul_and_unicode_roundtrip(store):
    e = Event(event="note", entity_type="user", entity_id="ué中",
              properties={"text": 'quote " backslash \\ newline \n tab \t',
                          "nested": {"k": [1, 2, {"d": None}]},
                          "num": 1.5, "bool": True},
              event_time=_t("2026-01-01T00:00:00Z"))
    eid = store.insert(e, APP)
    got = store.get(eid, APP)
    assert got.entity_id == "ué中"
    assert got.properties == e.properties


def test_native_fold_matches_python_fold(store):
    evs = [
        Event(event="$set", entity_type="user", entity_id="a",
              properties={"x": 1, "name": "A"},
              event_time=_t("2026-01-01T00:00:00Z")),
        Event(event="$set", entity_type="user", entity_id="a",
              properties={"x": 2, "y": [1, 2]},
              event_time=_t("2026-01-03T00:00:00Z")),
        Event(event="$unset", entity_type="user", entity_id="a",
              properties={"name": None},
              event_time=_t("2026-01-04T00:00:00Z")),
        Event(event="$set", entity_type="user", entity_id="b",
              properties={"deep": {"n": {"m": "q\"uote"}}},
              event_time=_t("2026-01-02T00:00:00Z")),
        Event(event="$set", entity_type="user", entity_id="gone",
              properties={"z": 1}, event_time=_t("2026-01-02T00:00:00Z")),
        Event(event="$delete", entity_type="user", entity_id="gone",
              event_time=_t("2026-01-05T00:00:00Z")),
        Event(event="rate", entity_type="user", entity_id="a",
              target_entity_type="item", target_entity_id="i",
              event_time=_t("2026-01-02T12:00:00Z")),
        Event(event="$set", entity_type="item", entity_id="other-type",
              properties={"w": 1}, event_time=_t("2026-01-01T00:00:00Z")),
    ]
    store.insert_batch(evs, APP)

    native = store.aggregate_properties(APP, "user")
    ref = aggregate_properties(
        e for e in evs if e.entity_type == "user")

    assert set(native) == set(ref) == {"a", "b"}
    for eid in native:
        assert native[eid].properties == ref[eid].properties, eid
        assert native[eid].first_updated == ref[eid].first_updated
        assert native[eid].last_updated == ref[eid].last_updated


def test_fold_backslash_and_unicode_ids(store):
    # literal backslash text and non-ASCII must survive the native fold
    evs = [
        Event(event="$set", entity_type="user", entity_id="C:\\users",
              properties={"p\\u0041th": "a\\u0042", "中文": "漢"},
              event_time=_t("2026-01-01T00:00:00Z")),
    ]
    store.insert_batch(evs, APP)
    native = store.aggregate_properties(APP, "user")
    ref = aggregate_properties(evs)
    assert set(native) == set(ref) == {"C:\\users"}
    assert native["C:\\users"].properties == ref["C:\\users"].properties


def test_microsecond_roundtrip(store):
    t = _t("2005-03-28T19:42:50.536110Z")  # float-timestamp rounding victim
    eid = store.insert(
        Event(event="e", entity_type="t", entity_id="1", event_time=t), APP)
    assert store.get(eid, APP).event_time == t


def test_limit_zero_returns_nothing(store):
    store.insert(Event(event="e", entity_type="t", entity_id="1",
                       event_time=_t("2026-01-01T00:00:00Z")), APP)
    assert list(store.find(APP, limit=0)) == []


def test_fold_time_window(store):
    for day, val in ((1, 1), (2, 2), (3, 3)):
        store.insert(
            Event(event="$set", entity_type="user", entity_id="u",
                  properties={"v": val},
                  event_time=_t(f"2026-01-0{day}T00:00:00Z")), APP)
    agg = store.aggregate_properties(
        APP, "user", until_time=_t("2026-01-03T00:00:00Z"))
    assert agg["u"].properties == {"v": 2}


def test_find_filters_and_limits(store):
    store.insert_batch(
        [Event(event="view", entity_type="user", entity_id="u1",
               target_entity_type="item", target_entity_id=f"i{k}",
               event_time=_t(f"2026-02-0{k}T00:00:00Z"))
         for k in range(1, 6)], APP)
    got = list(store.find(APP, limit=2, reversed=True))
    assert [e.target_entity_id for e in got] == ["i5", "i4"]
    got = list(store.find(APP, target_entity_id="i3"))
    assert len(got) == 1
    got = list(store.find(APP, start_time=_t("2026-02-02T00:00:00Z"),
                          until_time=_t("2026-02-04T00:00:00Z")))
    assert [e.target_entity_id for e in got] == ["i2", "i3"]


def test_torn_tail_write_is_ignored(tmp_path, store):
    ids = store.insert_batch(
        [Event(event="e", entity_type="t", entity_id="1",
               event_time=_t("2026-01-01T00:00:00Z")),
         Event(event="e", entity_type="t", entity_id="2",
               event_time=_t("2026-01-02T00:00:00Z"))], APP)
    store.close()
    path = tmp_path / "log" / "events_1.pel"
    raw = path.read_bytes()
    path.write_bytes(raw + b"\x40\x00\x00\x00\x00partial")  # truncated record

    from predictionio_tpu.data.filestore import NativeEventLogStore

    s2 = NativeEventLogStore(str(tmp_path / "log"))
    assert [e.event_id for e in s2.find(APP)] == ids
    # the torn tail is truncated at open: writes after it survive reopen
    new_id = s2.insert(Event(event="e", entity_type="t", entity_id="3",
                             event_time=_t("2026-01-03T00:00:00Z")), APP)
    s2.close()
    s3 = NativeEventLogStore(str(tmp_path / "log"))
    assert [e.event_id for e in s3.find(APP)] == ids + [new_id]
    s3.close()


def test_quickstart_on_eventlog_storage(tmp_path):
    """End-to-end train → query with EVENTDATA on the C++ event log —
    the deployment docs recommend for bulk events (the SPI tests cover
    the store alone; this proves the whole workflow path, env-config →
    registry → native store → streaming read → ALS → serving)."""
    import numpy as np

    from predictionio_tpu.core.workflow import prepare_deploy, run_train
    from predictionio_tpu.storage.registry import (Storage, StorageConfig,
                                                   set_storage)
    from tests.test_workflow import FACTORY, seed_ratings

    cfg = StorageConfig.from_env({
        "PIO_HOME": str(tmp_path),
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "NATIVE",
        "PIO_STORAGE_SOURCES_NATIVE_TYPE": "EVENTLOG",
    })
    assert cfg.eventdata_type == "EVENTLOG"
    st = Storage(cfg)
    set_storage(st)
    built = False
    try:
        try:
            st.events  # builds the C++ engine lazily
            built = True
        except RuntimeError as e:  # only the no-g++ signal may skip
            pytest.skip(f"native engine unavailable: {e}")
        seed_ratings(st)
        run_train(FACTORY, variant={
            "id": "elq", "engineFactory": FACTORY,
            "datasource": {"params": {"appName": "TestApp"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "numIterations": 3, "lambda": 0.05}}],
        }, storage=st, use_mesh=False)
        res = prepare_deploy(engine_factory=FACTORY,
                             storage=st).query({"user": "0", "num": 3})
        assert len(res["itemScores"]) == 3
        assert np.isfinite([s["score"] for s in res["itemScores"]]).all()
    finally:
        if built:
            st.events.close()
        set_storage(None)


@pytest.fixture(params=["eventlog", "sqlite", "indexed"])
def col_store(request, tmp_path):
    """Every scan_columnar provider — the C++ EVENTLOG engine, the SQL
    store (default SQLITE backend), and the embedded index — under one
    parity contract."""
    if request.param == "eventlog":
        from predictionio_tpu.data.filestore import NativeEventLogStore

        try:
            s = NativeEventLogStore(str(tmp_path / "log"))
        except RuntimeError as e:
            pytest.skip(str(e))
    elif request.param == "sqlite":
        from predictionio_tpu.data.events import SqliteEventStore

        s = SqliteEventStore(str(tmp_path / "ev.db"))
        s.init_channel(APP)
    else:
        from predictionio_tpu.storage.indexed import (ESEventStore,
                                                      IndexedStorageClient)

        s = ESEventStore(IndexedStorageClient(str(tmp_path / "idx")))
        s.init_channel(APP)
    yield s
    s.close()


class TestColumnarScan:
    """The columnar training read (C++ EVENTLOG engine AND the SQL
    store's SELECT-only variant) must be indistinguishable from the
    generic two-pass Python reader over find() — same vocabularies
    (content AND first-seen order), same arrays, same drop
    semantics."""

    def _mixed_workload(self, store):
        rng_events = [
            # (event, ent, tgt, props)
            ("rate", "u1", "i1", {"rating": 4.0}),
            ("rate", "u2", "i2", {"rating": 3}),          # int rating
            ("rate", "u1", "i2", {"rating": "4.5"}),      # numeric string
            ("rate", "u3", "i3", {}),                      # missing → drop
            ("rate", "u4", "i1", {"rating": "bad"}),       # malformed → drop
            ("rate", "u∞", "i☂", {"rating": 2.0}),         # unicode ids
            ("buy", "u2", "i3", {}),                       # const value
            ("buy", "u5", "i1", {"rating": 9.0}),          # const ignores prop
            ("view", "u1", "i1", {}),                      # filtered out
            ("rate", "u1", None, {"rating": 5.0}),         # no target → skip
            ("rate", "u6", "i4", {"rating": {"nested": 1}}),  # non-num → drop
            # the shared value grammar is NARROWER than Python float()
            # so both paths drop the same exotica (r5 review):
            ("rate", "u7", "i1", {"rating": "0x10"}),      # hex → drop
            ("rate", "u8", "i2", {"rating": "1_5"}),       # underscore → drop
            ("rate", "u9", "i3", {"rating": "inf"}),       # inf word → drop
            ("rate", "uA", "i4", {"rating": "nan"}),       # nan word → drop
            ("rate", "uB", "i1", {"rating": float("inf")}),  # inf value → drop
            ("rate", "uC", "i2", {"rating": True}),        # bool → 1.0
            ("rate", "uD", "i3", {"rating": " 2.5 "}),     # padded str → 2.5
            ("rate", "uE", "i4", {"rating": "1e2"}),       # exponent → 100.0
        ]
        t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        for k, (name, ent, tgt, props) in enumerate(rng_events):
            store.insert(Event(
                event=name, entity_type="user", entity_id=ent,
                target_entity_type="item" if tgt else None,
                target_entity_id=tgt, properties=props,
                event_time=t0 + dt.timedelta(seconds=k)), APP)

    def test_matches_generic_reader(self, col_store):
        from predictionio_tpu.data.pipeline import (
            interactions_from_columnar, read_interactions)

        store = col_store
        self._mixed_workload(store)
        spec = {"rate": "prop"}
        cols = store.scan_columnar(
            APP, entity_type="user", target_entity_type="item",
            event_names=["rate", "buy"], value_key="rating")
        fast = interactions_from_columnar(cols, spec, default_spec=4.0)

        import math

        from predictionio_tpu.data.store import _parse_value

        def value_fn(e):
            if e.event == "rate":
                v = _parse_value(e.properties.get("rating"))
                return v if v is not None and math.isfinite(v) else None
            return 4.0

        slow = read_interactions(
            lambda: store.find(APP, entity_type="user",
                               target_entity_type="item",
                               event_names=["rate", "buy"]),
            value_fn=value_fn)

        assert fast.n_events == slow.n_events
        assert list(fast.user_ids) == list(slow.user_ids)
        assert list(fast.item_ids) == list(slow.item_ids)
        fu, fi, fv = fast.arrays()
        su, si, sv = slow.arrays()
        assert (fu == su).all() and (fi == si).all()
        assert (fv == sv).all()

    def test_store_entry_point_both_paths(self, col_store, storage):
        """read_training_interactions dispatch: each scan_columnar
        provider (EVENTLOG, SQLITE) takes the fast path through the
        ENTRY POINT, MEMORY takes the generic path — identical."""
        store = col_store
        from predictionio_tpu.data.store import read_training_interactions

        a = storage.meta.create_app("ColApp")
        storage.events.init_channel(a.id)
        mem = storage.events

        t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        events = []
        for k in range(50):
            if k % 7 == 0:
                e = Event(event="buy", entity_type="user",
                          entity_id=f"u{k % 11}",
                          target_entity_type="item",
                          target_entity_id=f"i{k % 5}",
                          event_time=t0 + dt.timedelta(seconds=k))
            else:
                e = Event(event="rate", entity_type="user",
                          entity_id=f"u{k % 11}", target_entity_type="item",
                          target_entity_id=f"i{k % 5}",
                          properties={"rating": float(k % 5) + 0.5},
                          event_time=t0 + dt.timedelta(seconds=k))
            events.append(e)
        # same events into both stores; fix ids so overwrite semantics agree
        for e in events:
            e = e.with_id()
            store.insert(e, a.id)
            mem.insert(e, a.id)

        kw = dict(entity_type="user", target_entity_type="item",
                  event_names=["rate", "buy"], value_key="rating",
                  value_spec={"rate": "prop"}, default_spec=4.0,
                  storage=storage)
        generic = read_training_interactions("ColApp", **kw)
        storage._events = store  # swap the backend under the same app
        fast = read_training_interactions("ColApp", **kw)
        assert list(fast.user_ids) == list(generic.user_ids)
        assert list(fast.item_ids) == list(generic.item_ids)
        for (a1, b1) in zip(fast.arrays(), generic.arrays()):
            assert (a1 == b1).all()

    def test_event_groups_parity(self, col_store):
        store = col_store
        """Grouped multi-event read (Universal Recommender shape):
        columnar demux must equal the generic two-scan reader — same
        per-name pairs, same SHARED vocabulary pair, same order."""
        from predictionio_tpu.data.pipeline import (
            event_groups_from_columnar, read_event_groups)

        self._mixed_workload(store)
        names = ["rate", "buy", "view"]
        cols = store.scan_columnar(
            APP, entity_type="user", target_entity_type="item",
            event_names=names)
        f_pairs, f_u, f_i = event_groups_from_columnar(cols, names)
        s_pairs, s_u, s_i = read_event_groups(
            lambda: store.find(APP, entity_type="user",
                               target_entity_type="item",
                               event_names=names),
            names)
        assert list(f_u) == list(s_u) and list(f_i) == list(s_i)
        for n in names:
            assert (f_pairs[n][0] == s_pairs[n][0]).all(), n
            assert (f_pairs[n][1] == s_pairs[n][1]).all(), n
        assert f_pairs["view"][0].size == 1  # the one view event

    def test_times_us_microsecond_parity(self, col_store):
        """µs-precision parity: every scan_columnar provider must
        return the EXACT integer microsecond timestamps — the same
        expected array pins all three backends (EVENTLOG, SQLITE, ES)
        to bit-identical ``times_us``. Regression for the ES float-
        second epoch field, which rounded sub-second times (≈0.5 µs
        spacing) until the exact ``eventTimeUs`` doc field landed."""
        import numpy as np

        store = col_store
        stamps = [
            "2026-01-02T03:04:05Z",             # whole second
            "2026-01-02T03:04:05.123Z",         # millis
            "2026-01-02T03:04:05.123456Z",      # full micros
            "2026-01-02T03:04:05.123457Z",      # 1 µs later — must differ
            "2026-01-02T03:04:05.000001Z",      # 1 µs past the second
            "2026-01-02T08:34:05.999999+05:30", # tz-shifted, .999999
        ]
        from predictionio_tpu.data.event import parse_event_time

        for k, s in enumerate(stamps):
            store.insert(Event(
                event="rate", entity_type="user", entity_id=f"u{k}",
                target_entity_type="item", target_entity_id=f"i{k}",
                properties={"rating": 1.0},
                event_time=parse_event_time(s)), APP)
        cols = store.scan_columnar(
            APP, entity_type="user", target_entity_type="item",
            event_names=["rate"], value_key="rating")
        epoch = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)
        want = np.sort(np.asarray(
            [int((parse_event_time(s) - epoch).total_seconds() * 1e6
                 + 0.5) for s in stamps], np.int64))
        got = np.sort(np.asarray(cols.times_us, np.int64))
        assert got.dtype == np.int64
        np.testing.assert_array_equal(got, want)
        # the two 1-µs-apart events stayed distinct (the old float-
        # second ES field collapsed them)
        assert len(np.unique(got)) == len(stamps)


class TestNativeJsonlImport:
    """`pio import` NDJSON parity: the C++ fast path must produce
    events FIELD-IDENTICAL to the Python Event.from_json path (modulo
    generated eventId / creationTime), fall back on anything unusual,
    and surface Python's validation errors for invalid lines."""

    LINES = [
        '{"event":"rate","entityType":"user","entityId":"u1",'
        '"targetEntityType":"item","targetEntityId":"i1",'
        '"properties":{"rating":4.5},"eventTime":"2026-01-02T03:04:05Z"}',
        '',  # blank → skipped
        '{"event":"buy","entityType":"user","entityId":"u\\u221e",'
        '"targetEntityType":"item","targetEntityId":"i☂",'
        '"eventTime":"2026-01-02T03:04:05.5+05:30"}',
        '{"event":"view","entityType":"user","entityId":"u2",'
        '"targetEntityType":"item","targetEntityId":"i2",'
        '"eventTime":"2026-01-02T03:04:05.123456-08:00",'
        '"tags":["a","b"],"prId":"pr-9"}',
        '{"event":"note","entityType":"user","entityId":"u3",'
        '"properties":{"nested":{"k":[1,2]},"s":"q\\"uote"},'
        '"eventTime":"2026-01-02 03:04:05"}',  # space sep, no tz
        '{"event":"$set","entityType":"user","entityId":"u4",'
        '"properties":{"plan":"pro"},'
        '"eventTime":"2026-01-03T00:00:00Z"}',  # $-event → fallback
        '{"eventId":"deadbeefdeadbeefdeadbeefdeadbeef","event":"pin",'
        '"entityType":"user","entityId":"u5",'
        '"eventTime":"2026-01-04T00:00:00+00:00"}',
    ]

    def _import(self, store, text):
        import io

        from predictionio_tpu.tools.export_import import import_events

        class _St:
            events = store

        return import_events(APP, io.StringIO(text), storage=_St())

    def test_field_parity_with_python_path(self, store):
        from predictionio_tpu.data.event import Event
        import json as _json

        n = self._import(store, "\n".join(self.LINES) + "\n")
        assert n == 6  # 7 lines minus the blank
        native = sorted(store.find(APP),
                        key=lambda e: (e.event_time, e.event))
        ref = sorted((Event.from_json(_json.loads(l))
                      for l in self.LINES if l),
                     key=lambda e: (e.event_time, e.event))
        assert len(native) == len(ref) == 6
        for a, b in zip(native, ref):
            assert a.event == b.event
            assert a.entity_type == b.entity_type
            assert a.entity_id == b.entity_id
            assert a.target_entity_type == b.target_entity_type
            assert a.target_entity_id == b.target_entity_id
            assert a.properties == b.properties
            assert a.tags == b.tags
            assert a.pr_id == b.pr_id
            assert a.event_time == b.event_time  # µs-exact incl. tz
        # explicit eventId preserved
        assert store.get("deadbeefdeadbeefdeadbeefdeadbeef", APP) is not None
        # every generated id is unique
        ids = [e.event_id for e in native]
        assert len(set(ids)) == len(ids)

    def test_invalid_lines_raise_python_errors(self, store):
        import json as _json

        from predictionio_tpu.data.event import EventValidationError

        with pytest.raises(EventValidationError):
            self._import(store, '{"event":"x","entityType":"user",'
                                '"entityId":"u","bogusField":1}\n')
        with pytest.raises(EventValidationError):
            self._import(store, '{"event":"x","entityType":"user"}\n')
        with pytest.raises(EventValidationError):  # one-sided target
            self._import(store, '{"event":"x","entityType":"u",'
                                '"entityId":"1","targetEntityId":"i"}\n')
        with pytest.raises(EventValidationError):  # bad timestamp
            self._import(store, '{"event":"x","entityType":"u",'
                                '"entityId":"1","eventTime":"yesterday"}\n')
        # NOTHING the strict C++ grammar accepts may be a line Python
        # rejects (r5 review: each of these was once natively accepted
        # — the first POISONED every later read of the namespace)
        for bad in (
            '{"event":"e","entityType":"user","entityId":"u1",'
            '"properties":{"a":}}',                    # malformed nested
            '{"event":"e","entityType":"u","entityId":"a\\uZZZZ"}',
            '{"event":"e","entityType":"user","entityId":"u1"}GARBAGE',
            '{"event":"e","entityType":"u","entityId":"1" "prId":"x"}',
            '{"event":"e","entityType":"u","entityId":"1",'
            '"eventTime":"2026-02-30T00:00:00Z"}',     # nonexistent date
            '{"event":"e","entityType":"u","entityId":"1",'
            '"properties":{"n":01}}',                  # leading zero
        ):
            with pytest.raises((_json.JSONDecodeError,
                                EventValidationError)):
                self._import(store, bad + "\n")
        # a LONE surrogate escape: json.loads accepts it but the
        # Python serialize path dies at utf-8 encode — the native path
        # must fall back (it once emitted raw surrogate bytes into the
        # frame, making the whole namespace unreadable)
        with pytest.raises(UnicodeEncodeError):
            self._import(store, '{"event":"e","entityType":"u",'
                                '"entityId":"a\\ud800"}\n')
        # and the store must still read back cleanly afterwards
        assert list(store.find(APP)) == []

    def test_formfeed_only_lines_are_blank(self, store):
        """Lines that strip() to empty but aren't space/tab (\\f, \\xa0)
        were silently skipped by the legacy loop — same here."""
        n = self._import(store,
                         '{"event":"e","entityType":"u","entityId":"1"}\n'
                         '\f\n\xa0\n'
                         '{"event":"e","entityType":"u","entityId":"2"}\n')
        assert n == 2
        assert len(list(store.find(APP))) == 2

    def test_py310_incompatible_timestamps_fall_back(self, store):
        """Timestamp shapes Python 3.10's fromisoformat rejects (±HHMM
        offset, 1-digit fraction) must NOT be consumed natively — on
        this interpreter the fallback parses them, on 3.10 it raises;
        either way the native path never decides."""
        import json as _json

        from predictionio_tpu.data.event import Event

        lines = ['{"event":"e","entityType":"u","entityId":"1",'
                 '"eventTime":"2026-01-02T03:04:05.5+05:30"}',
                 '{"event":"e","entityType":"u","entityId":"2",'
                 '"eventTime":"2026-01-02T03:04:05+0530"}']
        n = self._import(store, "\n".join(lines) + "\n")
        assert n == 2
        got = sorted(store.find(APP), key=lambda e: e.entity_id)
        ref = sorted((Event.from_json(_json.loads(l)) for l in lines),
                     key=lambda e: e.entity_id)
        for a, b in zip(got, ref):
            assert a.event_time == b.event_time

    def test_export_reimport_native_parity(self, store, tmp_path):
        """Re-importing this tool's own export (every line carries
        eventId + creationTime) must match what Event.from_json makes
        of the same lines, field for field INCLUDING creationTime —
        i.e. the export shape stays on the native path and parses
        identically to the Python path."""
        import io
        import json as _json

        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.filestore import NativeEventLogStore
        from predictionio_tpu.tools.export_import import (export_events,
                                                          import_events)

        self._import(store, "\n".join(l for l in self.LINES if l))
        out = io.StringIO()
        export_events(APP, out, storage=type("S", (), {"events": store}))
        lines = [l for l in out.getvalue().splitlines() if l]

        store2 = NativeEventLogStore(str(tmp_path / "reimport"))
        out.seek(0)
        n = import_events(APP, out, storage=type("S", (), {"events": store2}))
        assert n == len(lines) == 6
        got = {e.event_id: e for e in store2.find(APP)}
        ref = {e.event_id: e
               for e in (Event.from_json(_json.loads(l)) for l in lines)}
        assert got.keys() == ref.keys()
        for k, a in got.items():
            b = ref[k]
            for f in ("event", "entity_type", "entity_id",
                      "target_entity_type", "target_entity_id",
                      "properties", "tags", "pr_id", "event_time",
                      "creation_time"):
                assert getattr(a, f) == getattr(b, f), (k, f)
        store2.close()

    def test_import_then_train_read(self, store):
        """Imported events feed the columnar training read correctly."""
        lines = []
        for k in range(500):
            lines.append(
                '{"event":"rate","entityType":"user","entityId":"u%d",'
                '"targetEntityType":"item","targetEntityId":"i%d",'
                '"properties":{"rating":%d}}' % (k % 20, k % 12, k % 5 + 1))
        n = self._import(store, "\n".join(lines))
        assert n == 500
        cols = store.scan_columnar(APP, entity_type="user",
                                   target_entity_type="item",
                                   event_names=["rate"],
                                   value_key="rating")
        assert cols.n == 500
        assert np.isfinite(cols.values).all()
        assert set(cols.names) == {"rate"}

    def test_duplicate_property_keys_fall_back(self, store):
        """json.loads keeps the LAST duplicate key; the C++ scanner's
        first-match property extraction would keep the FIRST — so a
        line with duplicate keys must never be consumed natively.
        (json.dumps can't emit duplicates; the lines are hand-built.)"""
        import json as _json

        line = ('{"event":"rate","entityType":"user","entityId":"u1",'
                '"targetEntityType":"item","targetEntityId":"i1",'
                '"properties":{"rating":1,"rating":2},'
                '"eventTime":"2026-01-02T03:04:05Z"}')
        assert _json.loads(line)["properties"] == {"rating": 2}
        n = self._import(store, line + "\n")
        assert n == 1
        evs = list(store.find(APP))
        assert len(evs) == 1
        assert evs[0].properties == {"rating": 2}  # last wins, as Python
        cols = store.scan_columnar(APP, value_key="rating")
        assert cols.values.tolist() == [2.0]

    def test_duplicate_top_level_keys_fall_back(self, store):
        import json as _json

        line = ('{"event":"rate","entityType":"user","entityId":"u1",'
                '"entityId":"u2","eventTime":"2026-01-02T03:04:05Z"}')
        assert _json.loads(line)["entityId"] == "u2"
        n = self._import(store, line + "\n")
        assert n == 1
        evs = list(store.find(APP))
        assert len(evs) == 1 and evs[0].entity_id == "u2"

    def test_escaped_key_duplicates_detected(self, store):
        """Duplicate detection must compare UNESCAPED key text:
        "\\u0072ating" and "rating" are the same key."""
        line = ('{"event":"rate","entityType":"user","entityId":"u1",'
                '"properties":{"\\u0072ating":1,"rating":2},'
                '"eventTime":"2026-01-02T03:04:05Z"}')
        n = self._import(store, line + "\n")
        assert n == 1
        evs = list(store.find(APP))
        assert evs[0].properties == {"rating": 2}

    def test_distinct_keys_stay_native(self, store):
        """Non-duplicate multi-key objects must not be rejected by the
        duplicate check (no false positives)."""
        line = ('{"event":"rate","entityType":"user","entityId":"u1",'
                '"properties":{"rating":1,"rating2":2,"ratin":3},'
                '"eventTime":"2026-01-02T03:04:05Z"}')
        n = self._import(store, line + "\n")
        assert n == 1
        evs = list(store.find(APP))
        assert evs[0].properties == {"rating": 1, "rating2": 2, "ratin": 3}

    def test_batch_creation_times_strictly_increase(self, store):
        """Defaulted creationTimes within one import batch must be
        distinct and follow line order (now_us + line index), and a
        back-to-back second batch must not collide with the first —
        the snapshot cache's watermark math needs creationTime to be
        a usable tiebreaker, not a pile of equal timestamps."""
        def batch(tag, k):
            return "\n".join(
                '{"event":"e","entityType":"u","entityId":"%s%d"}' % (tag, i)
                for i in range(k)) + "\n"

        self._import(store, batch("a", 50))
        self._import(store, batch("b", 50))
        evs = sorted(store.find(APP), key=lambda e: e.creation_time)
        times = [e.creation_time for e in evs]
        assert len(set(times)) == 100  # all distinct
        order = [e.entity_id for e in evs]
        assert order == [f"a{i}" for i in range(50)] + \
            [f"b{i}" for i in range(50)]


class TestNativeJsonlExport:
    """`pio export` native parity: every line must json-loads-equal
    what Event.to_json_str would emit for the same event (key order,
    ms-truncated +00:00 timestamps, omitted-empty fields), across the
    cursor-chunk boundary."""

    def test_loads_equal_with_python_export(self, store):
        import io
        import json as _json

        from predictionio_tpu.tools.export_import import export_events

        t0 = dt.datetime(2026, 2, 3, 4, 5, 6, 789123,
                         tzinfo=dt.timezone.utc)
        evs = [
            Event(event="rate", entity_type="user", entity_id="u∞",
                  target_entity_type="item", target_entity_id='i"q',
                  properties={"rating": 4.5, "nested": {"a": [1, None]}},
                  event_time=t0),
            Event(event="note", entity_type="user", entity_id="u2",
                  properties={}, tags=["a", "b\\c"], pr_id="pr-1",
                  event_time=t0 + dt.timedelta(seconds=1)),
            Event(event="plain", entity_type="t", entity_id="x",
                  event_time=t0 + dt.timedelta(seconds=2,
                                               microseconds=999)),
        ]
        store.insert_batch(evs, APP)

        out = io.StringIO()
        n = export_events(APP, out, storage=type("S", (), {"events": store}))
        assert n == 3
        native_lines = [l for l in out.getvalue().splitlines() if l]
        ref_lines = [e.to_json_str() for e in store.find(APP)]
        assert len(native_lines) == len(ref_lines) == 3
        for a, b in zip(native_lines, ref_lines):
            da, db = _json.loads(a), _json.loads(b)
            assert da == db
            # key ORDER parity too (consumers may stream-parse)
            assert list(da) == list(db)

    def test_chunk_boundary_and_reimport(self, store, tmp_path):
        import io
        import json as _json

        from predictionio_tpu.data.filestore import NativeEventLogStore
        from predictionio_tpu.tools.export_import import (export_events,
                                                          import_events)

        t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        store.insert_batch(
            [Event(event="e", entity_type="u", entity_id=str(k),
                   target_entity_type="i", target_entity_id=str(k % 7),
                   event_time=t0 + dt.timedelta(seconds=k))
             for k in range(257)], APP)
        chunks = list(store.iter_jsonl_chunks(APP, chunk_events=100))
        assert len(chunks) == 3  # 100 + 100 + 57
        text = "".join(chunks)
        assert text.count("\n") == 257

        s2 = NativeEventLogStore(str(tmp_path / "re"))
        n = import_events(APP, io.StringIO(text),
                          storage=type("S", (), {"events": s2}))
        assert n == 257
        a = [e.event_id for e in store.find(APP)]
        b = [e.event_id for e in s2.find(APP)]
        assert a == b
        s2.close()


def test_universal_workflow_on_eventlog(tmp_path):
    """Universal Recommender end-to-end on the C++ event log: the
    grouped columnar read feeds the real run_train → prepare_deploy →
    query path (the r5 verify flow, cemented as suite coverage)."""
    import numpy as np

    from predictionio_tpu.core.workflow import prepare_deploy, run_train
    from predictionio_tpu.storage.registry import (Storage, StorageConfig,
                                                   set_storage)

    st = Storage(StorageConfig(metadata_type="MEMORY",
                               modeldata_type="MEMORY",
                               eventdata_type="EVENTLOG",
                               home=str(tmp_path)))
    try:
        st.events  # builds the C++ engine (skip when no g++)
    except RuntimeError as e:
        pytest.skip(str(e))
    set_storage(st)
    a = st.meta.create_app("URLog")
    st.events.init_channel(a.id)
    rng = np.random.default_rng(2)
    st.events.insert_batch([
        Event(event=["buy", "view", "view", "like"][k % 4],
              entity_type="user",
              entity_id=f"u{int(rng.integers(0, 40))}",
              target_entity_type="item",
              target_entity_id=f"i{int(rng.integers(0, 30))}")
        for k in range(1200)], a.id)
    factory = "predictionio_tpu.templates.universal.engine:engine_factory"
    variant = {"id": "default", "engineFactory": factory,
               "datasource": {"params": {
                   "appName": "URLog",
                   "eventNames": ["buy", "view", "like"]}},
               "algorithms": [{"name": "ur",
                               "params": {"maxIndicatorsPerItem": 20}}]}
    try:
        iid = run_train(factory, variant=variant, storage=st,
                        use_mesh=False)
        eng = prepare_deploy(factory, instance_id=iid, storage=st)
        out = eng.query({"user": "u3", "num": 5})
        assert out["itemScores"], "UR query must return scored items"
    finally:
        st.events.close()
        set_storage(None)


class TestImportFuzzParity:
    """Randomized check of the strict-narrower contract: for ANY line,
    if the native path consumed it, the Python path must also accept
    it and produce the same event fields; if Python rejects a line,
    the native path must not have consumed it. Seeded → deterministic."""

    def test_random_event_lines(self, store, tmp_path):
        import io
        import json as _json
        import random

        from predictionio_tpu.data.event import (Event,
                                                  EventValidationError)
        from predictionio_tpu.data.filestore import NativeEventLogStore
        from predictionio_tpu.tools.export_import import import_events

        rnd = random.Random(77)
        names = ["rate", "buy", "$set", "$unset", "$delete", "e-x", "вид"]
        ids = ["u1", "ü", "a b", 'q"t', "x\\y", "", "0", "日本", "a\tb"]
        props_pool = [{}, {"rating": 4.5}, {"rating": "3"},
                      {"rating": "bad"}, {"n": {"d": [1, None]}},
                      {"s": 'esc"\\'}, {"rating": True}]
        times = ["2026-01-02T03:04:05Z", "2026-01-02T03:04:05.123Z",
                 "2026-13-01T00:00:00Z", "2026-02-30T00:00:00Z",
                 "2026-01-02 03:04:05", "bogus", "2026-01-02T03:04:05+0230",
                 "2026-01-02T03:04:05.123456-08:00", None]
        lines = []
        for k in range(400):
            d = {"event": rnd.choice(names),
                 "entityType": rnd.choice(["user", "item", ""]),
                 "entityId": rnd.choice(ids)}
            if rnd.random() < 0.7:
                d["targetEntityType"] = rnd.choice(["item", ""])
                d["targetEntityId"] = rnd.choice(ids)
            elif rnd.random() < 0.2:
                d["targetEntityId"] = "half"   # one-sided
            if rnd.random() < 0.6:
                d["properties"] = rnd.choice(props_pool)
            t = rnd.choice(times)
            if t is not None:
                d["eventTime"] = t
            if rnd.random() < 0.3:
                d["prId"] = rnd.choice(["pr-1", "", 5, "ü"])
            if rnd.random() < 0.2:
                d["eventId"] = rnd.choice(
                    ["deadbeefdeadbeefdeadbeefdeadbeef", "", 0, "short"])
            if rnd.random() < 0.2:
                d["tags"] = rnd.choice([[], ["a"], ["a", 'b"c']])
            if rnd.random() < 0.15:
                d["creationTime"] = rnd.choice(
                    ["2026-01-01T00:00:00.500Z", "nope", ""])
            if rnd.random() < 0.1:
                d["bogus"] = 1
            line = _json.dumps(d, ensure_ascii=rnd.random() < 0.5)
            if rnd.random() < 0.05:
                line = line + "garbage"          # corrupt some lines
            lines.append((line, d))

        for i, (line, d) in enumerate(lines):
            s = NativeEventLogStore(str(tmp_path / f"fz{i}"))
            try:
                # what does Python say?
                try:
                    ref = Event.from_json(_json.loads(line))
                except (ValueError, EventValidationError):
                    ref = None
                try:
                    n = import_events(APP, io.StringIO(line + "\n"),
                                      storage=type("S", (),
                                                   {"events": s}))
                except (ValueError, EventValidationError,
                        _json.JSONDecodeError):
                    n = -1  # import raised (must mean Python rejects)
                if ref is None:
                    assert n <= 0, (line, "native accepted what "
                                          "Python rejects")
                else:
                    assert n == 1, (line, "both should accept")
                    got = next(iter(s.find(APP)))
                    assert got.event == ref.event, line
                    assert got.entity_id == ref.entity_id, line
                    assert got.target_entity_type == \
                        ref.target_entity_type, line
                    assert got.target_entity_id == \
                        ref.target_entity_id, line
                    assert got.properties == ref.properties, line
                    assert got.tags == ref.tags, line
                    assert got.pr_id == ref.pr_id, line
                    if "eventTime" in d:
                        assert got.event_time == ref.event_time, line
                    if d.get("creationTime"):
                        assert got.creation_time == ref.creation_time, line
            finally:
                s.close()

    def test_duplicate_keys_last_wins(self, tmp_path):
        """Duplicate JSON keys — ``json.dumps`` can never emit them, so
        the random fuzz above is blind to this grammar corner. Python's
        ``json.loads`` keeps the LAST occurrence; the native parser
        must agree on every field it narrows (fixed in the native
        eventlog parser; this pins the behavior)."""
        import io
        import json as _json

        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.filestore import NativeEventLogStore
        from predictionio_tpu.tools.export_import import import_events

        lines = [
            # top-level dup: event name, last value wins
            '{"event": "rate", "event": "buy", "entityType": "user", '
            '"entityId": "u1"}',
            # dup entityId, including one non-string earlier occurrence
            '{"event": "rate", "entityType": "user", "entityId": "old", '
            '"entityId": "new"}',
            # dup eventTime: first invalid, last valid (accept) and the
            # reverse (reject) — narrowing must use the surviving value
            '{"event": "e", "entityType": "u", "entityId": "x", '
            '"eventTime": "bogus", "eventTime": "2026-01-02T03:04:05Z"}',
            '{"event": "e", "entityType": "u", "entityId": "x", '
            '"eventTime": "2026-01-02T03:04:05Z", "eventTime": "bogus"}',
            # dup inside properties objects
            '{"event": "e", "entityType": "u", "entityId": "x", '
            '"properties": {"rating": 1.5, "rating": 4.5}}',
            # the whole properties object duplicated
            '{"event": "e", "entityType": "u", "entityId": "x", '
            '"properties": {"a": 1}, "properties": {"b": 2}}',
            # dup targetEntityId where the first would be one-sided
            '{"event": "e", "entityType": "u", "entityId": "x", '
            '"targetEntityType": "item", "targetEntityId": "t1", '
            '"targetEntityId": "t2"}',
        ]
        for i, line in enumerate(lines):
            s = NativeEventLogStore(str(tmp_path / f"dup{i}"))
            try:
                try:
                    ref = Event.from_json(_json.loads(line))
                except ValueError:
                    ref = None
                try:
                    n = import_events(APP, io.StringIO(line + "\n"),
                                      storage=type("S", (), {"events": s}))
                except ValueError:
                    n = -1
                if ref is None:
                    assert n <= 0, (line, "native accepted what Python "
                                          "rejects")
                else:
                    assert n == 1, (line, "both should accept")
                    got = next(iter(s.find(APP)))
                    assert got.event == ref.event, line
                    assert got.entity_id == ref.entity_id, line
                    assert got.target_entity_id == ref.target_entity_id, \
                        line
                    assert got.properties == ref.properties, line
                    if '"eventTime"' in line:  # else defaults to now()
                        assert got.event_time == ref.event_time, line
            finally:
                s.close()
