"""TPU kernels and compute primitives for the hot ops.

The reference delegates its hot math to Spark MLlib → netlib BLAS
(SURVEY.md §2b); here the equivalents are XLA programs plus hand-written
Pallas TPU kernels for the ops where fusion/streaming matters:

- :mod:`.gram` — batched weighted Gram accumulation (the ALS inner op).
- :mod:`.topk` — streaming score+top-k over item tiles (serving path).
- :mod:`.segment` — segment reductions (Naive Bayes, CCO counts).

Every Pallas kernel has an XLA fallback; ``use_pallas()`` decides by
backend (compiled on TPU, XLA elsewhere, interpret-mode in tests).
"""

from predictionio_tpu.ops.gram import (gather_gram, gather_gram_xla,
                                       resolve_gram_mode, rows_gram,
                                       rows_gram_xla)
from predictionio_tpu.ops.segment import segment_count, segment_mean, segment_sum
from predictionio_tpu.ops.topk import (adc_scores, adc_shortlist,
                                       merge_shortlists, rerank_partial,
                                       rerank_topk, score_topk,
                                       score_topk_xla)


def use_pallas(platform=None) -> bool:
    """Compiled Pallas kernels only make sense on real TPU backends.

    ``platform`` is the platform the trace will actually run on (pass
    the mesh's / target device's ``.platform``); when None the default
    backend decides — callers compiling for an explicit device or mesh
    must pass it, because ``jax.default_backend()`` can differ from the
    execution platform (e.g. CPU mesh under a tunneled-TPU backend).
    ``PIO_NO_PALLAS=1`` forces the XLA fallbacks (A/B benching, triage).
    """
    import os

    if os.environ.get("PIO_NO_PALLAS"):
        return False
    if platform is None:
        import jax

        platform = jax.default_backend()
    return platform == "tpu"


__all__ = [
    "adc_scores", "adc_shortlist", "gather_gram", "gather_gram_xla",
    "merge_shortlists", "rerank_partial", "rerank_topk",
    "resolve_gram_mode",
    "rows_gram", "rows_gram_xla", "score_topk", "score_topk_xla",
    "segment_sum", "segment_count", "segment_mean", "use_pallas",
]
