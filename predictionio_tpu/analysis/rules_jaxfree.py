"""PL02 — jax-free import closure for ops-box CLI verbs.

``tools/cli.py`` declares ``_JAX_VERBS`` — the verbs whose command path
is allowed to import jax. Every OTHER verb is documented to work on a
jax-less ops box (``pio models``/``variants``/``index``/``fsck``/
``lint`` …), which means the modules its ``cmd_*`` function imports —
plus everything THOSE import at module scope, transitively — must never
reach ``jax``/``jaxlib``.

The check therefore:

1. parses ``build_parser()`` to map each verb to its ``cmd_*`` function
   (``x = sub.add_parser("verb", …)`` followed by
   ``x.set_defaults(fn=cmd_verb)``);
2. closes each non-jax verb's command function over the *local* call
   graph inside cli.py (helpers like ``_http_json`` or
   ``_configure_tracing`` contribute their lazy imports too);
3. collects every module imported anywhere inside those functions, and
4. walks each one's **module-scope** import closure (shared
   :class:`~predictionio_tpu.analysis.imports.ImportGraph`) looking for
   a chain that ends at jax/jaxlib. Function-local imports inside the
   closure are invisible by construction — the lazy-import idiom in
   ``ann/__init__.py`` is exactly the allowed escape hatch.

The cli module's own module-scope imports are checked the same way:
``pio --help`` must not pay a jax import either.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from predictionio_tpu.analysis.core import (
    Finding,
    Project,
    SourceModule,
    call_name,
    const_str,
)
from predictionio_tpu.analysis.imports import (
    imports_of_statement,
    resolve_from_base,
)

RULE = "PL02"
_JAX_TOPS = {"jax", "jaxlib"}


def _jax_verbs(cli: SourceModule) -> Set[str]:
    """Literal ``_JAX_VERBS = {...}`` set, empty when absent."""
    for node in cli.tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "_JAX_VERBS"
                        for t in node.targets)
                and isinstance(node.value, (ast.Set, ast.Tuple, ast.List))):
            return {s for e in node.value.elts
                    if (s := const_str(e)) is not None}
    return set()


def _verb_map(cli: SourceModule) -> Dict[str, str]:
    """verb → cmd function name, from the add_parser/set_defaults idiom
    anywhere in the module (normally inside ``build_parser``)."""
    var_verb: Dict[str, str] = {}
    verbs: Dict[str, str] = {}
    for node in ast.walk(cli.tree):
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and call_name(node.value) == "add_parser"
                and node.value.args):
            verb = const_str(node.value.args[0])
            if verb:
                var_verb[node.targets[0].id] = verb
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "set_defaults"
              and isinstance(node.func.value, ast.Name)):
            verb = var_verb.get(node.func.value.id)
            if verb is None:
                continue
            for kw in node.keywords:
                if kw.arg == "fn" and isinstance(kw.value, ast.Name):
                    verbs[verb] = kw.value.id
    return verbs


def _local_functions(cli: SourceModule) -> Dict[str, ast.AST]:
    return {n.name: n for n in cli.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _reachable_locals(entry: str, funcs: Dict[str, ast.AST]) -> Set[str]:
    """Fixpoint over the intra-module call graph: every local function
    reachable from ``entry`` by plain-name calls or references."""
    seen: Set[str] = set()
    todo = [entry]
    while todo:
        name = todo.pop()
        if name in seen or name not in funcs:
            continue
        seen.add(name)
        for node in ast.walk(funcs[name]):
            if isinstance(node, ast.Name) and node.id in funcs:
                todo.append(node.id)
    return seen


def _function_imports(fn: ast.AST, cli: SourceModule,
                      project: Project) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            out.extend(imports_of_statement(node, cli, project))
        elif (isinstance(node, ast.Call)
              and call_name(node) in ("import_module",)
              and node.args):
            s = const_str(node.args[0])
            if s:
                out.append((s, node.lineno))
    return out


def _closure_finding(project: Project, cli: SourceModule, root_mod: str,
                     line: int, context: str) -> Optional[Finding]:
    graph = project.import_graph()
    top = root_mod.split(".")[0]
    if top in _JAX_TOPS:
        chain: Optional[List[str]] = [root_mod]
    elif root_mod in project.modules or top == project.package:
        target = root_mod if root_mod in project.modules else None
        if target is None:
            # from X import attr resolved to a non-module: walk up
            name = root_mod
            while name and name not in project.modules:
                name = name.rsplit(".", 1)[0] if "." in name else ""
            target = name or None
        if target is None:
            return None
        chain = graph.external_path(target, _JAX_TOPS)
    else:
        return None  # external, non-jax (stdlib, numpy, …)
    if chain is None:
        return None
    return Finding(
        RULE, cli.relpath, line, f"{context}:{root_mod}",
        f"{context} reaches jax through module-scope imports: "
        + " -> ".join(chain)
        + " — break the chain or make the jax import lazy "
          "(function-local), like ann/__init__.py does")


def check(project: Project) -> List[Finding]:
    cli = project.get(f"{project.package}.tools.cli")
    if cli is None:
        return []
    out: List[Finding] = []

    # the CLI module itself: module-scope closure must be jax-free
    for name, line in (project.import_graph()
                       .internal[cli.name]
                       + project.import_graph().external[cli.name]):
        f = _closure_finding(project, cli, name, line, "cli-startup")
        if f:
            out.append(f)

    jax_verbs = _jax_verbs(cli)
    funcs = _local_functions(cli)
    for verb, fn_name in sorted(_verb_map(cli).items()):
        if verb in jax_verbs or fn_name not in funcs:
            continue
        for local in sorted(_reachable_locals(fn_name, funcs)):
            for mod_name, line in _function_imports(
                    funcs[local], cli, project):
                f = _closure_finding(project, cli, mod_name, line,
                                     f"verb '{verb}'")
                if f:
                    out.append(f)
    # one verb importing a jax-bound module can be reached through many
    # helpers; identical keys collapse to one finding
    uniq: Dict[str, Finding] = {}
    for f in out:
        uniq.setdefault(f.key, f)
    return list(uniq.values())
