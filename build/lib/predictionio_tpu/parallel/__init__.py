from predictionio_tpu.parallel.mesh import (
    MeshConfig,
    make_mesh,
    shard_batch,
    replicated,
)
from predictionio_tpu.parallel.ring_attention import (
    attention_reference,
    ring_attention,
)
from predictionio_tpu.parallel.ulysses import ulysses_attention

__all__ = [
    "MeshConfig", "make_mesh", "shard_batch", "replicated",
    "attention_reference", "ring_attention", "ulysses_attention",
]
