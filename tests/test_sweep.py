"""Distributed `pio eval` sweep (core/sweep.py + storage/leaderboard.py).

Covers the tentpole contract end to end: vmapped-vs-serial parity
(identical rankings, scores within fp tolerance), the uneven tail
bucket, a NaN-scoring candidate ranking last without poisoning the
sweep, compiles ≤ geometry buckets, the persisted leaderboard
artifact, the FAILED row recording the exception, the jax-free
``pio evals`` verbs, and the trainer's ``--gate eval``.
"""

import datetime as dt
import json
import math
import os
import subprocess
import sys
from dataclasses import dataclass

import numpy as np
import pytest

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineParams,
    Evaluation,
    FirstServing,
    IdentityPreparator,
)
from predictionio_tpu.controller.base import WorkflowContext
from predictionio_tpu.controller.evaluation import Metric
from predictionio_tpu.core.sweep import SweepProgram, run_sweep
from predictionio_tpu.core.workflow import run_evaluation
from predictionio_tpu.storage import leaderboard as lb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- a transparent toy engine: model is y = scale * x --------------------------


@dataclass
class ToyDSParams:
    n: int = 40
    eval_k: int = 2


@dataclass
class ToyData:
    x: np.ndarray
    y: np.ndarray


class ToyDS(DataSource):
    ParamsClass = ToyDSParams

    def _all(self):
        rng = np.random.default_rng(7)
        x = rng.normal(1.0, 0.5, self.params.n).astype(np.float32)
        y = (3.0 * x).astype(np.float32)
        return x, y

    def read_training(self, ctx):
        return ToyData(*self._all())

    def read_eval(self, ctx):
        x, y = self._all()
        folds = []
        k = self.params.eval_k
        for f in range(k):
            tr = np.arange(len(x)) % k != f
            te = np.nonzero(~tr)[0]
            qa = [({"x": float(x[j])}, float(y[j])) for j in te]
            folds.append((ToyData(x[tr], y[tr]), {"fold": f}, qa))
        return folds


@dataclass
class ToyParams:
    scale: float = 1.0


class ToyAlgo(Algorithm):
    ParamsClass = ToyParams

    def train(self, ctx, pd):
        return {"scale": float(self.params.scale)}

    @classmethod
    def sweep_programs(cls, ctx, pd, params_list, qa, metric):
        if getattr(metric, "sweep_kind", None) != "sq_err":
            return None
        import jax.numpy as jnp

        xe = np.asarray([q["x"] for q, _ in qa], np.float32)
        ye = np.asarray([a for _, a in qa], np.float32)

        def build():
            def one(hyper, xe, ye):
                err = hyper[0] * xe - ye
                return (err * err).sum(), jnp.asarray(
                    xe.shape[0], jnp.float32)
            return one

        hyper = np.asarray([[p.scale] for p in params_list], np.float32)
        return [SweepProgram(("toy", xe.shape), build, hyper,
                             (xe, ye), list(range(len(params_list))))]

    def predict(self, model, query):
        return {"y": model["scale"] * query["x"]}


class PlainAlgo(ToyAlgo):
    """Same model, but NO usable sweep program — forces the serial
    fallback for its whole group (the mixed-grid path)."""

    @classmethod
    def sweep_programs(cls, ctx, pd, params_list, qa, metric):
        return None


class ToyNegRMSE(Metric):
    sweep_kind = "sq_err"

    def calculate(self, ctx, eval_data):
        errs = [(p["y"] - a) ** 2
                for _, qpa in eval_data for q, p, a in qpa]
        return (-math.sqrt(sum(errs) / len(errs)) if errs
                else float("nan"))

    def sweep_finalize(self, stat_sum, stat_count):
        if stat_count <= 0:
            return float("nan")
        return -math.sqrt(stat_sum / stat_count)

    @property
    def header(self):
        return "ToyNegRMSE"


def toy_factory():
    return Engine(data_source_cls=ToyDS,
                  preparator_cls=IdentityPreparator,
                  algorithm_cls_map={"toy": ToyAlgo, "plain": PlainAlgo},
                  serving_cls=FirstServing)


class ToyEvaluation(Evaluation):
    engine_factory = staticmethod(toy_factory)
    metric = ToyNegRMSE()


def _toy_candidates(scales, algo="toy"):
    return [EngineParams(ToyDSParams(), None,
                         [(algo, ToyParams(scale=s))], None)
            for s in scales]


def _ctx(storage):
    return WorkflowContext(storage=storage, mesh=None, verbose=0)


class TestToySweep:
    def test_parity_uneven_tail(self, storage):
        """5 candidates pad to the next ladder width (8): the sweep's
        scores and ranking must equal the serial path's exactly."""
        scales = [0.5, 1.0, 2.0, 3.0, 4.0]
        sres = run_sweep(_ctx(storage), toy_factory(),
                         _toy_candidates(scales), ToyNegRMSE())
        assert sres.vmapped == 5 and sres.serial == 0
        assert sres.compiles <= sres.buckets <= 2  # one per fold
        iid, serial = run_evaluation(ToyEvaluation(),
                                     _toy_candidates(scales),
                                     storage=storage, use_mesh=False)
        for (_, ss, _), (_, ds, _) in zip(serial.candidates,
                                          sres.result.candidates):
            assert ds == pytest.approx(ss, abs=1e-5)
        assert sres.result.best_index == serial.best_index == 3

    def test_nan_candidate_ranks_last(self, storage, tmp_path):
        """A candidate whose program yields NaN must lose to every
        finite candidate on BOTH paths — and not poison the others."""
        storage.config.home = str(tmp_path)
        scales = [3.0, float("nan"), 1.0]
        iid_d, res_d = run_evaluation(
            ToyEvaluation(), _toy_candidates(scales), storage=storage,
            use_mesh=False, distributed=True)
        iid_s, res_s = run_evaluation(
            ToyEvaluation(), _toy_candidates(scales), storage=storage,
            use_mesh=False)
        for res in (res_d, res_s):
            assert res.best_index == 0
            assert math.isnan(res.candidates[1][1])
            assert not math.isnan(res.candidates[2][1])
        doc = lb.read(str(tmp_path), iid_d)
        by_index = {e["index"]: e for e in doc["entries"]}
        assert by_index[1]["rank"] == 2 and by_index[1]["score"] is None
        assert lb.digest(doc) == lb.digest(lb.read(str(tmp_path), iid_s))

    def test_mixed_grid_serial_fallback(self, storage):
        """toy (sweepable) + plain (sweep_programs → None) in one grid:
        the plain group falls back to eval_batch; scores still match
        the all-serial run."""
        cands = _toy_candidates([1.0, 3.0]) + \
            _toy_candidates([1.0, 3.0], algo="plain")
        sres = run_sweep(_ctx(storage), toy_factory(), cands, ToyNegRMSE())
        assert sres.vmapped == 2 and sres.serial == 2
        _, serial = run_evaluation(ToyEvaluation(), cands,
                                   storage=storage, use_mesh=False)
        for (_, ss, _), (_, ds, _) in zip(serial.candidates,
                                          sres.result.candidates):
            assert ds == pytest.approx(ss, abs=1e-5)

    def test_sweep_shards(self, storage):
        """shard_map over the 8 virtual CPU devices: same scores."""
        scales = [0.5, 1.0, 2.0, 3.0]
        base = run_sweep(_ctx(storage), toy_factory(),
                         _toy_candidates(scales), ToyNegRMSE())
        sh = run_sweep(_ctx(storage), toy_factory(),
                       _toy_candidates(scales), ToyNegRMSE(),
                       sweep_shards=4)
        assert sh.shards == 4
        for (_, bs, _), (_, ss, _) in zip(base.result.candidates,
                                          sh.result.candidates):
            assert ss == pytest.approx(bs, abs=1e-5)


# -- real templates through the sweep ------------------------------------------


def seed_classification(storage, app_name="SweepClsApp"):
    from predictionio_tpu.data.event import Event

    app = storage.meta.create_app(app_name)
    storage.events.init_channel(app.id)
    rng = np.random.default_rng(5)
    evs = []
    for i in range(120):
        label = i % 2
        base = [0.0, 0.0, 0.0] if label == 0 else [4.0, 4.0, 0.0]
        feats = rng.normal(base, 0.4)
        evs.append(Event(
            event="$set", entity_type="user", entity_id=f"u{i}",
            properties={"attr0": float(feats[0]),
                        "attr1": float(feats[1]),
                        "attr2": float(feats[2]), "label": label}))
    storage.events.insert_batch(evs, app.id)


class TestClassificationSweep:
    def test_eight_point_grid_parity_and_compiles(self, storage, tmp_path):
        """The CI smoke: an 8-point NB/LR grid — compiles ≤ buckets,
        identical ranking to the serial path, leaderboard persisted."""
        from predictionio_tpu.templates.classification.engine import (
            ClsEvaluation,
            DataSourceParams,
            LRAlgoParams,
            NBAlgoParams,
        )

        storage.config.home = str(tmp_path)
        seed_classification(storage)
        dsp = DataSourceParams(app_name="SweepClsApp", eval_k=2)
        cands = [EngineParams(dsp, None,
                              [("naive", NBAlgoParams(lambda_=l))], None)
                 for l in (0.25, 0.5, 1.0, 2.0, 4.0, 8.0)]
        cands += [EngineParams(dsp, None,
                               [("lr", LRAlgoParams(reg=r, iterations=40))],
                               None)
                  for r in (0.0, 0.01)]
        iid_s, res_s = run_evaluation(ClsEvaluation(), cands,
                                      storage=storage, use_mesh=False)
        iid_d, res_d = run_evaluation(ClsEvaluation(), cands,
                                      storage=storage, use_mesh=False,
                                      distributed=True)
        for (_, ss, _), (_, ds) in zip(
                res_s.candidates,
                [(c[0], c[1]) for c in res_d.candidates]):
            assert ds == pytest.approx(ss, abs=1e-5)
        doc = lb.read(str(tmp_path), iid_d)
        assert doc["mode"] == "distributed"
        assert doc["compiles"] <= doc["buckets"]
        assert doc["vmapped"] == len(cands) and doc["serial"] == 0
        assert lb.digest(doc) == lb.digest(lb.read(str(tmp_path), iid_s))
        vi = storage.meta.get_evaluation_instance(iid_d)
        assert vi.status == "EVALCOMPLETED"


class TestTextClassificationTemplate:
    def _seed(self, storage):
        from predictionio_tpu.data.event import Event

        app = storage.meta.create_app("TxtApp")
        storage.events.init_channel(app.id)
        pos = ["great movie loved it", "wonderful acting superb plot",
               "amazing fantastic film", "loved the cast great script"]
        neg = ["terrible movie hated it", "awful acting boring plot",
               "dreadful bad film", "hated the cast awful script"]
        evs = []
        for i in range(40):
            lab = i % 2
            text = (pos if lab else neg)[i % 4] + f" tok{i}"
            evs.append(Event(event="$set", entity_type="doc",
                             entity_id=f"d{i}",
                             properties={"text": text, "label": lab}))
        storage.events.insert_batch(evs, app.id)

    def test_hash_features_deterministic(self):
        from predictionio_tpu.templates.textclassification.engine import (
            HashingConfig,
            hash_features,
        )

        cfg = HashingConfig(hash_bits=8, ngrams=2)
        a = hash_features(["the quick brown fox"], cfg)
        b = hash_features(["the quick brown fox"], cfg)
        assert a.shape == (1, 256)
        np.testing.assert_array_equal(a, b)
        assert a.sum() == 7.0  # 4 unigrams + 3 bigrams

    def test_registered_in_gallery(self):
        from predictionio_tpu.templates import TEMPLATES

        assert TEMPLATES["textclassification"] == \
            "predictionio_tpu.templates.textclassification.engine"
        eng_json = os.path.join(
            REPO, "predictionio_tpu", "templates", "textclassification",
            "engine.json")
        spec = json.load(open(eng_json))
        assert "textclassification" in spec["engineFactory"]

    def test_sweep_parity(self, storage, tmp_path):
        from predictionio_tpu.templates.textclassification.engine import (
            TextDataSourceParams,
            TextEvaluation,
            TextLRParams,
            TextNBParams,
        )

        storage.config.home = str(tmp_path)
        self._seed(storage)
        ds = TextDataSourceParams(app_name="TxtApp", eval_k=2,
                                  hash_bits=9)
        cands = [EngineParams(ds, None,
                              [("naive", TextNBParams(lambda_=l))], None)
                 for l in (0.25, 1.0)]
        cands += [EngineParams(ds, None,
                               [("lr", TextLRParams(iterations=40,
                                                    reg=r))], None)
                  for r in (0.0, 0.01)]
        iid_s, res_s = run_evaluation(TextEvaluation(), cands,
                                      storage=storage, use_mesh=False)
        iid_d, res_d = run_evaluation(TextEvaluation(), cands,
                                      storage=storage, use_mesh=False,
                                      distributed=True)
        for (_, ss, _), (_, ds_, _) in zip(res_s.candidates,
                                           res_d.candidates):
            assert ds_ == pytest.approx(ss, abs=1e-5)
        assert lb.digest(lb.read(str(tmp_path), iid_s)) == \
            lb.digest(lb.read(str(tmp_path), iid_d))


# -- leaderboard artifact ------------------------------------------------------


class TestLeaderboard:
    def test_rank_and_digest(self):
        scores = [0.5, float("nan"), 0.9, 0.9]
        ranks = lb.rank_candidates(scores, True)
        # ties keep candidate order (max() first-argmax), NaN last
        assert ranks == [2, 3, 0, 1]
        assert lb.rank_candidates([-1.0, -2.0], False) == [1, 0]
        eps = [{"algorithmsParams": [{"name": "a", "params": {"k": i}}]}
               for i in range(4)]
        doc = lb.build("i1", "M", True, eps, scores)
        doc2 = lb.build("i2", "M", True, eps, scores)
        assert lb.digest(doc) == lb.digest(doc2)  # timing-independent
        assert doc["entries"][0]["index"] == 2
        assert lb.candidate_rank_for(
            doc, [{"name": "a", "params": {"k": 2}}]) == 0
        assert lb.candidate_rank_for(
            doc, [{"name": "a", "params": {"k": 99}}]) is None

    def test_write_read_latest(self, tmp_path):
        home = str(tmp_path)
        eps = [{"algorithmsParams": []}]
        d1 = lb.build("a", "M", True, eps, [0.1])
        d1["createdAt"] = 100.0
        d2 = lb.build("b", "M", True, eps, [0.2])
        d2["createdAt"] = 200.0
        lb.write(home, d1)
        lb.write(home, d2)
        assert lb.read(home, "a")["instanceId"] == "a"
        assert lb.read(home, "missing") is None
        assert lb.latest(home)["instanceId"] == "b"

    def test_run_evaluation_persists(self, storage, tmp_path):
        storage.config.home = str(tmp_path)
        iid, _ = run_evaluation(ToyEvaluation(),
                                _toy_candidates([1.0, 3.0]),
                                storage=storage, use_mesh=False,
                                distributed=True)
        doc = lb.read(str(tmp_path), iid)
        assert doc["version"] == lb.LEADERBOARD_VERSION
        assert doc["instanceId"] == iid
        assert doc["metric"] == "ToyNegRMSE"
        assert doc["gridSize"] == 2
        assert len(doc["entries"][0]["foldScores"]) == 2
        assert doc["entries"][0]["engineParams"]["algorithmsParams"][0][
            "name"] == "toy"


# -- satellite: FAILED rows explain themselves ---------------------------------


class BoomDS(ToyDS):
    def read_eval(self, ctx):
        raise ValueError("boom: no such app")


def boom_factory():
    return Engine(data_source_cls=BoomDS,
                  preparator_cls=IdentityPreparator,
                  algorithm_cls_map={"toy": ToyAlgo},
                  serving_cls=FirstServing)


class BoomEvaluation(Evaluation):
    engine_factory = staticmethod(boom_factory)
    metric = ToyNegRMSE()


class TestFailedRecordsError:
    @pytest.mark.parametrize("distributed", [False, True])
    def test_error_text_recorded(self, storage, distributed):
        with pytest.raises(ValueError):
            run_evaluation(BoomEvaluation(), _toy_candidates([1.0]),
                           storage=storage, use_mesh=False,
                           distributed=distributed)
        rows = storage.meta.list_evaluation_instances()
        vi = rows[0] if rows[0].status == "FAILED" else rows[-1]
        assert vi.status == "FAILED"
        assert "ValueError" in vi.evaluator_results
        assert "boom: no such app" in vi.evaluator_results


# -- satellite: jax-free `pio evals` / `pio eval leaderboard` ------------------


class TestEvalsCliJaxFree:
    def test_evals_verbs_survive_poisoned_jax(self, tmp_path):
        """`pio evals list/show` and `pio eval leaderboard` run on ops
        boxes without jax — poison the import and drive the real CLI."""
        code = (
            "import sys, os, json, datetime as dt\n"
            "sys.modules['jax'] = None  # poison: any import explodes\n"
            "from predictionio_tpu.tools import cli\n"
            "from predictionio_tpu.storage.registry import get_storage\n"
            "from predictionio_tpu.storage.meta import EvaluationInstance\n"
            "from predictionio_tpu.storage import leaderboard as lb\n"
            "st = get_storage()\n"
            "iid = st.meta.new_instance_id()\n"
            "now = dt.datetime.now(dt.timezone.utc)\n"
            "st.meta.insert_evaluation_instance(EvaluationInstance(\n"
            "    id=iid, status='FAILED', start_time=now, end_time=now,\n"
            "    evaluation_class='my.Ev',\n"
            "    engine_params_generator_class='my.Grid', batch='',\n"
            "    env={}, evaluator_results='ValueError: boom',\n"
            "    evaluator_results_html='', evaluator_results_json=''))\n"
            "doc = lb.build(iid, 'M', True,\n"
            "               [{'algorithmsParams': []}], [0.5])\n"
            "lb.write(st.config.home, doc)\n"
            "for argv in (['pio', 'evals', 'list', '--json'],\n"
            "             ['pio', 'evals', 'show', iid, '--json'],\n"
            "             ['pio', 'eval', 'leaderboard', '--json']):\n"
            "    sys.argv = argv\n"
            "    cli.main()\n"
            "print('JAXFREE_OK', sys.modules['jax'] is None)\n"
        )
        env = dict(os.environ, PIO_HOME=str(tmp_path))
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             cwd=REPO, env=env)
        assert out.returncode == 0, out.stderr
        assert "JAXFREE_OK True" in out.stdout
        assert "ValueError: boom" in out.stdout


# -- satellite: trainer --gate eval --------------------------------------------


class TestTrainerEvalGate:
    def _trainer(self, storage, tmp_path, **cfg_kw):
        from predictionio_tpu.server.trainer import (
            ContinuousTrainer,
            TrainerConfig,
        )

        storage.config.home = str(tmp_path)
        cfg = TrainerConfig(engine_factory="f", app_name="App",
                            gate="eval", **cfg_kw)
        return ContinuousTrainer(cfg, storage=storage,
                                 clock=lambda: 1000.0,
                                 sleep=lambda s: None)

    def _engine_instance(self, storage, iid, lam):
        from predictionio_tpu.storage.meta import EngineInstance

        now = dt.datetime.now(dt.timezone.utc)
        storage.meta.insert_engine_instance(EngineInstance(
            id=iid, status="COMPLETED", start_time=now, end_time=now,
            engine_factory="f", engine_variant="default", batch="",
            env={}, mesh_conf={}, data_source_params="{}",
            preparator_params="{}",
            algorithms_params=json.dumps(
                [{"name": "als", "params": {"lambda_": lam}}]),
            serving_params="{}"))

    def _leaderboard(self, home, lams, scores, created=900.0):
        eps = [{"algorithmsParams":
                [{"name": "als", "params": {"lambda_": l}}]}
               for l in lams]
        doc = lb.build("ev1", "NegRMSE", True, eps, scores,
                       mode="distributed")
        doc["createdAt"] = created
        lb.write(home, doc)

    def test_refuses_lower_ranked_candidate(self, storage, tmp_path):
        tr = self._trainer(storage, tmp_path)
        self._engine_instance(storage, "cand", 0.5)
        self._engine_instance(storage, "champ", 0.1)
        self._leaderboard(str(tmp_path), [0.1, 0.5], [0.9, 0.2])
        tr.registry.champion = lambda: {"instance_id": "champ"}
        ok, detail = tr._gate("cand")
        assert not ok
        assert detail["candidate_rank"] == 1
        assert detail["champion_rank"] == 0
        assert "sweep rank 1 > champion rank 0" in detail["reason"]

    def test_promotes_better_ranked_candidate(self, storage, tmp_path):
        tr = self._trainer(storage, tmp_path)
        self._engine_instance(storage, "cand", 0.1)
        self._engine_instance(storage, "champ", 0.5)
        self._leaderboard(str(tmp_path), [0.1, 0.5], [0.9, 0.2])
        tr.registry.champion = lambda: {"instance_id": "champ"}
        ok, detail = tr._guardrail_eval("cand")
        assert ok and detail["candidate_rank"] == 0

    def test_trivial_passes(self, storage, tmp_path):
        tr = self._trainer(storage, tmp_path)
        # no leaderboard at all
        ok, detail = tr._guardrail_eval("cand")
        assert ok and "no sweep leaderboard" in detail["reason"]
        # candidate params the grid never swept
        self._engine_instance(storage, "cand", 9.9)
        self._leaderboard(str(tmp_path), [0.1, 0.5], [0.9, 0.2])
        ok, detail = tr._guardrail_eval("cand")
        assert ok and "not in swept grid" in detail["reason"]
        # no champion → first generation promotes
        self._engine_instance(storage, "cand2", 0.5)
        tr.registry.champion = lambda: None
        ok, detail = tr._guardrail_eval("cand2")
        assert ok and "no champion" in detail["reason"]

    def test_stale_leaderboard_passes(self, storage, tmp_path):
        tr = self._trainer(storage, tmp_path,
                           eval_leaderboard_max_age=50.0)
        self._engine_instance(storage, "cand", 0.5)
        self._engine_instance(storage, "champ", 0.1)
        # clock=1000, createdAt=900 → 100s old > 50s max age
        self._leaderboard(str(tmp_path), [0.1, 0.5], [0.9, 0.2],
                          created=900.0)
        tr.registry.champion = lambda: {"instance_id": "champ"}
        ok, detail = tr._guardrail_eval("cand")
        assert ok and "stale" in detail["reason"]

    def test_injected_regression_refused(self, storage, tmp_path):
        from predictionio_tpu.utils import faults

        tr = self._trainer(storage, tmp_path)
        faults.FAULTS.arm("promote.regression", error="regressed")
        try:
            ok, detail = tr._guardrail_eval("cand")
            assert not ok and "injected regression" in detail["reason"]
        finally:
            faults.FAULTS.disarm("promote.regression")


class TestCliFlags:
    def test_eval_parser_flags(self):
        from predictionio_tpu.tools.cli import build_parser

        p = build_parser()
        a = p.parse_args(["eval", "mod:Ev", "mod:Grid",
                          "--distributed", "--sweep-shards", "4"])
        assert a.distributed and a.sweep_shards == 4
        a = p.parse_args(["eval", "leaderboard"])
        assert a.engine_params_generator is None
        a = p.parse_args(["evals", "list", "--json"])
        assert a.evals_cmd == "list" and a.json
        a = p.parse_args(["train", "--continuous", "--gate", "eval",
                          "--eval-leaderboard-max-age", "60"])
        assert a.gate == "eval"
        assert a.eval_leaderboard_max_age == 60.0

    def test_evals_is_not_a_jax_verb(self):
        from predictionio_tpu.tools.cli import _JAX_VERBS

        assert "evals" not in _JAX_VERBS
