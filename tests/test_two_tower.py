"""Two-tower retrieval tests: model learns clique structure; template
round trip; DP-mesh training runs (BASELINE config 5)."""

import numpy as np
import pytest

from predictionio_tpu.core.workflow import prepare_deploy, run_train
from predictionio_tpu.models.two_tower import (
    TwoTowerParams,
    two_tower_embed_items,
    two_tower_train,
    two_tower_user_embed,
)

TT_FACTORY = "predictionio_tpu.templates.twotower.engine:engine_factory"


@pytest.fixture(scope="module")
def clique_pairs():
    """Users 0-19 interact with items 0-9; users 20-39 with items 10-19."""
    rng = np.random.default_rng(0)
    us, its = [], []
    for u in range(40):
        lo, hi = (0, 10) if u < 20 else (10, 20)
        for i in range(lo, hi):
            if rng.random() < 0.6:
                us.append(u)
                its.append(i)
    return np.asarray(us, np.int32), np.asarray(its, np.int32)


class TestTwoTowerModel:
    def _retrieval_accuracy(self, uv, iv_embeds, p, n_users=40):
        hits = 0
        for u in range(n_users):
            ue = two_tower_user_embed(uv, u, n_users, p)
            top = np.argsort(-(iv_embeds @ ue))[:5]
            lo, hi = (0, 10) if u < 20 else (10, 20)
            hits += sum(1 for i in top if lo <= i < hi) / 5
        return hits / n_users

    def test_learns_cliques(self, clique_pairs):
        us, its = clique_pairs
        p = TwoTowerParams(embed_dim=16, out_dim=16, hidden=[32], epochs=30,
                           batch_size=128, learning_rate=0.02, seed=0)
        uv, iv = two_tower_train(us, its, 40, 20, p)
        embeds = two_tower_embed_items(iv, 20, p)
        acc = self._retrieval_accuracy(uv, embeds, p)
        assert acc > 0.8, acc

    def test_lr_temperature_grid_shares_executable(self, clique_pairs):
        """r4: learning_rate rides in the optimizer state and
        temperature is traced, so candidates differing only in those
        share one geometry-keyed compiled program."""
        import predictionio_tpu.models.two_tower as tt

        u, i = clique_pairs
        nu, ni = 40, 20
        base = dict(embed_dim=8, hidden=[16], out_dim=8, batch_size=64,
                    epochs=2, seed=3)
        tt._compiled_train_epoch.cache_clear()
        outs = []
        for lr, temp in ((0.01, 0.1), (0.05, 0.1), (0.01, 0.5)):
            outs.append(tt.two_tower_train(
                u, i, nu, ni, tt.TwoTowerParams(
                    **base, learning_rate=lr, temperature=temp)))
        info = tt._compiled_train_epoch.cache_info()
        assert info.misses == 1, \
            f"lr/temperature grid built {info.misses} programs"
        # the hyperparameters genuinely reach the program
        import jax

        a = jax.tree.leaves(outs[0][0])[0]
        b = jax.tree.leaves(outs[1][0])[0]
        c = jax.tree.leaves(outs[2][0])[0]
        assert not np.allclose(a, b) and not np.allclose(a, c)

    def test_mesh_training_runs(self, clique_pairs, cpu_mesh):
        us, its = clique_pairs
        p = TwoTowerParams(embed_dim=8, out_dim=8, hidden=[16], epochs=3,
                           batch_size=64, seed=0)
        uv, iv = two_tower_train(us, its, 40, 20, p, mesh=cpu_mesh)
        embeds = two_tower_embed_items(iv, 20, p)
        assert embeds.shape == (20, 8)
        assert np.isfinite(embeds).all()
        # embeddings are L2-normalized for cosine retrieval
        assert np.allclose(np.linalg.norm(embeds, axis=1), 1.0, atol=1e-3)


class TestTwoTowerTemplate:
    def test_train_deploy_query(self, storage):
        from predictionio_tpu.data.event import Event

        app = storage.meta.create_app("TTApp")
        storage.events.init_channel(app.id)
        rng = np.random.default_rng(1)
        evs = []
        for u in range(30):
            lo, hi = (0, 8) if u < 15 else (8, 16)
            for i in range(lo, hi):
                if rng.random() < 0.7:
                    evs.append(Event(event="view", entity_type="user",
                                     entity_id=f"u{u}",
                                     target_entity_type="item",
                                     target_entity_id=f"i{i}"))
        storage.events.insert_batch(evs, app.id)
        variant = {
            "engineFactory": TT_FACTORY,
            "datasource": {"params": {"appName": "TTApp"}},
            "algorithms": [{"name": "twotower",
                            "params": {"embedDim": 16, "outDim": 16,
                                       "hidden": [32], "epochs": 25,
                                       "batchSize": 128,
                                       "learningRate": 0.02}}],
        }
        run_train(TT_FACTORY, variant=variant, storage=storage, use_mesh=False)
        deployed = prepare_deploy(engine_factory=TT_FACTORY, storage=storage)
        res = deployed.query({"user": "u1", "num": 5})
        items = [int(s["item"][1:]) for s in res["itemScores"]]
        assert len(items) == 5
        assert sum(1 for i in items if i < 8) >= 4, items
        assert deployed.query({"user": "nobody", "num": 3}) == {"itemScores": []}


class TestDeviceServing:
    def test_resident_scorer_matches_host_path(self, storage, monkeypatch):
        """r5: with both towers materialized, two-tower serving rides
        the shared ALS ResidentScorer — device and host paths must
        rank identically, and batch_predict must serve a micro-batch
        in ONE device dispatch."""
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.models import als as als_mod

        app = storage.meta.create_app("TTDevApp")
        storage.events.init_channel(app.id)
        rng = np.random.default_rng(2)
        evs = [Event(event="view", entity_type="user",
                     entity_id=f"u{int(u)}", target_entity_type="item",
                     target_entity_id=f"i{int(i)}")
               for u, i in zip(rng.integers(0, 20, 300),
                               rng.integers(0, 30, 300))]
        storage.events.insert_batch(evs, app.id)
        variant = {
            "engineFactory": TT_FACTORY,
            "datasource": {"params": {"appName": "TTDevApp"}},
            "algorithms": [{"name": "twotower",
                            "params": {"embedDim": 8, "outDim": 8,
                                       "hidden": [16], "epochs": 5,
                                       "batchSize": 64}}],
        }
        run_train(TT_FACTORY, variant=variant, storage=storage,
                  use_mesh=False)

        monkeypatch.setenv("PIO_ALS_SERVE", "host")
        host = prepare_deploy(engine_factory=TT_FACTORY, storage=storage)
        host_res = host.query({"user": "u3", "num": 5})

        monkeypatch.setenv("PIO_ALS_SERVE", "device")
        dev = prepare_deploy(engine_factory=TT_FACTORY, storage=storage)
        dev_res = dev.query({"user": "u3", "num": 5})
        assert [s["item"] for s in dev_res["itemScores"]] == \
            [s["item"] for s in host_res["itemScores"]]
        np.testing.assert_allclose(
            [s["score"] for s in dev_res["itemScores"]],
            [s["score"] for s in host_res["itemScores"]], rtol=1e-4)

        # micro-batch path: one resident dispatch for the whole batch
        calls = {"n": 0}
        orig = als_mod.ResidentScorer.recommend_batch

        def counting(self, *a, **k):
            calls["n"] += 1
            return orig(self, *a, **k)

        monkeypatch.setattr(als_mod.ResidentScorer, "recommend_batch",
                            counting)
        batch = [{"user": f"u{u}", "num": 4} for u in range(6)] + \
            [{"user": "nobody", "num": 4}]
        outs = dev.batch_query(batch)
        assert calls["n"] == 1
        assert outs[-1] == {"itemScores": []}
        for q, o in zip(batch[:-1], outs[:-1]):
            single = dev.query(q)
            assert [s["item"] for s in o["itemScores"]] == \
                [s["item"] for s in single["itemScores"]]


class TestEvaluation:
    def test_leave_one_out_recall(self, storage):
        """read_eval + Recall@k through the MetricEvaluator on
        clique-structured events: the held-out item is from the user's
        own clique, so recall@10 over a 12-item catalog beats random."""
        import numpy as np

        from predictionio_tpu.controller.base import WorkflowContext
        from predictionio_tpu.controller.engine import EngineParams
        from predictionio_tpu.controller.evaluation import MetricEvaluator
        from predictionio_tpu.data.event import Event
        from predictionio_tpu.templates.twotower.engine import (
            DataSourceParams,
            TTAlgorithmParams,
            TTEvaluation,
            engine_factory,
        )

        app = storage.meta.create_app("TTEvalApp")
        storage.events.init_channel(app.id)
        evs = []
        for u in range(8):
            for it in range(12):
                if u % 2 == it % 2:
                    evs.append(Event(
                        event="view", entity_type="user", entity_id=f"u{u}",
                        target_entity_type="item",
                        target_entity_id=f"i{it}"))
        storage.events.insert_batch(evs, app.id)

        ctx = WorkflowContext(storage=storage)
        candidates = [EngineParams(
            data_source_params=DataSourceParams(app_name="TTEvalApp"),
            algorithms_params=[("twotower", TTAlgorithmParams(
                embed_dim=8, out_dim=8, hidden=[16], batch_size=16,
                epochs=40, learning_rate=0.05))])]
        ev = TTEvaluation()
        res = MetricEvaluator(ev.metric, ev.other_metrics).evaluate(
            ctx, engine_factory(), candidates)
        assert res.best_score > 0.5, res.best_score
        assert ev.metric.header == "Recall@10"
