"""Shared plumbing for the profile_*.py harnesses: in-memory Storage
wiring and running an asyncio HTTP server (Event/Engine Server) on a
background thread with readiness polling and clean shutdown."""

from __future__ import annotations

import http.client
import threading
import time
from contextlib import contextmanager


def make_memory_storage():
    """A fresh all-in-memory Storage installed as process default."""
    from predictionio_tpu.data.events import MemoryEventStore
    from predictionio_tpu.storage.meta import MetaStore
    from predictionio_tpu.storage.models import MemoryModelStore
    from predictionio_tpu.storage.registry import (Storage, StorageConfig,
                                                   set_storage)

    st = Storage(StorageConfig(metadata_type="MEMORY",
                               eventdata_type="MEMORY",
                               modeldata_type="MEMORY"))
    st._meta = MetaStore(":memory:")
    st._events = MemoryEventStore()
    st._models = MemoryModelStore()
    set_storage(st)
    return st


@contextmanager
def server_thread(server, port: int, timeout: float = 15.0):
    """Run an Event/Engine Server's asyncio loop on a daemon thread,
    wait for `GET /` to answer, yield, then shut it down."""
    loop_box = {}

    def run():
        import asyncio

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop_box["loop"] = loop
        loop.run_until_complete(server.serve_forever())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
            try:
                conn.request("GET", "/")
                conn.getresponse().read()
                break
            finally:
                conn.close()
        except OSError:
            time.sleep(0.2)
    else:
        raise TimeoutError("server did not come up")
    try:
        yield
    finally:
        loop_box["loop"].call_soon_threadsafe(server.http.request_shutdown)
        t.join(timeout=5)
