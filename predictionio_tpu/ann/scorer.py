"""Device-resident ANN serving: fused ADC scan → shortlist → re-rank.

The serving half of the ANN subsystem. Mirrors the exact path's
:class:`predictionio_tpu.models.als.ResidentScorer` contract exactly —
same AOT bucket-ladder warmup, same packed single-fetch output, same
PAD-row masking — so the :class:`~predictionio_tpu.server.aot.AOTWarmup`
/ ``MicroBatcher`` machinery and ``serve_topk_batch`` work unchanged;
a template swaps scorers, nothing above it moves.

One serving dispatch runs, fused in a single jitted program:

    Q = U[user_ids]                   (gather query embeddings)
    LUT = Q_sub · codebooks           ((B, m, K) inner-product tables)
    adc = Σ_m LUT[b, m, code[m, n]]   ((B, N) approximate scores)
    shortlist = top_k'(adc)           ((B, k′) candidate rows)
    exact = Q · V[shortlist]          (float re-rank, gathered rows only)
    out = top_k(exact) packed as [vals ++ idx.astype(f32)]

Device latency records under ``path="ann"`` (vs the exact path's
``"aot"``) so per-bucket ANN-vs-exact p50 is one
``device_p50_ms_by_bucket(path=...)`` call; un-warmed geometry falls
back to jit dispatch recorded as ``"jit"`` — the same
zero-compile-after-warmup audit as the exact path catches warmup gaps.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import numpy as np

from predictionio_tpu.ann.index import PQIndex
from predictionio_tpu.models.als import _bucket_k, serve_on_device

DEFAULT_SHORTLIST = 128


def _rotate_query(Q, rotation):
    """OPQ query rotation: the LUT must be built against the rotated
    query (codes quantize ``V @ R``; R orthogonal ⇒ ``q·v == qR·vR``),
    while the exact re-rank keeps the UN-rotated Q against the
    un-rotated corpus. HIGHEST precision for run-to-run determinism."""
    import jax
    import jax.numpy as jnp

    return jnp.dot(Q, rotation, preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)


def _ann_topk_impl(U, V, codebooks, codesT, user_ids, rows_valid=None,
                   rotation=None, *, k: int, kprime: int):
    import jax.numpy as jnp

    from predictionio_tpu import ops
    from predictionio_tpu.ops.topk import _mask_pad_rows

    Q = U[user_ids]
    if rows_valid is not None:
        Q = _mask_pad_rows(Q, rows_valid)
    Qr = Q if rotation is None else _rotate_query(Q, rotation)
    _svals, sidx = ops.adc_shortlist(Qr, codebooks, codesT, kprime)
    vals, idx = ops.rerank_topk(Q, V, sidx, k)
    # ONE packed output array — one host fetch per query batch, same
    # rationale as als._gather_score_topk_impl (indices exact in f32
    # below 2^24)
    return jnp.concatenate([vals, idx.astype(jnp.float32)], axis=-1)


@functools.lru_cache(maxsize=1)
def _ann_topk_jit():
    import jax

    return jax.jit(_ann_topk_impl, static_argnames=("k", "kprime"))


class ANNScorer:
    """Serving-time ANN scorer: PQ codes + codebooks + float corpus
    resident in HBM, one fused dispatch per query batch.

    Same external contract as ``ResidentScorer`` (``recommend_batch``,
    ``recommend``, ``warm_buckets``, ``set_bucket_ladder``,
    ``built_from``) so ``maybe_*_scorer`` callers, ``serve_topk_batch``
    and the AOT warmup hook treat the two interchangeably.
    """

    def built_from(self, U, V) -> bool:
        if self._source is None:
            return False
        return self._source[0]() is U and self._source[1]() is V

    def __init__(self, U: np.ndarray, V: np.ndarray, index: PQIndex,
                 shortlist: int = DEFAULT_SHORTLIST):
        import weakref

        try:
            self._source = (weakref.ref(U), weakref.ref(V))
        except TypeError:
            self._source = None
        self.n_users, self.rank = U.shape
        self.n_items = V.shape[0]
        if self.n_items >= 1 << 24:
            raise ValueError("ANNScorer supports catalogs < 2^24 items")
        if index.n_items != self.n_items:
            raise ValueError(
                f"index covers {index.n_items} items, corpus has "
                f"{self.n_items}")
        if index.dim != self.rank:
            raise ValueError(
                f"index dim {index.dim} != embedding dim {self.rank}")
        self.m, self.K = index.m, index.k
        #: the shortlist the caller asked for (pre-clamp) — what
        #: ``maybe_ann_scorer`` compares for cached reuse
        self._want_shortlist = int(shortlist)
        #: shortlist size k′ — the recall/latency knob (clamped to the
        #: catalog; serving k is further clamped to k′)
        self.shortlist = max(1, min(int(shortlist), self.n_items))
        self._place(U, V, index)
        self.bucket_ladder = None
        self._aot: dict = {}   # (B, k) -> compiled

    def _place(self, U, V, index: PQIndex) -> None:
        """Device placement of the serving state (subclass hook — the
        sharded scorer pads + lays the corpus out over its mesh here)."""
        import jax
        import jax.numpy as jnp

        self._U = jax.device_put(jnp.asarray(U, jnp.float32))
        # float corpus stays resident for the exact re-rank; UNPADDED —
        # the re-rank gathers only shortlist rows, never scans V
        self._V = jax.device_put(jnp.asarray(V, jnp.float32))
        self._codebooks = jax.device_put(
            jnp.asarray(index.codebooks, jnp.float32))
        # (m, N) uint8, subspace-major: each unrolled ADC step gathers
        # one contiguous row
        self._codesT = jax.device_put(jnp.asarray(
            np.ascontiguousarray(np.asarray(index.codes, np.uint8).T)))
        # OPQ rotation (None for plain-PQ / legacy v1 blobs — those
        # keep the exact pre-rotation program and executables)
        self._rot = (None if index.rotation is None else jax.device_put(
            jnp.asarray(index.rotation, jnp.float32)))

    # -- AOT bucket ladder (server/aot) ---------------------------------------

    def set_bucket_ladder(self, ladder) -> None:
        self.bucket_ladder = ladder

    def _serving_k(self, want: int) -> int:
        """Bucketed serving k, never beyond the shortlist (the re-rank
        can only return k′ rows) or the catalog."""
        return min(_bucket_k(want), self.shortlist, self.n_items)

    def _aot_key(self, B: int, k: int) -> tuple:
        import jax

        return ("ann_adc_topk", self.n_users, self.rank, self.m, self.K,
                self.n_items, B, k, self.shortlist,
                self._rot is not None, jax.default_backend())

    def _ensure_executable(self, B: int, k: int) -> bool:
        """AOT lower+compile one (bucket, k) serving program via the
        process-wide cache. True = cold compile, False = cache hit."""
        import jax

        from predictionio_tpu.server.aot import EXECUTABLES

        key = self._aot_key(B, k)
        was_cold = EXECUTABLES.get(key) is None

        def build():
            rot_sds = (None if self._rot is None else jax.ShapeDtypeStruct(
                (self.rank, self.rank), np.float32))
            sds = (
                jax.ShapeDtypeStruct((self.n_users, self.rank), np.float32),
                jax.ShapeDtypeStruct((self.n_items, self.rank), np.float32),
                jax.ShapeDtypeStruct(
                    (self.m, self.K, self.rank // self.m), np.float32),
                jax.ShapeDtypeStruct((self.m, self.n_items), np.uint8),
                jax.ShapeDtypeStruct((B,), np.int32),
                jax.ShapeDtypeStruct((), np.int32),  # rows_valid
                rot_sds,
            )
            return _ann_topk_jit().lower(
                *sds, k=k, kprime=self.shortlist).compile()

        self._aot[(B, k)] = EXECUTABLES.get_or_compile(key, build)
        return was_cold

    def warm_buckets(self, ladder, ks=(16,)) -> dict:
        """Deploy-time warmup over the bucket ladder — same return
        shape as ``ResidentScorer.warm_buckets``."""
        self.set_bucket_ladder(ladder)
        compiled = cached = 0
        for B in ladder:
            for k in ks:
                if self._ensure_executable(B, self._serving_k(k)):
                    compiled += 1
                else:
                    cached += 1
        return {"targets": compiled + cached,
                "compiled": compiled, "cached": cached}

    def _topk(self, user_ids, k: int, rows: Optional[int] = None):
        """One serving dispatch at a bucket-padded batch. Warmed
        buckets run the precompiled executable under ``path="ann"``;
        anything else is a counted jit fallback (= warmup gap)."""
        import time

        import jax.numpy as jnp

        from predictionio_tpu.server import aot
        from predictionio_tpu.utils import tracing

        B = len(user_ids)
        rows_valid = np.int32(B if rows is None else rows)
        prog = self._aot.get((B, k))
        path = "ann" if prog is not None else "jit"
        with tracing.span("serving.device", bucket=B, k=k, path=path):
            t0 = time.perf_counter()
            if prog is not None:
                packed = np.asarray(prog(
                    self._U, self._V, self._codebooks, self._codesT,
                    np.asarray(user_ids, np.int32), rows_valid,
                    self._rot))
            else:
                packed = np.asarray(_ann_topk_jit()(
                    self._U, self._V, self._codebooks, self._codesT,
                    jnp.asarray(user_ids, jnp.int32), rows_valid,
                    self._rot, k=k, kprime=self.shortlist))
            out = packed[..., :k], packed[..., k:].astype(np.int32)
            aot.record_device_latency(B, time.perf_counter() - t0, path,
                                      trace_exemplar=tracing.exemplar())
        return out

    def recommend_batch(
        self, user_ids: np.ndarray, num: int,
        exclude: Optional[list] = None,
    ) -> list:
        """Top-``num`` per user → list of (item_indices, scores);
        identical batch/k bucketing and host-side exclusion filtering
        as ``ResidentScorer.recommend_batch``, with k clamped to the
        shortlist (over-asking an ANN index cannot improve recall)."""
        if not exclude:
            exclude = [None] * len(user_ids)
        exclude = [np.asarray([] if e is None else e, np.int32)
                   for e in exclude]
        max_ex = max((e.size for e in exclude), default=0)
        want = min(num + max_ex, self.n_items)
        k = self._serving_k(want)
        B = len(user_ids)
        Bp = (self.bucket_ladder.snap(B)
              if self.bucket_ladder is not None else 0)
        if Bp < B:
            Bp = 1
            while Bp < B:
                Bp *= 2
        ids = np.asarray(user_ids, np.int32)
        if Bp != B:
            ids = np.concatenate([ids, np.zeros(Bp - B, np.int32)])
        vals, idx = self._topk(ids, k, rows=B)
        vals, idx = np.asarray(vals)[:B], np.asarray(idx)[:B]
        out = []
        for row in range(len(user_ids)):
            iv, vv = idx[row], vals[row]
            if exclude[row].size:
                keep = ~np.isin(iv, exclude[row])
                iv, vv = iv[keep], vv[keep]
            out.append((iv[:num], vv[:num]))
        return out

    def recommend(self, user: int, num: int,
                  exclude: Optional[np.ndarray] = None):
        [(iv, vv)] = self.recommend_batch(
            np.asarray([user]), num,
            [np.asarray(exclude if exclude is not None else [], np.int32)])
        return iv, vv


@functools.lru_cache(maxsize=None)
def _sharded_ann_jit(mesh, local_n: int, n_items: int, k: int,
                     kprime: int, rotated: bool):
    """One jitted shard_map program per (mesh, geometry, k, k′): the
    whole sharded serving path — per-shard ADC scan at a global column
    offset, all-gather of per-shard shortlists, distributed top-k′
    merge, partial exact re-rank + psum — fused in ONE executable so
    serving stays single-dispatch exactly like the unsharded path.

    With ``shards == 1`` every collective degenerates (all_gather of
    one shard, psum over one device, top-k′ of an already-sorted list)
    and the outputs are bitwise identical to ``_ann_topk_impl``.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from predictionio_tpu import ops
    from predictionio_tpu.ops.topk import _NEG, _mask_pad_rows
    from predictionio_tpu.parallel.mesh import shard_map_unchecked

    def body(U, V_local, codebooks, codesT_local, user_ids, rows_valid,
             *rot):
        Q = _mask_pad_rows(U[user_ids], rows_valid)
        Qr = Q if not rotated else _rotate_query(Q, rot[0])
        off = jax.lax.axis_index("shards") * local_n
        # local scan, GLOBAL row ids + validity: pad rows (only the
        # last shard's tail) come out at _NEG and never win the merge
        _lv, li_ = ops.adc_shortlist(Qr, codebooks, codesT_local, kprime,
                                     n_valid=n_items, col_offset=off)
        gv = jax.lax.all_gather(_lv, "shards")        # (S, B, k′)
        gi = jax.lax.all_gather(li_, "shards")
        _mv, mi = ops.merge_shortlists(gv, gi, kprime)
        part = ops.rerank_partial(Q, V_local, mi, off)
        exact = jax.lax.psum(part, "shards")
        # zero-padded V rows re-rank to 0.0 which would beat real _NEG
        # candidates — push any pad candidate back below everything
        exact = jnp.where(mi < n_items, exact, _NEG)
        vals, loc = jax.lax.top_k(exact, k)
        idx = jnp.take_along_axis(mi, loc, axis=1)
        return jnp.concatenate([vals, idx.astype(jnp.float32)], axis=-1)

    in_specs = [P(), P("shards", None), P(), P(None, "shards"), P(), P()]
    if rotated:
        in_specs.append(P())
    # unchecked: the streamed ADC scan's lax.scan carries per-shard
    # (varying) tiles, which the replication checker rejects without
    # pvary annotations it cannot see through ops.adc_shortlist
    sm = shard_map_unchecked(body, mesh, tuple(in_specs), P())
    return jax.jit(sm)


class ShardedANNScorer(ANNScorer):
    """ANN scorer with the serving corpus partitioned item-wise over a
    ``"shards"`` mesh axis: each device holds ``1/S`` of the PQ codes
    and exact-rerank vectors, queries replicate, and one pjit'd
    program runs scan → all-gather → merge → re-rank across the mesh.

    This is how catalogs beyond one chip's HBM serve: per-device
    residency is ``local_n · (m + 4·dim)`` bytes instead of
    ``N · (m + 4·dim)``. Same external contract as ``ANNScorer``;
    ``shards=1`` is bitwise identical to it (asserted in tests).
    """

    def __init__(self, U: np.ndarray, V: np.ndarray, index: PQIndex,
                 shortlist: int = DEFAULT_SHORTLIST, *,
                 shards: Optional[int] = None, mesh=None):
        from predictionio_tpu.parallel.mesh import shards_mesh

        if mesh is None:
            if not shards or int(shards) < 1:
                raise ValueError(
                    "ShardedANNScorer needs shards >= 1 or an explicit mesh")
            mesh = shards_mesh(int(shards))
        if "shards" not in mesh.axis_names:
            raise ValueError(
                'sharded ANN serving mesh must carry a "shards" axis')
        self.mesh = mesh
        self.shards = int(mesh.shape["shards"])
        super().__init__(U, V, index, shortlist=shortlist)

    def _place(self, U, V, index: PQIndex) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from predictionio_tpu.parallel.mesh import pad_to_multiple
        from predictionio_tpu.server import aot

        #: padded per-device item rows — shard i owns global rows
        #: [i·local_n, (i+1)·local_n); pad rows live only in the last
        #: shard's tail and are masked by ``n_valid`` / the pad re-mask
        self.local_n = pad_to_multiple(self.n_items, self.shards) \
            // self.shards
        # a shard can only nominate from its own rows, so k′ beyond
        # local_n is meaningless (and would break the local top-k)
        self.shortlist = max(1, min(self.shortlist, self.local_n))
        n_pad = self.local_n * self.shards
        rep = NamedSharding(self.mesh, P())
        self._replicated = rep
        Vp = np.asarray(V, np.float32)
        codesT = np.ascontiguousarray(
            np.asarray(index.codes, np.uint8).T)
        if n_pad != self.n_items:
            Vp = np.concatenate([Vp, np.zeros(
                (n_pad - self.n_items, self.rank), np.float32)])
            codesT = np.concatenate([codesT, np.zeros(
                (self.m, n_pad - self.n_items), np.uint8)], axis=1)
        self._U = jax.device_put(jnp.asarray(U, jnp.float32), rep)
        self._V = jax.device_put(
            jnp.asarray(Vp), NamedSharding(self.mesh, P("shards", None)))
        self._codebooks = jax.device_put(
            jnp.asarray(index.codebooks, jnp.float32), rep)
        self._codesT = jax.device_put(
            jnp.asarray(codesT), NamedSharding(self.mesh, P(None, "shards")))
        self._rot = (None if index.rotation is None else jax.device_put(
            jnp.asarray(index.rotation, jnp.float32), rep))
        aot.record_shard_layout(self.shards, self.local_n, self.shortlist)

    def _aot_key(self, B: int, k: int) -> tuple:
        import jax

        return ("ann_sharded_topk", self.n_users, self.rank, self.m,
                self.K, self.n_items, self.local_n, self.shards, B, k,
                self.shortlist, self._rot is not None,
                jax.default_backend())

    def _fn(self, k: int):
        return _sharded_ann_jit(self.mesh, self.local_n, self.n_items,
                                k, self.shortlist, self._rot is not None)

    def _ensure_executable(self, B: int, k: int) -> bool:
        import jax

        from predictionio_tpu.server.aot import EXECUTABLES

        key = self._aot_key(B, k)
        was_cold = EXECUTABLES.get(key) is None

        def build():
            from jax.sharding import NamedSharding, PartitionSpec as P

            n_pad = self.local_n * self.shards
            rep = self._replicated
            rows = NamedSharding(self.mesh, P("shards", None))
            cols = NamedSharding(self.mesh, P(None, "shards"))
            sds = [
                jax.ShapeDtypeStruct(
                    (self.n_users, self.rank), np.float32, sharding=rep),
                jax.ShapeDtypeStruct(
                    (n_pad, self.rank), np.float32, sharding=rows),
                jax.ShapeDtypeStruct(
                    (self.m, self.K, self.rank // self.m), np.float32,
                    sharding=rep),
                jax.ShapeDtypeStruct((self.m, n_pad), np.uint8,
                                     sharding=cols),
                jax.ShapeDtypeStruct((B,), np.int32, sharding=rep),
                jax.ShapeDtypeStruct((), np.int32, sharding=rep),
            ]
            if self._rot is not None:
                sds.append(jax.ShapeDtypeStruct(
                    (self.rank, self.rank), np.float32, sharding=rep))
            return self._fn(k).lower(*sds).compile()

        self._aot[(B, k)] = EXECUTABLES.get_or_compile(key, build)
        return was_cold

    def _topk(self, user_ids, k: int, rows: Optional[int] = None):
        import time

        import jax
        import jax.numpy as jnp

        from predictionio_tpu.server import aot
        from predictionio_tpu.utils import tracing

        B = len(user_ids)
        rows_valid = np.int32(B if rows is None else rows)
        prog = self._aot.get((B, k))
        path = "ann" if prog is not None else "jit"
        ids = jax.device_put(
            jnp.asarray(np.asarray(user_ids, np.int32)), self._replicated)
        rv = jax.device_put(jnp.asarray(rows_valid), self._replicated)
        args = [self._U, self._V, self._codebooks, self._codesT, ids, rv]
        if self._rot is not None:
            args.append(self._rot)
        with tracing.span("serving.device", bucket=B, k=k, path=path):
            t0 = time.perf_counter()
            fn = prog if prog is not None else self._fn(k)
            packed = np.asarray(fn(*args))
            out = packed[..., :k], packed[..., k:].astype(np.int32)
            aot.record_device_latency(B, time.perf_counter() - t0, path,
                                      trace_exemplar=tracing.exemplar())
        return out


def _resolve_shards(index: PQIndex, shards: int) -> int:
    """Shard-count resolution: ``PIO_ANN_SHARDS`` env beats the
    explicit argument beats the index blob's ``shards`` build hint."""
    env = os.environ.get("PIO_ANN_SHARDS", "").strip()
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    if shards:
        return int(shards)
    try:
        return int((index.meta or {}).get("shards") or 0)
    except (TypeError, ValueError):
        return 0


def maybe_ann_scorer(U, V, index: Optional[PQIndex], cached=None,
                     shortlist: int = DEFAULT_SHORTLIST,
                     shards: int = 0):
    """ANN twin of ``als.maybe_resident_scorer``: None (→ caller's
    exact/host path) when there is no index or the catalog is below
    ``_SERVE_MIN_ITEMS`` in auto mode; honors the same
    ``PIO_ALS_SERVE`` override and reuses ``cached`` only when built
    from these exact U/V arrays.

    ``shards > 1`` (explicit, ``PIO_ANN_SHARDS``, or the index blob's
    build hint) selects the mesh-sharded scorer; when the process has
    fewer devices than shards it logs and degrades to the unsharded
    scorer rather than failing the deploy.
    """
    import logging

    if index is None:
        return None
    if not serve_on_device(V.shape[0]):
        return None
    want = _resolve_shards(index, shards)
    if want > 1:
        if (cached is not None and type(cached) is ShardedANNScorer
                and cached.built_from(U, V)
                and cached._want_shortlist == int(shortlist)
                and cached.shards == want):
            return cached
        try:
            return ShardedANNScorer(U, V, index, shortlist=shortlist,
                                    shards=want)
        except ValueError as e:
            logging.getLogger("pio.ann").warning(
                "sharded ANN serving unavailable (%s); serving unsharded",
                e)
    if (cached is not None and type(cached) is ANNScorer
            and cached.built_from(U, V)
            and cached._want_shortlist == int(shortlist)):
        return cached
    return ANNScorer(U, V, index, shortlist=shortlist)
