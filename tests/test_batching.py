"""Continuous micro-batching serving layer."""

from __future__ import annotations

import asyncio

import pytest

from predictionio_tpu.server.batching import MicroBatcher


def run(coro):
    return asyncio.run(coro)


class TestMicroBatcher:
    def test_single_query_passthrough(self):
        calls = []

        def fn(qs):
            calls.append(list(qs))
            return [q * 10 for q in qs]

        async def main():
            mb = MicroBatcher(fn, max_batch=8, max_wait_ms=1.0)
            out = await mb.submit(7)
            mb.stop()
            return out

        assert run(main()) == 70
        assert calls == [[7]]

    def test_usable_after_stop(self):
        """r4 review: a server that shuts down and serves again reuses
        its batcher — stop() must leave it restartable, not 500 every
        batched query on a dead executor."""
        def fn(qs):
            return [q * 2 for q in qs]

        async def main():
            mb = MicroBatcher(fn, max_batch=8, max_wait_ms=1.0)
            a = await mb.submit(1)
            mb.stop()
            b = await mb.submit(2)  # restarts worker + executor
            mb.stop()
            return a, b

        assert run(main()) == (2, 4)

    def test_concurrent_queries_coalesce(self):
        calls = []

        def fn(qs):
            calls.append(len(qs))
            return [q + 1 for q in qs]

        async def main():
            mb = MicroBatcher(fn, max_batch=64, max_wait_ms=20.0)
            outs = await asyncio.gather(*(mb.submit(i) for i in range(32)))
            mb.stop()
            return outs

        outs = run(main())
        assert outs == [i + 1 for i in range(32)]  # order preserved
        assert sum(calls) == 32
        assert len(calls) < 32  # genuinely coalesced
        assert max(calls) > 1

    def test_max_batch_bound(self):
        calls = []

        def fn(qs):
            calls.append(len(qs))
            return list(qs)

        async def main():
            mb = MicroBatcher(fn, max_batch=4, max_wait_ms=50.0)
            await asyncio.gather(*(mb.submit(i) for i in range(10)))
            mb.stop()

        run(main())
        assert max(calls) <= 4

    def test_batch_error_propagates(self):
        def fn(qs):
            raise ValueError("boom")

        async def main():
            mb = MicroBatcher(fn, max_batch=8, max_wait_ms=5.0)
            res = await asyncio.gather(*(mb.submit(i) for i in range(3)),
                                       return_exceptions=True)
            mb.stop()
            return res

        res = run(main())
        # isolation re-runs each query alone; every caller sees the
        # ORIGINAL error for their own query, never a wrapper
        assert all(isinstance(r, ValueError) for r in res)

    def test_bad_query_isolated_from_siblings(self):
        def fn(qs):
            if any(q < 0 for q in qs):
                raise ValueError("negative query")
            return [q * 2 for q in qs]

        async def main():
            mb = MicroBatcher(fn, max_batch=8, max_wait_ms=20.0)
            res = await asyncio.gather(*(mb.submit(q) for q in (-1, 5, 7)),
                                       return_exceptions=True)
            iso = mb.isolations
            mb.stop()
            return res, iso

        res, iso = run(main())
        assert isinstance(res[0], ValueError)   # offender gets its error
        assert res[1:] == [10, 14]              # siblings still answered

    def test_length_mismatch_recovers_by_isolation(self):
        def fn(qs):
            return [qs[0]]  # wrong arity for batches, fine for singles

        async def main():
            mb = MicroBatcher(fn, max_batch=8, max_wait_ms=20.0)
            res = await asyncio.gather(*(mb.submit(i) for i in range(2)),
                                       return_exceptions=True)
            mb.stop()
            return res

        res = run(main())
        assert res == [0, 1]  # per-query re-runs deliver correct results


@pytest.mark.scenario
def test_engine_server_batching_end_to_end(storage):
    """EngineServer(batching=True) answers concurrent queries correctly
    and in fewer device dispatches than queries."""
    import urllib.request
    import json
    import threading

    from tests.test_workflow import FACTORY, VARIANT, seed_ratings
    from predictionio_tpu.core.workflow import run_train
    from predictionio_tpu.server.engine_server import EngineServer

    seed_ratings(storage)
    run_train(FACTORY, variant=VARIANT, storage=storage, use_mesh=False)
    server = EngineServer(engine_factory=FACTORY, storage=storage,
                          host="127.0.0.1", port=0, batching=True,
                          batch_max=16, batch_wait_ms=10.0)

    import asyncio

    async def drive():
        await server.http.start()
        port = server.http.bound_port
        def q(u):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/queries.json",
                data=json.dumps({"user": str(u), "num": 3}).encode())
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())
        outs = await asyncio.gather(*(
            asyncio.to_thread(q, u % 10) for u in range(12)))
        await server.http.stop()
        return outs

    outs = asyncio.run(drive())
    assert all(len(o["itemScores"]) == 3 for o in outs)
    assert server._batcher.submitted == 12
    assert server._batcher.batches <= 12
