"""Spec-string resolution: ``"module.path:attr"`` → object.

The Python replacement for the reference's reflective class loading
(WorkflowUtils.getEngine etc.). Shared by the CLI, EngineFactory, and
the plugin loader so error behavior stays uniform.
"""

from __future__ import annotations

import importlib
from typing import Any


def resolve_spec(spec: str) -> Any:
    if ":" in spec:
        mod_name, attr = spec.split(":", 1)
    else:
        mod_name, _, attr = spec.rpartition(".")
    if not mod_name or not attr:
        raise ImportError(f"bad spec {spec!r}; expected 'module.path:attr'")
    mod = importlib.import_module(mod_name)
    try:
        return getattr(mod, attr)
    except AttributeError as e:
        raise ImportError(f"{mod_name!r} has no attribute {attr!r}") from e
