"""Webhook connectors: translate 3rd-party payloads into events.

Reference: [U] data/.../webhooks/{JsonConnector,FormConnector,
segmentio/SegmentIOConnector,mailchimp/MailChimpConnector}.scala
(unverified, SURVEY.md §2a). A connector maps one provider payload to
the event wire JSON; the event server inserts it through the normal
validated path. Register custom connectors with
:func:`register_connector`.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Dict, Optional


class Connector(ABC):
    #: "json" (JSON body) or "form" (urlencoded form body)
    kind: str = "json"

    @abstractmethod
    def to_event_json(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Translate the provider payload into event wire JSON; raise
        ValueError on malformed payloads."""


class SegmentIOConnector(Connector):
    """Segment.com HTTP tracking payloads (track/identify/page/screen/
    group/alias), mirroring the reference's SegmentIOConnector."""

    kind = "json"
    SUPPORTED = ("track", "identify", "page", "screen", "group", "alias")

    def to_event_json(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        if not isinstance(payload, dict):
            raise ValueError("segmentio payload must be a JSON object")
        typ = payload.get("type")
        if typ not in self.SUPPORTED:
            raise ValueError(f"unsupported segmentio type {typ!r}")
        user = payload.get("userId") or payload.get("anonymousId")
        if not user:
            raise ValueError("segmentio payload needs userId or anonymousId")
        name = payload.get("event") if typ == "track" else typ
        if not name:
            raise ValueError("track payload needs an event name")
        props: Dict[str, Any] = {}
        for key in ("properties", "traits", "context"):
            val = payload.get(key)
            if isinstance(val, dict) and val:
                props[key] = val
        out: Dict[str, Any] = {
            "event": str(name),
            "entityType": "user",
            "entityId": str(user),
            "properties": props,
        }
        if payload.get("timestamp"):
            out["eventTime"] = payload["timestamp"]
        return out


class MailChimpConnector(Connector):
    """MailChimp webhook form payloads (subscribe/unsubscribe/profile/
    upemail/cleaned/campaign), mirroring the reference's
    MailChimpConnector (form-encoded ``data[...]`` keys)."""

    kind = "form"
    SUPPORTED = ("subscribe", "unsubscribe", "profile", "upemail", "cleaned",
                 "campaign")

    def to_event_json(self, form: Dict[str, str]) -> Dict[str, Any]:
        typ = form.get("type")
        if typ not in self.SUPPORTED:
            raise ValueError(f"unsupported mailchimp type {typ!r}")
        data = {
            k[len("data["):-1]: v
            for k, v in form.items()
            if k.startswith("data[") and k.endswith("]")
        }
        entity_id = data.get("email") or data.get("new_email") or data.get("id")
        if not entity_id:
            raise ValueError("mailchimp payload needs data[email] or data[id]")
        out: Dict[str, Any] = {
            "event": str(typ),
            "entityType": "user",
            "entityId": str(entity_id),
            "properties": data,
        }
        if form.get("fired_at"):
            # MailChimp fires "YYYY-MM-DD HH:MM:SS" (UTC)
            out["eventTime"] = form["fired_at"].replace(" ", "T") + "+00:00"
        return out


_CONNECTORS: Dict[str, Connector] = {
    "segmentio": SegmentIOConnector(),
    "mailchimp": MailChimpConnector(),
}


def register_connector(name: str, connector: Connector) -> None:
    _CONNECTORS[name] = connector


def get_connector(name: str) -> Optional[Connector]:
    return _CONNECTORS.get(name)
