"""Request-scoped tracing (dependency-free, fail-open).

The metrics in :mod:`predictionio_tpu.utils.metrics` say *how often*;
this module says *why this one*. Every request entering
:mod:`predictionio_tpu.server.http` gets a 128-bit trace id, and every
decision point on the hot path — deadline checks, breaker trips,
coalesced commits, storage scans, train stages — can open a nested
:func:`span` under it. Spans are linked by ``(trace_id, span_id,
parent_id)``, timed with the monotonic clock, and exported to:

- a bounded in-memory **ring buffer** (always, while tracing is
  enabled) that backs the ``/traces`` debug endpoint and the
  slow-query log;
- an optional **JSONL file** (``pio trace`` tails/greps it) with
  size-based rotation in the :mod:`atomic_write` style (``os.replace``
  + directory fsync — a reader never sees a half-rotated file).

Sampling is hybrid head+tail: the probabilistic decision is made once
per trace at the root span (children inherit it), but a span whose
status is ``error`` or whose duration crosses ``slow_span_ms`` is
exported regardless — the interesting 1% is never the sampled 1%.

Context propagation uses :mod:`contextvars`: nested ``with span(...)``
blocks parent correctly across ``await`` points and through
``asyncio.to_thread`` (which copies the context). Plain
``ThreadPoolExecutor.submit`` does NOT copy context — wrap the callable
with :func:`bind_current` to carry the active span into the pool.

Tracing is **disabled by default** and fail-open by construction:
``span()`` on the disabled path is one attribute read returning a
shared no-op handle, and every exporter call is wrapped so a failing
exporter (drill it with the ``trace.export`` fault site) increments
``pio_trace_export_failures_total`` and nothing else — a trace is never
worth failing the request it describes.

Interop: inbound W3C ``traceparent`` headers are honoured
(``00-<trace>-<span>-<flags>``), as is the simpler ``X-PIO-Trace-Id``;
responses are tagged with ``X-PIO-Trace-Id`` so a client can quote the
id back at ``/traces`` or ``pio trace``.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import random
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from predictionio_tpu.utils import faults
from predictionio_tpu.utils.atomic_write import fsync_dir
from predictionio_tpu.utils.metrics import REGISTRY

logger = logging.getLogger("pio.trace")

_M_SPANS = REGISTRY.counter(
    "pio_trace_spans_total", "Spans finished", ("status",))
_M_EXPORT_FAILURES = REGISTRY.counter(
    "pio_trace_export_failures_total",
    "Span exports that raised (fail-open: the request was unaffected)")

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")
_TRACE_ID_RE = re.compile(r"^[0-9a-fA-F]{16,64}$")

_CURRENT: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "pio_current_span", default=None)


# ids need uniqueness, not unpredictability: a Mersenne PRNG seeded
# from the OS once is ~30% cheaper per span than an os.urandom syscall
_ID_RNG = random.Random(os.urandom(16))


def new_trace_id() -> str:
    # | 1 — the all-zero trace id is invalid per W3C trace-context
    return f"{_ID_RNG.getrandbits(128) | 1:032x}"


def new_span_id() -> str:
    return f"{_ID_RNG.getrandbits(64) | 1:016x}"


class Span:
    """One timed operation. Created via :func:`span`/:func:`root_span`,
    finished (and exported) when its ``with`` block exits."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attrs",
                 "start_us", "duration_us", "status", "error", "sampled",
                 "_t0")

    def __init__(self, name: str, trace_id: str, parent_id: Optional[str],
                 sampled: bool, attrs: Optional[Dict[str, Any]] = None) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.sampled = sampled
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.status = "ok"
        self.error: Optional[str] = None
        self.start_us = time.time_ns() // 1000
        self.duration_us = 0
        self._t0 = time.perf_counter_ns()

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def set_error(self, message: str) -> None:
        self.status = "error"
        self.error = message

    def traceparent(self) -> str:
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flags}"

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "startUs": self.start_us,
            "durationUs": self.duration_us,
            "status": self.status,
        }
        if self.error is not None:
            d["error"] = self.error
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class _SpanHandle:
    """Context manager (sync AND async) that activates a span on enter
    and finishes/exports it on exit. Exceptions mark the span ``error``
    and propagate."""

    __slots__ = ("span", "_tracer", "_token")

    def __init__(self, tracer: "Tracer", span: Span) -> None:
        self.span = span
        self._tracer = tracer
        self._token: Optional[contextvars.Token] = None

    def __enter__(self) -> Span:
        self._token = _CURRENT.set(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        self._tracer.finish(self.span, exc_type, exc)
        return False

    async def __aenter__(self) -> Span:
        return self.__enter__()

    async def __aexit__(self, exc_type, exc, tb) -> bool:
        return self.__exit__(exc_type, exc, tb)


class _NoopSpan:
    """Shared do-nothing handle returned while tracing is disabled —
    the whole disabled-path cost of ``with span(...)`` is one attribute
    read plus this object's (empty) enter/exit."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = None
    sampled = False
    status = "ok"

    def set_attr(self, key: str, value: Any) -> None:
        pass

    def set_error(self, message: str) -> None:
        pass

    def traceparent(self) -> str:
        return ""

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    async def __aenter__(self) -> "_NoopSpan":
        return self

    async def __aexit__(self, exc_type, exc, tb) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


# -- exporters -----------------------------------------------------------------


class RingBufferExporter:
    """Bounded deque of finished span dicts — the store behind the
    ``/traces`` endpoint and the slow-query log. Receives EVERY span
    while tracing is enabled (sampling gates only the file exporter):
    the ring's job is "what just happened", and a bounded recent window
    costs the same either way."""

    def __init__(self, capacity: int = 2048) -> None:
        self.capacity = capacity
        self._buf: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def export(self, span_dict: Dict[str, Any]) -> None:
        with self._lock:
            self._buf.append(span_dict)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def spans(self, trace_id: Optional[str] = None,
              min_duration_ms: Optional[float] = None,
              errors_only: bool = False,
              limit: int = 100) -> List[Dict[str, Any]]:
        """Newest-first filtered view (the ``/traces`` contract)."""
        with self._lock:
            snap = list(self._buf)
        out: List[Dict[str, Any]] = []
        for d in reversed(snap):
            if trace_id is not None and d.get("traceId") != trace_id:
                continue
            if min_duration_ms is not None and \
                    d.get("durationUs", 0) < min_duration_ms * 1000.0:
                continue
            if errors_only and d.get("status") != "error":
                continue
            out.append(d)
            if len(out) >= limit:
                break
        return out

    def trace(self, trace_id: str) -> List[Dict[str, Any]]:
        """All buffered spans of one trace, oldest first."""
        with self._lock:
            snap = list(self._buf)
        got = [d for d in snap if d.get("traceId") == trace_id]
        got.sort(key=lambda d: d.get("startUs", 0))
        return got

    def export_by_trace_ids(self, trace_ids) -> List[Dict[str, Any]]:
        """All buffered spans belonging to any of the given trace ids,
        oldest first — the incident-bundle pin of the traces the
        offending latency buckets name via exemplars."""
        wanted = set(trace_ids)
        if not wanted:
            return []
        with self._lock:
            snap = list(self._buf)
        got = [d for d in snap if d.get("traceId") in wanted]
        got.sort(key=lambda d: d.get("startUs", 0))
        return got


class JSONLExporter:
    """Append-one-JSON-line-per-span file exporter with size-based
    rotation. Rotation follows the :mod:`atomic_write` discipline:
    ``os.replace`` to ``<path>.1`` then directory fsync, so ``pio
    trace`` never reads a half-moved file. Thread-safe; opens lazily so
    configuring a path costs nothing until the first sampled span."""

    def __init__(self, path: str, max_bytes: int = 32 * 1024 * 1024) -> None:
        self.path = path
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._f: Optional[Any] = None
        self._size = 0

    def _open(self) -> None:
        """Caller holds the lock."""
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "ab")
        self._size = self._f.tell()

    def _rotate(self) -> None:
        """Caller holds the lock."""
        assert self._f is not None
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._f = None
        os.replace(self.path, self.path + ".1")
        d = os.path.dirname(self.path)
        fsync_dir(d if d else ".")
        self._open()

    def export(self, span_dict: Dict[str, Any]) -> None:
        data = (json.dumps(span_dict, separators=(",", ":"),
                           default=str) + "\n").encode("utf-8")
        with self._lock:
            if self._f is None:
                self._open()
            assert self._f is not None
            if self._size and self._size + len(data) > self.max_bytes:
                self._rotate()
            self._f.write(data)
            self._f.flush()
            self._size += len(data)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# -- tracer --------------------------------------------------------------------


class Tracer:
    """Process-wide tracing state: the enabled flag, the sampling
    policy, the ring buffer, and any extra exporters. There is one
    instance, :data:`TRACER`; :meth:`configure` is how the CLI flags
    reach it."""

    def __init__(self) -> None:
        self.enabled = False
        #: probability a NEW trace is file-exported (errors and slow
        #: spans always are — tail sampling)
        self.sample_rate = 1.0
        #: spans at/over this duration export regardless of sampling
        self.slow_span_ms = 250.0
        #: root spans at/over this get their full tree logged (0 = off)
        self.slow_query_ms = 0.0
        self.ring = RingBufferExporter()
        self.exporters: List[Any] = []
        self._rng = random.Random()

    def configure(self, enabled: bool = True,
                  sample_rate: Optional[float] = None,
                  slow_span_ms: Optional[float] = None,
                  slow_query_ms: Optional[float] = None,
                  jsonl_path: Optional[str] = None,
                  ring_capacity: Optional[int] = None,
                  exporters: Optional[List[Any]] = None) -> "Tracer":
        if sample_rate is not None:
            if not (0.0 <= sample_rate <= 1.0):
                raise ValueError(
                    f"sample_rate must be in [0, 1], got {sample_rate}")
            self.sample_rate = sample_rate
        if slow_span_ms is not None:
            self.slow_span_ms = slow_span_ms
        if slow_query_ms is not None:
            self.slow_query_ms = slow_query_ms
        if ring_capacity is not None:
            self.ring = RingBufferExporter(ring_capacity)
        if exporters is not None:
            self.exporters = list(exporters)
        if jsonl_path is not None:
            self.exporters = [e for e in self.exporters
                              if not isinstance(e, JSONLExporter)]
            self.exporters.append(JSONLExporter(jsonl_path))
        self.enabled = enabled
        return self

    def reset(self) -> None:
        """Back to the disabled defaults (tests)."""
        for e in self.exporters:
            close = getattr(e, "close", None)
            if close:
                try:
                    close()
                except Exception:
                    pass
        self.__init__()  # type: ignore[misc]

    # -- span lifecycle --------------------------------------------------------

    def _decide_sampled(self) -> bool:
        r = self.sample_rate
        return r >= 1.0 or (r > 0.0 and self._rng.random() < r)

    def finish(self, span: Span, exc_type=None, exc=None) -> None:
        """Close the books on a span: stamp duration, fold in any
        in-flight exception, export (fail-open), maybe log slowness."""
        if exc is not None and span.status != "error":
            span.set_error(f"{getattr(exc_type, '__name__', 'Exception')}: {exc}")
        span.duration_us = (time.perf_counter_ns() - span._t0) // 1000
        _M_SPANS.inc((span.status,))
        d = span.to_dict()
        try:
            faults.inject("trace.export")
            self.ring.export(d)
        except Exception:
            _M_EXPORT_FAILURES.inc()
        if span.sampled or span.status == "error" or \
                span.duration_us >= self.slow_span_ms * 1000.0:
            for exp in self.exporters:
                try:
                    faults.inject("trace.export")
                    exp.export(d)
                except Exception:
                    _M_EXPORT_FAILURES.inc()
        if span.parent_id is None and self.slow_query_ms > 0 and \
                span.duration_us >= self.slow_query_ms * 1000.0:
            try:
                self._log_slow(span)
            except Exception:  # the log is best-effort like the export
                _M_EXPORT_FAILURES.inc()

    def _log_slow(self, root: Span) -> None:
        tree = self.ring.trace(root.trace_id)
        logger.warning(
            "slow request trace=%s %s took %.1fms (threshold %.0fms)\n%s",
            root.trace_id, root.name, root.duration_us / 1000.0,
            self.slow_query_ms, render_trace_tree(tree))


TRACER = Tracer()


# -- span entry points ---------------------------------------------------------


def span(name: str, **attrs: Any):
    """Open a child span of the context's current span (or a new root
    if there is none). Usable as ``with`` and ``async with``. On the
    disabled path this returns the shared no-op handle."""
    tr = TRACER
    if not tr.enabled:
        return NOOP_SPAN
    parent = _CURRENT.get()
    if parent is not None:
        s = Span(name, parent.trace_id, parent.span_id, parent.sampled, attrs)
    else:
        s = Span(name, new_trace_id(), None, tr._decide_sampled(), attrs)
    return _SpanHandle(tr, s)


def root_span(name: str, trace_id: Optional[str] = None,
              parent_span_id: Optional[str] = None,
              sampled: Optional[bool] = None, **attrs: Any):
    """Open a trace root, honouring inbound propagation headers: an
    inbound trace id continues that trace; an inbound sampled flag
    overrides the local sampling decision. Ignores any span already in
    context (this IS the context boundary)."""
    tr = TRACER
    if not tr.enabled:
        return NOOP_SPAN
    if sampled is None:
        sampled = tr._decide_sampled()
    s = Span(name, trace_id or new_trace_id(), parent_span_id, sampled, attrs)
    return _SpanHandle(tr, s)


def detached_span(name: str, **attrs: Any):
    """A new root regardless of context — for background work (e.g. the
    coalescer's group commit) that serves MANY requests' traces and
    links to them via attributes instead of parentage."""
    tr = TRACER
    if not tr.enabled:
        return NOOP_SPAN
    s = Span(name, new_trace_id(), None, tr._decide_sampled(), attrs)
    return _SpanHandle(tr, s)


# -- context helpers -----------------------------------------------------------


def current_span() -> Optional[Span]:
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    s = _CURRENT.get()
    return s.trace_id if s is not None else None


def exemplar() -> Optional[str]:
    """Trace id for histogram exemplars — None when tracing is off or
    no span is active, so ``observe(..., exemplar=tracing.exemplar())``
    is safe on every path."""
    if not TRACER.enabled:
        return None
    s = _CURRENT.get()
    return s.trace_id if s is not None else None


def add_attrs(**attrs: Any) -> None:
    """Attach attributes to the current span, if any — lets deep code
    (e.g. a storage backend) annotate the span its caller opened."""
    s = _CURRENT.get()
    if s is not None:
        s.attrs.update(attrs)


def bind_current(fn: Callable) -> Callable:
    """Carry the caller's context (current span included) into a plain
    ``ThreadPoolExecutor``; ``asyncio.to_thread`` does this natively,
    raw ``submit`` does not."""
    ctx = contextvars.copy_context()

    def _bound(*args, **kwargs):
        return ctx.run(fn, *args, **kwargs)

    return _bound


# -- propagation headers -------------------------------------------------------


def parse_traceparent(value: str) -> Optional[Tuple[str, str, bool]]:
    """``(trace_id, parent_span_id, sampled)`` or None if malformed.
    Per W3C: all-zero ids are invalid; unknown versions are accepted on
    the 00 field layout."""
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, bool(int(flags, 16) & 1)


def extract_headers(
        headers: Dict[str, str]) -> Tuple[Optional[str], Optional[str],
                                          Optional[bool]]:
    """Inbound propagation from lowercase-keyed headers: prefer W3C
    ``traceparent``, fall back to ``x-pio-trace-id`` (id only, local
    sampling decision)."""
    tp = headers.get("traceparent")
    if tp:
        parsed = parse_traceparent(tp)
        if parsed is not None:
            return parsed
    tid = headers.get("x-pio-trace-id")
    if tid and _TRACE_ID_RE.match(tid):
        return tid.lower(), None, None
    return None, None, None


# -- presentation --------------------------------------------------------------


def render_trace_tree(spans: List[Dict[str, Any]]) -> str:
    """Indented one-line-per-span tree of a trace's span dicts (the
    slow-query log and ``pio trace --tree`` share this)."""
    by_id = {d["spanId"]: d for d in spans if d.get("spanId")}
    children: Dict[Optional[str], List[Dict[str, Any]]] = {}
    for d in spans:
        pid = d.get("parentId")
        key = pid if pid in by_id else None
        children.setdefault(key, []).append(d)
    for kids in children.values():
        kids.sort(key=lambda d: d.get("startUs", 0))
    lines: List[str] = []

    def emit(d: Dict[str, Any], depth: int) -> None:
        dur = d.get("durationUs", 0) / 1000.0
        status = d.get("status", "ok")
        attrs = d.get("attrs") or {}
        extra = " ".join(f"{k}={v}" for k, v in attrs.items())
        err = f" error={d['error']!r}" if d.get("error") else ""
        lines.append(f"{'  ' * depth}{d.get('name', '?')} {dur:.2f}ms "
                     f"[{status}]{err}{' ' + extra if extra else ''}")
        for kid in children.get(d.get("spanId"), []):
            emit(kid, depth + 1)

    for root in children.get(None, []):
        emit(root, 0)
    return "\n".join(lines)


def traces_payload(trace_id: Optional[str] = None,
                   min_ms: Optional[float] = None,
                   errors_only: bool = False,
                   limit: int = 100) -> Dict[str, Any]:
    """The ``/traces`` endpoint body (shared by both servers)."""
    spans = TRACER.ring.spans(trace_id=trace_id, min_duration_ms=min_ms,
                              errors_only=errors_only, limit=limit)
    return {"enabled": TRACER.enabled, "count": len(spans), "spans": spans}


def default_trace_path(home: str) -> str:
    """Where servers write (and ``pio trace`` reads) the JSONL export
    when ``--trace-file`` is not given."""
    return os.path.join(home, "traces", "spans.jsonl")
