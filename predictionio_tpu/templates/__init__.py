"""Built-in engine templates.

Equivalent of the reference's ``examples/scala-parallel-*`` templates
(SURVEY.md §2c) — the behavioral test suite of the framework. Each
template module exposes ``engine_factory()`` plus its DASE component
classes, and ships an ``engine.json`` the CLI can copy into a new
engine directory (``pio template new <name> <dir>``).
"""

# grown as templates land; `pio template list` reflects exactly this dict
TEMPLATES = {
    "recommendation": "predictionio_tpu.templates.recommendation.engine",
    "classification": "predictionio_tpu.templates.classification.engine",
    "textclassification": "predictionio_tpu.templates.textclassification.engine",
    "similarproduct": "predictionio_tpu.templates.similarproduct.engine",
    "ecommercerecommendation": "predictionio_tpu.templates.ecommercerecommendation.engine",
    "universal": "predictionio_tpu.templates.universal.engine",
    "twotower": "predictionio_tpu.templates.twotower.engine",
    "sequentialrec": "predictionio_tpu.templates.sequentialrec.engine",
    "vanilla": "predictionio_tpu.templates.vanilla.engine",
}
