"""Sequence-parallel attention + distributed shell, on the 8-device
CPU mesh (SURVEY.md §4 testing model: real SPMD semantics, no TPU)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from predictionio_tpu.parallel.ring_attention import (
    attention_reference,
    ring_attention,
)
from predictionio_tpu.parallel.ulysses import ulysses_attention


def _qkv(B=2, S=32, H=8, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    return mk(), mk(), mk()


class TestRingAttention:
    def test_matches_reference(self, cpu_mesh):
        q, k, v = _qkv()
        out = ring_attention(q, k, v, mesh=cpu_mesh)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_causal_matches_reference(self, cpu_mesh):
        q, k, v = _qkv(seed=1)
        out = ring_attention(q, k, v, mesh=cpu_mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_cross_length_causal(self, cpu_mesh):
        """Sq != Sk: K blocks must stride by their OWN local length."""
        rng = np.random.default_rng(4)
        q = jnp.asarray(rng.standard_normal((2, 8, 4, 16)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((2, 16, 4, 16)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((2, 16, 4, 16)), jnp.float32)
        out = ring_attention(q, k, v, mesh=cpu_mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_unknown_axis_raises(self, cpu_mesh):
        q, k, v = _qkv()
        with pytest.raises(ValueError):
            ring_attention(q, k, v, mesh=cpu_mesh, axis="seq")

    def test_no_mesh_fallback(self):
        q, k, v = _qkv(S=8)
        out = ring_attention(q, k, v, mesh=None, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5)

    def test_indivisible_seq_raises(self, cpu_mesh):
        q, k, v = _qkv(S=30)  # 30 % 8 != 0
        with pytest.raises(ValueError):
            ring_attention(q, k, v, mesh=cpu_mesh)


class TestUlysses:
    def test_matches_reference(self, cpu_mesh):
        q, k, v = _qkv(seed=2)
        out = ulysses_attention(q, k, v, mesh=cpu_mesh)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_causal_matches_reference(self, cpu_mesh):
        q, k, v = _qkv(seed=3)
        out = ulysses_attention(q, k, v, mesh=cpu_mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_indivisible_heads_raises(self, cpu_mesh):
        q, k, v = _qkv(H=6)  # 6 % 8 != 0
        with pytest.raises(ValueError):
            ulysses_attention(q, k, v, mesh=cpu_mesh)


class TestDistributedShell:
    def test_single_process_degenerate(self):
        from predictionio_tpu.parallel import distributed as dist

        assert dist.initialize() is False  # no multi-process requested
        assert dist.process_count() == 1
        assert dist.is_coordinator()
        dist.barrier()  # no-op, must not raise
        tree = {"a": np.arange(3)}
        out = dist.broadcast_from_coordinator(tree)
        np.testing.assert_array_equal(out["a"], tree["a"])
        assert len(dist.local_devices()) >= 1

    def test_config_from_env(self, monkeypatch):
        from predictionio_tpu.parallel.distributed import DistributedConfig

        monkeypatch.setenv("PIO_COORDINATOR_ADDRESS", "10.0.0.1:1234")
        monkeypatch.setenv("PIO_NUM_PROCESSES", "4")
        monkeypatch.setenv("PIO_PROCESS_ID", "2")
        cfg = DistributedConfig.from_env()
        assert cfg.requested
        assert (cfg.coordinator_address, cfg.num_processes, cfg.process_id) \
            == ("10.0.0.1:1234", 4, 2)
