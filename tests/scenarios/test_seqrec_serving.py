"""Tier-2 scenario: the sequential-recommendation template end to end —
train on ordered interaction events, serve next-item queries from LIVE
user history and from anonymous session history."""

from __future__ import annotations

import json
import os
import time

import pytest

from tests.scenarios import harness as h


def _sequential_events():
    """Deterministic loops: users cycle i0→i1→i2→i3→i0…, so after
    seeing iK the next item is i(K+1 mod 4). eventTime orders the
    sequence explicitly."""
    events = []
    t0 = 1735689600  # 2025-01-01T00:00:00Z epoch
    for u in range(6):
        for step in range(12):
            item = f"i{(u + step) % 4}"
            ts = time.strftime("%Y-%m-%dT%H:%M:%S.000Z",
                               time.gmtime(t0 + u * 1000 + step))
            events.append({"event": "view", "entityType": "user",
                           "entityId": f"u{u}", "targetEntityType": "item",
                           "targetEntityId": item, "eventTime": ts})
    return events


@pytest.mark.scenario
def test_seqrec_full_loop(tmp_path):
    env = h.scenario_env(str(tmp_path / "pio_home"))
    engine_dir = str(tmp_path / "engine")
    access_key = h.new_app(env, "SeqApp")

    h.pio(["template", "new", "sequentialrec", engine_dir], env)
    vp = os.path.join(engine_dir, "engine.json")
    with open(vp) as f:
        variant = json.load(f)
    variant["datasource"]["params"]["appName"] = "SeqApp"
    variant["algorithms"][0]["params"].update(
        {"hidden": 16, "numBlocks": 1, "numHeads": 2, "seqLen": 8,
         "epochs": 60, "lr": 0.01})
    with open(vp, "w") as f:
        json.dump(variant, f)

    es_port = h.free_port()
    with h.Server(["eventserver", "--ip", "127.0.0.1",
                   "--port", str(es_port)], env, es_port) as es:
        events = _sequential_events()
        for i in range(0, len(events), 50):  # batch API caps at 50
            status, body = es.post(
                f"/batch/events.json?accessKey={access_key}",
                events[i:i + 50])
            assert status == 200
            assert all(item["status"] == 201 for item in body)

    out = h.pio(["train", "--engine-dir", engine_dir], env,
                timeout=600).stdout
    assert "Training completed" in out

    dp_port = h.free_port()
    with h.Server(["deploy", "--engine-dir", engine_dir, "--ip",
                   "127.0.0.1", "--port", str(dp_port)], env, dp_port) as dp:
        # anonymous session: after ...i1, i2 the next item is i3
        status, body = dp.post(
            "/queries.json", {"history": ["i0", "i1", "i2"], "num": 2})
        assert status == 200, body
        items = [s["item"] for s in body["itemScores"]]
        assert items and items[0] == "i3", body

        # known user: u0's recorded history ends ...i2, i3 → next is i0
        status, body = dp.post("/queries.json", {"user": "u0", "num": 2})
        assert status == 200, body
        items = [s["item"] for s in body["itemScores"]]
        assert items and items[0] == "i0", body

        # blackList removes the would-be top item
        status, body = dp.post(
            "/queries.json",
            {"history": ["i0", "i1", "i2"], "num": 2, "blackList": ["i3"]})
        assert status == 200
        assert all(s["item"] != "i3" for s in body["itemScores"]), body
