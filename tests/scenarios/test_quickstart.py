"""Tier-2 acceptance scenario: the full quickstart loop, real processes.

Mirrors the reference's quickstart integration scenario (reference: [U]
tests/pio_tests/scenarios/quickstart_test.py — app new → import events →
build → train → deploy → query → assert predictions; SURVEY.md §4), with
real ``bin/pio`` subprocesses and HTTP servers — no Docker, CPU JAX.
"""

from __future__ import annotations

import pytest

from tests.scenarios import harness as h


@pytest.mark.scenario
def test_quickstart_full_loop(tmp_path):
    env = h.scenario_env(str(tmp_path / "pio_home"))
    engine_dir = str(tmp_path / "engine")

    # -- app new ---------------------------------------------------------
    access_key = h.new_app(env, "ScenarioApp")
    assert access_key

    # -- build (static validation of the engine dir) ---------------------
    h.write_engine_variant(engine_dir, "ScenarioApp")
    h.pio(["build", "--engine-dir", engine_dir], env)

    # -- event ingestion over HTTP ---------------------------------------
    es_port = h.free_port()
    with h.Server(["eventserver", "--ip", "127.0.0.1",
                   "--port", str(es_port), "--stats"], env, es_port) as es:
        status, body = es.get("/")
        assert status == 200

        events = h.rating_events()
        # single inserts for a few, batch for the rest (both API paths)
        for ev in events[:3]:
            status, body = es.post(f"/events.json?accessKey={access_key}", ev)
            assert status == 201, body
            assert body["eventId"]
        status, body = es.post(
            f"/batch/events.json?accessKey={access_key}", events[3:])
        assert status == 200
        assert all(item["status"] == 201 for item in body)

        status, body = es.get(f"/events.json?accessKey={access_key}&limit=500")
        assert status == 200
        assert len(body) == len(events)

        status, body = es.get("/stats.json")
        assert status == 200

        # Prometheus exposition: ingestion counters are live
        status, text = es.request("GET", "/metrics", None)
        assert status == 200
        assert "pio_events_ingested_total" in str(text)

        # -- train (separate process, shared PIO_HOME storage) -----------
        out = h.pio(["train", "--engine-dir", engine_dir], env).stdout
        assert "Training completed" in out

        # -- deploy + query ----------------------------------------------
        dp_port = h.free_port()
        with h.Server(["deploy", "--engine-dir", engine_dir, "--ip",
                       "127.0.0.1", "--port", str(dp_port)], env, dp_port) as dp:
            status, body = dp.get("/")
            assert status == 200

            status, body = dp.post("/queries.json", {"user": "0", "num": 4})
            assert status == 200, body
            scores = body["itemScores"]
            assert len(scores) == 4
            # user 0 belongs to the even clique: top recs must be even items
            assert all(int(s["item"]) % 2 == 0 for s in scores), scores
            assert scores == sorted(scores, key=lambda s: -s["score"])

            # unknown user → graceful empty result, not an error
            status, body = dp.post("/queries.json", {"user": "nope", "num": 4})
            assert status == 200
            assert body["itemScores"] == []

            # /reload hot-swaps to the latest completed instance
            status, _ = dp.get("/reload")
            assert status == 200
            status, body = dp.post("/queries.json", {"user": "1", "num": 3})
            assert status == 200
            assert all(int(s["item"]) % 2 == 1 for s in body["itemScores"])


@pytest.mark.scenario
def test_batchpredict_cli(tmp_path):
    """`pio batchpredict`: queries JSONL in → predictions JSONL out."""
    import json

    env = h.scenario_env(str(tmp_path / "pio_home"))
    engine_dir = str(tmp_path / "engine")
    access_key = h.new_app(env, "BatchApp")
    h.write_engine_variant(engine_dir, "BatchApp")

    es_port = h.free_port()
    with h.Server(["eventserver", "--ip", "127.0.0.1",
                   "--port", str(es_port)], env, es_port) as es:
        status, body = es.post(
            f"/batch/events.json?accessKey={access_key}", h.rating_events())
        assert status == 200

    h.pio(["train", "--engine-dir", engine_dir], env)

    qfile = tmp_path / "queries.jsonl"
    qfile.write_text("\n".join(
        json.dumps({"user": str(u), "num": 3}) for u in range(4)))
    ofile = tmp_path / "predictions.jsonl"
    h.pio(["batchpredict", "--engine-dir", engine_dir,
           "--input", str(qfile), "--output", str(ofile)], env)

    lines = [json.loads(l) for l in ofile.read_text().splitlines() if l]
    assert len(lines) == 4
    for rec in lines:
        assert len(rec["prediction"]["itemScores"]) == 3
