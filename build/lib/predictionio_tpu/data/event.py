"""The event data model.

Reproduces the behavioral contract of the reference's event model
(reference: [U] data/src/main/scala/org/apache/predictionio/data/storage/
{Event,DataMap,PropertyMap,EventJson4sSupport}.scala — paths unverified,
see SURVEY.md provenance note):

- An :class:`Event` is an immutable record ``(eventId, event, entityType,
  entityId, targetEntityType?, targetEntityId?, properties, eventTime,
  tags, prId, creationTime)``.
- Reserved "special" events ``$set`` / ``$unset`` / ``$delete`` mutate an
  entity's property snapshot; :func:`aggregate_properties` folds a stream
  of them (ordered by ``eventTime``) into per-entity
  :class:`PropertyMap` snapshots.
- Event names beginning with ``$`` other than the reserved three are
  rejected; ``$unset`` with empty properties and ``$set``/``$unset`` with
  a target entity are rejected, mirroring the reference's
  ``EventValidation``.

Timestamps are timezone-aware :class:`datetime.datetime`; the wire format
is ISO-8601 with milliseconds, matching the reference's joda-time
serialization.
"""

from __future__ import annotations

import datetime as _dt
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional

RESERVED_EVENTS = ("$set", "$unset", "$delete")

#: Property value types permitted on the wire (JSON scalars, lists, maps).
JsonValue = Any


class EventValidationError(ValueError):
    """Raised when an event violates the ingestion contract."""


def utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


def parse_event_time(value: Any) -> _dt.datetime:
    """Parse an ISO-8601 timestamp (the reference accepts joda ISO8601)."""
    if isinstance(value, _dt.datetime):
        dt = value
    elif isinstance(value, str):
        s = value.strip()
        if s.endswith("Z"):
            s = s[:-1] + "+00:00"
        try:
            dt = _dt.datetime.fromisoformat(s)
        except ValueError as e:
            raise EventValidationError(f"Cannot parse eventTime {value!r}: {e}") from e
    else:
        raise EventValidationError(f"eventTime must be an ISO8601 string, got {type(value).__name__}")
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return dt


def format_event_time(dt: _dt.datetime) -> str:
    """ISO-8601 with milliseconds, e.g. ``2026-07-29T12:34:56.789+00:00``."""
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return dt.isoformat(timespec="milliseconds")


@dataclass(frozen=True)
class Event:
    """One immutable event record."""

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: Dict[str, JsonValue] = field(default_factory=dict)
    event_time: _dt.datetime = field(default_factory=utcnow)
    tags: List[str] = field(default_factory=list)
    pr_id: Optional[str] = None
    event_id: Optional[str] = None
    creation_time: _dt.datetime = field(default_factory=utcnow)

    def with_id(self) -> "Event":
        if self.event_id is not None:
            return self
        # bare __new__ + __dict__ copy, not dataclasses.replace or
        # copy.copy: replace() re-runs __init__ over all 11 fields
        # (~20 µs) and copy.copy pays __reduce_ex__/_reconstruct
        # (~11 µs) per event — real costs on the bulk-ingest path.
        # os.urandom.hex is uuid4().hex minus the UUID-class parsing
        # (same 16 random bytes, ~7 µs → ~1 µs each).
        ev = object.__new__(type(self))
        ev.__dict__.update(self.__dict__)
        ev.__dict__["event_id"] = os.urandom(16).hex()
        return ev

    # -- wire (de)serialization ------------------------------------------------

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "Event":
        """Parse the reference wire format (camelCase keys)."""
        if not isinstance(obj, dict):
            raise EventValidationError("event payload must be a JSON object")
        unknown = set(obj) - {
            "event", "entityType", "entityId", "targetEntityType",
            "targetEntityId", "properties", "eventTime", "tags", "prId",
            "eventId", "creationTime",
        }
        if unknown:
            raise EventValidationError(f"unknown fields: {sorted(unknown)}")
        try:
            name = obj["event"]
            entity_type = obj["entityType"]
            entity_id = obj["entityId"]
        except KeyError as e:
            raise EventValidationError(f"missing required field {e.args[0]!r}") from e
        props = obj.get("properties") or {}
        if not isinstance(props, dict):
            raise EventValidationError("properties must be a JSON object")
        def opt_str(field: str):
            # empty string = absent: storage backends serialize None
            # and "" identically (the frame/doc formats have no
            # distinct null), so accepting "" stored backend-divergent
            # events — '{"targetEntityType":"item","targetEntityId":""}'
            # now fails the one-sided-target validation uniformly
            # (found by the r5 import fuzz). Non-string values are a
            # typed error, not a crash five layers down in the
            # serializer.
            v = obj.get(field)
            if v is None or v == "":
                return None
            if not isinstance(v, str):
                raise EventValidationError(f"{field} must be a string")
            return v

        ev = cls(
            event=str(name),
            entity_type=str(entity_type),
            entity_id=str(entity_id),
            target_entity_type=opt_str("targetEntityType"),
            target_entity_id=opt_str("targetEntityId"),
            properties=dict(props),
            event_time=parse_event_time(obj["eventTime"]) if "eventTime" in obj and obj["eventTime"] is not None else utcnow(),
            tags=list(obj.get("tags") or []),
            pr_id=opt_str("prId"),
            event_id=opt_str("eventId"),
            creation_time=parse_event_time(obj["creationTime"]) if obj.get("creationTime") else utcnow(),
        )
        validate_event(ev)
        return ev

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "eventId": self.event_id,
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
        }
        if self.target_entity_type is not None:
            out["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            out["targetEntityId"] = self.target_entity_id
        out["properties"] = dict(self.properties)
        out["eventTime"] = format_event_time(self.event_time)
        if self.tags:
            out["tags"] = list(self.tags)
        if self.pr_id is not None:
            out["prId"] = self.pr_id
        out["creationTime"] = format_event_time(self.creation_time)
        return out

    def to_json_str(self) -> str:
        return json.dumps(self.to_json(), separators=(",", ":"), sort_keys=False)


def validate_event(ev: Event) -> None:
    """Enforce the reference's EventValidation rules."""
    if not ev.event:
        raise EventValidationError("event name must be non-empty")
    if not ev.entity_type:
        raise EventValidationError("entityType must be non-empty")
    if not ev.entity_id:
        raise EventValidationError("entityId must be non-empty")
    if ev.event.startswith("$") and ev.event not in RESERVED_EVENTS:
        raise EventValidationError(
            f"event name {ev.event!r} starting with '$' is reserved; "
            f"allowed special events: {', '.join(RESERVED_EVENTS)}"
        )
    if ev.event in ("$set", "$unset"):
        if ev.target_entity_type is not None or ev.target_entity_id is not None:
            raise EventValidationError(f"{ev.event} must not have a target entity")
    if ev.event == "$unset" and not ev.properties:
        raise EventValidationError("$unset requires non-empty properties")
    if ev.event == "$delete" and ev.properties:
        raise EventValidationError("$delete must not have properties")
    if (ev.target_entity_type is None) != (ev.target_entity_id is None):
        raise EventValidationError(
            "targetEntityType and targetEntityId must be both present or both absent"
        )
    if ev.target_entity_type == "" or ev.target_entity_id == "":
        # "" is indistinguishable from None in every storage format
        # (frames/docs have no distinct null) — programmatic inserts
        # must pass None for "no target", or the backends diverge
        raise EventValidationError(
            "target entity fields must be None when absent, not empty strings"
        )


@dataclass
class PropertyMap:
    """An entity's folded property snapshot with update lineage.

    Mirrors the reference's ``PropertyMap`` (DataMap + firstUpdated /
    lastUpdated timestamps).
    """

    properties: Dict[str, JsonValue]
    first_updated: _dt.datetime
    last_updated: _dt.datetime

    def get(self, key: str, default: Any = None) -> Any:
        return self.properties.get(key, default)

    def __getitem__(self, key: str) -> Any:
        return self.properties[key]

    def __contains__(self, key: str) -> bool:
        return key in self.properties


def aggregate_properties(events: Iterable[Event]) -> Dict[str, PropertyMap]:
    """Fold ``$set``/``$unset``/``$delete`` events into per-entity snapshots.

    Events are folded in ``eventTime`` order (ties broken by creation
    time then insertion order, matching the reference's sort-by-eventTime
    fold in ``PEventAggregator``). Non-special events are ignored.
    Returns ``{entityId: PropertyMap}`` for entities that currently exist
    (a trailing ``$delete`` removes the entity).
    """
    ordered = sorted(
        (e for e in events if e.event in RESERVED_EVENTS),
        key=lambda e: (e.event_time, e.creation_time),
    )
    state: Dict[str, PropertyMap] = {}
    for e in ordered:
        eid = e.entity_id
        if e.event == "$set":
            cur = state.get(eid)
            if cur is None:
                state[eid] = PropertyMap(dict(e.properties), e.event_time, e.event_time)
            else:
                cur.properties.update(e.properties)
                cur.last_updated = max(cur.last_updated, e.event_time)
        elif e.event == "$unset":
            cur = state.get(eid)
            if cur is not None:
                for k in e.properties:
                    cur.properties.pop(k, None)
                cur.last_updated = max(cur.last_updated, e.event_time)
        elif e.event == "$delete":
            state.pop(eid, None)
    return state
