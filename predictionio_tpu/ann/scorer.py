"""Device-resident ANN serving: fused ADC scan → shortlist → re-rank.

The serving half of the ANN subsystem. Mirrors the exact path's
:class:`predictionio_tpu.models.als.ResidentScorer` contract exactly —
same AOT bucket-ladder warmup, same packed single-fetch output, same
PAD-row masking — so the :class:`~predictionio_tpu.server.aot.AOTWarmup`
/ ``MicroBatcher`` machinery and ``serve_topk_batch`` work unchanged;
a template swaps scorers, nothing above it moves.

One serving dispatch runs, fused in a single jitted program:

    Q = U[user_ids]                   (gather query embeddings)
    LUT = Q_sub · codebooks           ((B, m, K) inner-product tables)
    adc = Σ_m LUT[b, m, code[m, n]]   ((B, N) approximate scores)
    shortlist = top_k'(adc)           ((B, k′) candidate rows)
    exact = Q · V[shortlist]          (float re-rank, gathered rows only)
    out = top_k(exact) packed as [vals ++ idx.astype(f32)]

Device latency records under ``path="ann"`` (vs the exact path's
``"aot"``) so per-bucket ANN-vs-exact p50 is one
``device_p50_ms_by_bucket(path=...)`` call; un-warmed geometry falls
back to jit dispatch recorded as ``"jit"`` — the same
zero-compile-after-warmup audit as the exact path catches warmup gaps.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import numpy as np

from predictionio_tpu.ann.index import PQIndex
from predictionio_tpu.models.als import _SERVE_MIN_ITEMS, _bucket_k

DEFAULT_SHORTLIST = 128


def _ann_topk_impl(U, V, codebooks, codesT, user_ids, rows_valid=None, *,
                   k: int, kprime: int):
    import jax.numpy as jnp

    from predictionio_tpu import ops
    from predictionio_tpu.ops.topk import _mask_pad_rows

    Q = U[user_ids]
    if rows_valid is not None:
        Q = _mask_pad_rows(Q, rows_valid)
    _svals, sidx = ops.adc_shortlist(Q, codebooks, codesT, kprime)
    vals, idx = ops.rerank_topk(Q, V, sidx, k)
    # ONE packed output array — one host fetch per query batch, same
    # rationale as als._gather_score_topk_impl (indices exact in f32
    # below 2^24)
    return jnp.concatenate([vals, idx.astype(jnp.float32)], axis=-1)


@functools.lru_cache(maxsize=1)
def _ann_topk_jit():
    import jax

    return jax.jit(_ann_topk_impl, static_argnames=("k", "kprime"))


class ANNScorer:
    """Serving-time ANN scorer: PQ codes + codebooks + float corpus
    resident in HBM, one fused dispatch per query batch.

    Same external contract as ``ResidentScorer`` (``recommend_batch``,
    ``recommend``, ``warm_buckets``, ``set_bucket_ladder``,
    ``built_from``) so ``maybe_*_scorer`` callers, ``serve_topk_batch``
    and the AOT warmup hook treat the two interchangeably.
    """

    def built_from(self, U, V) -> bool:
        if self._source is None:
            return False
        return self._source[0]() is U and self._source[1]() is V

    def __init__(self, U: np.ndarray, V: np.ndarray, index: PQIndex,
                 shortlist: int = DEFAULT_SHORTLIST):
        import jax
        import jax.numpy as jnp
        import weakref

        try:
            self._source = (weakref.ref(U), weakref.ref(V))
        except TypeError:
            self._source = None
        self.n_users, self.rank = U.shape
        self.n_items = V.shape[0]
        if self.n_items >= 1 << 24:
            raise ValueError("ANNScorer supports catalogs < 2^24 items")
        if index.n_items != self.n_items:
            raise ValueError(
                f"index covers {index.n_items} items, corpus has "
                f"{self.n_items}")
        if index.dim != self.rank:
            raise ValueError(
                f"index dim {index.dim} != embedding dim {self.rank}")
        self.m, self.K = index.m, index.k
        #: shortlist size k′ — the recall/latency knob (clamped to the
        #: catalog; serving k is further clamped to k′)
        self.shortlist = max(1, min(int(shortlist), self.n_items))
        self._U = jax.device_put(jnp.asarray(U, jnp.float32))
        # float corpus stays resident for the exact re-rank; UNPADDED —
        # the re-rank gathers only shortlist rows, never scans V
        self._V = jax.device_put(jnp.asarray(V, jnp.float32))
        self._codebooks = jax.device_put(
            jnp.asarray(index.codebooks, jnp.float32))
        # (m, N) uint8, subspace-major: each unrolled ADC step gathers
        # one contiguous row
        self._codesT = jax.device_put(jnp.asarray(
            np.ascontiguousarray(np.asarray(index.codes, np.uint8).T)))
        self.bucket_ladder = None
        self._aot: dict = {}   # (B, k) -> compiled

    # -- AOT bucket ladder (server/aot) ---------------------------------------

    def set_bucket_ladder(self, ladder) -> None:
        self.bucket_ladder = ladder

    def _serving_k(self, want: int) -> int:
        """Bucketed serving k, never beyond the shortlist (the re-rank
        can only return k′ rows) or the catalog."""
        return min(_bucket_k(want), self.shortlist, self.n_items)

    def _aot_key(self, B: int, k: int) -> tuple:
        import jax

        return ("ann_adc_topk", self.n_users, self.rank, self.m, self.K,
                self.n_items, B, k, self.shortlist, jax.default_backend())

    def _ensure_executable(self, B: int, k: int) -> bool:
        """AOT lower+compile one (bucket, k) serving program via the
        process-wide cache. True = cold compile, False = cache hit."""
        import jax

        from predictionio_tpu.server.aot import EXECUTABLES

        key = self._aot_key(B, k)
        was_cold = EXECUTABLES.get(key) is None

        def build():
            sds = (
                jax.ShapeDtypeStruct((self.n_users, self.rank), np.float32),
                jax.ShapeDtypeStruct((self.n_items, self.rank), np.float32),
                jax.ShapeDtypeStruct(
                    (self.m, self.K, self.rank // self.m), np.float32),
                jax.ShapeDtypeStruct((self.m, self.n_items), np.uint8),
                jax.ShapeDtypeStruct((B,), np.int32),
                jax.ShapeDtypeStruct((), np.int32),  # rows_valid
            )
            return _ann_topk_jit().lower(
                *sds, k=k, kprime=self.shortlist).compile()

        self._aot[(B, k)] = EXECUTABLES.get_or_compile(key, build)
        return was_cold

    def warm_buckets(self, ladder, ks=(16,)) -> dict:
        """Deploy-time warmup over the bucket ladder — same return
        shape as ``ResidentScorer.warm_buckets``."""
        self.set_bucket_ladder(ladder)
        compiled = cached = 0
        for B in ladder:
            for k in ks:
                if self._ensure_executable(B, self._serving_k(k)):
                    compiled += 1
                else:
                    cached += 1
        return {"targets": compiled + cached,
                "compiled": compiled, "cached": cached}

    def _topk(self, user_ids, k: int, rows: Optional[int] = None):
        """One serving dispatch at a bucket-padded batch. Warmed
        buckets run the precompiled executable under ``path="ann"``;
        anything else is a counted jit fallback (= warmup gap)."""
        import time

        import jax.numpy as jnp

        from predictionio_tpu.server import aot
        from predictionio_tpu.utils import tracing

        B = len(user_ids)
        rows_valid = np.int32(B if rows is None else rows)
        prog = self._aot.get((B, k))
        path = "ann" if prog is not None else "jit"
        with tracing.span("serving.device", bucket=B, k=k, path=path):
            t0 = time.perf_counter()
            if prog is not None:
                packed = np.asarray(prog(
                    self._U, self._V, self._codebooks, self._codesT,
                    np.asarray(user_ids, np.int32), rows_valid))
            else:
                packed = np.asarray(_ann_topk_jit()(
                    self._U, self._V, self._codebooks, self._codesT,
                    jnp.asarray(user_ids, jnp.int32), rows_valid,
                    k=k, kprime=self.shortlist))
            out = packed[..., :k], packed[..., k:].astype(np.int32)
            aot.record_device_latency(B, time.perf_counter() - t0, path,
                                      trace_exemplar=tracing.exemplar())
        return out

    def recommend_batch(
        self, user_ids: np.ndarray, num: int,
        exclude: Optional[list] = None,
    ) -> list:
        """Top-``num`` per user → list of (item_indices, scores);
        identical batch/k bucketing and host-side exclusion filtering
        as ``ResidentScorer.recommend_batch``, with k clamped to the
        shortlist (over-asking an ANN index cannot improve recall)."""
        if not exclude:
            exclude = [None] * len(user_ids)
        exclude = [np.asarray([] if e is None else e, np.int32)
                   for e in exclude]
        max_ex = max((e.size for e in exclude), default=0)
        want = min(num + max_ex, self.n_items)
        k = self._serving_k(want)
        B = len(user_ids)
        Bp = (self.bucket_ladder.snap(B)
              if self.bucket_ladder is not None else 0)
        if Bp < B:
            Bp = 1
            while Bp < B:
                Bp *= 2
        ids = np.asarray(user_ids, np.int32)
        if Bp != B:
            ids = np.concatenate([ids, np.zeros(Bp - B, np.int32)])
        vals, idx = self._topk(ids, k, rows=B)
        vals, idx = np.asarray(vals)[:B], np.asarray(idx)[:B]
        out = []
        for row in range(len(user_ids)):
            iv, vv = idx[row], vals[row]
            if exclude[row].size:
                keep = ~np.isin(iv, exclude[row])
                iv, vv = iv[keep], vv[keep]
            out.append((iv[:num], vv[:num]))
        return out

    def recommend(self, user: int, num: int,
                  exclude: Optional[np.ndarray] = None):
        [(iv, vv)] = self.recommend_batch(
            np.asarray([user]), num,
            [np.asarray(exclude if exclude is not None else [], np.int32)])
        return iv, vv


def maybe_ann_scorer(U, V, index: Optional[PQIndex], cached=None,
                     shortlist: int = DEFAULT_SHORTLIST):
    """ANN twin of ``als.maybe_resident_scorer``: None (→ caller's
    exact/host path) when there is no index or the catalog is below
    ``_SERVE_MIN_ITEMS`` in auto mode; honors the same
    ``PIO_ALS_SERVE`` override and reuses ``cached`` only when built
    from these exact U/V arrays."""
    if index is None:
        return None
    mode = os.environ.get("PIO_ALS_SERVE", "auto")
    if mode == "host" or (mode == "auto"
                          and V.shape[0] < _SERVE_MIN_ITEMS):
        return None
    if (cached is not None and isinstance(cached, ANNScorer)
            and cached.built_from(U, V) and cached.shortlist == shortlist):
        return cached
    return ANNScorer(U, V, index, shortlist=shortlist)
