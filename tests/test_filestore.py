"""Native (C++) event-log engine specifics: durability, index rebuild,
and the native $set/$unset/$delete fold vs the Python reference fold."""

import datetime as dt
import json

import pytest

from predictionio_tpu.data.event import Event, aggregate_properties, parse_event_time


def _t(s):
    return parse_event_time(s)


@pytest.fixture
def store(tmp_path):
    from predictionio_tpu.data.filestore import NativeEventLogStore

    try:
        s = NativeEventLogStore(str(tmp_path / "log"))  # builds the engine
    except RuntimeError as e:  # no g++ in this environment
        pytest.skip(str(e))
    yield s
    s.close()


APP = 1


def test_reopen_rebuilds_index(tmp_path, store):
    ids = store.insert_batch(
        [Event(event="rate", entity_type="user", entity_id=str(i),
               target_entity_type="item", target_entity_id="x",
               properties={"rating": float(i)},
               event_time=_t(f"2026-01-0{i+1}T00:00:00Z"))
         for i in range(3)],
        APP)
    store.delete(ids[1], APP)
    store.close()

    from predictionio_tpu.data.filestore import NativeEventLogStore

    s2 = NativeEventLogStore(str(tmp_path / "log"))
    evs = list(s2.find(APP))
    assert [e.event_id for e in evs] == [ids[0], ids[2]]
    assert s2.get(ids[1], APP) is None
    assert s2.get(ids[2], APP).properties == {"rating": 2.0}
    s2.close()


def test_overwrite_by_id(store):
    e = Event(event="$set", entity_type="user", entity_id="u",
              properties={"a": 1}, event_time=_t("2026-01-01T00:00:00Z"))
    eid = store.insert(e, APP)
    e2 = Event(event_id=eid, event="$set", entity_type="user", entity_id="u",
               properties={"a": 2}, event_time=_t("2026-01-01T00:00:00Z"))
    store.insert(e2, APP)
    evs = list(store.find(APP))
    assert len(evs) == 1 and evs[0].properties == {"a": 2}


def test_nul_and_unicode_roundtrip(store):
    e = Event(event="note", entity_type="user", entity_id="ué中",
              properties={"text": 'quote " backslash \\ newline \n tab \t',
                          "nested": {"k": [1, 2, {"d": None}]},
                          "num": 1.5, "bool": True},
              event_time=_t("2026-01-01T00:00:00Z"))
    eid = store.insert(e, APP)
    got = store.get(eid, APP)
    assert got.entity_id == "ué中"
    assert got.properties == e.properties


def test_native_fold_matches_python_fold(store):
    evs = [
        Event(event="$set", entity_type="user", entity_id="a",
              properties={"x": 1, "name": "A"},
              event_time=_t("2026-01-01T00:00:00Z")),
        Event(event="$set", entity_type="user", entity_id="a",
              properties={"x": 2, "y": [1, 2]},
              event_time=_t("2026-01-03T00:00:00Z")),
        Event(event="$unset", entity_type="user", entity_id="a",
              properties={"name": None},
              event_time=_t("2026-01-04T00:00:00Z")),
        Event(event="$set", entity_type="user", entity_id="b",
              properties={"deep": {"n": {"m": "q\"uote"}}},
              event_time=_t("2026-01-02T00:00:00Z")),
        Event(event="$set", entity_type="user", entity_id="gone",
              properties={"z": 1}, event_time=_t("2026-01-02T00:00:00Z")),
        Event(event="$delete", entity_type="user", entity_id="gone",
              event_time=_t("2026-01-05T00:00:00Z")),
        Event(event="rate", entity_type="user", entity_id="a",
              target_entity_type="item", target_entity_id="i",
              event_time=_t("2026-01-02T12:00:00Z")),
        Event(event="$set", entity_type="item", entity_id="other-type",
              properties={"w": 1}, event_time=_t("2026-01-01T00:00:00Z")),
    ]
    store.insert_batch(evs, APP)

    native = store.aggregate_properties(APP, "user")
    ref = aggregate_properties(
        e for e in evs if e.entity_type == "user")

    assert set(native) == set(ref) == {"a", "b"}
    for eid in native:
        assert native[eid].properties == ref[eid].properties, eid
        assert native[eid].first_updated == ref[eid].first_updated
        assert native[eid].last_updated == ref[eid].last_updated


def test_fold_backslash_and_unicode_ids(store):
    # literal backslash text and non-ASCII must survive the native fold
    evs = [
        Event(event="$set", entity_type="user", entity_id="C:\\users",
              properties={"p\\u0041th": "a\\u0042", "中文": "漢"},
              event_time=_t("2026-01-01T00:00:00Z")),
    ]
    store.insert_batch(evs, APP)
    native = store.aggregate_properties(APP, "user")
    ref = aggregate_properties(evs)
    assert set(native) == set(ref) == {"C:\\users"}
    assert native["C:\\users"].properties == ref["C:\\users"].properties


def test_microsecond_roundtrip(store):
    t = _t("2005-03-28T19:42:50.536110Z")  # float-timestamp rounding victim
    eid = store.insert(
        Event(event="e", entity_type="t", entity_id="1", event_time=t), APP)
    assert store.get(eid, APP).event_time == t


def test_limit_zero_returns_nothing(store):
    store.insert(Event(event="e", entity_type="t", entity_id="1",
                       event_time=_t("2026-01-01T00:00:00Z")), APP)
    assert list(store.find(APP, limit=0)) == []


def test_fold_time_window(store):
    for day, val in ((1, 1), (2, 2), (3, 3)):
        store.insert(
            Event(event="$set", entity_type="user", entity_id="u",
                  properties={"v": val},
                  event_time=_t(f"2026-01-0{day}T00:00:00Z")), APP)
    agg = store.aggregate_properties(
        APP, "user", until_time=_t("2026-01-03T00:00:00Z"))
    assert agg["u"].properties == {"v": 2}


def test_find_filters_and_limits(store):
    store.insert_batch(
        [Event(event="view", entity_type="user", entity_id="u1",
               target_entity_type="item", target_entity_id=f"i{k}",
               event_time=_t(f"2026-02-0{k}T00:00:00Z"))
         for k in range(1, 6)], APP)
    got = list(store.find(APP, limit=2, reversed=True))
    assert [e.target_entity_id for e in got] == ["i5", "i4"]
    got = list(store.find(APP, target_entity_id="i3"))
    assert len(got) == 1
    got = list(store.find(APP, start_time=_t("2026-02-02T00:00:00Z"),
                          until_time=_t("2026-02-04T00:00:00Z")))
    assert [e.target_entity_id for e in got] == ["i2", "i3"]


def test_torn_tail_write_is_ignored(tmp_path, store):
    ids = store.insert_batch(
        [Event(event="e", entity_type="t", entity_id="1",
               event_time=_t("2026-01-01T00:00:00Z")),
         Event(event="e", entity_type="t", entity_id="2",
               event_time=_t("2026-01-02T00:00:00Z"))], APP)
    store.close()
    path = tmp_path / "log" / "events_1.pel"
    raw = path.read_bytes()
    path.write_bytes(raw + b"\x40\x00\x00\x00\x00partial")  # truncated record

    from predictionio_tpu.data.filestore import NativeEventLogStore

    s2 = NativeEventLogStore(str(tmp_path / "log"))
    assert [e.event_id for e in s2.find(APP)] == ids
    # the torn tail is truncated at open: writes after it survive reopen
    new_id = s2.insert(Event(event="e", entity_type="t", entity_id="3",
                             event_time=_t("2026-01-03T00:00:00Z")), APP)
    s2.close()
    s3 = NativeEventLogStore(str(tmp_path / "log"))
    assert [e.event_id for e in s3.find(APP)] == ids + [new_id]
    s3.close()


def test_quickstart_on_eventlog_storage(tmp_path):
    """End-to-end train → query with EVENTDATA on the C++ event log —
    the deployment docs recommend for bulk events (the SPI tests cover
    the store alone; this proves the whole workflow path, env-config →
    registry → native store → streaming read → ALS → serving)."""
    import numpy as np

    from predictionio_tpu.core.workflow import prepare_deploy, run_train
    from predictionio_tpu.storage.registry import (Storage, StorageConfig,
                                                   set_storage)
    from tests.test_workflow import FACTORY, seed_ratings

    cfg = StorageConfig.from_env({
        "PIO_HOME": str(tmp_path),
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "NATIVE",
        "PIO_STORAGE_SOURCES_NATIVE_TYPE": "EVENTLOG",
    })
    assert cfg.eventdata_type == "EVENTLOG"
    st = Storage(cfg)
    set_storage(st)
    built = False
    try:
        try:
            st.events  # builds the C++ engine lazily
            built = True
        except RuntimeError as e:  # only the no-g++ signal may skip
            pytest.skip(f"native engine unavailable: {e}")
        seed_ratings(st)
        run_train(FACTORY, variant={
            "id": "elq", "engineFactory": FACTORY,
            "datasource": {"params": {"appName": "TestApp"}},
            "algorithms": [{"name": "als", "params": {
                "rank": 4, "numIterations": 3, "lambda": 0.05}}],
        }, storage=st, use_mesh=False)
        res = prepare_deploy(engine_factory=FACTORY,
                             storage=st).query({"user": "0", "num": 3})
        assert len(res["itemScores"]) == 3
        assert np.isfinite([s["score"] for s in res["itemScores"]]).all()
    finally:
        if built:
            st.events.close()
        set_storage(None)
