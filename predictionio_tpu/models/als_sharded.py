"""Sharded ALS: SPMD over a device mesh via shard_map + ICI collectives.

This is the TPU replacement for MLlib ALS's block-partitioned
shuffle-join (reference behavior: Spark ALS ``InBlock``/``OutBlock``
structures exchanged over the shuffle each half-iteration — SURVEY.md
§2d P2/C1). Layout:

- Users (and items) are range-partitioned into ``n_dev`` equal blocks;
  each device owns one block of U rows and one of V rows.
- Ratings are laid out TWICE on the host in the padded-row format of
  :mod:`predictionio_tpu.models.als` (see ``rows_layout``), partitioned
  to match: device d holds the rating rows of d's users (by-user copy)
  and of d's items (by-item copy), with entity indices block-local.
  This replaces the shuffle — partitioning happens once at data-prep
  time, not per iteration.
- Each half-step inside ``shard_map``: one ``all_gather`` of the
  counterpart factor block over the ``data`` axis (the only collective —
  riding ICI), then purely local batched-matmul row accumulation and a
  batched Cholesky solve for the local block.
- The full iteration loop is a single ``lax.scan`` under one jit: zero
  host round-trips, 2 all_gathers per iteration of size n·k.

Per-device memory: (block_e, k, k) normal matrices + the full counterpart
factor matrix — the same asymptotics as MLlib's per-executor blocks.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

from predictionio_tpu.models.als import (
    ALSParams,
    RatingsCOO,
    _counts,
    _row_chunk,
    _solve_psd,
    chunk_update,
    init_factors,
    rows_layout,
)


def _partition_rows(
    idx_self: np.ndarray, idx_other: np.ndarray, vals: np.ndarray,
    block: int, n_dev: int, width: int, chunk_rows: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Per-device padded-row layouts, equalized to the same row count.

    Returns arrays shaped [n_dev, n_chunks, RC(, W)]: (row_entity
    block-local, other_idx, vals, mask).
    """
    owner = idx_self // block
    layouts = []
    for d in range(n_dev):
        sel = owner == d
        layouts.append(rows_layout(
            (idx_self[sel] - d * block).astype(np.int32),
            idx_other[sel].astype(np.int32),
            vals[sel].astype(np.float32),
            block, width, chunk_rows))
    R = max(l[0].shape[0] for l in layouts)
    outs = []
    for j, fill in enumerate((block - 1, 0, 0.0, 0.0)):
        dtype = layouts[0][j].dtype
        shape = (n_dev, R) + layouts[0][j].shape[1:]
        arr = np.full(shape, fill, dtype)
        for d, l in enumerate(layouts):
            arr[d, : l[j].shape[0]] = l[j]
        n_chunks = R // chunk_rows
        outs.append(arr.reshape((n_dev, n_chunks, chunk_rows) + shape[2:]))
    return tuple(outs)  # type: ignore[return-value]


def _pad_rows(arr: np.ndarray, n: int) -> np.ndarray:
    if arr.shape[0] == n:
        return arr
    pad = np.zeros((n - arr.shape[0],) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0)


@functools.lru_cache(maxsize=8)
def _compiled_sharded(mesh, n_dev: int, block_u: int, block_i: int,
                      rank: int, iterations: int, reg: float, implicit: bool,
                      alpha: float, weighted_reg: bool,
                      pallas: bool = False):
    # ``pallas`` keys the cache so flipping PIO_NO_PALLAS mid-process
    # takes effect (chunk_update branches on it at trace time)
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from predictionio_tpu.parallel.mesh import get_shard_map, pvary

    shard_map = get_shard_map()
    k = rank
    eye = jnp.eye(k, dtype=jnp.float32)

    def _pvary(x):
        return pvary(x, "data")

    def local_normal_eq(F_full, chunks, n_local):
        """Accumulate A [n_local,k,k], b [n_local,k] from this device's
        rating rows (row_entity already block-local). Same math as the
        single-device path via the shared chunk_update."""
        A0 = _pvary(jnp.zeros((n_local, k, k), jnp.float32))
        b0 = _pvary(jnp.zeros((n_local, k), jnp.float32))

        def body(carry, chunk):
            return chunk_update(*carry, chunk, F_full, implicit, alpha,
                                pallas), None

        (A, b), _ = jax.lax.scan(body, (A0, b0), chunks)
        return A, b

    def reg_term(cnt):
        lam = reg * cnt if weighted_reg else jnp.full_like(cnt, reg)
        lam = jnp.where(cnt > 0, jnp.maximum(lam, 1e-8), 1.0)
        return lam[:, None, None] * eye

    def body(u_re, u_oi, u_v, u_m, i_re, i_oi, i_v, i_m, cnt_u, cnt_i, V0):
        # inside shard_map: leading device dim is local size 1 → squeeze
        u_chunks = (u_re[0], u_oi[0], u_v[0], u_m[0])
        i_chunks = (i_re[0], i_oi[0], i_v[0], i_m[0])
        Ru = reg_term(cnt_u[0])
        Ri = reg_term(cnt_i[0])
        V_l = V0  # [block_i, k] local block (spec splits rows)

        def step(carry, _):
            U_l, V_l = carry
            V_full = jax.lax.all_gather(V_l, "data", tiled=True)
            A, b = local_normal_eq(V_full, u_chunks, block_u)
            if implicit:
                A = A + (V_full.T @ V_full)[None, :, :]
            U_l = _solve_psd(A + Ru, b)
            U_full = jax.lax.all_gather(U_l, "data", tiled=True)
            A, b = local_normal_eq(U_full, i_chunks, block_i)
            if implicit:
                A = A + (U_full.T @ U_full)[None, :, :]
            V_l = _solve_psd(A + Ri, b)
            return (U_l, V_l), None

        # mark the zero carry as varying over the mesh axis (vma typing)
        U0_l = _pvary(jnp.zeros((block_u, k), jnp.float32))
        (U_l, V_l), _ = jax.lax.scan(step, (U0_l, V_l), None, length=iterations)
        return U_l, V_l

    rows4 = P("data", None, None, None)
    rows3 = P("data", None, None)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(rows3, rows4, rows4, rows4, rows3, rows4, rows4, rows4,
                  P("data", None), P("data", None), P("data", None)),
        out_specs=(P("data", None), P("data", None)),
    )
    return jax.jit(fn)


def als_train_sharded(
    coo: RatingsCOO, p: ALSParams, mesh
) -> Tuple[np.ndarray, np.ndarray]:
    """Train ALS over the mesh's ``data`` axis; returns full (U, V)."""
    import jax

    n_dev = int(np.prod(mesh.devices.shape))
    if "data" not in mesh.axis_names:
        raise ValueError(f"mesh must have a 'data' axis, got {mesh.axis_names}")

    block_u = -(-coo.n_users // n_dev)  # ceil
    block_i = -(-coo.n_items // n_dev)
    n_users_p, n_items_p = block_u * n_dev, block_i * n_dev
    W = p.row_width
    RC = _row_chunk(p.rank)

    u_parts = _partition_rows(coo.user_idx, coo.item_idx, coo.rating,
                              block_u, n_dev, W, RC)
    i_parts = _partition_rows(coo.item_idx, coo.user_idx, coo.rating,
                              block_i, n_dev, W, RC)

    cnt_u = _pad_rows(_counts(coo.user_idx, coo.n_users), n_users_p)
    cnt_i = _pad_rows(_counts(coo.item_idx, coo.n_items), n_items_p)

    # identical init to the single-device path; padding rows zeroed so
    # they contribute nothing to the first implicit Gram term
    V0 = _pad_rows(init_factors(coo.n_items, p.rank, p.seed), n_items_p)

    from predictionio_tpu import ops

    # key Pallas on the MESH devices, not jax.default_backend(): a CPU
    # mesh can be traced while the default backend is a tunneled TPU
    # (and vice versa)
    mesh_is_tpu = all(d.platform == "tpu" for d in mesh.devices.flat)
    pallas = ops.use_pallas("tpu" if mesh_is_tpu else "cpu")
    train = _compiled_sharded(
        mesh, n_dev, block_u, block_i,
        p.rank, p.iterations, float(p.reg), bool(p.implicit), float(p.alpha),
        bool(p.weighted_reg), pallas)

    # place inputs directly onto the mesh with their shard_map layouts —
    # never through the default backend (which may be a different
    # platform, e.g. the tunneled TPU while training on a CPU mesh)
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    shardings = [NamedSharding(mesh, P("data", *([None] * (a.ndim - 1))))
                 for a in (*u_parts, *i_parts)]
    args = [jax.device_put(a, s) for a, s in zip((*u_parts, *i_parts), shardings)]
    rows = NamedSharding(mesh, P("data", None))
    args += [jax.device_put(cnt_u.reshape(n_dev, block_u), rows),
             jax.device_put(cnt_i.reshape(n_dev, block_i), rows),
             jax.device_put(V0, rows)]
    U, V = train(*args)

    def fetch(x):
        # multi-host: the result spans non-addressable devices — gather
        # the global value onto every host (replicated model output,
        # the torrent-broadcast analogue in reverse)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(x)

    return (fetch(U)[: coo.n_users], fetch(V)[: coo.n_items])
