"""pypio bridge (DataFrame reads, model hand-off, cleanup) + gated
network storage backends."""

from __future__ import annotations

import datetime as dt

import pytest

from predictionio_tpu.data.event import Event


@pytest.fixture()
def bridged(storage):
    import pypio

    app = storage.meta.create_app("PyApp", "")
    storage.events.init_channel(app.id)
    t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
    evs = [
        Event(event="rate", entity_type="user", entity_id="u1",
              target_entity_type="item", target_entity_id="i1",
              properties={"rating": 4.0}, event_time=t0),
        Event(event="buy", entity_type="user", entity_id="u2",
              target_entity_type="item", target_entity_id="i2",
              event_time=t0 + dt.timedelta(hours=1)),
        Event(event="$set", entity_type="user", entity_id="u1",
              properties={"plan": "pro"}, event_time=t0),
        Event(event="$set", entity_type="user", entity_id="u1",
              properties={"plan": "free"},
              event_time=t0 + dt.timedelta(days=1)),
    ]
    storage.events.insert_batch(evs, app.id)
    pypio.init(storage)
    yield pypio
    pypio.stop()


class TestBridge:
    def test_find_events_dataframe(self, bridged):
        df = bridged.find_events("PyApp")
        assert len(df) == 4
        assert set(df.columns) >= {"event", "entityId", "properties",
                                   "eventTime"}
        rated = df[df.event == "rate"].iloc[0]
        assert rated.properties["rating"] == 4.0

        df = bridged.find_events("PyApp", event_names=["buy"])
        assert list(df.entityId) == ["u2"]

    def test_aggregate_properties_dataframe(self, bridged):
        df = bridged.data.PEventStore.aggregate_properties("PyApp", "user")
        # later $set wins the fold
        assert df.loc["u1", "plan"] == "free"

    def test_model_round_trip(self, bridged):
        bridged.save_model({"w": [1, 2, 3]}, "inst-1", algorithm="nb")
        assert bridged.load_model("inst-1", algorithm="nb") == {"w": [1, 2, 3]}
        # a second algorithm on the same instance preserves the first
        bridged.save_model("lr-model", "inst-1", algorithm="lr")
        assert bridged.load_model("inst-1", algorithm="nb") == {"w": [1, 2, 3]}
        assert bridged.load_model("inst-1", algorithm="lr") == "lr-model"

    def test_cleanup_functions(self, bridged):
        from pypio.workflow import CleanupFunctions

        calls = []
        CleanupFunctions.clear()
        CleanupFunctions.add(lambda: calls.append(1))
        CleanupFunctions.add(lambda: calls.append(2))
        CleanupFunctions.run()
        assert calls == [1, 2]
        CleanupFunctions.clear()

    def test_clean_events(self, bridged, storage):
        from pypio.workflow import clean_events

        counts = clean_events("PyApp", keep_days=30000)
        assert counts["kept"] >= 1

    def test_utils(self):
        from pypio.utils import new_string_array, to_datetime

        assert new_string_array(("a", "b"), gateway=object()) == ["a", "b"]
        t = to_datetime("2026-01-01T00:00:00.000Z")
        assert t.tzinfo is not None and t.year == 2026


class TestGatedBackends:
    def test_types_registered(self):
        from predictionio_tpu.storage import registry as reg

        assert "S3" in reg._MODEL_BACKENDS
        assert "HDFS" in reg._MODEL_BACKENDS
        assert "PGSQL" in reg._EVENT_BACKENDS
        assert "MYSQL" in reg._EVENT_BACKENDS

    def test_missing_driver_message(self):
        from predictionio_tpu.storage.registry import Storage, StorageConfig
        from predictionio_tpu.storage.remote import StorageClientError

        st = Storage(StorageConfig(eventdata_type="PGSQL"))
        with pytest.raises(StorageClientError, match="psycopg2"):
            _ = st.events
        # the metadata repository gates identically (shared-source idiom)
        st = Storage(StorageConfig(metadata_type="MYSQL"))
        with pytest.raises(StorageClientError, match="pymysql"):
            _ = st.meta

    def test_s3_without_driver(self):
        from predictionio_tpu.storage.remote import (
            S3ModelStore,
            StorageClientError,
        )

        try:
            import boto3  # noqa: F401
        except ImportError:
            pass
        else:
            pytest.skip("boto3 installed; gate not exercisable")
        with pytest.raises(StorageClientError, match="boto3"):
            S3ModelStore(bucket="b")

    def test_source_properties_routing(self):
        """Each repository binds ITS source's settings, not first-match."""
        from predictionio_tpu.storage.registry import StorageConfig

        cfg = StorageConfig.from_env({
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "S3HOT",
            "PIO_STORAGE_SOURCES_S3COLD_TYPE": "S3",
            "PIO_STORAGE_SOURCES_S3COLD_BUCKET_NAME": "archive",
            "PIO_STORAGE_SOURCES_S3HOT_TYPE": "S3",
            "PIO_STORAGE_SOURCES_S3HOT_BUCKET_NAME": "serving",
        })
        assert cfg.modeldata_type == "S3"
        assert cfg.source_properties("MODELDATA")["BUCKET_NAME"] == "serving"
