"""Engine Server: low-latency query serving on :8000.

Reference: [U] core/.../workflow/CreateServer.scala (MasterActor +
akka-http; unverified, SURVEY.md §3.2). Routes preserved:

- ``POST /queries.json`` → prediction JSON (the p50-critical path)
- ``GET  /``             → engine status JSON
- ``GET  /health``       → alive / degraded / not-ready probe
- ``GET  /reload``       → hot-swap to the latest COMPLETED instance
- ``GET  /stop``         → shut the server down
- ``GET  /plugins.json`` + ``/plugins/{name}/{path}`` → plugin surface

TPU-first serving design: the model stays resident (factor matrices /
params as device arrays), prediction runs on a worker thread pool so the
asyncio loop never blocks on device dispatch, and the optional feedback
loop posts served (query, prediction, prId) back to the event store —
the reference's feedback mechanism — without touching the hot path
(fire-and-forget task).

Resilience contract (docs/operations.md "Failure modes"):

- **Deadline**: with ``query_timeout_ms`` set, a query that outlives
  its budget answers ``504`` — a hung storage backend or slow model
  can no longer block ``/queries.json`` indefinitely.
- **Load shedding**: with ``max_inflight`` set, requests past the cap
  answer ``503`` + ``Retry-After`` immediately (mirror of the ingest
  429 contract) instead of queueing without bound.
- **Feedback breaker**: a down Event Server trips the sink's circuit
  breaker open; feedback then drops fast (counted per cause) instead
  of stacking HTTP timeouts two-threads deep.
- **Hardened /reload**: the last-good engine is retained on any
  failure; the candidate engine must answer a probe query (the last
  successfully served one) before the swap, so a reload under live
  traffic serves either the old or the new instance — never an error.

Multi-model serving (``variants=...`` / ``pio deploy --variants``):
several registry generations stay resident at once (champion /
challenger / canary — server/variants.py), each query is dispatched to
an arm by a deterministic sticky weighted split, the serving arm is
returned (and overridable) via the ``X-PIO-Variant`` header, feedback
is attributed per arm (server/variant_metrics.py), ``/reload?variant=``
swaps ONE arm without disturbing the others, and ``/variants`` +
``POST /variants/weights`` expose the split with probe-then-apply
edit semantics.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import math
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from predictionio_tpu.core.plugins import engine_server_plugins
from predictionio_tpu.core.workflow import DeployedEngine, prepare_deploy
from predictionio_tpu.data.event import Event, utcnow
from predictionio_tpu.server.http import (
    HTTPServer,
    Request,
    Response,
    Router,
    traces_handler,
)
from predictionio_tpu.storage.registry import Storage, get_storage
from predictionio_tpu.utils import faults, tracing
from predictionio_tpu.utils.resilience import (
    OPEN,
    CircuitBreaker,
    CircuitOpenError,
)


class EngineServer:
    def __init__(
        self,
        engine_factory: Optional[str] = None,
        instance_id: Optional[str] = None,
        storage: Optional[Storage] = None,
        host: str = "0.0.0.0",
        port: int = 8000,
        variant_id: str = "",
        feedback: bool = False,
        feedback_app_name: Optional[str] = None,
        feedback_url: Optional[str] = None,
        feedback_access_key: Optional[str] = None,
        feedback_channel: Optional[str] = None,
        event_sink: Optional[Any] = None,
        plugins: Optional[List[Any]] = None,
        ssl_context: Optional[Any] = None,
        bind_retries: int = 3,
        bind_retry_sec: float = 1.0,
        batching: bool = False,
        batch_max: int = 64,
        batch_wait_ms: float = 0.0,
        aot_buckets: Optional[str] = None,
        aot_topk: int = 16,
        query_timeout_ms: float = 0.0,
        max_inflight: int = 0,
        reload_probe: bool = True,
        require_engine: bool = True,
        access_log: bool = False,
        variants: Optional[str] = None,
        variant_salt: str = "pio",
        tenant_quotas: Optional[Any] = None,
        scrape_interval: float = 10.0,
        incident_dir: Optional[str] = None,
    ) -> None:
        self.storage = storage or get_storage()
        self.engine_factory = engine_factory
        self.variant_id = variant_id
        self.feedback = feedback or bool(feedback_url) or event_sink is not None
        self.feedback_app_name = feedback_app_name
        self._event_sink = event_sink
        if self._event_sink is None and feedback_url:
            # the reference contract: feedback goes through the Event
            # Server's authenticated HTTP API (SURVEY.md §3.2), the only
            # path that works when event storage is remote to this host
            from predictionio_tpu.server.eventsink import HTTPEventSink

            if not feedback_access_key:
                raise ValueError("feedback_url requires feedback_access_key")
            self._event_sink = HTTPEventSink(
                feedback_url, feedback_access_key, feedback_channel)
        self.plugins = plugins if plugins is not None else engine_server_plugins()
        self.deployed: Optional[DeployedEngine] = None
        self._load_error: Optional[str] = None
        if not variants:
            try:
                self.deployed = prepare_deploy(
                    engine_factory=engine_factory, instance_id=instance_id,
                    storage=self.storage, variant_id=variant_id)
            except Exception as e:
                # with require_engine=False the server still comes up (and
                # reports not-ready) so ops can deploy before the first
                # train and /reload the model in later
                if require_engine:
                    raise
                self._load_error = f"{type(e).__name__}: {e}"
        self.start_time = utcnow()
        #: replica identity, surfaced on /health: a router (or any
        #: client) that sees the instance id change knows it is talking
        #: to a RESTARTED process — not a flapping one — and resets the
        #: replica's breaker/EWMA state instead of keeping it ejected
        self.instance_uid = uuid.uuid4().hex[:12]
        self.start_epoch = time.time()
        #: EWMA of successful-query handler latency (loop-thread-only);
        #: feeds the Retry-After hint on shed 503s
        self._lat_ewma = 0.0
        self.query_count = 0
        self.query_timeout = max(0.0, query_timeout_ms) / 1e3
        self.max_inflight = max(0, max_inflight)
        self.reload_probe = reload_probe
        #: loop-thread-only in-flight request count (handler entry to
        #: handler exit); admission control reads it before any await
        self._inflight = 0
        # per-app weighted-fair admission under max_inflight: an app
        # over its weighted share of the cap is shed FIRST, so one
        # bursting tenant cannot move other tenants' p99 (weights from
        # quotas.json; with no X-PIO-App header every request shares
        # one bucket and the behavior degenerates to the global cap)
        from predictionio_tpu.server.tenancy import FairInflight, TenantQuotas

        if isinstance(tenant_quotas, TenantQuotas):
            self.quotas = tenant_quotas
        elif tenant_quotas:
            self.quotas = TenantQuotas(str(tenant_quotas))
        else:
            self.quotas = TenantQuotas.for_home(self.storage.config.home)
        self._fair = FairInflight(self.max_inflight,
                                  weight_of=self.quotas.weight)
        #: guards query_count and _feedback_inflight — both are touched
        #: from the event loop AND the feedback worker threads, so the
        #: unlocked += the server shipped with could drift both the
        #: 256-inflight feedback bound and the status counter
        self._counts_lock = threading.Lock()
        self._last_good_query: Optional[Any] = None
        self._reload_lock: Optional[asyncio.Lock] = None
        self.reload_generation = 0
        #: outcome of the most recent /reload swap attempt
        #: ({"outcome": "promoted"|"rolled_back"|"refused", ...}), so the
        #: continuous trainer and the router can verify a promotion
        #: landed without scraping metrics
        self.last_swap: Optional[Dict[str, Any]] = None
        self._model_registry: Optional[Any] = None
        from predictionio_tpu.utils.metrics import REGISTRY

        self._m_queries = REGISTRY.counter(
            "pio_engine_queries_total", "Queries served", ("status",))
        self._m_latency = REGISTRY.histogram(
            "pio_engine_query_seconds", "Query latency (handler, seconds)",
            labelnames=("status",))
        self._m_feedback = REGISTRY.counter(
            "pio_engine_feedback_total", "Feedback events sent", ("status",))
        self._m_shed = REGISTRY.counter(
            "pio_engine_shed_total",
            "Queries shed by the max-inflight cap", ("app",))
        self._m_deadline = REGISTRY.counter(
            "pio_engine_deadline_exceeded_total",
            "Queries that outlived query_timeout_ms")
        self._m_reloads = REGISTRY.counter(
            "pio_engine_reloads_total", "Reload attempts", ("result",))
        self._m_reload_gen = REGISTRY.gauge(
            "pio_engine_reload_generation",
            "Engine swaps served since start (0 = the deploy-time model)")
        self._m_reload_gen.set(0)
        from predictionio_tpu.utils.metrics import build_info
        from predictionio_tpu.utils.timeseries import (
            TimeSeriesStore,
            scaled_tiers,
        )

        build_info(self.instance_uid)
        #: local metrics history (GET /metrics/history), scraped from
        #: the registry every scrape_interval by a background task
        self.scrape_interval = max(0.05, scrape_interval)
        self.tsdb = TimeSeriesStore(
            REGISTRY, tiers=scaled_tiers(self.scrape_interval))
        #: a down Event Server must fail FAST after a few sink errors,
        #: not tie both feedback workers up in 5 s connect timeouts
        self._sink_breaker = CircuitBreaker(
            "engine_feedback_sink", failure_threshold=5, reset_timeout=10.0)
        self._breakers: Dict[str, CircuitBreaker] = {
            "feedback_sink": self._sink_breaker}
        # incident flight recorder: breaker-open / crash / SIGQUIT
        # postmortem bundles under <home>/incidents (utils/incidents)
        self.incidents = None
        if incident_dir:
            from predictionio_tpu.utils.incidents import (
                IncidentCapturer,
                IncidentStore,
                default_incident_dir,
            )

            if incident_dir == "auto":
                incident_dir = default_incident_dir(
                    self.storage.config.home)
            self.incidents = IncidentCapturer(
                IncidentStore(incident_dir), process="engine")
            self.incidents.add_source("health", self._health_doc)
            self.incidents.set_history(self.tsdb, lambda: [
                "pio_engine_queries_total",
                "pio_engine_query_seconds_bucket",
                "pio_engine_query_seconds_count",
                "pio_engine_shed_total", "pio_engine_feedback_total",
                "pio_circuit_breaker_state",
            ])
            for b in self._breakers.values():
                b.on_open = lambda name: self.incidents.trigger(
                    "breaker-open", {"breaker": name})
        self._feedback_pool = None
        self._feedback_inflight = 0
        #: AOT warmup: compile the serving program for every padded
        #: batch bucket at deploy time (and pre-swap at /reload), so no
        #: query shape ≤ max_batch ever XLA-compiles on the hot path
        self._warmup = None
        ladder = None
        if aot_buckets is not None:
            from predictionio_tpu.server.aot import AOTWarmup, BucketLadder

            ladder = BucketLadder.parse(aot_buckets, batch_max)
            # an explicit ladder defines its own max batch: collecting
            # past the top bucket would dispatch an uncompiled shape
            batch_max = ladder.max_batch
            if not variants:
                self._warmup = AOTWarmup(ladder, ks=(aot_topk,))
                if self.deployed is not None:
                    self._warmup.start(self.deployed)
        #: multi-model serving: the resident variant set + its online
        #: scoreboard. Each arm gets its OWN AOTWarmup over the shared
        #: ladder geometry — same-geometry arms are pure executable-cache
        #: hits, so residency costs HBM, not compiles.
        self._mux = None
        self._scoreboard = None
        if variants:
            from predictionio_tpu.server.variant_metrics import (
                VariantScoreboard,
            )
            from predictionio_tpu.server.variants import VariantSet

            warm_factory = None
            if ladder is not None:
                def warm_factory(_ladder=ladder, _k=aot_topk):
                    from predictionio_tpu.server.aot import AOTWarmup

                    return AOTWarmup(_ladder, ks=(_k,))
            self._mux = VariantSet(
                self.storage, variants, engine_factory=engine_factory,
                variant_id=variant_id, salt=variant_salt,
                warm_factory=warm_factory)
            self._scoreboard = VariantScoreboard()
            try:
                self._mux.load()
            except Exception as e:
                if require_engine:
                    raise
                self._load_error = f"{type(e).__name__}: {e}"
            default = self._mux.get(self._mux.default)
            if default.serving():
                # the default (champion) arm also serves every legacy
                # single-model path: /, probes, model generation
                self.deployed = default.deployed
                self._warmup = default.warmup
                self._mux.start_warmups()
        self._batcher = None
        if batching:
            from predictionio_tpu.server.batching import MicroBatcher

            # bind late so /reload hot-swaps reach the batcher too
            self._batcher = MicroBatcher(
                self._batch_worker,
                max_batch=batch_max, max_wait_ms=batch_wait_ms,
                ladder=ladder)
        router = Router()
        router.route("POST", "/queries.json", self._queries)
        router.route("POST", "/feedback.json", self._feedback_route)
        router.route("GET", "/variants", self._variants_route)
        router.route("POST", "/variants/weights", self._variants_weights)
        router.route("GET", "/", self._status)
        router.route("GET", "/health", self._health)
        router.route("GET", "/reload", self._reload)
        router.route("GET", "/stop", self._stop)
        router.route("GET", "/metrics", self._metrics)
        router.route("GET", "/metrics/history", self._metrics_history)
        router.route("GET", "/traces", traces_handler)
        router.route("GET", "/plugins.json", self._plugins_list)
        router.route("GET", "/plugins/{name}/{path+}", self._plugin_route)
        router.route("POST", "/plugins/{name}/{path+}", self._plugin_route)
        if ssl_context is None:
            from predictionio_tpu.server.ssl_config import ssl_context_from_env
            ssl_context = ssl_context_from_env()
        self.http = HTTPServer(router, host, port,
                               ssl_context=ssl_context,
                               bind_retries=bind_retries,
                               bind_retry_sec=bind_retry_sec,
                               access_log=access_log,
                               server_name="engine")

    # -- workers ---------------------------------------------------------------

    def _deployed_for(self, variant: Optional[str]) -> DeployedEngine:
        """The engine behind one serving arm (the single deployed
        engine when multi-model serving is off)."""
        if variant is not None and self._mux is not None:
            rv = self._mux.get(variant)
            if rv.deployed is not None:
                return rv.deployed
        return self.deployed

    def _query_worker(self, query: Any,
                      variant: Optional[str] = None) -> Any:
        # to_thread copies the contextvars context, so this span parents
        # to the request's engine.query span automatically
        with tracing.span("engine.predict"):
            faults.inject("serving.query")
            return self._deployed_for(variant).query(query)

    def _batch_worker(self, queries: List[Any],
                      variant: Optional[str] = None) -> List[Any]:
        faults.inject("serving.query")
        return self._deployed_for(variant).batch_query(queries)

    # -- handlers --------------------------------------------------------------

    def _retry_after_hint(self) -> float:
        """Best real estimate of when a shed/not-ready 503 is worth
        retrying, instead of a hardcoded constant: the AOT warmup's
        remaining compile time when it is still warming, else the
        longest open-breaker reset window, else a couple of in-flight
        query durations (shedding clears one slot per completion)."""
        if self._warmup is not None and self._warmup.state in (
                "idle", "warming"):
            eta = self._warmup.retry_after()
            if eta > 0:
                return eta
        open_waits = [b.retry_after() for b in self._breakers.values()
                      if b.state == OPEN]
        if open_waits:
            return max(open_waits)
        if self._lat_ewma > 0:
            return max(0.1, 2.0 * self._lat_ewma)
        return 1.0

    @staticmethod
    def _unavailable(message: str, retry_after: float = 1.0) -> Response:
        body = {"message": message,
                "retryAfterSec": round(max(0.0, retry_after), 3)}
        resp = Response.json(body, status=503)
        # the header is integral seconds (RFC 9110 delta-seconds); ceil
        # so the hint is never shorter than the real wait
        resp.headers["Retry-After"] = str(max(1, math.ceil(retry_after)))
        return resp

    async def _queries(self, req: Request) -> Response:
        t0 = time.perf_counter()
        # admission control BEFORE any await: shedding costs ~nothing,
        # which is the whole point — past the cap the server answers
        # instantly instead of queueing work it cannot finish. The cap
        # is weighted-fair per app (X-PIO-App, propagated by the
        # router): at saturation the tenant OVER its share sheds first,
        # quiet tenants keep their seats. Requests with no app header
        # share one default bucket — single-tenant behavior unchanged.
        app = req.headers.get("x-pio-app", "")
        # router-originated synthetic canaries are marked X-PIO-Probe:
        # they measure the serving path but must not CHARGE anyone —
        # no tenant's fair-share seat, no variant scoreboard sample
        probe = "x-pio-probe" in req.headers
        if self.max_inflight and not probe \
                and not self._fair.try_acquire(app):
            self._m_shed.inc((app or "-",))
            self._m_queries.inc(("503",))
            return self._unavailable(
                f"server overloaded ({self._inflight} queries in "
                f"flight; app {app or 'default'} at "
                f"{self._fair.inflight(app)}/{self._fair.share(app)} "
                "of its fair share)",
                retry_after=self._retry_after_hint())
        try:
            if self.deployed is None:
                self._m_queries.inc(("503",))
                return self._unavailable(
                    f"no engine loaded ({self._load_error}); "
                    "train and GET /reload",
                    retry_after=self._retry_after_hint())
            self._inflight += 1
            try:
                async with tracing.span(
                        "engine.query",
                        deadline_ms=self.query_timeout * 1e3,
                        inflight=self._inflight,
                        feedback_breaker=self._sink_breaker.state) as sp:
                    status, resp = await self._query_once(req)
                    sp.set_attr("status", status)
                    if status in ("500", "504"):
                        sp.set_error(f"query answered {status}")
            finally:
                self._inflight -= 1
        finally:
            if self.max_inflight and not probe:
                self._fair.release(app)
        self._m_queries.inc((status,))
        dt = time.perf_counter() - t0
        if status == "200":
            # loop-thread-only, like _inflight — no lock needed
            self._lat_ewma = dt if self._lat_ewma == 0 else (
                0.9 * self._lat_ewma + 0.1 * dt)
        # the latency histogram observes EVERY outcome — the 400/500
        # (and 504) tails are exactly the slow failures worth seeing
        self._m_latency.observe(dt, (status,), exemplar=tracing.exemplar())
        if self._scoreboard is not None and not probe:
            served_by = resp.headers.get("X-PIO-Variant")
            if served_by:
                self._scoreboard.observe_request(served_by, dt, status)
        return resp

    async def _query_once(self, req: Request) -> "tuple[str, Response]":
        status, resp, variant = await self._dispatch_once(req)
        if variant is not None:
            # which arm answered (or would have) — clients and the
            # chaos harness read the split from this header
            resp.headers["X-PIO-Variant"] = variant
        return status, resp

    async def _dispatch_once(
            self, req: Request) -> "tuple[str, Response, Optional[str]]":
        variant: Optional[str] = None
        try:
            query = req.json()
        except json.JSONDecodeError as e:
            return "400", Response.json(
                {"message": f"invalid JSON: {e}"}, status=400), None
        if query is None:
            return ("400",
                    Response.json({"message": "empty query"}, status=400),
                    None)
        if self._mux is not None:
            from predictionio_tpu.server.variants import (
                VariantError,
                entity_of,
            )

            override = req.headers.get("x-pio-variant")
            try:
                variant = self._mux.choose(entity_of(query),
                                           override or None)
            except VariantError as e:
                return ("400",
                        Response.json({"message": str(e)}, status=400),
                        None)
        # a routing hop can carry the client's REMAINING budget down in
        # X-PIO-Deadline-Ms; the effective deadline is the tighter of
        # that and the server's own --query-timeout-ms
        timeout = self.query_timeout
        hop = req.headers.get("x-pio-deadline-ms")
        if hop:
            try:
                hop_sec = float(hop) / 1e3
            except ValueError:
                hop_sec = 0.0
            if hop_sec > 0:
                timeout = min(timeout, hop_sec) if timeout > 0 else hop_sec
        try:
            if self._batcher is not None:
                work = self._batcher.submit(query, group=variant)
            else:
                work = asyncio.to_thread(self._query_worker, query, variant)
            if timeout > 0:
                prediction = await asyncio.wait_for(work, timeout)
            else:
                prediction = await work
        except asyncio.TimeoutError:
            # the worker thread may still be running; admission control
            # above bounds how many such stragglers can pile up
            self._m_deadline.inc()
            return "504", Response.json(
                {"message": "query deadline exceeded "
                            f"({timeout * 1e3:.0f} ms)"},
                status=504), variant
        except (ValueError, KeyError, TypeError) as e:
            # malformed/invalid query (bad fields, unknown entity, wrong types)
            return "400", Response.json(
                {"message": f"query failed: {type(e).__name__}: {e}"},
                status=400), variant
        except Exception as e:
            # internal fault; retryable, so 500 (the reference returns
            # 500 on server faults). Micro-batch failures are isolated
            # per-query by the batcher, so a malformed query still
            # surfaces as its own ValueError → 400 above.
            return "500", Response.json(
                {"message": f"server error: {type(e).__name__}: {e}"},
                status=500), variant
        for p in self.plugins:
            prediction = p.output_blocker(query, prediction)
            p.output_sniffer(query, prediction)
        with self._counts_lock:
            self.query_count += 1
        self._last_good_query = query
        if self.feedback:
            pr_id = uuid.uuid4().hex
            if isinstance(prediction, dict):
                prediction = {**prediction, "prId": pr_id}
            if variant is not None and self._scoreboard is not None:
                # remember what was served under this prId so feedback
                # can be attributed and scored per arm
                self._scoreboard.record_served(pr_id, variant, prediction)
            self._submit_feedback(query, prediction, pr_id, variant)
        return "200", Response.json(prediction), variant

    def _submit_feedback(self, query: Any, prediction: Any,
                         pr_id: str, variant: Optional[str] = None) -> None:
        """Queue feedback on a DEDICATED small executor — a slow or down
        Event Server (HTTP sink blocks up to its timeout) must not eat
        the shared to_thread pool that query handling runs on. Bounded:
        past 256 in flight, feedback drops (counted), serving doesn't."""
        import concurrent.futures

        if self._feedback_pool is None:
            self._feedback_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="pio-feedback")
        with self._counts_lock:
            if self._feedback_inflight >= 256:
                drop = True
            else:
                drop = False
                self._feedback_inflight += 1
        if drop:
            self._m_feedback.inc(("dropped",))
            return

        def run():
            try:
                self._record_feedback(query, prediction, pr_id, variant)
            finally:
                with self._counts_lock:
                    self._feedback_inflight -= 1

        # a raw executor does not copy contextvars; bind_current carries
        # the request's span so feedback/sink spans join the query trace
        self._feedback_pool.submit(tracing.bind_current(run))

    def _sink(self):
        if self._event_sink is None:
            # no Event Server configured: fall back to the in-process
            # write against the app named in the trained instance's
            # data-source params
            from predictionio_tpu.server.eventsink import DirectEventSink

            app_name = self.feedback_app_name
            if not app_name:
                dsp = json.loads(self.deployed.instance.data_source_params)
                app_name = dsp.get("app_name") or dsp.get("appName")
            if not app_name:
                return None
            self._event_sink = DirectEventSink(self.storage, app_name)
        return self._event_sink

    def _record_feedback(self, query: Any, prediction: Any, pr_id: str,
                         variant: Optional[str] = None) -> None:
        """Feedback loop: served predictions become 'predict' events
        tagged with prId, delivered through the configured sink —
        the Event Server's authenticated HTTP API when a feedback URL
        is set (reference: CreateServer feedback, SURVEY.md §3.2), else
        a direct local write. Delivery runs through the sink breaker:
        repeated failures trip it open and subsequent feedback drops
        fast (counted as breaker_open) until the sink recovers. With
        multi-model serving the event carries the SERVING VARIANT, so
        downstream consumers can score arms without the prId map."""
        with tracing.span("engine.feedback", pr_id=pr_id) as sp:
            try:
                sink = self._sink()
                if sink is None:
                    sp.set_attr("result", "no_sink")
                    return
                props = {"query": query, "prediction": prediction}
                if variant is not None:
                    props["variant"] = variant
                self._sink_breaker.call(sink.send, Event(
                    event="predict",
                    entity_type="pio_pr", entity_id=pr_id,
                    properties=props,
                    pr_id=pr_id,
                ))
                self._m_feedback.inc(("ok",))
                sp.set_attr("result", "ok")
            except CircuitOpenError:
                self._m_feedback.inc(("breaker_open",))
                sp.set_error("feedback sink breaker open")
            except Exception as e:
                self._m_feedback.inc(("error",))  # never breaks serving
                sp.set_error(f"{type(e).__name__}: {e}")

    # -- variant surface -------------------------------------------------------

    async def _feedback_route(self, req: Request) -> Response:
        """POST /feedback.json — close the online loop for one served
        prediction: ``{"prId": ..., "rating": 4.0, "item": ...}`` or
        ``{"prId": ..., "click": true}`` (an explicit ``"variant"``
        attributes directly when the prId is unknown/evicted). Accrues
        into the per-variant online series the ``--gate online``
        promotion gate reads."""
        if self._scoreboard is None:
            return Response.json(
                {"message": "variant serving not enabled "
                            "(deploy with --variants)"}, status=404)
        try:
            body = req.json()
        except json.JSONDecodeError as e:
            return Response.json(
                {"message": f"invalid JSON: {e}"}, status=400)
        if not isinstance(body, dict):
            return Response.json(
                {"message": "feedback body must be a JSON object"},
                status=400)
        rating = body.get("rating")
        if rating is not None:
            try:
                rating = float(rating)
            except (TypeError, ValueError):
                return Response.json(
                    {"message": f"bad rating {body.get('rating')!r}"},
                    status=400)
        clicked = body.get("click", body.get("clicked"))
        variant = self._scoreboard.observe_feedback(
            pr_id=body.get("prId"),
            variant=body.get("variant"),
            rating=rating,
            item=body.get("item"),
            clicked=bool(clicked) if clicked is not None else None)
        if variant is None:
            return Response.json(
                {"message": "feedback not attributable: unknown prId "
                            "and no variant given"}, status=404)
        return Response.json({"accepted": True, "variant": variant})

    async def _variants_route(self, req: Request) -> Response:
        """GET /variants — the resident variant set: per-arm generation,
        warmup state, weights, and accrued online stats."""
        if self._mux is None:
            return Response.json(
                {"message": "variant serving not enabled"}, status=404)
        snap = self._mux.snapshot()
        if self._scoreboard is not None:
            stats = self._scoreboard.snapshot()
            for name, v in snap["variants"].items():
                v["online"] = stats.get(name)
        return Response.json(snap)

    async def _variants_weights(self, req: Request) -> Response:
        """POST /variants/weights — probe-then-apply split edit:
        ``{"weights": {"champion": 9, "challenger": 1}}``. Every named
        arm must be resident AND serving or NOTHING changes (409)."""
        if self._mux is None:
            return Response.json(
                {"message": "variant serving not enabled"}, status=404)
        from predictionio_tpu.server.variants import VariantError

        try:
            body = req.json()
        except json.JSONDecodeError as e:
            return Response.json(
                {"message": f"invalid JSON: {e}"}, status=400)
        weights = body.get("weights") if isinstance(body, dict) else None
        if not isinstance(weights, dict):
            return Response.json(
                {"message": 'body must be {"weights": {name: weight}}'},
                status=400)
        try:
            eff = self._mux.set_weights(weights)
        except VariantError as e:
            return Response.json({"message": str(e)}, status=409)
        return Response.json({
            "applied": True,
            "effectiveWeights": dict(eff),
            "weightsEpoch": self._mux.weights_epoch,
        })

    async def _status(self, req: Request) -> Response:
        if self.deployed is None:
            return Response.json({
                "status": "not-ready",
                "message": self._load_error,
                "startTime": self.start_time.isoformat(timespec="milliseconds"),
                "queryCount": self.query_count,
            })
        ei = self.deployed.instance
        return Response.json({
            "status": "alive",
            "engineFactory": ei.engine_factory,
            "engineInstanceId": ei.id,
            "engineVariant": ei.engine_variant,
            "startTime": self.start_time.isoformat(timespec="milliseconds"),
            "queryCount": self.query_count,
            "algorithms": [name for name, _ in self.deployed.algorithms],
        })

    def _health_doc(self) -> Dict[str, Any]:
        """Sync health/variants snapshot for incident bundles — the
        /health body's facts without going through the event loop."""
        doc: Dict[str, Any] = {
            "breakers": {n: b.state for n, b in self._breakers.items()},
            "inflight": self._inflight,
            "reloadGeneration": self.reload_generation,
            "lastSwap": self.last_swap,
            "instance": self.instance_uid,
            "startedAt": round(self.start_epoch, 3),
            "loaded": self.deployed is not None,
        }
        if self._warmup is not None:
            doc["warmup"] = self._warmup.progress()
        if self._mux is not None:
            doc["variants"] = self._mux.snapshot()
        return doc

    async def _health(self, req: Request) -> Response:
        """Liveness/readiness for supervisors and load balancers.

        - ``200 {"status": "ok"}``       — serving, all breakers closed
        - ``200 {"status": "degraded"}`` — serving, but a dependency
          breaker is open, the server is at its inflight cap, or AOT
          warmup FAILED (queries still serve via jit fallback, just
          with first-shape compile cliffs); a supervisor must NOT
          restart on this (restarting doesn't fix a down dependency),
          which is why degraded stays < 500
        - ``503 {"status": "not-ready"}``— no engine loaded yet, or
          the AOT bucket ladder is still compiling (``warmup`` block
          carries progress); a load balancer keeps traffic off the
          instance until every serving bucket is precompiled
        """
        open_breakers = [n for n, b in self._breakers.items()
                         if b.state == OPEN]
        at_capacity = bool(self.max_inflight
                           and self._inflight >= self.max_inflight)
        body = {
            "breakers": {n: b.state for n, b in self._breakers.items()},
            "inflight": self._inflight,
            "inflightByApp": self._fair.snapshot(),
            "reloadGeneration": self.reload_generation,
            "modelGeneration": self._model_generation(),
            "lastSwap": self.last_swap,
            "instance": self.instance_uid,
            "startedAt": round(self.start_epoch, 3),
        }
        if self._warmup is not None:
            body["warmup"] = self._warmup.progress()
        if self._mux is not None:
            # the resident variant set: per-arm generation + warmup
            # state, so a router/operator sees the split without /variants
            body["variants"] = self._mux.snapshot()
        if self.deployed is None:
            return self._not_ready(self._load_error or "no engine loaded",
                                   body)
        if self._warmup is not None and self._warmup.state in (
                "idle", "warming"):
            return self._not_ready("aot warmup in progress", body)
        mux_warm = (self._mux.warm_state() if self._mux is not None
                    else "ready")
        if mux_warm == "warming":
            return self._not_ready("variant aot warmup in progress", body)
        failed_arms = ([n for n, v in body["variants"]["variants"].items()
                        if v["state"] == "failed"]
                       if self._mux is not None else [])
        warmup_failed = (self._warmup is not None
                         and self._warmup.state == "failed")
        if (open_breakers or at_capacity or warmup_failed
                or mux_warm == "failed" or failed_arms):
            reason = ("breaker open: " + ",".join(open_breakers)
                      if open_breakers else
                      "at inflight capacity" if at_capacity else
                      "aot warmup failed" if warmup_failed else
                      "variant aot warmup failed" if mux_warm == "failed"
                      else "variant failed: " + ",".join(failed_arms))
            return Response.json(
                {"status": "degraded", "reason": reason, **body})
        return Response.json({"status": "ok", **body})

    def _model_generation(self) -> Optional[int]:
        """Registry generation of the SERVING instance, or None when no
        engine is loaded / the instance predates the registry / there is
        no registry at this storage home (batch-only deployments)."""
        if self.deployed is None:
            return None
        try:
            if self._model_registry is None:
                from predictionio_tpu.storage.models import model_registry

                self._model_registry = model_registry(self.storage)
            return self._model_registry.find_gen(self.deployed.instance.id)
        except Exception:
            return None

    def _record_swap(self, outcome: str, **extra: Any) -> Dict[str, Any]:
        """Remember a /reload outcome for /health's ``lastSwap``:
        ``promoted`` (swap landed), ``rolled_back`` (candidate failed
        warmup/probe, old engine kept), ``refused`` (candidate never
        loaded — prepare_deploy failed)."""
        self.last_swap = {"outcome": outcome,
                          "at": round(time.time(), 3), **extra}
        return self.last_swap

    def _not_ready(self, reason: str, body: Dict[str, Any]) -> Response:
        hint = self._retry_after_hint()
        resp = Response.json(
            {"status": "not-ready", "reason": reason,
             "retryAfterSec": round(hint, 3), **body},
            status=503)
        resp.headers["Retry-After"] = str(max(1, math.ceil(hint)))
        return resp

    def _probe_worker(self, candidate: DeployedEngine, probe: Any) -> None:
        faults.inject("serving.reload")
        candidate.query(probe)

    async def _reload(self, req: Request) -> Response:
        """Hot-swap to the latest COMPLETED instance (reference: /reload).

        Hardened: reloads are serialized; the last-good engine keeps
        serving throughout; the candidate must answer a probe query
        (the last successfully served one) before the swap. A candidate
        that loads but cannot serve therefore never becomes live —
        equivalent to an automatic rollback, minus the window where
        live traffic could have hit the broken engine.
        """
        if self._reload_lock is None:
            self._reload_lock = asyncio.Lock()
        async with tracing.span("engine.reload",
                                generation=self.reload_generation) as sp, \
                self._reload_lock:
            if self._mux is not None:
                return await self._reload_variant_locked(req, sp)
            factory = self.engine_factory or (
                self.deployed.instance.engine_factory
                if self.deployed is not None else None)
            if factory is None:
                self._m_reloads.inc(("failed",))
                sp.set_error("no engine factory known")
                return Response.json(
                    {"message": "reload failed: no engine factory known"},
                    status=500)
            try:
                new = await asyncio.to_thread(
                    prepare_deploy, factory, None, self.storage,
                    self.variant_id)
            except Exception as e:
                self._m_reloads.inc(("failed",))
                sp.set_error(f"reload failed: {e}")
                self._record_swap("refused", reason=f"{type(e).__name__}: {e}")
                return Response.json(
                    {"message": f"reload failed: {e}", "swap": "refused"},
                    status=500)
            if self._warmup is not None:
                # warm the CANDIDATE's bucket ladder BEFORE the probe
                # and swap: a same-geometry candidate is pure
                # executable-cache hits (zero compiles); a new geometry
                # compiles here, off the hot path, while the old engine
                # keeps serving. Either way the probe below — and the
                # first post-swap query — run precompiled.
                try:
                    await asyncio.to_thread(self._warmup.warm_sync, new)
                    self._warmup.mark_ready()
                except Exception as e:
                    old = self.deployed
                    self._m_reloads.inc(("rolled_back",))
                    sp.set_error("aot warmup failed; rolled back")
                    kept = (old.instance.id if old is not None else None)
                    self._record_swap(
                        "rolled_back", reason="aot warmup failed",
                        engineInstanceId=kept)
                    return Response.json(
                        {"message": "reload rolled back: aot warmup failed: "
                                    f"{type(e).__name__}: {e}",
                         "engineInstanceId": kept, "swap": "rolled_back"},
                        status=500)
            probe = self._last_good_query
            if self.reload_probe and probe is not None:
                try:
                    work = asyncio.to_thread(self._probe_worker, new, probe)
                    if self.query_timeout > 0:
                        await asyncio.wait_for(work, self.query_timeout)
                    else:
                        await work
                except Exception as e:
                    old = self.deployed
                    self._m_reloads.inc(("rolled_back",))
                    sp.set_error("probe query failed; rolled back")
                    kept = (old.instance.id if old is not None else None)
                    self._record_swap(
                        "rolled_back", reason="probe query failed",
                        engineInstanceId=kept)
                    return Response.json(
                        {"message": "reload rolled back: probe query failed: "
                                    f"{type(e).__name__}: {e}",
                         "engineInstanceId": kept, "swap": "rolled_back"},
                        status=500)
            self.deployed = new
            self.reload_generation += 1
            self._m_reload_gen.set(self.reload_generation)
            self._m_reloads.inc(("ok",))
            sp.set_attr("result", "ok")
            self._load_error = None
            self._record_swap("promoted", engineInstanceId=new.instance.id,
                              modelGeneration=self._model_generation())
            return Response.json({"message": "Reloaded",
                                  "engineInstanceId": new.instance.id,
                                  "reloadGeneration": self.reload_generation,
                                  "modelGeneration": self._model_generation(),
                                  "swap": "promoted"})

    async def _reload_variant_locked(self, req: Request, sp: Any) -> Response:
        """``/reload[?variant=name]`` under multi-model serving: swap
        ONE arm onto its freshly-resolved registry generation, leaving
        every other arm resident and serving. Defaults to the champion
        arm. Outcomes mirror the single-model reload: ``promoted``,
        ``rolled_back`` (default arm keeps its last-good engine),
        ``failed`` (a non-default arm drops out of the split — the
        champion absorbs its weight until the next successful swap)."""
        from predictionio_tpu.server.variants import VariantError

        target = req.param("variant") or self._mux.default
        probe_fn = None
        if self.reload_probe and self._last_good_query is not None:
            last = self._last_good_query

            def probe_fn(candidate: Any, _q: Any = last) -> None:
                faults.inject("serving.reload")
                candidate.query(_q)

        try:
            out = await asyncio.to_thread(
                self._mux.reload_variant, target, probe_fn)
        except VariantError as e:
            self._m_reloads.inc(("failed",))
            sp.set_error(str(e))
            return Response.json({"message": str(e)}, status=404)
        if out["outcome"] == "promoted":
            rv = self._mux.get(target)
            if target == self._mux.default:
                self.deployed = rv.deployed
                self._warmup = rv.warmup or self._warmup
                self._load_error = None
            self.reload_generation += 1
            self._m_reload_gen.set(self.reload_generation)
            self._m_reloads.inc(("ok",))
            sp.set_attr("result", "ok")
            self._record_swap(
                "promoted", variant=target,
                engineInstanceId=out.get("engineInstanceId"),
                modelGeneration=out.get("generation"))
            return Response.json({
                "message": "Reloaded", "variant": target,
                "engineInstanceId": out.get("engineInstanceId"),
                "modelGeneration": out.get("generation"),
                "reloadGeneration": self.reload_generation,
                "swap": "promoted"})
        result = out["outcome"]  # rolled_back | failed
        self._m_reloads.inc((result,))
        sp.set_error(f"variant reload {result}: {out.get('error')}")
        self._record_swap(result, variant=target, reason=out.get("error"))
        return Response.json(
            {"message": f"reload {result}: {out.get('error')}",
             "variant": target, "swap": result},
            status=500)

    async def _stop(self, req: Request) -> Response:
        asyncio.get_running_loop().call_later(0.05, self.http.request_shutdown)
        return Response.json({"message": "Shutting down"})

    async def _metrics(self, req: Request) -> Response:
        from predictionio_tpu.utils.metrics import REGISTRY

        return Response.text(REGISTRY.render(),
                             content_type="text/plain; version=0.0.4")

    async def _metrics_history(self, req: Request) -> Response:
        from predictionio_tpu.utils.timeseries import history_payload

        status, payload = history_payload(
            self.tsdb, req.param("series") or "", req.param("window") or "")
        return Response.json(payload, status=status)

    async def _plugins_list(self, req: Request) -> Response:
        return Response.json({"plugins": {
            "outputblockers": [p.name for p in self.plugins],
            "outputsniffers": [p.name for p in self.plugins],
        }})

    async def _plugin_route(self, req: Request) -> Response:
        name = req.path_params["name"]
        for p in self.plugins:
            if p.name == name:
                body = req.json() if req.body else None
                out = p.handle_route(req.path_params["path"], body)
                return Response.json(out)
        return Response.json({"message": f"no plugin {name!r}"}, status=404)

    # -- lifecycle -------------------------------------------------------------

    async def serve_forever(self) -> None:
        from predictionio_tpu.utils.timeseries import scrape_loop

        if self.incidents is not None:
            from predictionio_tpu.utils.incidents import (
                install_crash_handlers,
            )

            install_crash_handlers(self.incidents)
        scraper = asyncio.create_task(
            scrape_loop(self.tsdb, self.scrape_interval),
            name="pio-engine-tsdb")
        try:
            await self.http.serve_forever()
        finally:
            scraper.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await scraper
            # the batcher's collector task must die BEFORE the loop
            # closes: a pending queue.get() getter cancelled at
            # interpreter teardown touches the closed loop and raises
            # "Event loop is closed" (surfaced by the r4 concurrency
            # harness)
            if self._batcher is not None:
                self._batcher.stop()

    def run(self) -> None:
        asyncio.run(self.serve_forever())
