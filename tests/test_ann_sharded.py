"""Mesh-sharded ANN serving: distributed ADC scan + top-k merge.

Contract under test (docs/perf.md "Sharded retrieval"):

- ``shards=1`` through the full shard_map program is BITWISE identical
  to the single-device ``ANNScorer`` — degenerate collectives must not
  perturb one bit;
- 2-/4-way sharded serving returns the SAME items as unsharded (each
  global-top candidate is in its own shard's local top-k′, so the
  k′×S merge provably covers the dense top-k′);
- the OPQ rotation + shard hint round-trip through the versioned
  ``PIOANN01`` blob, and legacy un-rotated v1 blobs still load and
  serve;
- PAD-masked parity holds across every AOT bucket of a ladder.

Runs on the conftest's 8 virtual CPU devices.
"""

import os
import struct

import numpy as np
import pytest

from predictionio_tpu import ann
from predictionio_tpu.ann.index import PQIndex, shard_layout, shard_view
from predictionio_tpu.ann.scorer import ANNScorer, ShardedANNScorer


@pytest.fixture(autouse=True, scope="module")
def _restore_aot_counters():
    from predictionio_tpu.server import aot as aot_mod

    counters = (aot_mod.EXECUTABLES._m_lookups, aot_mod._DISPATCHES)
    snaps = [dict(c._values) for c in counters]
    yield
    for c, snap in zip(counters, snaps):
        with c._lock:
            c._values.clear()
            c._values.update(snap)


def _clustered(n, d, centers, seed=0, noise=0.2):
    rng = np.random.default_rng(seed)
    C = rng.standard_normal((centers, d)).astype(np.float32)
    V = (C[rng.integers(0, centers, size=n)]
         + noise * rng.standard_normal((n, d)).astype(np.float32))
    V /= np.linalg.norm(V, axis=1, keepdims=True) + 1e-9
    return V


def _corpus(n=3000, d=16, seed=8, n_users=64):
    rng = np.random.default_rng(seed)
    V = _clustered(n, d, 40, seed=seed)
    U = rng.standard_normal((n_users, d)).astype(np.float32)
    U /= np.linalg.norm(U, axis=1, keepdims=True) + 1e-9
    return U, V


# -- shards=1 bitwise parity ---------------------------------------------------


class TestShard1Bitwise:
    def test_topk_bitwise_equal_to_single_device(self):
        U, V = _corpus()
        idx = ann.build_index(V, 4, 16, iters=3, sample=len(V))
        base = ANNScorer(U, V, idx, shortlist=64)
        s1 = ShardedANNScorer(U, V, idx, shortlist=64, shards=1)
        ids = np.arange(32, dtype=np.int32)
        bv, bi = base._topk(ids, 10)
        sv, si = s1._topk(ids, 10)
        assert np.array_equal(bv, sv)   # bitwise, not allclose
        assert np.array_equal(bi, si)

    def test_bitwise_holds_with_opq_rotation(self):
        U, V = _corpus(seed=9)
        idx = ann.build_index(V, 4, 16, iters=3, sample=len(V),
                              opq=True, opq_iters=2)
        assert idx.rotation is not None
        base = ANNScorer(U, V, idx, shortlist=64)
        s1 = ShardedANNScorer(U, V, idx, shortlist=64, shards=1)
        ids = np.arange(16, dtype=np.int32)
        bv, bi = base._topk(ids, 10)
        sv, si = s1._topk(ids, 10)
        assert np.array_equal(bv, sv) and np.array_equal(bi, si)


# -- distributed merge parity on real meshes -----------------------------------


class TestMergeParity:
    @pytest.mark.parametrize("shards", [2, 4])
    def test_sharded_equals_unsharded(self, shards):
        """Every dense-top-k′ candidate sits inside its own shard's
        local top-k′, so merge(k′×S) ⊇ dense top-k′ and the served
        items match exactly."""
        U, V = _corpus(n=3100, seed=10)   # uneven: last shard padded
        idx = ann.build_index(V, 4, 16, iters=3, sample=len(V))
        base = ANNScorer(U, V, idx, shortlist=64)
        sh = ShardedANNScorer(U, V, idx, shortlist=64, shards=shards)
        assert sh.local_n * shards >= 3100
        ids = np.arange(32, dtype=np.int32)
        bv, bi = base._topk(ids, 16)
        sv, si = sh._topk(ids, 16)
        assert np.array_equal(bi, si)
        # non-owner shards contribute exact zeros through the psum, so
        # values match up to fp reduction order
        np.testing.assert_allclose(bv, sv, rtol=1e-5, atol=1e-6)

    def test_ops_level_merge_matches_dense_topk(self):
        import jax.numpy as jnp

        from predictionio_tpu.ops import merge_shortlists

        rng = np.random.default_rng(3)
        S, B, kp = 4, 8, 16
        vals = rng.standard_normal((S, B, kp)).astype(np.float32)
        # per-shard shortlists arrive sorted desc (lax.top_k output)
        vals = -np.sort(-vals, axis=-1)
        idx = rng.integers(0, 10_000, (S, B, kp)).astype(np.int32)
        mv, mi = merge_shortlists(jnp.asarray(vals), jnp.asarray(idx), kp)
        flat_v = np.moveaxis(vals, 0, 1).reshape(B, S * kp)
        flat_i = np.moveaxis(idx, 0, 1).reshape(B, S * kp)
        for b in range(B):
            order = np.argsort(-flat_v[b], kind="stable")[:kp]
            np.testing.assert_allclose(np.asarray(mv)[b], flat_v[b][order])
            np.testing.assert_array_equal(np.asarray(mi)[b],
                                          flat_i[b][order])

    def test_pad_candidates_never_served(self):
        """k′·S larger than the corpus forces pad indices through the
        merge; the served ids must all be real rows."""
        U, V = _corpus(n=50, seed=11)
        idx = ann.build_index(V, 4, 8, iters=2, sample=len(V))
        sh = ShardedANNScorer(U, V, idx, shortlist=16, shards=4)
        assert sh.local_n * 4 > 50          # pad tail exists
        ids = np.arange(8, dtype=np.int32)
        _, si = sh._topk(ids, 8)
        assert si.max() < 50


# -- versioned blob: OPQ rotation + shard hint ---------------------------------


class TestVersionedBlob:
    def test_plain_index_stays_version_1(self):
        V = _clustered(400, 16, 10, seed=12)
        idx = ann.build_index(V, 4, 16, iters=2, sample=400)
        blob = idx.to_bytes()
        (hlen,) = struct.unpack_from("<I", blob, 8)
        import json

        header = json.loads(blob[12:12 + hlen])
        assert header["version"] == 1
        assert "has_rotation" not in header

    def test_opq_shard_blob_roundtrip_and_serves(self):
        U, V = _corpus(n=800, seed=13)
        idx = ann.build_index(V, 4, 16, iters=2, sample=800,
                              opq=True, opq_iters=2, shards=4)
        R = idx.rotation
        assert R is not None
        # learned rotation stays orthogonal (inner products preserved)
        np.testing.assert_allclose(R @ R.T, np.eye(R.shape[0]),
                                   atol=1e-4)
        back = PQIndex.from_bytes(idx.to_bytes())
        np.testing.assert_array_equal(back.rotation, R)
        np.testing.assert_array_equal(back.codes, idx.codes)
        assert back.meta.get("shards") == 4
        s = ANNScorer(U, V, back, shortlist=64)
        iv, vv = s.recommend(3, 5)
        assert len(iv) == 5 and np.isfinite(vv).all()

    def test_legacy_v1_blob_loads_and_serves(self):
        """Un-rotated blobs written before the OPQ/shards header
        extension keep loading — and serve through both scorers."""
        U, V = _corpus(n=600, seed=14)
        idx = ann.build_index(V, 4, 16, iters=2, sample=600)
        back = PQIndex.from_bytes(idx.to_bytes())   # v1 wire bytes
        assert back.rotation is None
        single = ANNScorer(U, V, back, shortlist=64)
        sharded = ShardedANNScorer(U, V, back, shortlist=64, shards=2)
        ids = np.arange(8, dtype=np.int32)
        bv, bi = single._topk(ids, 8)
        sv, si = sharded._topk(ids, 8)
        assert np.array_equal(bi, si)

    def test_manifest_carries_rotation_and_shards(self, tmp_path):
        V = _clustered(500, 16, 10, seed=15)
        idx = ann.build_index(V, 4, 16, iters=2, sample=500,
                              opq=True, opq_iters=1, shards=2)
        man = ann.manifest_dict(idx, "0" * 64)
        assert man["version"] == 2
        assert man["rotation_bytes"] == 16 * 16 * 4
        assert man["shards"] == 2


# -- PAD masking across AOT buckets --------------------------------------------


class TestPadMaskingAcrossBuckets:
    def test_parity_on_every_bucket(self):
        from predictionio_tpu.server.aot import BucketLadder

        U, V = _corpus(n=2600, seed=16)
        idx = ann.build_index(V, 4, 16, iters=3, sample=len(V))
        ladder = BucketLadder([4, 8, 16])
        base = ANNScorer(U, V, idx, shortlist=64)
        sh = ShardedANNScorer(U, V, idx, shortlist=64, shards=4)
        base.warm_buckets(ladder, ks=(8,))
        sh.warm_buckets(ladder, ks=(8,))
        for B in (1, 3, 4, 5, 8, 11, 16):   # off-bucket → PAD rows
            ids = np.arange(B, dtype=np.int32)
            want = base.recommend_batch(ids, 8)
            got = sh.recommend_batch(ids, 8)
            assert len(got) == B
            for (wi, wv), (gi, gv) in zip(want, got):
                np.testing.assert_array_equal(wi, gi)
                np.testing.assert_allclose(wv, gv, rtol=1e-5, atol=1e-6)

    def test_zero_compiles_after_warmup(self):
        from predictionio_tpu.server import aot as aot_mod
        from predictionio_tpu.server.aot import BucketLadder

        U, V = _corpus(n=2400, seed=17)
        idx = ann.build_index(V, 4, 16, iters=2, sample=len(V))
        sh = ShardedANNScorer(U, V, idx, shortlist=64, shards=2)
        sh.warm_buckets(BucketLadder([8, 16]), ks=(8,))
        sh.recommend_batch(np.arange(8, dtype=np.int32), 8)  # first touch
        compiles0 = aot_mod.EXECUTABLES.counts().get("compile", 0)
        for B in (2, 8, 13, 16):
            sh.recommend_batch(np.arange(B, dtype=np.int32), 8)
        assert aot_mod.EXECUTABLES.counts().get("compile", 0) == compiles0


# -- scorer selection ----------------------------------------------------------


class TestScorerSelection:
    def test_blob_shard_hint_selects_sharded(self, monkeypatch):
        monkeypatch.setenv("PIO_ALS_SERVE", "device")
        monkeypatch.delenv("PIO_ANN_SHARDS", raising=False)
        U, V = _corpus(n=400, seed=18)
        idx = ann.build_index(V, 4, 8, iters=2, sample=400, shards=2)
        s = ann.maybe_ann_scorer(U, V, idx)
        assert isinstance(s, ShardedANNScorer) and s.shards == 2
        # cached reuse: same arrays, same geometry → same object
        assert ann.maybe_ann_scorer(U, V, idx, cached=s) is s

    def test_env_overrides_hint_and_argument(self, monkeypatch):
        monkeypatch.setenv("PIO_ALS_SERVE", "device")
        monkeypatch.setenv("PIO_ANN_SHARDS", "4")
        U, V = _corpus(n=400, seed=19)
        idx = ann.build_index(V, 4, 8, iters=2, sample=400, shards=2)
        s = ann.maybe_ann_scorer(U, V, idx, shards=2)
        assert isinstance(s, ShardedANNScorer) and s.shards == 4

    def test_too_few_devices_degrades_to_unsharded(self, monkeypatch):
        monkeypatch.setenv("PIO_ALS_SERVE", "device")
        monkeypatch.delenv("PIO_ANN_SHARDS", raising=False)
        U, V = _corpus(n=400, seed=20)
        idx = ann.build_index(V, 4, 8, iters=2, sample=400)
        s = ann.maybe_ann_scorer(U, V, idx, shards=64)   # > 8 devices
        assert type(s) is ANNScorer

    def test_shards_one_means_unsharded(self, monkeypatch):
        monkeypatch.setenv("PIO_ALS_SERVE", "device")
        monkeypatch.delenv("PIO_ANN_SHARDS", raising=False)
        U, V = _corpus(n=400, seed=21)
        idx = ann.build_index(V, 4, 8, iters=2, sample=400)
        assert type(ann.maybe_ann_scorer(U, V, idx, shards=1)) is ANNScorer


# -- jax-free layout math ------------------------------------------------------


class TestShardViewMath:
    def test_layout_and_view(self):
        lay = shard_layout(100, 8)
        assert lay == {"shards": 8, "rows_per_shard": 13,
                       "padded_items": 104}
        man = {"n_items": 1_000_000, "m": 8, "dim": 64,
               "codebook_bytes": 8 * 256 * 8 * 4, "rotation_bytes": 0}
        sv = shard_view(man, 4)
        assert sv["rows_per_shard"] == 250_000
        assert sv["code_bytes_per_shard"] == 250_000 * 8
        assert sv["rerank_bytes_per_shard"] == 250_000 * 64 * 4
        assert sv["hbm_per_device_bytes"] == (
            sv["code_bytes_per_shard"] + sv["rerank_bytes_per_shard"]
            + sv["replicated_bytes"])

    def test_cli_index_status_shards_is_jax_free(self, tmp_path,
                                                 monkeypatch):
        """`pio index status --shards N` must never import jax — it
        runs on ops boxes with no accelerator stack."""
        import json as _json
        import subprocess
        import sys

        V = _clustered(300, 16, 8, seed=22)
        idx = ann.build_index(V, 4, 8, iters=2, sample=300)
        ann.save_index(idx, str(tmp_path))
        code = (
            "import sys, json\n"
            "sys.modules['jax'] = None  # poison: any import explodes\n"
            "from predictionio_tpu.ann.index import shard_view\n"
            f"man = json.load(open({str(tmp_path / 'ann_index.json')!r}))\n"
            "print(json.dumps(shard_view(man, 4)))\n"
        )
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        assert out.returncode == 0, out.stderr
        sv = _json.loads(out.stdout)
        assert sv["shards"] == 4 and sv["rows_per_shard"] == 75
