"""App-facing event access: the stable API templates program against.

Equivalent of the reference's ``PEventStore`` / ``LEventStore`` +
``Common`` app-name resolution (reference: [U] data/.../store/ —
unverified, SURVEY.md §2a). Templates call these with an **app name**
(not id); channel by name. Two access shapes:

- :func:`find` / :func:`aggregate_properties` — bulk reads for training
  (the reference's ``PEventStore``; instead of producing an RDD they
  produce Python iterators/dicts that the data pipeline turns into
  columnar numpy/jax arrays).
- :func:`find_by_entity` — low-latency point lookups at serving time
  (the reference's ``LEventStore.findByEntity``, used by the e-commerce
  template for live business rules).
"""

from __future__ import annotations

import datetime as _dt
import math as _math
import os as _os
import re as _re
import time as _time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from predictionio_tpu.data.event import Event, PropertyMap
from predictionio_tpu.storage.registry import Storage, get_storage
from predictionio_tpu.utils import tracing as _tracing
from predictionio_tpu.utils.metrics import REGISTRY as _REGISTRY

_SNAP_HITS = _REGISTRY.counter(
    "pio_snapshot_cache_hits_total",
    "Training columnar scans served from the snapshot cache")
_SNAP_MISSES = _REGISTRY.counter(
    "pio_snapshot_cache_misses_total",
    "Training columnar scans that fell back to a full rescan",
    labelnames=("reason",))
_SNAP_DELTA_ROWS = _REGISTRY.counter(
    "pio_snapshot_delta_rows_total",
    "Rows appended to snapshots by incremental delta scans")
_SCAN_SECONDS = _REGISTRY.histogram(
    "pio_columnar_scan_seconds",
    "Wall time of columnar training reads (cached or not)",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0,
             2.5, 5.0, 10.0, 30.0, 60.0, 120.0))

# The rating-value grammar shared with the native columnar scan
# (eventlog.cc decimal_number_shape): JSON-style decimal numbers —
# DELIBERATELY narrower than Python float() (no hex, no inf/nan
# words, no underscore literals, ASCII digits only — the C++ side is
# byte-oriented) so the native and generic training reads keep/drop
# exactly the same events on every backend.
_NUM_RE = _re.compile(r"[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?", _re.ASCII)


def _native_scan(storage: Optional[Storage]):
    """(scan_columnar, storage) when the configured event store
    exposes the native columnar scan, else (None, None). Unconfigured
    storage is not an error — the generic find() path resolves (or is
    test-seamed) on its own."""
    try:
        st = storage or get_storage()
        scan = getattr(st.events, "scan_columnar", None)
    except Exception:
        return None, None
    return (scan, st) if scan is not None else (None, None)


# -- snapshot cache -----------------------------------------------------------
#
# Repeat `pio train` over a mostly-append-only log should cost O(new
# events), not O(event log) (ISSUE 1 / docs/perf.md "Incremental
# columnar snapshot cache"). The policy layer lives here; the disk
# format in data/snapshot.py; the per-backend creationTime predicate
# pushdown in the stores' scan_columnar/creation_stats.

_scan_cache_override: Optional[bool] = None

# Rewriting the snapshot npz costs O(snapshot); a steady-state warm
# read must not pay it for a tiny delta. The snapshot is recompacted
# only once the delta reaches 1/_COMPACT_FACTOR of its size — below
# that the old snapshot (and watermark) stay put and the next train
# re-scans the same still-small delta.
_COMPACT_FACTOR = 8


def set_scan_cache(enabled: Optional[bool]) -> Optional[bool]:
    """Process-wide snapshot-cache toggle; returns the previous value
    so callers (run_train's --no-scan-cache plumbing) can restore it.
    None defers to the ``PIO_SCAN_CACHE`` env var (default on)."""
    global _scan_cache_override
    prev = _scan_cache_override
    _scan_cache_override = enabled
    return prev


def scan_cache_enabled() -> bool:
    if _scan_cache_override is not None:
        return _scan_cache_override
    return _os.environ.get("PIO_SCAN_CACHE", "1").strip().lower() not in (
        "0", "false", "no", "off")


def _cached_scan(
    scan,
    st: Storage,
    app_id: int,
    channel_id: Optional[int],
    entity_type: Optional[str],
    target_entity_type: Optional[str],
    event_names: Optional[Sequence[str]],
    value_key: Optional[str],
):
    """Snapshot-cached columnar scan: load the persisted ColumnarEvents
    for this (store, namespace, filter) key, scan only
    ``creationTime > watermark``, and concatenate. Any doubt — missing
    or corrupt snapshot, deleted events, creationTimes at/below the
    watermark, out-of-order eventTimes in the delta, a backend that
    cannot answer the watermark probe — falls back to a full rescan
    (and re-primes the cache). Returns whatever contract ``scan`` has:
    a ColumnarEvents, or None when the backend declines columnar.

    Concurrency: the watermark is taken BEFORE any scan starts and
    every scan is bounded ``creationTime <= watermark``, so events
    ingested DURING the scan are neither half-seen now nor skipped
    later — the result is a consistent point-in-time read at the
    watermark, and the next train's delta picks up the remainder.
    """
    from predictionio_tpu.data import snapshot as _snap
    from predictionio_tpu.data.pipeline import concat_columnar

    events = st.events
    identity = getattr(events, "cache_identity", None)
    stats_fn = getattr(events, "creation_stats", None)
    stats = stats_fn(app_id, channel_id) if stats_fn is not None else None
    if identity is None or stats is None:
        _SNAP_MISSES.inc(("unsupported",))
        _tracing.add_attrs(scan_cache="miss:unsupported")
        return scan(app_id, channel_id, entity_type=entity_type,
                    target_entity_type=target_entity_type,
                    event_names=event_names, value_key=value_key)

    count_now, max_c = stats
    watermark = max_c if count_now else _snap.EMPTY_WATERMARK
    directory = _snap.cache_dir(st)
    key = _snap.filter_fingerprint(
        identity, app_id, channel_id, entity_type, target_entity_type,
        event_names, value_key)

    loaded = _snap.load_snapshot(directory, key)
    if loaded is not None:
        cols0, man = loaded
        # count(creation ≤ old watermark) must still equal what the
        # snapshot saw: a lower count means deletions, a higher one
        # means events arrived bearing creationTimes inside the
        # already-covered window — either way the delta can't see them
        at_w = events.creation_stats(app_id, channel_id,
                                     until_us=man.watermark_us)
        if at_w is not None and at_w[0] == man.pre_count:
            delta = scan(app_id, channel_id, entity_type=entity_type,
                         target_entity_type=target_entity_type,
                         event_names=event_names, value_key=value_key,
                         created_after_us=man.watermark_us,
                         created_until_us=watermark)
            if delta is not None:
                if delta.n == 0:
                    _SNAP_HITS.inc()
                    _tracing.add_attrs(scan_cache="hit")
                    if watermark > man.watermark_us:
                        _snap.update_manifest(directory, key, watermark,
                                              count_now, cols0.n)
                    return cols0
                # scan order is (eventTime, creationTime, id): appending
                # is only order-preserving when every delta event sorts
                # strictly after the snapshot's last (strict, because
                # eventTime ties break by fields the two scans can't
                # compare across the boundary)
                if (cols0.n == 0
                        or int(delta.times_us.min())
                        > int(cols0.times_us.max())):
                    merged = concat_columnar(cols0, delta)
                    if merged is not None:
                        _SNAP_HITS.inc()
                        _tracing.add_attrs(scan_cache="hit:delta")
                        _SNAP_DELTA_ROWS.inc(n=delta.n)
                        if delta.n * _COMPACT_FACTOR >= cols0.n:
                            _snap.save_snapshot(directory, key, merged,
                                                watermark, count_now)
                        return merged
                    _SNAP_MISSES.inc(("overflow",))
                    _tracing.add_attrs(scan_cache="miss:overflow")
                else:
                    _SNAP_MISSES.inc(("out_of_order",))
                    _tracing.add_attrs(scan_cache="miss:out_of_order")
            else:
                _SNAP_MISSES.inc(("declined",))
                _tracing.add_attrs(scan_cache="miss:declined")
        else:
            _SNAP_MISSES.inc(("mutated",))
            _tracing.add_attrs(scan_cache="miss:mutated")
    else:
        _SNAP_MISSES.inc(("cold",))
        _tracing.add_attrs(scan_cache="miss:cold")

    cols = scan(app_id, channel_id, entity_type=entity_type,
                target_entity_type=target_entity_type,
                event_names=event_names, value_key=value_key,
                created_until_us=watermark)
    if cols is not None:
        _snap.save_snapshot(directory, key, cols, watermark, count_now)
    return cols


def _scan_with_cache(
    scan,
    st: Storage,
    app_id: int,
    channel_id: Optional[int],
    start_time: Optional[_dt.datetime],
    until_time: Optional[_dt.datetime],
    entity_type: Optional[str],
    target_entity_type: Optional[str],
    event_names: Optional[Sequence[str]],
    value_key: Optional[str],
):
    """Route one columnar scan through the snapshot cache when
    eligible; always record scan wall time. Time-windowed reads
    (start/until) bypass the cache entirely — a window is not the
    repeat-train shape, and a windowed snapshot would go stale as the
    window slides."""
    t0 = _time.perf_counter()
    try:
        with _tracing.span("storage.scan", app_id=app_id) as sp:
            if (start_time is not None or until_time is not None
                    or not scan_cache_enabled()):
                sp.set_attr("scan_cache", "bypassed")
                cols = scan(app_id, channel_id, start_time=start_time,
                            until_time=until_time, entity_type=entity_type,
                            target_entity_type=target_entity_type,
                            event_names=event_names, value_key=value_key)
            else:
                cols = _cached_scan(scan, st, app_id, channel_id,
                                    entity_type, target_entity_type,
                                    event_names, value_key)
            if cols is not None:
                sp.set_attr("records", int(cols.n))
            return cols
    finally:
        _SCAN_SECONDS.observe(_time.perf_counter() - t0,
                              exemplar=_tracing.exemplar())


def _parse_value(v) -> Optional[float]:
    """Per-event training value from a property: numbers and bools
    pass through; strings must match the decimal grammar; anything
    else (absent, lists, dicts, exotic literals) is None."""
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str) and _NUM_RE.fullmatch(v.strip(" ")):
        # spaces only: the C++ scan sees control chars as their JSON
        # escapes (a real tab arrives as \t bytes) and drops them —
        # stripping them here would diverge
        return float(v)
    return None


def resolve_app_channel(
    app_name: str, channel_name: Optional[str] = None, storage: Optional[Storage] = None
) -> Tuple[int, Optional[int]]:
    st = storage or get_storage()
    app = st.meta.get_app_by_name(app_name)
    if app is None:
        raise ValueError(f"App {app_name!r} does not exist; create it with `pio app new`")
    channel_id: Optional[int] = None
    if channel_name:
        ch = st.meta.get_channel_by_name(app.id, channel_name)
        if ch is None:
            raise ValueError(f"Channel {channel_name!r} does not exist in app {app_name!r}")
        channel_id = ch.id
    return app.id, channel_id


def find(
    app_name: str,
    channel_name: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    entity_type: Optional[str] = None,
    entity_id: Optional[str] = None,
    event_names: Optional[Sequence[str]] = None,
    target_entity_type: Optional[str] = None,
    target_entity_id: Optional[str] = None,
    limit: Optional[int] = None,
    reversed: bool = False,
    storage: Optional[Storage] = None,
) -> Iterator[Event]:
    st = storage or get_storage()
    app_id, channel_id = resolve_app_channel(app_name, channel_name, st)
    return st.events.find(
        app_id,
        channel_id,
        start_time=start_time,
        until_time=until_time,
        entity_type=entity_type,
        entity_id=entity_id,
        event_names=event_names,
        target_entity_type=target_entity_type,
        target_entity_id=target_entity_id,
        limit=limit,
        reversed=reversed,
    )


def aggregate_properties(
    app_name: str,
    entity_type: str,
    channel_name: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    storage: Optional[Storage] = None,
) -> Dict[str, PropertyMap]:
    st = storage or get_storage()
    app_id, channel_id = resolve_app_channel(app_name, channel_name, st)
    return st.events.aggregate_properties(
        app_id, entity_type, channel_id, start_time=start_time, until_time=until_time
    )


def read_training_interactions(
    app_name: str,
    channel_name: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    entity_type: Optional[str] = None,
    target_entity_type: Optional[str] = None,
    event_names: Optional[Sequence[str]] = None,
    value_key: Optional[str] = None,
    value_spec: Optional[Dict[str, object]] = None,
    default_spec: object = 1.0,
    chunk_size: int = 65536,
    prefer_streaming: bool = False,
    storage: Optional[Storage] = None,
):
    """Bulk (entity, target[, value]) read for training — the
    ``PEventStore.find → RDD[Rating]`` equivalent, returning
    :class:`~predictionio_tpu.data.pipeline.InteractionData`.

    When the backing store exposes a native columnar scan (the C++
    EVENTLOG engine), the whole scan/parse/vocabulary pass runs in C++
    and no per-event Python object is ever built (measured 22× faster
    at 1M events — docs/perf.md); every other backend streams through
    the generic two-pass :func:`~predictionio_tpu.data.pipeline.
    read_interactions` with identical results.

    ``value_spec`` maps event name → ``"prop"`` (read
    ``properties[value_key]`` under the shared decimal grammar
    (``_NUM_RE``): numbers, bools, and plain decimal strings parse;
    absent/malformed/non-finite drops the event — identically on the
    native and generic paths) or a float constant; unlisted names take
    ``default_spec``. E.g. the recommendation template:
    ``value_key="rating", value_spec={"rate": "prop"},
    default_spec=buy_rating``.
    """
    from predictionio_tpu.data.pipeline import (interactions_from_columnar,
                                                read_interactions)

    # prefer_streaming: the caller wants O(chunk) memory end-to-end
    # (event log may exceed host RAM) — the columnar scan materializes
    # ~26 B/event host-side (50× less than Event objects, but not
    # O(chunk)), so honor the streaming contract over raw speed
    scan, st = (None, None) if prefer_streaming else _native_scan(storage)
    if scan is not None:
        app_id, channel_id = resolve_app_channel(app_name, channel_name, st)
        cols = _scan_with_cache(
            scan, st, app_id, channel_id, start_time, until_time,
            entity_type, target_entity_type, event_names, value_key)
        if cols is not None:
            return interactions_from_columnar(cols, value_spec,
                                              default_spec,
                                              chunk_size=chunk_size)

    def value_fn(e):
        spec = (value_spec or {}).get(e.event, default_spec)
        if spec == "prop":
            if value_key is None:
                return None
            v = _parse_value(e.properties.get(value_key))
            return v if (v is not None and _math.isfinite(v)) else None
        return float(spec)  # type: ignore[arg-type]

    # module-level find(): resolves the app itself, and stays the
    # monkeypatchable seam templates' streaming tests rely on
    return read_interactions(
        lambda: find(
            app_name, channel_name, start_time=start_time,
            until_time=until_time, entity_type=entity_type,
            event_names=event_names,
            target_entity_type=target_entity_type, storage=storage),
        chunk_size=chunk_size,
        value_fn=(value_fn
                  if (value_spec or value_key or default_spec != 1.0)
                  else None),
    )


def read_training_event_groups(
    app_name: str,
    names: Sequence[str],
    channel_name: Optional[str] = None,
    entity_type: Optional[str] = "user",
    target_entity_type: Optional[str] = "item",
    chunk_size: int = 65536,
    storage: Optional[Storage] = None,
):
    """Multi-event grouped read with one shared vocabulary pair (the
    Universal-Recommender shape) — native columnar scan on stores that
    expose it (demux by name is a numpy mask), the generic two-scan
    :func:`~predictionio_tpu.data.pipeline.read_event_groups`
    elsewhere. Returns ``({name: (user_idx, item_idx)}, user_ids,
    item_ids)`` identically on both paths."""
    from predictionio_tpu.data.pipeline import (event_groups_from_columnar,
                                                read_event_groups)

    scan, st = _native_scan(storage)
    if scan is not None:
        app_id, channel_id = resolve_app_channel(app_name, channel_name, st)
        cols = _scan_with_cache(
            scan, st, app_id, channel_id, None, None,
            entity_type, target_entity_type, list(names), None)
        if cols is not None:
            return event_groups_from_columnar(cols, names)
    return read_event_groups(
        lambda: find(
            app_name, channel_name, entity_type=entity_type,
            target_entity_type=target_entity_type,
            event_names=list(names), storage=storage),
        names, chunk_size=chunk_size)


def find_by_entity(
    app_name: str,
    entity_type: str,
    entity_id: str,
    channel_name: Optional[str] = None,
    event_names: Optional[Sequence[str]] = None,
    target_entity_type: Optional[str] = None,
    target_entity_id: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    limit: Optional[int] = None,
    latest: bool = True,
    storage: Optional[Storage] = None,
) -> List[Event]:
    """Serving-time point lookup (reference: LEventStore.findByEntity;
    `latest` mirrors its newest-first default)."""
    st = storage or get_storage()
    app_id, channel_id = resolve_app_channel(app_name, channel_name, st)
    return list(
        st.events.find(
            app_id,
            channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit,
            reversed=latest,
        )
    )
