"""Wire-behavior doubles for the ``psycopg2`` and ``pymysql`` drivers.

This image has neither SQL servers nor the DB-API drivers (zero egress,
no pip), so the PGSQL/MYSQL dialects could never execute — the round-3
suite's one skip. These modules emulate the exact DB-API surface and
the SERVER BEHAVIORS the real dialects branch on, over a shared
on-disk sqlite database per (host, database) pair, so that the REAL
``PostgresDialect`` / ``MySQLDialect`` classes
(predictionio_tpu/storage/sqldialect.py) execute their own SQL and
error handling unmodified:

======================  ==============================================
dialect behavior        emulated how
======================  ==============================================
format paramstyle       ``%s`` placeholders rewritten at the cursor
PG DDL types            SERIAL PRIMARY KEY / BYTEA translated to the
                        sqlite sqlite equivalents before execution
PG ``RETURNING id``     sqlite >= 3.35 runs it natively
PG ON CONFLICT upsert   sqlite >= 3.24 runs it natively (EXCLUDED.*)
PG aborted transaction  after any statement error the connection
                        refuses further statements
                        (``InFailedSqlTransaction``) until
                        ``rollback()`` — the behavior
                        ``SQLDialect.recover`` exists for
PG UndefinedTable       sqlite "no such table" mapped to
                        ``psycopg2.errors.UndefinedTable``
PG named cursor         ``cursor(name=...)`` accepted (streaming)
MySQL DDL types         AUTO_INCREMENT / LONGBLOB translated
MySQL REPLACE INTO      sqlite runs it natively
MySQL error codes       "no such table" → ``ProgrammingError`` with
                        ``args[0] == 1146`` (ER_NO_SUCH_TABLE);
                        duplicate ``CREATE INDEX`` →
                        ``InternalError`` with ``args[0] == 1061``
                        (ER_DUP_KEYNAME, no IF NOT EXISTS in MySQL)
MySQL SSCursor          ``cursor(SSCursor)`` accepted (streaming)
======================  ==============================================

What this cannot prove: the C wire protocol, authentication, and
genuine server-side DDL/planner behavior — that remains the live smoke
test's job (``test_pgsql_live_smoke``) on an image with a real server.

Shared state: connections with the same ``(host, database)`` hit the
same sqlite file under a process-wide temp dir — two fake connections
see each other's committed writes, like two sessions of one server.
"""

from __future__ import annotations

import atexit
import os
import shutil
import sqlite3
import tempfile
import threading
import types
from typing import Optional

_DIR = tempfile.mkdtemp(prefix="pio_fake_sql_")
atexit.register(shutil.rmtree, _DIR, ignore_errors=True)
_LOCK = threading.Lock()


def _db_path(host: str, database: str) -> str:
    with _LOCK:
        return os.path.join(_DIR, f"{host}_{database}.db")


def reset_all() -> None:
    """Wipe every fake server's state (fresh-test isolation)."""
    with _LOCK:
        for f in os.listdir(_DIR):
            os.unlink(os.path.join(_DIR, f))


# -- fake psycopg2 ------------------------------------------------------------


class PGError(Exception):
    pass


class PGOperationalError(PGError):
    pass


class PGUndefinedTable(PGError):
    pass


class PGInFailedSqlTransaction(PGError):
    pass


def _pg_translate(q: str) -> str:
    q = q.replace("%s", "?")
    q = q.replace("SERIAL PRIMARY KEY", "INTEGER PRIMARY KEY AUTOINCREMENT")
    q = q.replace("BYTEA", "BLOB")
    return q


def _pg_map(e: sqlite3.Error) -> PGError:
    if isinstance(e, sqlite3.OperationalError) and "no such table" in str(e):
        return PGUndefinedTable(str(e))
    return PGOperationalError(str(e))


class _PGCursor:
    def __init__(self, conn: "_PGConnection", name: Optional[str] = None):
        self._conn = conn
        self._cur = conn._sq.cursor()
        self.name = name

    def _run(self, method, q, arg):
        self._conn._check_usable()
        try:
            return method(_pg_translate(q), arg)
        except sqlite3.Error as e:
            # the server aborts the transaction: everything until
            # ROLLBACK now fails
            self._conn._failed = True
            raise _pg_map(e) from e

    def execute(self, q, args=()):
        return self._run(self._cur.execute, q, args)

    def executemany(self, q, rows):
        return self._run(self._cur.executemany, q, rows)

    def fetchone(self):
        return self._cur.fetchone()

    def fetchall(self):
        return self._cur.fetchall()

    def fetchmany(self, n=1):
        return self._cur.fetchmany(n)

    def close(self):
        self._cur.close()

    @property
    def lastrowid(self):
        return self._cur.lastrowid

    @property
    def rowcount(self):
        return self._cur.rowcount


class _PGConnection:
    def __init__(self, path: str):
        self._sq = sqlite3.connect(path, timeout=30.0)
        self._sq.execute("PRAGMA journal_mode=WAL")
        self._failed = False

    def _check_usable(self):
        if self._failed:
            raise PGInFailedSqlTransaction(
                "current transaction is aborted, commands ignored until "
                "end of transaction block")

    def cursor(self, name: Optional[str] = None):
        return _PGCursor(self, name)

    def commit(self):
        # COMMIT inside an aborted transaction is turned into ROLLBACK
        # by the server (no error)
        self._sq.rollback() if self._failed else self._sq.commit()
        self._failed = False

    def rollback(self):
        self._sq.rollback()
        self._failed = False

    def close(self):
        self._sq.close()


def make_psycopg2_module() -> types.ModuleType:
    m = types.ModuleType("psycopg2")
    errors = types.ModuleType("psycopg2.errors")
    errors.UndefinedTable = PGUndefinedTable
    errors.InFailedSqlTransaction = PGInFailedSqlTransaction
    m.errors = errors
    m.Error = PGError
    m.OperationalError = PGOperationalError
    m.Binary = lambda b: b
    m.connect_calls = []  # recorded kwargs, for URL-parsing assertions

    def connect(host=None, port=None, user=None, password=None, dbname=None):
        m.connect_calls.append(dict(host=host, port=port, user=user,
                                    password=password, dbname=dbname))
        return _PGConnection(_db_path(host or "localhost", dbname or "pio"))

    m.connect = connect
    return m


# -- fake pymysql -------------------------------------------------------------


class MyError(Exception):
    pass


class MyOperationalError(MyError):
    pass


class MyProgrammingError(MyError):
    pass


class MyInternalError(MyError):
    pass


class SSCursor:
    """Marker class token (pymysql.cursors.SSCursor)."""


def _my_translate(q: str) -> str:
    q = q.replace("%s", "?")
    q = q.replace("INTEGER PRIMARY KEY AUTO_INCREMENT",
                  "INTEGER PRIMARY KEY AUTOINCREMENT")
    q = q.replace("LONGBLOB", "BLOB")
    return q


def _my_map(e: sqlite3.Error) -> MyError:
    s = str(e)
    if isinstance(e, sqlite3.OperationalError):
        if "no such table" in s:
            return MyProgrammingError(1146, f"Table doesn't exist ({s})")
        if "already exists" in s and "index" in s:
            return MyInternalError(1061, f"Duplicate key name ({s})")
    return MyOperationalError(9999, s)


class _MyCursor:
    def __init__(self, conn: "_MyConnection"):
        self._cur = conn._sq.cursor()

    def _run(self, method, q, arg):
        try:
            return method(_my_translate(q), arg)
        except sqlite3.Error as e:
            raise _my_map(e) from e

    def execute(self, q, args=()):
        return self._run(self._cur.execute, q, args)

    def executemany(self, q, rows):
        return self._run(self._cur.executemany, q, rows)

    def fetchone(self):
        return self._cur.fetchone()

    def fetchall(self):
        return self._cur.fetchall()

    def fetchmany(self, n=1):
        return self._cur.fetchmany(n)

    def close(self):
        self._cur.close()

    @property
    def lastrowid(self):
        return self._cur.lastrowid

    @property
    def rowcount(self):
        return self._cur.rowcount


class _MyConnection:
    def __init__(self, path: str):
        self._sq = sqlite3.connect(path, timeout=30.0)
        self._sq.execute("PRAGMA journal_mode=WAL")

    def cursor(self, cursor=None):
        assert cursor is None or cursor is SSCursor
        return _MyCursor(self)

    def commit(self):
        self._sq.commit()

    def rollback(self):
        self._sq.rollback()

    def close(self):
        self._sq.close()


def make_pymysql_module() -> types.ModuleType:
    m = types.ModuleType("pymysql")
    err = types.ModuleType("pymysql.err")
    err.ProgrammingError = MyProgrammingError
    err.OperationalError = MyOperationalError
    err.InternalError = MyInternalError
    m.err = err
    cursors = types.ModuleType("pymysql.cursors")
    cursors.SSCursor = SSCursor
    m.cursors = cursors
    m.connect_calls = []

    def connect(host=None, port=None, user=None, password=None,
                database=None):
        m.connect_calls.append(dict(host=host, port=port, user=user,
                                    password=password, database=database))
        return _MyConnection(_db_path(host or "localhost",
                                      database or "pio"))

    m.connect = connect
    return m
