"""Meta store tests: apps/keys/channels CRUD + engine instance lifecycle
(the reference's basic_app_usecases.py scenario shape, SURVEY.md §4)."""

import pytest

from predictionio_tpu.data.event import utcnow
from predictionio_tpu.storage.meta import EngineInstance, MetaStore
from predictionio_tpu.storage.models import LocalFSModelStore, MemoryModelStore


@pytest.fixture(params=["sqlite", "es"])
def meta(request, tmp_path):
    if request.param == "es":
        from predictionio_tpu.storage.indexed import (ESMetaStore,
                                                      IndexedStorageClient)

        return ESMetaStore(IndexedStorageClient(str(tmp_path / "es")))
    return MetaStore(str(tmp_path / "meta.db"))


class TestApps:
    def test_crud(self, meta):
        app = meta.create_app("MyApp", "desc")
        assert app.id >= 1
        assert meta.get_app_by_name("MyApp").id == app.id
        assert meta.get_app(app.id).name == "MyApp"
        assert [a.name for a in meta.list_apps()] == ["MyApp"]
        assert meta.delete_app(app.id) is True
        assert meta.get_app_by_name("MyApp") is None

    def test_duplicate_name_rejected(self, meta):
        meta.create_app("A")
        with pytest.raises(Exception):
            meta.create_app("A")


class TestAccessKeys:
    def test_generate_and_auth(self, meta):
        app = meta.create_app("A")
        ak = meta.create_access_key(app.id)
        assert len(ak.key) > 20
        got = meta.get_access_key(ak.key)
        assert got.app_id == app.id and got.events == []
        assert meta.get_access_key("nope") is None

    def test_restricted_events(self, meta):
        app = meta.create_app("A")
        ak = meta.create_access_key(app.id, events=["rate", "buy"])
        assert meta.get_access_key(ak.key).events == ["rate", "buy"]

    def test_delete_app_cascades(self, meta):
        app = meta.create_app("A")
        ak = meta.create_access_key(app.id)
        meta.delete_app(app.id)
        assert meta.get_access_key(ak.key) is None


class TestChannels:
    def test_crud(self, meta):
        app = meta.create_app("A")
        ch = meta.create_channel(app.id, "backtest")
        assert meta.get_channel_by_name(app.id, "backtest").id == ch.id
        assert len(meta.list_channels(app.id)) == 1
        assert meta.delete_channel(ch.id) is True


class TestEngineInstances:
    def _mk(self, meta, status="COMPLETED", factory="m:f", variant=""):
        ei = EngineInstance(
            id=meta.new_instance_id(), status=status, start_time=utcnow(),
            end_time=None, engine_factory=factory, engine_variant=variant,
            batch="", env={}, mesh_conf={"devices": 1},
            data_source_params="{}", preparator_params="{}",
            algorithms_params="[]", serving_params="{}")
        meta.insert_engine_instance(ei)
        return ei

    def test_latest_completed(self, meta):
        self._mk(meta, status="FAILED")
        a = self._mk(meta)
        import time; time.sleep(0.01)
        b = self._mk(meta)
        latest = meta.get_latest_completed_engine_instance("m:f")
        assert latest.id == b.id
        assert meta.get_latest_completed_engine_instance("other:f") is None

    def test_update_status(self, meta):
        ei = self._mk(meta, status="TRAINING")
        ei.status = "COMPLETED"
        ei.end_time = utcnow()
        meta.update_engine_instance(ei)
        assert meta.get_engine_instance(ei.id).status == "COMPLETED"
        assert meta.get_engine_instance(ei.id).mesh_conf == {"devices": 1}


class TestModelStores:
    @pytest.mark.parametrize("kind", ["memory", "localfs"])
    def test_blob_round_trip(self, kind, tmp_path):
        ms = MemoryModelStore() if kind == "memory" else LocalFSModelStore(str(tmp_path / "m"))
        ms.put("inst-1", b"\x00\x01binary")
        assert ms.get("inst-1") == b"\x00\x01binary"
        assert ms.list_ids() == ["inst-1"]
        assert ms.delete("inst-1") is True
        assert ms.get("inst-1") is None

    def test_model_dir(self, tmp_path):
        ms = LocalFSModelStore(str(tmp_path / "m"))
        d = ms.model_dir("inst-2")
        import os
        assert os.path.isdir(d)
