"""Multi-host (DCN) execution path: REAL 2-process jax.distributed runs
over localhost — the proof the rendezvous, cross-host collectives, and
the run_train wiring work (SURVEY.md §2d P5/C2; the reference's
driver/executor control plane over netty RPC).

Each test spawns two subprocesses on the CPU platform with 2 virtual
devices each (a 4-device global mesh split across processes) and the
PIO_* rendezvous env vars that `parallel/distributed.initialize` (and
through it `run_train`) consumes.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(script: str, proc_id: int, port: int, extra_env=None):
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    env.update({
        "PYTHONPATH": REPO,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_PLATFORMS": "cpu",
        "PIO_MESH_PLATFORM": "cpu",
        "PIO_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "PIO_NUM_PROCESSES": "2",
        "PIO_PROCESS_ID": str(proc_id),
    })
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-c", script],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def _run_pair(script: str, extra_env=None, timeout=240):
    port = _free_port()
    procs = [_spawn(script, i, port, extra_env) for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
    return outs


COLLECTIVES = textwrap.dedent("""
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from predictionio_tpu.parallel import distributed

    multi = distributed.initialize()   # from the PIO_* env vars
    assert multi, "expected multi-process"
    assert jax.process_count() == 2
    assert len(jax.local_devices()) == 2
    assert len(jax.devices()) == 4
    distributed.barrier("pio_test_start")

    # control-plane broadcast (coordinator value wins)
    me = distributed.process_index()
    val = distributed.broadcast_from_coordinator(
        np.asarray([41.0 if me == 0 else -1.0], np.float32))
    assert float(np.asarray(val)[0]) == 41.0, val
    sid = distributed.broadcast_string("inst-xyz" if me == 0 else "")
    assert sid == "inst-xyz", sid

    # a cross-process collective: psum over the 4-device global mesh
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from predictionio_tpu.parallel.mesh import get_shard_map

    mesh = Mesh(np.array(jax.devices()).reshape(4), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    x = jax.make_array_from_callback(
        (8,), sharding,
        lambda idx: np.arange(8, dtype=np.float32)[idx])
    sm = get_shard_map()

    def f(x):
        return jax.lax.psum(x.sum(), "data")

    total = jax.jit(sm(f, mesh=mesh, in_specs=P("data"), out_specs=P()))(x)
    assert float(np.asarray(total)) == 28.0, total
    distributed.barrier("pio_test_done")
    print("COLLECTIVES_OK", me)
""")


TRAIN = textwrap.dedent("""
    import json
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    import os
    from predictionio_tpu.core.workflow import run_train
    from predictionio_tpu.storage.registry import Storage, StorageConfig, set_storage

    st = Storage(StorageConfig(metadata_type="SQLITE",
                               eventdata_type="SQLITE",
                               modeldata_type="LOCALFS",
                               home=os.environ["PIO_HOME"]))
    set_storage(st)
    FACTORY = "predictionio_tpu.templates.recommendation.engine:engine_factory"
    VARIANT = {
        "id": "default",
        "engineFactory": FACTORY,
        "datasource": {"params": {"appName": "MHApp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 4, "numIterations": 2,
                                   "lambda": 0.1}}],
    }
    iid = run_train(FACTORY, variant=VARIANT, storage=st, use_mesh=True)
    print("TRAIN_OK", jax.process_index(), iid)
""")


@pytest.mark.scenario
class TestTwoProcess:
    def test_rendezvous_barrier_broadcast_psum(self):
        outs = _run_pair(COLLECTIVES)
        assert all("COLLECTIVES_OK" in o for o in outs)

    def test_run_train_two_processes(self, tmp_path):
        # seed a shared sqlite event store both processes will read
        home = str(tmp_path / "pio_home")
        from predictionio_tpu.storage.registry import Storage, StorageConfig
        from tests.test_workflow import seed_ratings

        st = Storage(StorageConfig(metadata_type="SQLITE",
                                   eventdata_type="SQLITE",
                                   modeldata_type="LOCALFS", home=home))
        seed_ratings(st, app_name="MHApp")

        outs = _run_pair(TRAIN, extra_env={"PIO_HOME": home})
        ids = set()
        for o in outs:
            line = [l for l in o.splitlines() if l.startswith("TRAIN_OK")][-1]
            ids.add(line.split()[-1])
        assert len(ids) == 1, f"instance id differed across hosts: {ids}"

        # coordinator-only writes: exactly ONE engine instance row,
        # COMPLETED, and a loadable model
        st2 = Storage(StorageConfig(metadata_type="SQLITE",
                                    eventdata_type="SQLITE",
                                    modeldata_type="LOCALFS", home=home))
        instances = st2.meta.list_engine_instances()
        assert len(instances) == 1
        assert instances[0].status == "COMPLETED"
        from predictionio_tpu.core.workflow import prepare_deploy

        dep = prepare_deploy(
            engine_factory="predictionio_tpu.templates.recommendation."
                           "engine:engine_factory", storage=st2)
        res = dep.query({"user": "0", "num": 3})
        assert len(res["itemScores"]) == 3
