"""Event-ingestion throughput/latency for the Event Server.

Completes the per-surface perf evidence set (train: bench.py; predict:
profile_serving.py; index/CCO: profile_indexed.py): measures the
reference's headline ingestion surface — `POST /events.json` — end to
end over HTTP against a live EventServer, plus the batch API and the
filtered read path.

Measured layers (all warm, persistent connection):

- ``single_post``  — one event per POST (auth, validation, insert)
- ``batch_post``   — POST /batch/events.json with 50-event payloads
                     (the API's documented maximum per request)
- ``get_find``     — GET /events.json?limit=100 filtered reads

With ``--concurrency N`` the serial phases are replaced by a
group-commit comparison: N persistent connections (single-threaded
selector client, one request in flight per connection) drive
`single_post` against the same storage twice — ingest batching OFF
(per-event commit) then ON (write coalescer) — with durable acks in
both phases (``--volatile-acks`` drops that for the durability-cost
A/B), and the JSON reports both plus the speedup. Serial mode (the
default) is unchanged for comparability with earlier rounds.

With ``--verify-crc`` the HTTP phases are replaced by a checksum
overhead A/B at the EVENTLOG store SPI: the same batch ingest + full
scan against two fresh namespaces, one written in the legacy v1 frame
format (``PIO_EVENTLOG_FORMAT=1``, no record CRCs) and one in the
default v2 format (per-record CRC32C, verified on every index
rebuild) — what the end-to-end integrity contract costs on the ingest
hot path.

Usage::

    python profile_events.py [--events 5000] [--storage memory|sqlite]
    python profile_events.py --concurrency 16 --storage sqlite
    python profile_events.py --verify-crc --events 200000

Prints ONE JSON line.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import tempfile
import threading
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--events", type=int, default=None,
                    help="events to ingest (default 5000; 120 with "
                         "--failover, which ingests serially)")
    ap.add_argument("--storage", default="memory",
                    choices=["memory", "sqlite", "eventlog"])
    ap.add_argument("--port", type=int, default=8791)
    ap.add_argument("--concurrency", type=int, default=0,
                    help="run the concurrent single_post comparison "
                         "(ingest batching off vs on) with this many "
                         "client threads instead of the serial phases")
    ap.add_argument("--volatile-acks", action="store_true",
                    help="concurrency mode only: drop the durable-ack "
                         "(fsync-before-201) contract from BOTH phases "
                         "— the A/B for measuring what durability "
                         "itself costs with and without batching")
    ap.add_argument("--bulk", type=int, default=0,
                    help="additionally bulk-import this many events "
                         "through the store SPI (the `pio import` "
                         "path) and measure scan/aggregate reads — "
                         "the C++ EVENTLOG scale probe (VERDICT r4 #4)")
    ap.add_argument("--verify-crc", action="store_true",
                    help="EVENTLOG checksum overhead A/B: batch ingest "
                         "+ full scan with v1 (no CRC) vs v2 (CRC32C "
                         "per record) frame formats, at the store SPI")
    ap.add_argument("--segments", action="store_true",
                    help="EVENTLOG partitioned-log A/B at the store "
                         "SPI: single-file serial scan baseline vs "
                         "segmented log (compacted columnar sidecars) "
                         "scanned serially and with --scan-workers; "
                         "with --concurrency, also single-file vs "
                         "segmented ingest across N writer threads")
    ap.add_argument("--scan-workers", type=int, default=4,
                    help="segment scan fan-out width for the parallel "
                         "phase of --segments")
    ap.add_argument("--failover", action="store_true",
                    help="event-plane chaos harness: run the kill -9 "
                         "failover drill (two real event servers, "
                         "leader killed mid-stream) and report the "
                         "proof document — zero acked loss, promotion "
                         "latency, epoch bump, stale-epoch refusal, "
                         "fsck verdicts, incident-bundle count")
    ap.add_argument("--kill-after", type=int, default=40,
                    help="failover: kill -9 the leader after this many "
                         "acked events")
    ap.add_argument("--lease-ttl", type=float, default=0.35,
                    help="failover: event-plane lease TTL seconds")
    args = ap.parse_args()
    args.events = args.events or (120 if args.failover else 5000)
    if args.verify_crc or args.segments:
        args.storage = "eventlog"  # the A/B only exists natively

    if args.failover:
        # jax-free: the drill spawns real `pio eventserver` processes
        # (EVENTLOG storage, durable acks) and never imports jax here
        from predictionio_tpu.server.repl_server import run_failover_drill

        base = tempfile.mkdtemp(prefix="pio_failover_drill_")
        t0 = time.perf_counter()
        proof = run_failover_drill(base, events=args.events,
                                   kill_after=args.kill_after,
                                   lease_ttl=args.lease_ttl)
        print(json.dumps({
            "metric": "event_plane_failover",
            "events": args.events,
            "kill_after": args.kill_after,
            "lease_ttl_sec": args.lease_ttl,
            "wall_sec": round(time.perf_counter() - t0, 2),
            "dir": base,
            **proof,
        }))
        if not proof.get("ok"):
            raise SystemExit(3)
        return

    import jax

    jax.config.update("jax_platforms", "cpu")  # no accelerator needed

    from profile_common import make_memory_storage, server_thread
    from predictionio_tpu.server.event_server import EventServer
    from predictionio_tpu.storage.registry import (Storage, StorageConfig,
                                                   set_storage)

    if args.storage == "memory":
        st = make_memory_storage()
    else:  # file-backed: sqlite (the default TYPE) or eventlog
        home = tempfile.mkdtemp(prefix="pio_events_bench_")
        st = Storage(StorageConfig(home=home,
                                   eventdata_type=args.storage.upper()))
        set_storage(st)
    app = st.meta.create_app("EventsBench")
    st.events.init_channel(app.id)
    key = st.meta.create_access_key(app.id).key

    if args.verify_crc:
        # one fresh namespace per format (a file keeps its on-disk
        # format for life, so the env toggle only matters at creation);
        # same event stream, same chunking, measured at the store SPI
        # so the delta is the CRC computation + 5-byte-per-record
        # trailer IO and nothing else
        from predictionio_tpu.data.event import Event

        rng = np.random.default_rng(0)
        uu = rng.integers(0, 1000, args.events)
        ii = rng.integers(0, 500, args.events)
        evs = [Event(event="view", entity_type="user",
                     entity_id=str(int(uu[n])),
                     target_entity_type="item",
                     target_entity_id=str(int(ii[n])),
                     properties={"n": int(n)})
               for n in range(args.events)]
        prev = os.environ.get("PIO_EVENTLOG_FORMAT")
        results = {}
        try:
            for fmt, label in (("1", "v1_no_crc"), ("2", "v2_crc32c")):
                os.environ["PIO_EVENTLOG_FORMAT"] = fmt
                fapp = st.meta.create_app(f"EventsBenchCRC{fmt}")
                st.events.init_channel(fapp.id)
                CH = 20_000
                t0 = time.perf_counter()
                for lo in range(0, args.events, CH):
                    st.events.insert_batch(evs[lo:lo + CH], fapp.id)
                ingest_sec = time.perf_counter() - t0
                t0 = time.perf_counter()
                n_scanned = sum(1 for _ in st.events.find(fapp.id))
                scan_sec = time.perf_counter() - t0
                assert n_scanned == args.events
                # reopen: the v2 path re-verifies every record CRC
                # while rebuilding the index — the recovery-read cost
                st.events.close()
                t0 = time.perf_counter()
                st.events.init_channel(fapp.id)
                reopen_sec = time.perf_counter() - t0
                results[label] = {
                    "ingest_events_per_sec": round(args.events / ingest_sec),
                    "scan_events_per_sec": round(args.events / scan_sec),
                    "reopen_ms": round(reopen_sec * 1e3, 1),
                }
        finally:
            if prev is None:
                os.environ.pop("PIO_EVENTLOG_FORMAT", None)
            else:
                os.environ["PIO_EVENTLOG_FORMAT"] = prev
        v1, v2 = results["v1_no_crc"], results["v2_crc32c"]
        print(json.dumps({
            "metric": "eventlog_crc_overhead",
            "events": args.events,
            **results,
            "ingest_overhead_pct": round(
                (v1["ingest_events_per_sec"] / v2["ingest_events_per_sec"]
                 - 1) * 100, 1),
            "scan_overhead_pct": round(
                (v1["scan_events_per_sec"] / v2["scan_events_per_sec"]
                 - 1) * 100, 1),
        }))
        return

    if args.segments:
        # Partitioned-log A/B at the store SPI. Baseline: one
        # unsegmented file (rollover disabled), serial native columnar
        # scan. Treatment: the same stream through a segmented
        # namespace, sealed segments compacted into columnar sidecars
        # (the background-maintenance product), scanned serially and
        # with a --scan-workers thread pool. Scans repeat twice and
        # report the better run (warm page cache both sides).
        from predictionio_tpu.data.event import Event

        # MovieLens-1M shape: ~6k users × ~4k items — the dense
        # events-per-entity regime recommendation stores actually see
        rng = np.random.default_rng(0)
        N = args.events
        uu = rng.integers(0, 6_040, N)
        ii = rng.integers(0, 3_952, N)
        vv = rng.integers(1, 6, N)
        CH = 20_000

        def ingest(app_id, channel_id=None):
            t0 = time.perf_counter()
            for lo in range(0, N, CH):
                evs = [Event(event="rate", entity_type="user",
                             entity_id=str(int(uu[n])),
                             target_entity_type="item",
                             target_entity_id=str(int(ii[n])),
                             properties={"rating": float(vv[n])})
                       for n in range(lo, min(lo + CH, N))]
                st.events.insert_batch(evs, app_id, channel_id)
            return time.perf_counter() - t0

        def scan(app_id, workers):
            st.events.scan_workers = workers
            best = float("inf")
            cols = None
            for _ in range(2):
                t0 = time.perf_counter()
                cols = st.events.scan_columnar(app_id, value_key="rating")
                best = min(best, time.perf_counter() - t0)
            return cols, best

        # -- baseline: single file, serial scan
        st.events.segment_bytes = 0  # never roll
        app_a = st.meta.create_app("EventsBenchSegA")
        st.events.init_channel(app_a.id)
        single_ingest_sec = ingest(app_a.id)
        cols_a, single_scan_sec = scan(app_a.id, 1)
        assert cols_a is not None and cols_a.n == N
        single_bytes = os.path.getsize(
            st.events._path(app_a.id, None))

        # -- treatment: segmented (≈12 segments), compacted sidecars
        seg_bytes = max(1 << 20, single_bytes // 12)
        st.events.segment_bytes = seg_bytes
        app_b = st.meta.create_app("EventsBenchSegB")
        st.events.init_channel(app_b.id)
        seg_ingest_sec = ingest(app_b.id)
        ns = st.events._ns(app_b.id, None)
        t0 = time.perf_counter()
        for seg in list(ns.sealed):
            ns.compact(seg)
        compact_sec = time.perf_counter() - t0
        cols_s, seg_serial_sec = scan(app_b.id, 1)
        cols_p, seg_parallel_sec = scan(app_b.id, args.scan_workers)
        assert cols_s is not None and cols_s.n == N
        assert cols_p is not None and cols_p.n == N
        assert (cols_p.times_us == cols_s.times_us).all()
        assert (cols_p.values == cols_a.values).all()
        sources = [d["source"] for d in ns.last_scan["per_segment"]]

        out = {
            "metric": "eventlog_segments",
            "events": N,
            "segments": len(ns.sealed) + 1,
            "segment_bytes": seg_bytes,
            "scan_workers": args.scan_workers,
            "compacted_sources": sources.count("columnar"),
            "compact_sec": round(compact_sec, 2),
            "ingest": {
                "single_file_events_per_sec": round(N / single_ingest_sec),
                "segmented_events_per_sec": round(N / seg_ingest_sec),
                "rollover_overhead_pct": round(
                    (single_ingest_sec / seg_ingest_sec - 1) * -100, 1),
            },
            "scan": {
                "single_file_serial_events_per_sec": round(
                    N / single_scan_sec),
                "segmented_serial_events_per_sec": round(
                    N / seg_serial_sec),
                "segmented_parallel_events_per_sec": round(
                    N / seg_parallel_sec),
                "parallel_vs_single_serial_speedup": round(
                    single_scan_sec / seg_parallel_sec, 2),
                "parallel_vs_segmented_serial_speedup": round(
                    seg_serial_sec / seg_parallel_sec, 2),
            },
        }

        if args.concurrency:
            # single-file vs segmented ingest under N writer threads,
            # one (app, channel) partition per thread — the contention
            # the per-namespace writer lock (and rollover inside it)
            # adds or removes
            conc = args.concurrency
            n_conc = min(N, 200_000)
            per = max(1, n_conc // conc)

            def writer(app_id, ch, lo):
                for base in range(lo, lo + per, CH):
                    evs = [Event(event="rate", entity_type="user",
                                 entity_id=str(int(uu[n])),
                                 target_entity_type="item",
                                 target_entity_id=str(int(ii[n])),
                                 properties={"rating": float(vv[n])})
                           for n in range(base, min(base + CH, lo + per))]
                    st.events.insert_batch(evs, app_id, ch)

            def run_conc(seg, tag):
                st.events.segment_bytes = seg
                capp = st.meta.create_app(f"EventsBenchSegC{tag}")
                for t in range(conc):
                    st.events.init_channel(capp.id, t)
                threads = [threading.Thread(target=writer,
                                            args=(capp.id, t, t * per))
                           for t in range(conc)]
                t0 = time.perf_counter()
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()
                return per * conc / (time.perf_counter() - t0)

            single_rate = run_conc(0, "S")
            seg_rate = run_conc(max(1 << 20, (single_bytes * per // N) // 4),
                                "P")
            out["concurrent_ingest"] = {
                "writers": conc,
                "events": per * conc,
                "single_file_events_per_sec": round(single_rate),
                "segmented_events_per_sec": round(seg_rate),
            }

        print(json.dumps(out))
        return

    if args.concurrency:
        # N persistent connections, one event per POST; the same
        # storage serves both runs so backend state is identical.
        # Both servers run with DURABLE acks by default — 201 means
        # fsynced — the contract the group commit makes affordable (the
        # coalescer pays one sync per batch, the per-event path one per
        # POST). The client is a single-threaded selector loop over N
        # raw sockets with prebuilt request bytes (the wrk model): on
        # this one-core box, N client THREADS would burn the shared
        # core on GIL switching and charge it to both phases, burying
        # the server-side difference under harness overhead.
        import selectors
        import socket

        n_threads = args.concurrency
        per = max(1, args.events // n_threads)

        def build_requests(run_key):
            rng = np.random.default_rng(0)
            reqs = []
            for t in range(n_threads):
                rs = []
                for i in range(per):
                    body = json.dumps(
                        {"event": "view", "entityType": "user",
                         "entityId": str(int(rng.integers(0, 1000))),
                         "targetEntityType": "item",
                         "targetEntityId": str(int(rng.integers(0, 500))),
                         "properties": {"t": t, "n": i}}).encode()
                    rs.append(
                        (f"POST /events.json?accessKey={run_key} HTTP/1.1\r\n"
                         f"Host: localhost\r\n"
                         f"Content-Type: application/json\r\n"
                         f"Content-Length: {len(body)}\r\n\r\n"
                         ).encode() + body)
                reqs.append(rs)
            return reqs

        def run_concurrent(batching: bool, port: int):
            # fresh app (⇒ fresh table/log) per run: otherwise the
            # second run pays index-growth costs the first didn't
            run_app = st.meta.create_app(f"EventsBenchC{int(batching)}")
            st.events.init_channel(run_app.id)
            run_key = st.meta.create_access_key(run_app.id).key
            reqs = build_requests(run_key)
            server = EventServer(storage=st, host="127.0.0.1", port=port,
                                 ingest_batching=batching,
                                 durable_acks=not args.volatile_acks)
            warmup = min(64, per)
            total = per * n_threads

            def drive():
                """One socket per simulated client, one request in
                flight each, single event-loop thread. Returns
                (per-request latencies, global completion timestamps).
                """
                sel = selectors.DefaultSelector()
                socks = []
                for t in range(n_threads):
                    s = socket.create_connection(("127.0.0.1", port),
                                                 timeout=60)
                    s.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
                    state = {"t": t, "sent": 0, "done": 0, "buf": b"",
                             "clen": -1, "t0": 0.0, "sock": s}
                    sel.register(s, selectors.EVENT_READ, state)
                    socks.append(s)
                lat = np.empty(total)
                stamps = np.empty(total)
                completed = 0

                def send_next(st):
                    t = st["t"]
                    i = st["sent"] % per
                    st["t0"] = time.perf_counter()
                    st["sock"].sendall(reqs[t][i])
                    st["sent"] += 1

                def pump(goal, timed):
                    # until every socket has completed `goal` requests
                    nonlocal completed
                    pending = n_threads
                    ready = []
                    while pending:
                        for key, _ in sel.select():
                            st = key.data
                            if st["done"] >= goal:
                                continue
                            st["buf"] += st["sock"].recv(65536)
                            buf = st["buf"]
                            if st["clen"] < 0:
                                hdr_end = buf.find(b"\r\n\r\n")
                                if hdr_end < 0:
                                    continue
                                head = buf[:hdr_end]
                                assert head[9:12] == b"201", head[:80]
                                st["clen"] = int(
                                    head.lower()
                                    .split(b"content-length:")[1]
                                    .split(b"\r\n")[0])
                                st["buf"] = buf = buf[hdr_end + 4:]
                            if len(buf) < st["clen"]:
                                continue
                            now = time.perf_counter()
                            st["buf"] = buf[st["clen"]:]
                            st["clen"] = -1
                            st["done"] += 1
                            if timed:
                                lat[completed] = now - st["t0"]
                                stamps[completed] = now
                                completed += 1
                            if st["done"] >= goal:
                                pending -= 1
                            else:
                                ready.append(st)
                        # send the next burst only after every
                        # response in this pass is drained: clients
                        # that finished together re-submit together
                        for st in ready:
                            send_next(st)
                        ready.clear()

                # warmup: tables created, caches primed, batch
                # formation at steady state — then the timed run
                for s in socks:
                    send_next(sel.get_key(s).data)
                pump(warmup, False)
                t_run = time.perf_counter()
                for s in socks:
                    st = sel.get_key(s).data
                    st["done"] = 0
                    send_next(st)
                pump(per, True)
                for s in socks:
                    sel.unregister(s)
                    s.close()
                sel.close()
                return lat, stamps, t_run

            with server_thread(server, port):
                lat, stamps, t_start = drive()
            # two timed half-windows; report the better one, so a
            # noise spike from an unrelated process on this shared box
            # degrades one window, not the whole estimate (symmetric
            # for both phases)
            mid = total // 2
            rates = [mid / (stamps[mid - 1] - t_start),
                     (total - mid) / (stamps[-1] - stamps[mid - 1])]
            total_wall = stamps[-1] - t_start
            res = {
                "events": per * n_threads,
                "wall_sec": round(total_wall, 3),
                "events_per_sec": round(max(rates)),
                "p50_ms": round(float(np.percentile(lat, 50) * 1e3), 3),
                "p99_ms": round(float(np.percentile(lat, 99) * 1e3), 3),
            }
            if batching:
                ing = server._ingest
                res["commit_batches"] = ing.batches
                res["mean_batch"] = round(ing.submitted / max(ing.batches, 1), 1)
            return res

        off = run_concurrent(False, args.port)
        on = run_concurrent(True, args.port + 1)

        # the same commit-amortization effect isolated at the store SPI
        # (no HTTP, no client): per-event durable insert vs one
        # insert_batch group commit — the upper bound the coalescer
        # approaches as HTTP overhead shrinks
        from predictionio_tpu.data.event import Event

        st.events.set_durable(True)
        spi_app = st.meta.create_app("EventsBenchSPI")
        st.events.init_channel(spi_app.id)
        spi_n = min(2000, args.events)
        evs = [Event(event="view", entity_type="user", entity_id=str(i),
                     target_entity_type="item", target_entity_id="x",
                     properties={"n": i}) for i in range(2 * spi_n)]
        t0 = time.perf_counter()
        for e in evs[:spi_n]:
            st.events.insert(e, spi_app.id)
        spi_single = spi_n / (time.perf_counter() - t0)
        t0 = time.perf_counter()
        GROUP = 16  # what the coalescer forms at this concurrency
        for lo in range(spi_n, 2 * spi_n, GROUP):
            st.events.insert_batch(evs[lo:lo + GROUP], spi_app.id)
        spi_batch = spi_n / (time.perf_counter() - t0)

        print(json.dumps({
            "metric": "event_ingest_concurrent",
            "storage": args.storage,
            "concurrency": n_threads,
            "durable_acks": not args.volatile_acks,
            "batching_off": off,
            "batching_on": on,
            "speedup": round(on["events_per_sec"] / off["events_per_sec"],
                             2),
            "spi_group_commit": {
                "group": GROUP,
                "single_events_per_sec": round(spi_single),
                "batched_events_per_sec": round(spi_batch),
                "speedup": round(spi_batch / spi_single, 2),
            },
        }))
        return

    server = EventServer(storage=st, host="127.0.0.1", port=args.port)
    with server_thread(server, args.port):
        conn = http.client.HTTPConnection("127.0.0.1", args.port,
                                          timeout=10)
        rng = np.random.default_rng(0)

        def event(n):
            return {"event": "view", "entityType": "user",
                    "entityId": str(int(rng.integers(0, 1000))),
                    "targetEntityType": "item",
                    "targetEntityId": str(int(rng.integers(0, 500))),
                    "properties": {"n": int(n)}}

        # single-event POSTs
        n_single = args.events
        lat = np.empty(n_single)
        for i in range(n_single):
            body = json.dumps(event(i))
            t0 = time.perf_counter()
            conn.request("POST", f"/events.json?accessKey={key}", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            lat[i] = time.perf_counter() - t0
            assert resp.status == 201, data[:200]
        single = {
            "p50_ms": round(float(np.percentile(lat, 50) * 1e3), 3),
            "p99_ms": round(float(np.percentile(lat, 99) * 1e3), 3),
            "events_per_sec": round(n_single / float(lat.sum())),
        }

        # batch POSTs (50 per request — the API max); throughput only
        # counts if every PER-ITEM status is 201, not just the outer 200
        n_batches = max(1, args.events // 50)
        t0 = time.perf_counter()
        for b in range(n_batches):
            body = json.dumps([event(b * 50 + j) for j in range(50)])
            conn.request("POST", f"/batch/events.json?accessKey={key}",
                         body, {"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            assert resp.status == 200, data[:200]
            items = json.loads(data)
            bad = [it for it in items if it.get("status") != 201]
            assert not bad, f"batch items failed: {bad[:3]}"
        batch_sec = time.perf_counter() - t0
        batch = {
            "events_per_sec": round(n_batches * 50 / batch_sec),
            "batches": n_batches,
        }

        # filtered reads
        def read_once():
            conn.request(
                "GET",
                f"/events.json?accessKey={key}&event=view&limit=100")
            resp = conn.getresponse()
            data = resp.read()
            assert resp.status == 200, data[:200]

        read_once()
        rlat = np.empty(50)
        for i in range(50):
            t0 = time.perf_counter()
            read_once()
            rlat[i] = time.perf_counter() - t0
        reads = {"p50_ms": round(float(np.percentile(rlat, 50) * 1e3), 3)}

    out = {
        "metric": "event_ingest",
        "storage": args.storage,
        "single_post": single,
        "batch_post": batch,
        "get_find_limit100": reads,
        "total_events": n_single + n_batches * 50,
    }

    if args.bulk:
        # the `pio import` path: store-SPI bulk ingest (no HTTP), then
        # the training-read surfaces — full scan (the DataSource read)
        # and $set aggregation — at data sizes where the backend's own
        # costs dominate (VERDICT r4 #4: the EVENTLOG store had no
        # measured numbers; this found the MEMORY O(n²) in r4)
        from predictionio_tpu.data.event import Event

        rng2 = np.random.default_rng(1)
        uu = rng2.integers(0, 50_000, args.bulk)
        ii = rng2.integers(0, 100_000, args.bulk)
        t0 = time.perf_counter()
        CH = 20_000
        for lo in range(0, args.bulk, CH):
            evs = [Event(event="view", entity_type="user",
                         entity_id=str(int(uu[n])),
                         target_entity_type="item",
                         target_entity_id=str(int(ii[n])))
                   if n % 100 else
                   Event(event="$set", entity_type="user",
                         entity_id=str(int(uu[n])),
                         properties={"plan": "basic", "n": int(n)})
                   for n in range(lo, min(lo + CH, args.bulk))]
            st.events.insert_batch(evs, app.id)
        bulk_sec = time.perf_counter() - t0

        t0 = time.perf_counter()
        n_scanned = sum(1 for _ in st.events.find(app.id))
        scan_sec = time.perf_counter() - t0

        t0 = time.perf_counter()
        n_name = sum(1 for _ in st.events.find(app.id,
                                               event_names=["view"],
                                               limit=100))
        find100_sec = time.perf_counter() - t0

        t0 = time.perf_counter()
        props = st.events.aggregate_properties(app.id, "user")
        agg_sec = time.perf_counter() - t0

        # the actual `pio import` surface: NDJSON lines through
        # import_events (native C++ parse on EVENTLOG as of r5)
        import io

        from predictionio_tpu.tools.export_import import import_events

        app2 = st.meta.create_app("EventsBenchImport")
        st.events.init_channel(app2.id)
        buf = io.StringIO()
        for n in range(args.bulk):
            if n % 100:
                buf.write('{"event":"view","entityType":"user","entityId":"u%d",'
                          '"targetEntityType":"item","targetEntityId":"i%d",'
                          '"eventTime":"2026-03-01T00:00:00Z"}\n'
                          % (int(uu[n]), int(ii[n])))
            else:
                buf.write('{"event":"$set","entityType":"user","entityId":"u%d",'
                          '"properties":{"plan":"basic","n":%d}}\n'
                          % (int(uu[n]), n))
        buf.seek(0)
        t0 = time.perf_counter()
        n_imported = import_events(app2.id, buf, storage=st)
        jsonl_sec = time.perf_counter() - t0
        assert n_imported == args.bulk

        # the r5 columnar training read (native on EVENTLOG, generic
        # two-pass elsewhere) against the same events — what a `pio
        # train` DataSource actually calls
        from predictionio_tpu.data.store import read_training_interactions

        t0 = time.perf_counter()
        data = read_training_interactions(
            "EventsBench", entity_type="user", target_entity_type="item",
            event_names=["view"], storage=st)
        tu, ti, tv = data.arrays()
        columnar_sec = time.perf_counter() - t0

        # the `pio export` surface (native C++ emit on EVENTLOG)
        import os as _os

        from predictionio_tpu.tools.export_import import export_events

        with open(_os.devnull, "w") as devnull:
            t0 = time.perf_counter()
            n_exported = export_events(app2.id, devnull, storage=st)
            export_sec = time.perf_counter() - t0
        assert n_exported == args.bulk

        out["bulk_import"] = {
            "jsonl_import_sec": round(jsonl_sec, 2),
            "jsonl_import_events_per_sec": round(args.bulk / jsonl_sec),
            "jsonl_export_sec": round(export_sec, 2),
            "jsonl_export_events_per_sec": round(args.bulk / export_sec),
            "training_read_sec": round(columnar_sec, 2),
            "training_read_events_per_sec": round(
                max(data.n_events, 1) / columnar_sec),
            "training_read_pairs": data.n_events,
            "events": args.bulk,
            "events_per_sec": round(args.bulk / bulk_sec),
            "full_scan_sec": round(scan_sec, 2),
            "scanned": n_scanned,
            "find_limit100_ms": round(find100_sec * 1e3, 2),
            "find_limit100_matched": n_name,
            "aggregate_sec": round(agg_sec, 2),
            "aggregated_entities": len(props),
        }

    print(json.dumps(out))


if __name__ == "__main__":
    main()
