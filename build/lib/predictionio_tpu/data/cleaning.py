"""Self-cleaning data source: sliding event window with compaction.

Reference: [U] core/.../core/SelfCleaningDataSource.scala + EventWindow
(unverified, SURVEY.md §2a). Semantics reproduced:

- ``EventWindow(duration, remove_duplicates, compress_properties)`` on a
  data source's params;
- on training read, ``clean_persisted_events`` rewrites the app's event
  namespace: property events ($set/$unset/$delete) older than the window
  are folded into ONE ``$set`` snapshot per entity (property compaction),
  non-property events older than the window are dropped, duplicate
  events (same event/entity/target/properties) optionally deduplicated,
  and the store is rewritten via ``wipe`` + batched insert — the
  write+wipe path the reference drives through L/PEvents.

The fold itself reuses :func:`predictionio_tpu.data.event
.aggregate_properties` — the same code path training reads use, so a
compacted store aggregates to identical PropertyMaps (tested).
"""

from __future__ import annotations

import datetime as _dt
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from predictionio_tpu.data.event import (
    RESERVED_EVENTS,
    Event,
    aggregate_properties,
    utcnow,
)
from predictionio_tpu.data.store import resolve_app_channel
from predictionio_tpu.storage.registry import Storage, get_storage

_DURATION_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)\s*(seconds?|minutes?|hours?|days?|weeks?|s|m|h|d|w)\s*$",
    re.IGNORECASE,
)

_UNIT_SECONDS = {
    "s": 1.0, "second": 1.0, "seconds": 1.0,
    "m": 60.0, "minute": 60.0, "minutes": 60.0,
    "h": 3600.0, "hour": 3600.0, "hours": 3600.0,
    "d": 86400.0, "day": 86400.0, "days": 86400.0,
    "w": 604800.0, "week": 604800.0, "weeks": 604800.0,
}


def parse_duration(value) -> _dt.timedelta:
    """'3 days' / '12h' / timedelta / seconds-number → timedelta
    (reference: scala.concurrent.duration string syntax)."""
    if isinstance(value, _dt.timedelta):
        return value
    if isinstance(value, (int, float)):
        return _dt.timedelta(seconds=float(value))
    m = _DURATION_RE.match(str(value))
    if not m:
        raise ValueError(f"unparseable duration {value!r}")
    return _dt.timedelta(seconds=float(m.group(1)) * _UNIT_SECONDS[m.group(2).lower()])


@dataclass
class EventWindow:
    """Sliding window config (reference: EventWindow case class)."""

    duration: Optional[object] = None  # str | timedelta | seconds
    remove_duplicates: bool = False
    compress_properties: bool = False

    @classmethod
    def from_json(cls, obj: Optional[Dict]) -> Optional["EventWindow"]:
        if not obj:
            return None
        return cls(
            duration=obj.get("duration"),
            remove_duplicates=bool(obj.get("removeDuplicates", False)),
            compress_properties=bool(obj.get("compressProperties", False)),
        )


def _dedup_key(e: Event) -> Tuple:
    import json

    # event_time is part of the identity: a repeat interaction at a
    # different time is a legitimate new event, only true re-sends
    # (same payload AND same eventTime) collapse
    return (e.event, e.entity_type, e.entity_id, e.target_entity_type,
            e.target_entity_id, e.event_time,
            json.dumps(e.properties, sort_keys=True))


def clean_persisted_events(
    app_name: str,
    window: EventWindow,
    channel_name: Optional[str] = None,
    storage: Optional[Storage] = None,
    now: Optional[_dt.datetime] = None,
) -> Dict[str, int]:
    """Rewrite the (app, channel) namespace per the window. Returns
    counts {"kept", "dropped", "compacted"} for observability."""
    st = storage or get_storage()
    app_id, channel_id = resolve_app_channel(app_name, channel_name, st)
    now = now or utcnow()
    cutoff = (now - parse_duration(window.duration)) if window.duration else None

    events = sorted(
        st.events.find(app_id, channel_id),
        key=lambda e: (e.event_time, e.creation_time),
    )

    kept: List[Event] = []
    old_property_events: Dict[Tuple[str, str], List[Event]] = {}
    dropped = 0
    for e in events:
        is_old = cutoff is not None and e.event_time < cutoff
        if not is_old:
            kept.append(e)
        elif window.compress_properties and e.event in RESERVED_EVENTS:
            old_property_events.setdefault(
                (e.entity_type, e.entity_id), []).append(e)
        else:
            dropped += 1  # old non-property (or compaction off): discard

    compacted: List[Event] = []
    for (etype, eid), evs in sorted(old_property_events.items()):
        folded = aggregate_properties(evs).get(eid)
        if folded is None or not folded.properties:
            dropped += len(evs)
            continue  # entity fully $delete-d before the cutoff
        snapshot_time = max(e.event_time for e in evs)
        compacted.append(Event(
            event="$set", entity_type=etype, entity_id=eid,
            properties=dict(folded.properties),
            event_time=snapshot_time,
        ).with_id())
        dropped += len(evs) - 1

    result = compacted + kept
    if window.remove_duplicates:
        seen = set()
        deduped = []
        for e in result:
            k = _dedup_key(e)
            if k in seen:
                dropped += 1
                continue
            seen.add(k)
            deduped.append(e)
        result = deduped

    st.events.wipe(app_id, channel_id)
    if result:
        st.events.insert_batch(result, app_id, channel_id)
    return {"kept": len(result), "dropped": dropped, "compacted": len(compacted)}


class SelfCleaningDataSource:
    """Mixin for DataSource classes (reference: SelfCleaningDataSource
    trait). The template's params dict may carry an ``eventWindow``
    block; call :meth:`clean` at the top of ``read_training``."""

    def event_window(self) -> Optional[EventWindow]:
        params = getattr(self, "params", None) or {}
        if isinstance(params, dict):
            raw = params.get("eventWindow")
        else:
            raw = getattr(params, "event_window", None)
        if isinstance(raw, EventWindow) or raw is None:
            return raw
        return EventWindow.from_json(raw)

    def clean(self, ctx, app_name: str,
              channel_name: Optional[str] = None) -> Optional[Dict[str, int]]:
        window = self.event_window()
        if window is None:
            return None
        stats = clean_persisted_events(
            app_name, window, channel_name, storage=ctx.storage)
        ctx.log(f"self-cleaning {app_name}: {stats}")
        return stats
