"""Controller plumbing tests with tiny fake engines — the analogue of
the reference's core/controller fixture suite (Engine0/PDataSource0…,
EngineTest, MetricEvaluatorTest; SURVEY.md §4 Tier 1)."""

from dataclasses import dataclass, field
from typing import List

import pytest

from predictionio_tpu.controller import (
    Algorithm,
    AverageMetric,
    DataSource,
    Engine,
    EngineParams,
    Evaluation,
    FirstServing,
    IdentityPreparator,
    MetricEvaluator,
    WorkflowContext,
    params_from_json,
)


@dataclass
class DSParams:
    n: int = 10
    offset: float = 0.0
    lambda_: float = 0.5


class FakeDataSource(DataSource):
    ParamsClass = DSParams

    def read_training(self, ctx):
        return [self.params.offset + i for i in range(self.params.n)]

    def read_eval(self, ctx):
        td = self.read_training(ctx)
        qa = [(x, x * 2.0) for x in td]  # actual = 2x
        return [(td, {"fold": 0}, qa)]


@dataclass
class AlgoParams:
    mult: float = 1.0


class FakeAlgorithm(Algorithm):
    ParamsClass = AlgoParams

    def train(self, ctx, pd):
        return {"mean": sum(pd) / len(pd), "mult": self.params.mult}

    def predict(self, model, query):
        return query * model["mult"]


class SquaredError(AverageMetric):
    higher_is_better = False

    def calculate_one(self, q, p, a):
        return (p - a) ** 2


def make_engine():
    return Engine(FakeDataSource, IdentityPreparator, {"fake": FakeAlgorithm},
                  FirstServing)


class TestParamsExtraction:
    def test_snake_camel_and_keyword(self):
        p = params_from_json(DSParams, {"n": 3, "offset": 1.5, "lambda": 0.9})
        assert p.n == 3 and p.offset == 1.5 and p.lambda_ == 0.9

    def test_camel_case(self):
        @dataclass
        class P:
            num_iterations: int = 1
        assert params_from_json(P, {"numIterations": 7}).num_iterations == 7

    def test_unknown_key_raises(self):
        with pytest.raises(ValueError, match="unknown parameter"):
            params_from_json(DSParams, {"bogus": 1})

    def test_variant_parsing(self):
        engine = make_engine()
        ep = engine.params_from_variant({
            "datasource": {"params": {"n": 5}},
            "algorithms": [{"name": "fake", "params": {"mult": 2.0}}],
        })
        assert ep.data_source_params.n == 5
        assert ep.algorithms_params == [("fake", AlgoParams(mult=2.0))]

    def test_variant_unknown_algo(self):
        with pytest.raises(ValueError, match="unknown algorithm"):
            make_engine().params_from_variant(
                {"algorithms": [{"name": "nope", "params": {}}]})

    def test_variant_default_algo(self):
        ep = make_engine().params_from_variant({})
        assert ep.algorithms_params == [("fake", AlgoParams())]


class TestEngineTrainEval:
    def test_train(self):
        engine = make_engine()
        ep = engine.params_from_variant({"datasource": {"params": {"n": 4}}})
        models = engine.train(WorkflowContext(), ep)
        assert models == [{"mean": 1.5, "mult": 1.0}]

    def test_eval_produces_qpa(self):
        engine = make_engine()
        ep = EngineParams(DSParams(n=3), None, [("fake", AlgoParams(mult=2.0))], None)
        results = engine.eval(WorkflowContext(), ep)
        (info, qpa), = results
        assert info == {"fold": 0}
        assert qpa == [(0.0, 0.0, 0.0), (1.0, 2.0, 2.0), (2.0, 4.0, 4.0)]


class TestMetricEvaluator:
    def test_grid_picks_best(self):
        engine = make_engine()
        candidates = [
            EngineParams(DSParams(n=4), None, [("fake", AlgoParams(mult=m))], None)
            for m in (0.5, 2.0, 3.0)
        ]
        evaluator = MetricEvaluator(SquaredError())
        result = evaluator.evaluate(WorkflowContext(), engine, candidates)
        # actual = 2x, so mult=2.0 is exact (error 0)
        assert result.best_index == 1
        assert result.best_score == 0.0
        assert len(result.candidates) == 3
        assert "bestEngineParams" in result.to_json()

    def test_evaluation_binding(self):
        class Ev(Evaluation):
            engine_factory = staticmethod(make_engine)
            metric = SquaredError()

        result = Ev().run(WorkflowContext(), [
            EngineParams(DSParams(n=2), None, [("fake", AlgoParams(mult=2.0))], None)])
        assert result.best_score == 0.0
