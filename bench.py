"""Benchmark: ALS training throughput, MovieLens-20M-scale (driver metric).

Protocol (BASELINE.md): throughput = ratings × iterations / train
wall-clock (excluding event-store read / data prep) / chips. Rank 64,
10 iterations, f32 solves. The reference (Apache PredictionIO on
Spark/MLlib) publishes no numbers and the environment has no egress to
fetch ML-20M, so the dataset is a synthetic clone of its shape: 138,493
users × 26,744 items × 20M ratings, power-law degree distribution,
ratings in {0.5 … 5.0}. First measured run established the baseline
(see BENCH_BASELINE.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Flags: --quick (1/20 size, CI smoke), --rank, --iters, --nnz.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_BASELINE.json")


def synthetic_ml20m(nnz: int, n_users: int = 138_493, n_items: int = 26_744,
                    seed: int = 7):
    """Power-law user/item popularity, Zipf-ish, like MovieLens."""
    rng = np.random.default_rng(seed)
    # Zipf popularity via sorted exponential scores
    u_pop = rng.zipf(1.35, size=nnz * 2) % n_users
    i_pop = rng.zipf(1.25, size=nnz * 2) % n_items
    users = u_pop[:nnz].astype(np.int32)
    items = i_pop[:nnz].astype(np.int32)
    ratings = (rng.integers(1, 11, size=nnz) * 0.5).astype(np.float32)
    return users, items, ratings


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--rank", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--nnz", type=int, default=20_000_000)
    args = ap.parse_args()

    from predictionio_tpu.models.als import ALSParams, RatingsCOO, als_train

    nnz = args.nnz // 20 if args.quick else args.nnz
    n_users = 138_493 // (20 if args.quick else 1)
    n_items = 26_744 // (4 if args.quick else 1)
    users, items, ratings = synthetic_ml20m(nnz, n_users, n_items)
    coo = RatingsCOO(users, items, ratings, n_users, n_items)
    params = ALSParams(rank=args.rank, iterations=args.iters, reg=0.05, seed=1)

    import jax

    n_chips = 1  # single-chip bench (tunneled v5e); sharded path covers multi
    # warm-up/compile with 1 iteration on the same geometry? compilation is
    # cached per geometry; iterations is part of the cache key, so compile
    # cost is measured separately via a first timed run split below.
    t0 = time.perf_counter()
    U, V = als_train(coo, params)  # includes compile on first call
    t_total = time.perf_counter() - t0

    # second run: pure execute (compile cached)
    t1 = time.perf_counter()
    U, V = als_train(coo, params)
    t_exec = time.perf_counter() - t1

    assert np.isfinite(U).all() and np.isfinite(V).all()
    throughput = (coo.nnz * args.iters) / t_exec / n_chips

    # second driver metric (BASELINE.md): predict p50, recommendation
    # top-10 from the resident model — the engine-server hot path minus
    # HTTP framing. Sequential single-query calls, warm.
    from predictionio_tpu.models.als import ResidentScorer

    scorer = ResidentScorer(U, V)
    rng = np.random.default_rng(3)
    n_queries = 1_000 if args.quick else 10_000
    qusers = rng.integers(0, n_users, n_queries + 100)
    for u in qusers[:100]:  # warm both compile and caches
        scorer.recommend_batch(np.asarray([u]), 10)
    lat = np.empty(n_queries)
    for i, u in enumerate(qusers[100:]):
        q0 = time.perf_counter()
        scorer.recommend_batch(np.asarray([u]), 10)
        lat[i] = time.perf_counter() - q0
    p50_ms = float(np.percentile(lat, 50) * 1e3)
    p99_ms = float(np.percentile(lat, 99) * 1e3)

    baseline = None
    if os.path.exists(BASELINE_FILE):
        try:
            with open(BASELINE_FILE) as f:
                baseline = json.load(f).get("value")
        except Exception:
            baseline = None
    vs = (throughput / baseline) if baseline else 1.0

    print(json.dumps({
        "metric": "als_train_throughput_ml20m_synthetic",
        "value": round(throughput, 1),
        "unit": "rating-updates/sec/chip (ratings x iters / train-sec / chips)",
        "vs_baseline": round(vs, 4),
        "detail": {
            "nnz": coo.nnz, "rank": args.rank, "iterations": args.iters,
            "n_users": n_users, "n_items": n_items,
            "train_sec_warm": round(t_exec, 3),
            "train_sec_incl_compile": round(t_total, 3),
            "predict_p50_ms": round(p50_ms, 3),
            "predict_p99_ms": round(p99_ms, 3),
            "predict_queries": n_queries,
            # On this image's tunneled ("axon") chip, every device→host
            # fetch costs a ~66ms round trip once any prior fetch has
            # happened, so p50 here is the tunnel floor — the identical
            # query program measures ~0.1ms end-to-end before the first
            # fetch (see BASELINE.md serving note). One packed fetch per
            # query keeps it at 1× the floor.
            "predict_note": "p50 bounded by tunnel round-trip on this "
                            "image; ~0.1ms on directly-attached TPU",
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
        },
    }))


if __name__ == "__main__":
    main()
