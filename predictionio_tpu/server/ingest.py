"""Group-commit write coalescer for the Event Server ingest path.

Every backend's ``insert_batch`` already amortizes the expensive part
of a write — one SQL ``executemany`` + COMMIT (`data/events.py`), one
chunked native append (`data/filestore.py`), one WAL append
(`storage/indexed.py`) — but concurrent single-event POSTs never used
it: each request paid a full per-event commit. The reference's HBase
backend got batching for free from client-side put buffering
(SURVEY.md §3.3); this layer is the framework's equivalent, server
side, with a durability guarantee the client buffer never had.

Design mirrors :class:`~predictionio_tpu.server.batching.MicroBatcher`
(the query-path coalescer) and its r5 lessons:

- **No timed wait on the hot path.** Batches form naturally from
  service time: while a commit runs, new arrivals queue; the next
  collect drains EVERYTHING queued (up to ``max_batch``). A lone
  event pays ~0 extra latency.
- **One commit per (app, channel) group** per dispatch — namespaces
  are separate tables/logs, so a drained batch is grouped before the
  backend call.
- **Ack after commit.** A request's future resolves only once its
  group's ``insert_batch`` has returned, so a 201 still means the
  event is as durable as the backend makes a committed write.
- **Per-event failure isolation.** A failed group commit re-runs its
  events one by one (the MicroBatcher isolation move): each caller
  sees their OWN error; siblings of a poison event still land.
- **Bounded queue with backpressure.** Past ``max_queue`` pending
  events, ``submit`` raises :class:`IngestOverload`; the HTTP layer
  maps it to ``429`` + ``Retry-After`` instead of letting the queue
  grow without bound under a traffic spike. The Retry-After is
  *computed* — queue depth over the measured commit drain rate — so
  clients back off proportionally to actual congestion, and the
  coalescer keeps per-app accounting of who filled the queue (the
  global cap is the last-resort backstop behind the per-app token
  buckets in ``server/tenancy.py``).
- **Storage circuit breaker.** Repeated group-commit failures trip
  the ``ingest_storage`` breaker open; further submits fail
  IMMEDIATELY with :class:`StorageUnavailable` (HTTP layer → ``503``
  + ``Retry-After``) instead of queueing events that are doomed to
  time out against a down backend. Half-open trial commits close it
  again once storage recovers. Poison events do NOT trip it: a failed
  group whose per-event rerun succeeds proves storage is up.
- **Clean drain on shutdown.** ``aclose()`` refuses new work, lets
  the committer finish everything already accepted, then commits any
  remainder itself — no accepted (let alone acked) event is lost.

Enable with ``EventServer(ingest_batching=True)`` or
``pio eventserver --ingest-batching``.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import time
from typing import Dict, List, Optional, Tuple

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.replication import FencedWriteError
from predictionio_tpu.utils import faults, tracing
from predictionio_tpu.utils.resilience import CircuitBreaker

_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)

#: queue sentinel: aclose() pushes it behind everything already
#: accepted, so the committer drains in arrival order then exits
_STOP = object()


class IngestOverload(Exception):
    """Ingest queue at capacity — shed load instead of queueing."""

    def __init__(self, depth: int, limit: int,
                 retry_after: float = 1.0) -> None:
        super().__init__(
            f"ingest queue full ({depth}/{limit} events pending)")
        self.depth = depth
        self.limit = limit
        self.retry_after = retry_after


class StorageUnavailable(Exception):
    """The storage breaker is open: event storage is known-down, fail
    fast (HTTP layer → 503 + Retry-After) instead of queueing work."""

    def __init__(self, retry_after: float) -> None:
        super().__init__(
            "event storage unavailable (circuit breaker open, "
            f"retry after {retry_after:.1f}s)")
        self.retry_after = max(1.0, retry_after)


class WriteCoalescer:
    """Order-preserving group-commit front for an
    :class:`~predictionio_tpu.data.events.EventStore`."""

    def __init__(self, store, max_batch: int = 512,
                 max_queue: int = 4096) -> None:
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.store = store
        self.max_batch = max_batch
        self.max_queue = max_queue
        self._queue: asyncio.Queue = asyncio.Queue()
        self._worker: Optional[asyncio.Task] = None
        self._executor: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        self._closed = False
        self.submitted = 0    # events accepted into the queue
        self.batches = 0      # group commits issued
        self.isolations = 0   # failed groups re-run event-by-event
        self.rejected = 0     # submits refused by backpressure
        self.breaker_rejected = 0  # submits refused by the open breaker
        self.parallel_dispatches = 0  # dispatches spanning >1 namespace
        #: queued events per app (accepted, not yet dispatched to a
        #: commit) — when the global cap trips, this names the tenant
        #: that filled it
        self.queued_by_app: Dict[int, int] = {}
        #: EWMA of commit throughput (events/sec) — denominator for
        #: the computed 429 Retry-After
        self._drain_ewma = 0.0
        #: repeated commit failures → open → fast 503s. Decoupled use
        #: (admit at submit, record at commit) — see CircuitBreaker doc.
        self.breaker = CircuitBreaker(
            "ingest_storage", failure_threshold=8, reset_timeout=5.0)
        from predictionio_tpu.utils.metrics import REGISTRY

        self._m_depth = REGISTRY.gauge(
            "pio_ingest_queue_depth", "Events waiting for a group commit")
        self._m_batch = REGISTRY.histogram(
            "pio_ingest_batch_events", "Events per group commit",
            buckets=_BATCH_BUCKETS)
        self._m_commit = REGISTRY.histogram(
            "pio_ingest_commit_seconds", "Group-commit latency")
        self._m_coalesced = REGISTRY.counter(
            "pio_ingest_coalesced_events_total",
            "Events that shared their commit with at least one other")
        self._m_rejected = REGISTRY.counter(
            "pio_ingest_rejected_total",
            "Submits refused before queueing, by app and reason",
            ("app", "reason"))

    # -- plumbing --------------------------------------------------------------

    #: commit threads: groups for DIFFERENT (app, channel) namespaces
    #: hold different writer locks (segmented log: one lock per
    #: partition), so they commit concurrently. Within one namespace
    #: commits stay ordered — _commit awaits all groups of a dispatch
    #: before the next dispatch starts.
    _COMMIT_WORKERS = 4

    def _get_executor(self) -> concurrent.futures.ThreadPoolExecutor:
        # dedicated pool: commits must never wait behind the shared
        # to_thread pool, which blocked request handlers can saturate —
        # the deadlock the MicroBatcher hit in r4
        if self._executor is None:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=self._COMMIT_WORKERS,
                thread_name_prefix="pio-ingest")
        return self._executor

    def _ensure_worker(self) -> None:
        if self._worker is None or self._worker.done():
            self._worker = asyncio.get_running_loop().create_task(self._run())

    @property
    def depth(self) -> int:
        return self._queue.qsize()

    @property
    def drain_rate(self) -> float:
        """Measured commit throughput, events/sec (0 until observed)."""
        return self._drain_ewma

    def overload_retry_after(self) -> float:
        """Honest backoff hint for a queue-full 429: time to drain the
        current depth at the measured rate, clamped to [0.05s, 30s].
        Before any commit has been observed, 1s (the old constant)."""
        if self._drain_ewma <= 0:
            return 1.0
        return min(30.0, max(0.05, self._queue.qsize() / self._drain_ewma))

    # -- submit ----------------------------------------------------------------

    async def submit(self, event: Event, app_id: int,
                     channel_id: Optional[int] = None) -> str:
        """Enqueue one validated event; resolves to its eventId once
        the group commit that contains it has returned (or raises the
        per-event storage error)."""
        if self._closed:
            raise RuntimeError("ingest coalescer is closed")
        if not self.breaker.admit():
            self.breaker_rejected += 1
            self._m_rejected.inc((app_id, "breaker"))
            raise StorageUnavailable(self.breaker.retry_after())
        if self._queue.qsize() >= self.max_queue:
            self.rejected += 1
            self._m_rejected.inc((app_id, "queue_full"))
            raise IngestOverload(self._queue.qsize(), self.max_queue,
                                 self.overload_retry_after())
        self._ensure_worker()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.submitted += 1
        self.queued_by_app[app_id] = self.queued_by_app.get(app_id, 0) + 1
        # hot path: put_nowait (the queue is unbounded — depth limiting
        # happened above) skips a coroutine round trip per event, and
        # the depth gauge is refreshed once per dispatch in _collect().
        # The submitter's trace id rides along: the commit serves many
        # requests' traces, so its span LINKS to them instead of
        # parenting under any one (contextvars don't survive the queue)
        self._queue.put_nowait(
            (app_id, channel_id, event, fut, tracing.current_trace_id()))
        return await fut

    # -- committer -------------------------------------------------------------

    async def _collect(self) -> Tuple[List[tuple], bool]:
        """One dispatch's worth: block for the first item, yield once
        so ready handlers enqueue, then take everything queued (up to
        ``max_batch``). Returns (items, stop_seen). No timed wait —
        see module docstring."""
        first = await self._queue.get()
        if first is _STOP:
            return [], True
        items = [first]
        stop = False
        # quiescence loop: yield to ready handlers, drain what they
        # enqueued, repeat while the queue keeps growing. Still no
        # timed wait — sleep(0) adds zero idle time — but requests
        # that are already parsed and mid-handler make this dispatch
        # instead of the next one. Bounded by max_batch and by the
        # natural cap of in-flight requests (a client waiting for its
        # ack can't enqueue another event).
        while len(items) < self.max_batch:
            await asyncio.sleep(0)
            grew = False
            while len(items) < self.max_batch:
                try:
                    nxt = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if nxt is _STOP:
                    stop = True
                    break
                items.append(nxt)
                grew = True
            if stop or not grew:
                break
        self._m_depth.set(self._queue.qsize())
        return items, stop

    async def _run(self) -> None:
        while True:
            items, stop = await self._collect()
            if items:
                await self._commit(items)
            if stop:
                return

    def _insert_batch_guarded(self, events: List[Event], app_id: int,
                              channel_id: Optional[int]) -> List[str]:
        faults.inject("ingest.commit")
        return self.store.insert_batch(events, app_id, channel_id)

    def _insert_one_guarded(self, event: Event, app_id: int,
                            channel_id: Optional[int]) -> str:
        faults.inject("ingest.commit")
        return self.store.insert(event, app_id, channel_id)

    async def _commit(self, items: List[tuple]) -> None:
        """Group by (app, channel), one ``insert_batch`` per group.
        Groups are independent namespaces (separate tables / separate
        partition writer locks), so a multi-namespace dispatch commits
        them concurrently on the dedicated pool."""
        groups: Dict[Tuple[int, Optional[int]], List[tuple]] = {}
        for app_id, channel_id, event, fut, trace_id in items:
            groups.setdefault((app_id, channel_id), []).append(
                (event, fut, trace_id))
        if len(groups) == 1:
            ((app_id, channel_id), pairs), = groups.items()
            await self._commit_group(app_id, channel_id, pairs)
            return
        self.parallel_dispatches += 1
        await asyncio.gather(*(
            self._commit_group(app_id, channel_id, pairs)
            for (app_id, channel_id), pairs in groups.items()))

    async def _commit_group(self, app_id: int, channel_id: Optional[int],
                            pairs: List[tuple]) -> None:
        loop = asyncio.get_running_loop()
        ex = self._get_executor()
        events = [e for e, _, _ in pairs]
        left = self.queued_by_app.get(app_id, 0) - len(pairs)
        if left > 0:
            self.queued_by_app[app_id] = left
        else:
            self.queued_by_app.pop(app_id, None)
        # the commit serves MANY requests' traces: a detached root
        # span that links every submitter's trace id, so any one of
        # them finds its batched ack in /traces or the JSONL export
        links = sorted({t for _, _, t in pairs if t})[:64]
        self.batches += 1
        t0 = time.perf_counter()
        with tracing.detached_span(
                "ingest.commit", app_id=app_id,
                records=len(events),
                link_traces=links) as sp:
            try:
                ids = await loop.run_in_executor(
                    ex, self._insert_batch_guarded, events, app_id,
                    channel_id)
                if len(ids) != len(events):
                    raise RuntimeError(
                        f"insert_batch returned {len(ids)} ids for "
                        f"{len(events)} events")
            except FencedWriteError as e:
                # leadership lost, not a storage outage: the breaker
                # must stay closed (storage is fine — WE are fenced)
                # and per-item isolation is pointless (every retry
                # refuses identically); every caller sees the fence
                sp.set_error(f"fenced: {e}")
                for _, fut, _ in pairs:
                    if not fut.done():
                        fut.set_exception(e)
                return
            except Exception as e:
                self.breaker.record_failure()
                sp.set_error(f"{type(e).__name__}: {e}")
                if len(pairs) == 1:
                    if not pairs[0][1].done():
                        pairs[0][1].set_exception(e)
                    return
                # a poison event must not fail its commit siblings,
                # and each caller must see their OWN error — re-run
                # alone
                self.isolations += 1
                sp.set_attr("isolated", True)
                for event, fut, _ in pairs:
                    if fut.done():
                        continue
                    try:
                        eid = await loop.run_in_executor(
                            ex, self._insert_one_guarded, event, app_id,
                            channel_id)
                    except Exception as single_e:
                        if not fut.done():
                            fut.set_exception(single_e)
                    else:
                        # storage demonstrably works — the group
                        # failure was a poison event, not an outage
                        self.breaker.record_success()
                        if not fut.done():
                            fut.set_result(eid)
                return
        self.breaker.record_success()
        elapsed = time.perf_counter() - t0
        rate = len(events) / max(elapsed, 1e-6)
        self._drain_ewma = (rate if self._drain_ewma <= 0
                            else 0.3 * rate + 0.7 * self._drain_ewma)
        self._m_commit.observe(elapsed,
                               exemplar=links[0] if links else None)
        self._m_batch.observe(len(events))
        if len(events) > 1:
            self._m_coalesced.inc(n=len(events))
        for (_, fut, _), eid in zip(pairs, ids):
            if not fut.done():
                fut.set_result(eid)

    # -- lifecycle -------------------------------------------------------------

    async def aclose(self) -> None:
        """Refuse new submits, commit everything already accepted,
        release the executor. The coalescer is reusable afterwards
        (next ``submit`` restarts worker + executor) so a server that
        stops and serves again keeps working."""
        self._closed = True
        try:
            worker = self._worker
            if worker is not None and not worker.done():
                await self._queue.put(_STOP)
                await worker
            self._worker = None
            # leftovers are only possible if the worker had previously
            # died — drain them here so no accepted event is dropped
            leftovers: List[tuple] = []
            while True:
                try:
                    item = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if item is not _STOP:
                    leftovers.append(item)
            while leftovers:
                chunk = leftovers[:self.max_batch]
                leftovers = leftovers[self.max_batch:]
                await self._commit(chunk)
            self._m_depth.set(0)
            if self._executor is not None:
                self._executor.shutdown(wait=True)
                self._executor = None
        finally:
            self._closed = False
