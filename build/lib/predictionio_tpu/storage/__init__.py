from predictionio_tpu.storage.meta import (
    App,
    AccessKey,
    Channel,
    EngineInstance,
    EvaluationInstance,
    MetaStore,
)
from predictionio_tpu.storage.models import ModelStore, LocalFSModelStore
from predictionio_tpu.storage.registry import Storage, StorageConfig, get_storage, set_storage

__all__ = [
    "App",
    "AccessKey",
    "Channel",
    "EngineInstance",
    "EvaluationInstance",
    "MetaStore",
    "ModelStore",
    "LocalFSModelStore",
    "Storage",
    "StorageConfig",
    "get_storage",
    "set_storage",
]
