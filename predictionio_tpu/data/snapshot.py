"""Columnar snapshot cache: persisted ``ColumnarEvents`` + watermark.

The reference's training contract re-reads the FULL event history on
every ``pio train`` (PAPER.md §0: PEventStore → RDD per invocation).
For the steady-state retrain loop over a mostly-append-only log that
makes train startup O(total events) forever. This module is the disk
layer of the incremental scan cache that turns it into O(events since
last train):

- a **snapshot** is one ``ColumnarEvents`` (the arrays
  ``data/pipeline.columnar_from_rows`` builds) persisted as an ``.npz``
  next to a small JSON **manifest**;
- the manifest carries a **watermark** — the maximum ``creationTime``
  (epoch µs) the snapshot covers, taken from the store BEFORE the
  building scan started — plus the live-event count at that watermark
  and the hash of the filter key;
- on the next train, ``data/store.py`` loads the snapshot, asks the
  backend to scan only ``creationTime > watermark`` (predicate pushed
  down into C++/SQL/doc-values), and concatenates the delta
  (:func:`data.pipeline.concat_columnar`).

Invalidation rules (any failure falls back to a full rescan — the
cache can cost a rebuild, never correctness):

- manifest missing/unreadable, schema version bump, filter-key hash
  mismatch, npz corrupt/truncated, or array lengths disagreeing with
  the manifest;
- a per-column SHA-256 digest in the manifest disagreeing with the
  loaded array bytes (bit rot in the npz): counted on
  ``pio_integrity_failed_total{artifact="snapshot"}`` and treated as
  a cold cache — a corrupt snapshot costs a rebuild, never a wrong
  training set and never a crash;
- the live-event count at the old watermark no longer matches the
  manifest (events were deleted, or arrived bearing creationTimes at
  or below the watermark);
- the delta contains an event whose eventTime is ≤ the snapshot's
  maximum (out-of-order append: concatenation would not reproduce the
  (eventTime, creationTime, id) scan order);
- ``startTime``/``untilTime`` filters bypass the cache entirely (a
  time-windowed read is not the repeat-train shape).

Cache keys hash the full filter tuple PLUS a backend-provided
``cache_identity`` string (e.g. the sqlite path), so two stores that
happen to share an app id can never serve each other's snapshots.
Files live under ``<storage home>/scan_cache/`` (override with
``PIO_SCAN_CACHE_DIR``).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.utils import faults
from predictionio_tpu.utils.atomic_write import atomic_file, atomic_write_text
from predictionio_tpu.utils.integrity import (
    INTEGRITY_FAILED,
    INTEGRITY_VERIFIED,
)

# v2: per-column sha256 digests in the manifest. The bump itself
# invalidates pre-integrity snapshots (a cache miss, rebuilt on the
# next train).
SCHEMA_VERSION = 2

# watermark of an empty namespace: below every real creationTime, and
# matching the native scan's unbounded sentinel so `creation > W`
# selects everything and `creation <= W` selects nothing
EMPTY_WATERMARK = -(2**62)

_ARRAY_FIELDS = ("entity_idx", "target_idx", "name_idx", "values",
                 "times_us")
_TABLE_FIELDS = ("entity_ids", "target_ids", "names")
_DTYPES = {"entity_idx": "uint32", "target_idx": "uint32",
           "name_idx": "uint16", "values": "float64",
           "times_us": "int64"}


@dataclass
class SnapshotManifest:
    """The validity contract of one persisted snapshot."""

    schema: int
    filter_hash: str
    watermark_us: int
    pre_count: int  # live events with creationTime <= watermark_us
    n_rows: int     # rows in the npz arrays (post-filter)
    created_at: float
    digests: Dict[str, str] = field(default_factory=dict)  # field -> sha256


def cache_dir(storage) -> str:
    """Snapshot directory for a Storage (env-overridable)."""
    override = os.environ.get("PIO_SCAN_CACHE_DIR")
    if override:
        return override
    return os.path.join(storage.config.home, "scan_cache")


def filter_fingerprint(
    identity: str,
    app_id: int,
    channel_id: Optional[int],
    entity_type: Optional[str],
    target_entity_type: Optional[str],
    event_names: Optional[Sequence[str]],
    value_key: Optional[str],
) -> str:
    """Hash of (store identity, namespace, scan filters) — the cache
    key. Hashed rather than embedded so arbitrary ids/filters can't
    produce unbounded or path-hostile filenames."""
    payload = json.dumps(
        {"identity": identity, "app": app_id, "channel": channel_id,
         "entity_type": entity_type,
         "target_entity_type": target_entity_type,
         "event_names": (list(event_names)
                         if event_names is not None else None),
         "value_key": value_key},
        sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


def _paths(directory: str, fingerprint: str) -> Tuple[str, str]:
    base = os.path.join(directory, f"snap_{fingerprint}")
    return base + ".npz", base + ".json"


def _table_array(strings) -> np.ndarray:
    # numpy U-dtype: fixed-width unicode, loadable without pickle
    if len(strings):
        return np.asarray(list(strings), dtype=np.str_)
    return np.empty(0, dtype="U1")


def _digest(a: np.ndarray) -> str:
    """Per-column integrity digest over the exact array bytes."""
    return hashlib.sha256(np.ascontiguousarray(a).tobytes()).hexdigest()


def save_snapshot(
    directory: str,
    fingerprint: str,
    cols,
    watermark_us: int,
    pre_count: int,
) -> bool:
    """Persist ``cols`` + manifest atomically AND durably (fsync'd tmp
    file + rename + dir fsync via utils.atomic_write; the manifest
    lands LAST, so a manifest's presence implies a complete npz).
    Returns False instead of raising — a full disk or read-only cache
    dir must never fail the training read it rides on."""
    npz_path, man_path = _paths(directory, fingerprint)
    try:
        os.makedirs(directory, exist_ok=True)
        arrays = {
            "entity_idx": np.ascontiguousarray(cols.entity_idx),
            "target_idx": np.ascontiguousarray(cols.target_idx),
            "name_idx": np.ascontiguousarray(cols.name_idx),
            "values": np.ascontiguousarray(cols.values),
            "times_us": np.ascontiguousarray(cols.times_us),
            "entity_ids": _table_array(cols.entity_ids),
            "target_ids": _table_array(cols.target_ids),
            "names": _table_array(cols.names),
        }
        digests = {k: _digest(a) for k, a in arrays.items()}
        with atomic_file(npz_path, "wb") as f:
            np.savez(f, **arrays)
        return _write_manifest(man_path, fingerprint, watermark_us,
                               pre_count, cols.n, digests)
    except Exception:
        return False


def update_manifest(
    directory: str,
    fingerprint: str,
    watermark_us: int,
    pre_count: int,
    n_rows: int,
) -> bool:
    """Advance the watermark of an existing snapshot whose arrays are
    unchanged (an empty delta still moves the watermark forward, so
    later delta scans stay O(new events) instead of re-walking the
    whole post-watermark window). The column digests carry over from
    the existing manifest — the npz did not change."""
    _npz, man_path = _paths(directory, fingerprint)
    try:
        with open(man_path, "r", encoding="utf-8") as f:
            digests = json.load(f).get("digests")
        if not isinstance(digests, dict):
            return False  # pre-integrity manifest: let it invalidate
        return _write_manifest(man_path, fingerprint, watermark_us,
                               pre_count, n_rows, digests)
    except Exception:
        return False


def _write_manifest(man_path: str, fingerprint: str, watermark_us: int,
                    pre_count: int, n_rows: int,
                    digests: Dict[str, str]) -> bool:
    doc = {"schema": SCHEMA_VERSION, "filter": fingerprint,
           "watermark_us": int(watermark_us), "pre_count": int(pre_count),
           "n_rows": int(n_rows), "created_at": time.time(),
           "digests": digests}
    atomic_write_text(man_path, json.dumps(doc, separators=(",", ":")))
    return True


def load_snapshot(directory: str, fingerprint: str):
    """Load and validate one snapshot.

    Returns ``(ColumnarEvents, SnapshotManifest)``, or None on ANY
    defect — missing files, unreadable JSON, schema/filter mismatch,
    corrupt or truncated npz, wrong dtypes, or lengths that disagree
    with the manifest. Callers treat None as a cold cache."""
    from predictionio_tpu.data.pipeline import ColumnarEvents

    npz_path, man_path = _paths(directory, fingerprint)
    try:
        with open(man_path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if (doc.get("schema") != SCHEMA_VERSION
                or doc.get("filter") != fingerprint):
            return None
        digests = doc.get("digests")
        if not isinstance(digests, dict):
            return None
        man = SnapshotManifest(
            schema=int(doc["schema"]), filter_hash=doc["filter"],
            watermark_us=int(doc["watermark_us"]),
            pre_count=int(doc["pre_count"]), n_rows=int(doc["n_rows"]),
            created_at=float(doc.get("created_at", 0.0)),
            digests={str(k): str(v) for k, v in digests.items()})
        with open(npz_path, "rb") as f:
            raw = f.read()
        # byte-flip-on-read fault site, feeding the checks below
        raw = faults.corrupt_bytes("data.corrupt.snapshot", raw)
        try:
            with np.load(io.BytesIO(raw), allow_pickle=False) as z:
                arrays = {}
                for k in _ARRAY_FIELDS:
                    a = z[k]
                    if (a.ndim != 1 or a.shape[0] != man.n_rows
                            or a.dtype != np.dtype(_DTYPES[k])):
                        return None
                    arrays[k] = a
                tables = {}
                raw_tables = {}
                for k in _TABLE_FIELDS:
                    t = z[k]
                    if t.ndim != 1 or t.dtype.kind != "U":
                        return None
                    raw_tables[k] = t
                    tables[k] = t.tolist()
        except Exception:
            # valid manifest but unreadable npz = damage, not a cold
            # cache (the zip container's own CRC often trips before
            # the per-column digests get their chance)
            INTEGRITY_FAILED.inc(("snapshot",))
            return None
        # per-column digest verification: a flipped bit anywhere in the
        # arrays is a counted cache miss (rebuild), never a wrong
        # training set
        for k in (*_ARRAY_FIELDS, *_TABLE_FIELDS):
            stored = man.digests.get(k)
            a = arrays[k] if k in arrays else raw_tables[k]
            if stored is None or _digest(a) != stored:
                INTEGRITY_FAILED.inc(("snapshot",))
                return None
        INTEGRITY_VERIFIED.inc(("snapshot",))
        # index columns must point inside their tables, or downstream
        # vectorized gathers would read garbage
        for idx_k, tab_k in (("entity_idx", "entity_ids"),
                             ("target_idx", "target_ids"),
                             ("name_idx", "names")):
            a = arrays[idx_k]
            if a.size and int(a.max()) >= len(tables[tab_k]):
                return None
        cols = ColumnarEvents(
            entity_idx=arrays["entity_idx"],
            target_idx=arrays["target_idx"],
            name_idx=arrays["name_idx"], values=arrays["values"],
            times_us=arrays["times_us"],
            entity_ids=tables["entity_ids"],
            target_ids=tables["target_ids"], names=tables["names"])
        return cols, man
    except Exception:
        return None
