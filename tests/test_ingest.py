"""Group-commit ingestion: concurrent single-POST coalescing,
ack-after-commit durability (exactly-once across restart on every
backend), per-event failure isolation, queue backpressure, the batch
endpoint's single-commit fast path, and the auth TTL cache."""

import asyncio
import http.client
import json
import threading
import time

import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.events import MemoryEventStore
from predictionio_tpu.server.event_server import EventServer
from predictionio_tpu.server.ingest import IngestOverload, WriteCoalescer
from predictionio_tpu.storage.meta import MetaStore
from predictionio_tpu.storage.models import MemoryModelStore
from predictionio_tpu.storage.registry import (Storage, StorageConfig,
                                               set_storage)
from test_servers import ServerThread, free_port
from test_servers import http as http_req


def _mem_storage(events_store=None):
    st = Storage(StorageConfig(metadata_type="MEMORY",
                               eventdata_type="MEMORY",
                               modeldata_type="MEMORY"))
    st._meta = MetaStore(":memory:")
    st._events = events_store or MemoryEventStore()
    st._models = MemoryModelStore()
    return st


@pytest.fixture(params=["memory", "sqlite", "eventlog"])
def backend(request, tmp_path):
    """(name, Storage) per event backend; file-backed ones live under
    tmp_path so the test can 'restart' them from disk."""
    name = request.param
    if name == "memory":
        st = _mem_storage()
    else:
        st = Storage(StorageConfig(home=str(tmp_path),
                                   eventdata_type=name.upper()))
        if name == "eventlog":
            try:
                st.events
            except RuntimeError as e:  # no g++ in this environment
                pytest.skip(str(e))
    set_storage(st)
    yield name, st, tmp_path
    set_storage(None)
    try:
        st.events.close()
    except Exception:
        pass


def _post(conn, path, obj):
    conn.request("POST", path, json.dumps(obj),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    return resp.status, (json.loads(data) if data else None), resp.headers


def _setup_app(st, name="IngestApp"):
    app = st.meta.create_app(name)
    st.events.init_channel(app.id)
    key = st.meta.create_access_key(app.id).key
    return app, key


class TestConcurrentDurability:
    def test_exactly_once_after_restart(self, backend):
        name, st, home = backend
        app, key = _setup_app(st)
        port = free_port()
        server = EventServer(storage=st, host="127.0.0.1", port=port,
                             ingest_batching=True)
        N, M = 8, 20
        acked = [[] for _ in range(N)]
        errors = []

        def worker(t):
            try:
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=30)
                for m in range(M):
                    status, body, _ = _post(
                        conn, f"/events.json?accessKey={key}",
                        {"event": "view", "entityType": "user",
                         "entityId": f"u{t}", "targetEntityType": "item",
                         "targetEntityId": f"i{m}",
                         "properties": {"t": t, "m": m}})
                    assert status == 201, body
                    acked[t].append(body["eventId"])
                conn.close()
            except Exception as e:  # surfaced after join
                errors.append(e)

        with ServerThread(server):
            threads = [threading.Thread(target=worker, args=(t,))
                       for t in range(N)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        assert not errors, errors[:3]
        all_ids = [eid for lst in acked for eid in lst]
        assert len(all_ids) == N * M
        assert len(set(all_ids)) == N * M
        assert server._ingest.submitted == N * M

        # 'restart': reopen the durable backends from disk
        if name == "memory":
            store2 = st.events
        else:
            st.events.close()
            store2 = Storage(StorageConfig(
                home=str(home), eventdata_type=name.upper())).events
        evs = list(store2.find(app.id))
        assert sorted(e.event_id for e in evs) == sorted(all_ids)

    def test_shutdown_drains_accepted_events(self):
        """Every event the coalescer accepted is committed by server
        shutdown, even if its response never made it out."""

        class SlowStore(MemoryEventStore):
            def insert_batch(self, events, app_id, channel_id=None):
                time.sleep(0.03)
                return super().insert_batch(events, app_id, channel_id)

        st = _mem_storage(SlowStore())
        app, key = _setup_app(st)
        port = free_port()
        server = EventServer(storage=st, host="127.0.0.1", port=port,
                             ingest_batching=True)
        statuses = []

        def worker(m):
            try:
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=10)
                status, _, _ = _post(
                    conn, f"/events.json?accessKey={key}",
                    {"event": "view", "entityType": "user", "entityId": "u",
                     "targetEntityType": "item", "targetEntityId": str(m)})
                statuses.append(status)
            except Exception:
                pass  # shutdown may cut the connection; drain still runs

        with ServerThread(server):
            threads = [threading.Thread(target=worker, args=(m,))
                       for m in range(10)]
            for th in threads:
                th.start()
            time.sleep(0.05)  # let requests be accepted mid-commit
        for th in threads:
            th.join(timeout=10)
        # the drain guarantee: accepted == committed
        assert len(list(st.events.find(app.id))) == server._ingest.submitted
        # and nothing acked was lost
        assert statuses.count(201) <= server._ingest.submitted


class TestFailureIsolation:
    def test_poison_event_does_not_fail_siblings(self):
        class PoisonStore(MemoryEventStore):
            def insert(self, event, app_id, channel_id=None):
                if event.properties.get("poison"):
                    raise RuntimeError("poisoned event")
                return super().insert(event, app_id, channel_id)

            def insert_batch(self, events, app_id, channel_id=None):
                if any(e.properties.get("poison") for e in events):
                    raise RuntimeError("poisoned batch")
                return super().insert_batch(events, app_id, channel_id)

        st = _mem_storage(PoisonStore())
        app, key = _setup_app(st)
        port = free_port()
        server = EventServer(storage=st, host="127.0.0.1", port=port,
                             ingest_batching=True)
        results = {}

        def worker(m, poison):
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            status, body, _ = _post(
                conn, f"/events.json?accessKey={key}",
                {"event": "view", "entityType": "user", "entityId": str(m),
                 "targetEntityType": "item", "targetEntityId": "x",
                 "properties": {"poison": poison, "m": m}})
            results[m] = (status, body)
            conn.close()

        with ServerThread(server):
            threads = [threading.Thread(target=worker,
                                        args=(m, m % 4 == 0))
                       for m in range(16)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
        for m, (status, body) in results.items():
            if m % 4 == 0:
                assert status == 500, (m, body)
            else:
                assert status == 201, (m, body)
        stored = list(st.events.find(app.id))
        assert sorted(e.properties["m"] for e in stored) == \
            sorted(m for m in range(16) if m % 4 != 0)


class TestBackpressure:
    def test_queue_full_returns_429_and_recovers(self):
        class SlowStore(MemoryEventStore):
            def insert_batch(self, events, app_id, channel_id=None):
                time.sleep(0.1)
                return super().insert_batch(events, app_id, channel_id)

            def insert(self, event, app_id, channel_id=None):
                time.sleep(0.1)
                return super().insert(event, app_id, channel_id)

        st = _mem_storage(SlowStore())
        app, key = _setup_app(st)
        port = free_port()
        server = EventServer(storage=st, host="127.0.0.1", port=port,
                             ingest_batching=True, ingest_queue_depth=2)
        outcomes = []

        def worker(m):
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            status, body, headers = _post(
                conn, f"/events.json?accessKey={key}",
                {"event": "view", "entityType": "user", "entityId": str(m),
                 "targetEntityType": "item", "targetEntityId": "x"})
            outcomes.append((status, headers.get("Retry-After")))
            conn.close()

        with ServerThread(server):
            threads = [threading.Thread(target=worker, args=(m,))
                       for m in range(20)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            statuses = [s for s, _ in outcomes]
            assert set(statuses) <= {201, 429}
            assert 429 in statuses, statuses
            for status, retry_after in outcomes:
                if status == 429:
                    assert retry_after is not None
                    assert float(retry_after) >= 1
            # recovery: once the queue drains, single POSTs succeed
            deadline = time.time() + 10
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            while True:
                status, body, _ = _post(
                    conn, f"/events.json?accessKey={key}",
                    {"event": "view", "entityType": "user",
                     "entityId": "recovered", "targetEntityType": "item",
                     "targetEntityId": "x"})
                if status == 201:
                    break
                assert time.time() < deadline, "never recovered from 429"
                time.sleep(0.2)
            conn.close()
        # only acked events were stored (shed requests wrote nothing)
        stored = len(list(st.events.find(app.id)))
        assert stored == [s for s, _ in outcomes].count(201) + 1


class TestCoalescerUnit:
    def test_groups_by_app_channel_and_coalesces(self):
        commits = []

        class RecordingStore(MemoryEventStore):
            def insert_batch(self, events, app_id, channel_id=None):
                commits.append((app_id, channel_id, len(events)))
                time.sleep(0.01)  # service time → arrivals coalesce
                return super().insert_batch(events, app_id, channel_id)

        store = RecordingStore()

        async def main():
            c = WriteCoalescer(store)
            evs = [Event(event="view", entity_type="user",
                         entity_id=str(i), target_entity_type="item",
                         target_entity_id="x", properties={"i": i})
                   for i in range(40)]
            ids = await asyncio.gather(*[
                c.submit(e, 1, None if i % 2 else 7)
                for i, e in enumerate(evs)])
            assert len(set(ids)) == 40
            # far fewer commits than events, grouped per namespace
            assert c.batches < c.submitted
            assert all(app == 1 for app, _, _ in commits)
            await c.aclose()
            return c

        c = asyncio.run(main())
        assert len(list(store.find(1, None))) == 20
        assert len(list(store.find(1, 7))) == 20
        assert c.submitted == 40

    def test_submit_overload_raises(self):
        class SlowStore(MemoryEventStore):
            def insert_batch(self, events, app_id, channel_id=None):
                time.sleep(0.05)
                return super().insert_batch(events, app_id, channel_id)

        async def main():
            c = WriteCoalescer(SlowStore(), max_queue=1)
            ev = Event(event="view", entity_type="user", entity_id="u",
                       target_entity_type="item", target_entity_id="x")
            results = await asyncio.gather(
                *[c.submit(ev.with_id(), 1) for _ in range(6)],
                return_exceptions=True)
            overloads = [r for r in results if isinstance(r, IngestOverload)]
            oks = [r for r in results if isinstance(r, str)]
            assert overloads and oks
            assert len(overloads) + len(oks) == 6
            assert c.rejected == len(overloads)
            await c.aclose()

        asyncio.run(main())

    def test_reusable_after_aclose(self):
        store = MemoryEventStore()

        async def main():
            c = WriteCoalescer(store)
            ev = Event(event="view", entity_type="user", entity_id="u",
                       target_entity_type="item", target_entity_id="x")
            await c.submit(ev.with_id(), 1)
            await c.aclose()
            # a server that stops and serves again keeps working
            await c.submit(ev.with_id(), 1)
            await c.aclose()

        asyncio.run(main())
        assert len(list(store.find(1))) == 2


class TestBatchEndpointSingleCommit:
    def _counting_storage(self):
        class CountingStore(MemoryEventStore):
            batch_calls = 0
            insert_calls = 0

            def insert(self, event, app_id, channel_id=None):
                CountingStore.insert_calls += 1
                return super().insert(event, app_id, channel_id)

            def insert_batch(self, events, app_id, channel_id=None):
                CountingStore.batch_calls += 1
                return super().insert_batch(events, app_id, channel_id)

        store = CountingStore()
        return _mem_storage(store), store

    def test_all_valid_batch_is_one_commit(self):
        st, store = self._counting_storage()
        app, key = _setup_app(st)
        port = free_port()
        with ServerThread(EventServer(storage=st, host="127.0.0.1",
                                      port=port)):
            base = f"http://127.0.0.1:{port}"
            batch = [{"event": "view", "entityType": "user",
                      "entityId": str(m), "targetEntityType": "item",
                      "targetEntityId": "x"} for m in range(10)]
            code, body = http_req("POST",
                              f"{base}/batch/events.json?accessKey={key}",
                              batch)
            assert code == 200
            assert [it["status"] for it in body] == [201] * 10
            assert len({it["eventId"] for it in body}) == 10
        assert type(store).batch_calls == 1
        assert type(store).insert_calls == 0
        assert len(list(st.events.find(app.id))) == 10

    def test_mixed_validity_falls_back_per_event(self):
        st, store = self._counting_storage()
        app, key = _setup_app(st)
        port = free_port()
        with ServerThread(EventServer(storage=st, host="127.0.0.1",
                                      port=port)):
            base = f"http://127.0.0.1:{port}"
            good = {"event": "view", "entityType": "user", "entityId": "u",
                    "targetEntityType": "item", "targetEntityId": "x"}
            code, body = http_req("POST",
                              f"{base}/batch/events.json?accessKey={key}",
                              [good, {"event": ""}, good])
            assert code == 200
            assert [it["status"] for it in body] == [201, 400, 201]
        assert type(store).batch_calls == 0
        assert type(store).insert_calls == 2
        assert len(list(st.events.find(app.id))) == 2


class TestAuthCache:
    def test_hit_counter_and_epoch_invalidation(self):
        from predictionio_tpu.utils.metrics import REGISTRY

        st = _mem_storage()
        app, key = _setup_app(st)
        port = free_port()
        counter = REGISTRY.counter("pio_authcache_total",
                                   "Auth cache lookups", ("result",))
        hits0 = counter._values.get(("hit",), 0)
        with ServerThread(EventServer(storage=st, host="127.0.0.1",
                                      port=port)):
            base = f"http://127.0.0.1:{port}"
            ev = {"event": "view", "entityType": "user", "entityId": "u",
                  "targetEntityType": "item", "targetEntityId": "x"}
            url = f"{base}/events.json?accessKey={key}"
            assert http_req("POST", url, ev)[0] == 201  # miss, fills cache
            assert http_req("POST", url, ev)[0] == 201  # hit
            assert counter._values.get(("hit",), 0) > hits0
            # in-process revocation is effective immediately (epoch bump)
            st.meta.delete_access_key(key)
            assert http_req("POST", url, ev)[0] == 401
            # a channel created after a cached negative becomes visible
            key2 = st.meta.create_access_key(app.id).key
            url2 = f"{base}/events.json?accessKey={key2}&channel=late"
            assert http_req("POST", url2, ev)[0] == 400  # negative, cached
            ch = st.meta.create_channel(app.id, "late")
            st.events.init_channel(app.id, ch.id)
            assert http_req("POST", url2, ev)[0] == 201

    def test_cache_disabled_with_zero_ttl(self):
        st = _mem_storage()
        app, key = _setup_app(st)
        port = free_port()
        with ServerThread(EventServer(storage=st, host="127.0.0.1",
                                      port=port, auth_cache_ttl=0)):
            base = f"http://127.0.0.1:{port}"
            ev = {"event": "view", "entityType": "user", "entityId": "u",
                  "targetEntityType": "item", "targetEntityId": "x"}
            assert http_req("POST", f"{base}/events.json?accessKey={key}",
                        ev)[0] == 201


class TestWebhookThroughCoalescer:
    def test_webhook_post_group_commits(self):
        st = _mem_storage()
        app, key = _setup_app(st)
        port = free_port()
        server = EventServer(storage=st, host="127.0.0.1", port=port,
                             ingest_batching=True)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            payload = {"type": "track", "userId": "u42", "event": "signup",
                       "properties": {"plan": "pro"}}
            code, _ = http_req("POST",
                           f"{base}/webhooks/segmentio.json?accessKey={key}",
                           payload)
            assert code == 201
        assert server._ingest.submitted == 1
        evs = list(st.events.find(app.id, event_names=["signup"]))
        assert len(evs) == 1 and evs[0].entity_id == "u42"
