"""Self-attentive sequential recommendation (SASRec-style next-item model).

No counterpart exists in the reference (it has no sequence models —
SURVEY.md §5); this is the framework's long-context model family,
extending the template set the same way the two-tower target does
(BASELINE config 5). Architecture follows the public SASRec formulation
(Kang & McAuley 2018): item + position embeddings, a stack of causal
self-attention + pointwise-FFN blocks with pre-layernorm and residuals,
next-item scoring by inner product with the (tied) item embedding table.

TPU mapping:

- the whole training run is ONE jitted program: `lax.scan` over steps of
  `lax.scan` over a fixed epoch of batches — no per-step dispatch;
- attention is pluggable: local (single chip) or **ring attention** over
  a mesh sequence axis (`predictionio_tpu.parallel.ring_attention`) for
  histories too long for one chip's HBM — the same exact math;
- embedding/softmax matmuls hit the MXU in bf16-friendly shapes (dims
  padded to multiples of 128 upstream by the caller where it matters).

Padding convention: item id 0 is PAD; real items are 1..n_items.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np


@dataclass
class SeqRecParams:
    """num_blocks/num_heads/hidden per SASRec defaults; seq_len is the
    model's fixed context window (sequences are left-truncated/padded)."""

    hidden: int = 64
    num_blocks: int = 2
    num_heads: int = 2
    seq_len: int = 64
    # the model is deterministic (no dropout): serving parity and exact
    # ring-vs-local equivalence matter more here than SASRec's 0.2 dropout
    lr: float = 1e-3
    epochs: int = 20
    batch_size: int = 128
    l2: float = 0.0
    seed: int = 7
    # mid-train checkpoint/resume (SURVEY.md §5): save params +
    # optimizer state every N epochs; a restarted train with the same
    # dir resumes from the newest checkpoint and (batches are fixed per
    # seed) produces the same final model as an uninterrupted run. None
    # disables. The iteration loop then runs in blocks of
    # ``checkpoint_every`` epochs (each block one compiled program).
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 1


def init_params(n_items: int, p: SeqRecParams) -> Dict:
    """Parameter pytree. Vocabulary row 0 is PAD (zeroed, masked out)."""
    rng = np.random.default_rng(p.seed)
    d, V = p.hidden, n_items + 1

    def dense(shape, scale=None):
        scale = scale if scale is not None else 1.0 / np.sqrt(shape[0])
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    item_emb = dense((V, d), 0.02)
    item_emb[0] = 0.0
    params = {
        "item_emb": item_emb,
        "pos_emb": dense((p.seq_len, d), 0.02),
        "blocks": [],
        "ln_f": {"g": np.ones(d, np.float32), "b": np.zeros(d, np.float32)},
    }
    for _ in range(p.num_blocks):
        params["blocks"].append({
            "ln1": {"g": np.ones(d, np.float32), "b": np.zeros(d, np.float32)},
            "wq": dense((d, d)), "wk": dense((d, d)), "wv": dense((d, d)),
            "wo": dense((d, d)),
            "ln2": {"g": np.ones(d, np.float32), "b": np.zeros(d, np.float32)},
            "w1": dense((d, 4 * d)), "b1": np.zeros(4 * d, np.float32),
            "w2": dense((4 * d, d)), "b2": np.zeros(d, np.float32),
        })
    return params


def _ln(x, g, b, eps=1e-6):
    import jax.numpy as jnp

    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * g + b


def forward(params: Dict, seqs, p: SeqRecParams, mesh=None,
            seq_axis: str = "data"):
    """[B, S] int item ids (0=pad) → [B, S, d] contextual states.

    ``mesh`` routes attention through ring attention over ``seq_axis``
    (S must divide by the axis size); None = local attention.
    """
    import jax
    import jax.numpy as jnp

    from predictionio_tpu.parallel.ring_attention import (
        attention_reference,
        ring_attention,
    )

    B, S = seqs.shape
    d, H = p.hidden, p.num_heads
    Dh = d // H
    k_mask = seqs > 0            # [B, S]: pad positions never serve as keys
    mask = k_mask[..., None]     # [B, S, 1]

    x = params["item_emb"][seqs] * np.sqrt(d) + params["pos_emb"][None, :S]
    x = x * mask

    for blk in params["blocks"]:
        h = _ln(x, blk["ln1"]["g"], blk["ln1"]["b"])
        q = (h @ blk["wq"]).reshape(B, S, H, Dh)
        k = (h @ blk["wk"]).reshape(B, S, H, Dh)
        v = (h @ blk["wv"]).reshape(B, S, H, Dh)
        if mesh is not None:
            att = ring_attention(q, k, v, mesh=mesh, axis=seq_axis,
                                 causal=True, k_mask=k_mask)
        else:
            att = attention_reference(q, k, v, causal=True, k_mask=k_mask)
        x = x + att.reshape(B, S, d) @ blk["wo"]
        h = _ln(x, blk["ln2"]["g"], blk["ln2"]["b"])
        x = x + jax.nn.relu(h @ blk["w1"] + blk["b1"]) @ blk["w2"] + blk["b2"]
        x = x * mask
    return _ln(x, params["ln_f"]["g"], params["ln_f"]["b"]) * mask


def _loss(params, seqs, targets, p: SeqRecParams, mesh=None, l2=None):
    """Mean masked cross-entropy of next-item prediction.

    targets[b, t] = seqs[b, t+1]-style shifted ids, 0 where padded.
    """
    import jax
    import jax.numpy as jnp

    states = forward(params, seqs, p, mesh=mesh)  # [B, S, d]
    logits = states @ params["item_emb"].T        # [B, S, V] tied weights
    logp = jax.nn.log_softmax(logits, axis=-1)
    tgt_logp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    m = (targets > 0).astype(jnp.float32)
    loss = -(tgt_logp * m).sum() / jnp.maximum(m.sum(), 1.0)
    # l2 (when given) is a TRACED scalar — the compiled trainer passes
    # it so an eval grid over regularization shares one executable;
    # p.l2 is the Python-static path for direct callers
    reg = p.l2 if l2 is None else l2
    if l2 is not None or p.l2:
        loss = loss + reg * sum(
            jnp.sum(w ** 2) for w in jax.tree.leaves(params))
    return loss


def make_training_batches(sequences, p: SeqRecParams, seed: int = 0
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side prep: list of per-user item-id lists → fixed-shape
    (inputs [N, S], targets [N, S]) with left-padding, shuffled and
    padded to a whole number of batches."""
    S = p.seq_len
    xs, ys = [], []
    for seq in sequences:
        seq = [i for i in seq if i > 0]
        if len(seq) < 2:
            continue
        seq = seq[-(S + 1):]
        inp, tgt = seq[:-1], seq[1:]
        pad = S - len(inp)
        xs.append(np.pad(inp, (pad, 0)))
        ys.append(np.pad(tgt, (pad, 0)))
    if not xs:
        raise ValueError("no trainable sequences (all shorter than 2)")
    X = np.asarray(xs, np.int32)
    Y = np.asarray(ys, np.int32)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(X))
    X, Y = X[order], Y[order]
    bs = min(p.batch_size, len(X))
    n_batches = -(-len(X) // bs)
    padn = n_batches * bs - len(X)
    if padn:  # repeat leading rows: keeps shapes static, loss still masked
        X = np.concatenate([X, X[:padn]])
        Y = np.concatenate([Y, Y[:padn]])
    return X.reshape(n_batches, bs, S), Y.reshape(n_batches, bs, S)


def _make_tx():
    """The optimizer, constructed ONE way everywhere so checkpointed
    state and the compiled trainer always agree on structure.
    learning_rate is a placeholder: callers set
    ``opt_state.hyperparams["learning_rate"]`` per candidate."""
    import optax

    return optax.inject_hyperparams(optax.adam)(learning_rate=1e-3)


@functools.lru_cache(maxsize=8)
def _train_compiled(hidden: int, num_blocks: int, num_heads: int,
                    seq_len: int, epochs: int, use_l2: bool, mesh=None):
    """Jitted trainer keyed on GEOMETRY (array shapes are traced):
    ``lr`` rides inside the optimizer state (optax.inject_hyperparams)
    and ``l2`` is a traced scalar, so a `pio eval` grid over either
    shares one executable. ``use_l2`` is static: the common l2=0 path
    must not pay the full parameter-norm reduction for a multiply by a
    traced zero. ``mesh`` routes attention through the
    sequence-parallel ring path. Signature:
    ``train(params, opt_state, X, Y, l2)``."""
    import jax

    import optax

    p = SeqRecParams(hidden=hidden, num_blocks=num_blocks,
                     num_heads=num_heads, seq_len=seq_len, l2=0.0)
    tx = _make_tx()

    def train(params, opt_state, X, Y, l2):
        def batch_step(carry, xy):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(_loss)(
                params, xy[0], xy[1], p, mesh,
                l2 if use_l2 else None)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        def epoch(carry, _):
            carry, losses = jax.lax.scan(batch_step, carry, (X, Y))
            return carry, losses.mean()

        (params, opt_state), losses = jax.lax.scan(
            epoch, (params, opt_state), None, length=epochs)
        return params, opt_state, losses

    return jax.jit(train)


def seq_rec_train(sequences, n_items: int, p: SeqRecParams, mesh=None,
                  seq_axis: str = "data") -> Tuple[Dict, np.ndarray]:
    """Train on per-user item-id sequences; returns (params, loss/epoch).

    The full run is one compiled program (scan over epochs of scan over
    batches) — zero host round-trips after dispatch. ``mesh`` shards
    attention over ``seq_axis`` via ring attention (requires
    ``seq_len %% axis size == 0``); incompatible meshes fall back to
    local attention rather than failing the train.
    """
    import jax
    import jax.numpy as jnp

    import optax

    if mesh is not None and (
            seq_axis not in mesh.axis_names
            or p.seq_len % mesh.shape[seq_axis]):
        mesh = None
    X, Y = make_training_batches(sequences, p, seed=p.seed)
    params = jax.tree.map(jnp.asarray, init_params(n_items, p))

    def compiled(n_epochs: int):
        return _train_compiled(p.hidden, p.num_blocks, p.num_heads,
                               p.seq_len, int(n_epochs), bool(p.l2), mesh)

    opt_state = _make_tx().init(params)
    # the candidate's lr enters THROUGH the optimizer state (a traced
    # leaf); l2 is a traced argument — neither recompiles the program
    opt_state.hyperparams["learning_rate"] = jnp.float32(p.lr)
    l2 = jnp.float32(p.l2)

    if not p.checkpoint_dir:
        params, _, losses = compiled(p.epochs)(params, opt_state, X, Y, l2)
        return params, np.asarray(losses)

    # checkpointed path: epoch blocks between saves; params + optimizer
    # state fully determine the remainder (batches are fixed per seed),
    # so resume reproduces the uninterrupted run
    from predictionio_tpu.utils.checkpoint import (CheckpointGeometryError,
                                                   TrainCheckpointer)

    ckpt = TrainCheckpointer(p.checkpoint_dir)
    start = 0
    if ckpt.latest_step() is not None:
        template = {"params": jax.tree.map(np.asarray, params),
                    "opt_state": jax.tree.map(np.asarray, opt_state)}
        try:
            # newest→oldest walk: a crash-truncated newest save falls
            # back to the previous good step instead of a full retrain
            state, latest = ckpt.restore_latest_compatible(template)
            params = jax.tree.map(jnp.asarray, state["params"])
            opt_state = jax.tree.map(jnp.asarray, state["opt_state"])
            # THIS run's lr wins over the checkpointed one (annealing
            # restarts must not silently keep the old rate)
            opt_state.hyperparams["learning_rate"] = jnp.float32(p.lr)
            start = min(int(latest), p.epochs)
        except CheckpointGeometryError:
            # CONFIRMED stale (different geometry) → fresh start; WIPE
            # the dir, else the fresh run's lower step numbers stay
            # shadowed by the stale latest_step and every future resume
            # restores the bad checkpoint again. Transient read errors
            # propagate — wiping on those destroys valid checkpoints.
            import warnings

            warnings.warn(
                "seq_rec checkpoints are stale (geometry/format change) — wiped; training restarts from scratch",
                RuntimeWarning)
            ckpt.clear()
    loss_parts = []
    epoch = start
    while epoch < p.epochs:
        n = min(max(1, p.checkpoint_every), p.epochs - epoch)
        params, opt_state, losses = compiled(n)(params, opt_state, X, Y, l2)
        loss_parts.append(np.asarray(losses))
        epoch += n
        ckpt.save(epoch, {"params": jax.tree.map(np.asarray, params),
                          "opt_state": jax.tree.map(np.asarray, opt_state)})
    ckpt.close()
    # losses cover only the epochs run in THIS process (a resumed run
    # reports the remainder)
    return params, (np.concatenate(loss_parts) if loss_parts
                    else np.zeros(0, np.float32))


@functools.lru_cache(maxsize=8)
def _scores_compiled(hidden: int, num_blocks: int, num_heads: int,
                     seq_len: int):
    """Jitted serving path (the p50-critical call): one dispatch per
    query batch instead of dozens of eager ops."""
    import jax

    p = SeqRecParams(hidden=hidden, num_blocks=num_blocks,
                     num_heads=num_heads, seq_len=seq_len)

    def score(params, x):
        states = forward(params, x, p)          # [B, S, d]
        return states[:, -1] @ params["item_emb"].T  # [B, V]

    return jax.jit(score)


def seq_rec_scores(params: Dict, history, p: SeqRecParams) -> np.ndarray:
    """Scores over the full vocabulary for the NEXT item after ``history``
    (a list of item ids); [V] numpy array, PAD row = -inf."""
    S = p.seq_len
    seq = [i for i in history if i > 0][-S:]
    x = np.zeros((1, S), np.int32)
    if seq:
        x[0, S - len(seq):] = seq
    score = _scores_compiled(p.hidden, p.num_blocks, p.num_heads, p.seq_len)
    logits = np.array(score(params, x)[0])  # writable host copy
    logits[0] = -np.inf
    return logits
