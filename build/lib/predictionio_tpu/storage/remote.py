"""Network storage backends: S3 / HDFS model stores, SQL servers.

The reference shipped six network backends (HBase, JDBC, Elasticsearch,
HDFS, LocalFS, S3 — SURVEY.md §2a). These register their TYPE names
with factories that bind lazily: each store is a full implementation
that connects when its driver (boto3 / pyarrow+libhdfs / psycopg2 /
pymysql) is present and raises :class:`StorageClientError` with install
instructions when not. The PGSQL/MYSQL types run the shared SQL store
implementations (events, meta, model blobs) on their engine's dialect —
see :mod:`predictionio_tpu.storage.sqldialect`.

Config (same env scheme as every backend, reference pio-env.sh names):

    PIO_STORAGE_SOURCES_<S>_TYPE=S3|HDFS|PGSQL|MYSQL
    PIO_STORAGE_SOURCES_<S>_BUCKET_NAME / _BASE_PATH   (S3)
    PIO_STORAGE_SOURCES_<S>_HOSTS / _PORTS / _PATH     (HDFS)
    PIO_STORAGE_SOURCES_<S>_URL / _USERNAME / _PASSWORD (SQL)
"""

from __future__ import annotations

import os
import re
from typing import List, Optional

from predictionio_tpu.storage.models import ModelStore


class StorageClientError(RuntimeError):
    """Backend selected but unusable (missing driver / bad config) —
    reference: StorageClientException."""


def _source_env(key: str, default: str = "") -> str:
    # any source name may carry the setting; first match wins. Source
    # names are discovered from their (mandatory) _TYPE key, so names
    # with underscores (MY_PG) resolve too — and because the name is
    # matched as a whole, *_BASE_PATH can never shadow a lookup of PATH.
    names = [m.group(1) for k in os.environ
             if (m := re.match(r"^PIO_STORAGE_SOURCES_(.+)_TYPE$", k))]
    for name in names:
        v = os.environ.get(f"PIO_STORAGE_SOURCES_{name}_{key}")
        if v is not None:
            return v
    return default


class S3ModelStore(ModelStore):
    """Model blobs on S3 (reference: [U] storage/s3/ S3Models).

    ``props`` = the backing source's settings (StorageConfig
    ``source_properties``); direct construction may pass bucket/base
    explicitly or fall back to a single-source env scan.
    """

    def __init__(self, bucket: Optional[str] = None,
                 base_path: Optional[str] = None,
                 props: Optional[dict] = None) -> None:
        try:
            import boto3  # type: ignore[import-not-found]
        except ImportError as e:
            raise StorageClientError(
                "MODELDATA type S3 requires the boto3 driver "
                "(pip install boto3)") from e
        props = props or {}
        self.bucket = (bucket or props.get("BUCKET_NAME")
                       or _source_env("BUCKET_NAME"))
        if not self.bucket:
            raise StorageClientError(
                "S3 model store needs PIO_STORAGE_SOURCES_<S>_BUCKET_NAME")
        self.base = (base_path or props.get("BASE_PATH")
                     or _source_env("BASE_PATH", "pio_models")).strip("/")
        self._s3 = boto3.client("s3")

    def _key(self, instance_id: str) -> str:
        return f"{self.base}/{instance_id}.bin"

    def put(self, instance_id: str, blob: bytes) -> None:
        self._s3.put_object(Bucket=self.bucket, Key=self._key(instance_id),
                            Body=blob)

    def get(self, instance_id: str) -> Optional[bytes]:
        try:
            r = self._s3.get_object(Bucket=self.bucket,
                                    Key=self._key(instance_id))
        except self._s3.exceptions.NoSuchKey:
            return None
        return r["Body"].read()

    def delete(self, instance_id: str) -> bool:
        self._s3.delete_object(Bucket=self.bucket, Key=self._key(instance_id))
        return True

    def list_ids(self) -> List[str]:
        out, token = [], None
        while True:
            kw = {"Bucket": self.bucket, "Prefix": self.base + "/"}
            if token:
                kw["ContinuationToken"] = token
            r = self._s3.list_objects_v2(**kw)
            out += [o["Key"][len(self.base) + 1:-4]
                    for o in r.get("Contents", ())
                    if o["Key"].endswith(".bin")]
            if not r.get("IsTruncated"):
                return out
            token = r.get("NextContinuationToken")


class HDFSModelStore(ModelStore):
    """Model blobs on HDFS via pyarrow (reference: [U] storage/hdfs/
    HDFSModels). Needs libhdfs (a Hadoop install) at runtime."""

    def __init__(self, host: Optional[str] = None, port: Optional[int] = None,
                 path: Optional[str] = None,
                 props: Optional[dict] = None) -> None:
        try:
            from pyarrow import fs
        except ImportError as e:  # pragma: no cover - pyarrow is baked in
            raise StorageClientError(
                "MODELDATA type HDFS requires pyarrow") from e
        props = props or {}
        host = host or props.get("HOSTS") or _source_env("HOSTS", "default")
        port = port if port is not None else int(
            props.get("PORTS") or _source_env("PORTS", "8020"))
        self.root = (path or props.get("PATH")
                     or _source_env("PATH", "/pio_models")).rstrip("/")
        try:
            self._fs = fs.HadoopFileSystem(host, port)
        except Exception as e:
            raise StorageClientError(
                f"cannot reach HDFS at {host}:{port} (libhdfs present?): {e}"
            ) from e

    def _key(self, instance_id: str) -> str:
        return f"{self.root}/{instance_id}.bin"

    def put(self, instance_id: str, blob: bytes) -> None:
        from pyarrow import fs

        self._fs.create_dir(self.root, recursive=True)
        with self._fs.open_output_stream(self._key(instance_id)) as f:
            f.write(blob)

    def get(self, instance_id: str) -> Optional[bytes]:
        from pyarrow import fs

        info = self._fs.get_file_info(self._key(instance_id))
        if info.type == fs.FileType.NotFound:
            return None
        with self._fs.open_input_stream(self._key(instance_id)) as f:
            return f.read()

    def delete(self, instance_id: str) -> bool:
        from pyarrow import fs

        info = self._fs.get_file_info(self._key(instance_id))
        if info.type == fs.FileType.NotFound:
            return False
        self._fs.delete_file(self._key(instance_id))
        return True

    def list_ids(self) -> List[str]:
        from pyarrow import fs

        sel = fs.FileSelector(self.root, allow_not_found=True)
        return [i.base_name[:-4] for i in self._fs.get_file_info(sel)
                if i.base_name.endswith(".bin")]


def _sql_dialect(type_name: str, cfg, repo: str):
    """Dialect for a SQL-server source; raises StorageClientError with
    install instructions when the DB-API driver is absent."""
    from predictionio_tpu.storage.sqldialect import dialect_for

    return dialect_for(type_name, cfg.source_properties(repo), "")


def register_all() -> None:
    from predictionio_tpu.storage import registry as reg
    from predictionio_tpu.data.events import SQLEventStore
    from predictionio_tpu.storage.meta import MetaStore
    from predictionio_tpu.storage.models import SQLModelStore

    reg.register_model_backend(
        "S3", lambda cfg: S3ModelStore(
            props=cfg.source_properties("MODELDATA")))
    reg.register_model_backend(
        "HDFS", lambda cfg: HDFSModelStore(
            props=cfg.source_properties("MODELDATA")))
    # SQL-server backends (reference: [U] storage/jdbc/ — every repo type
    # on PostgreSQL/MySQL). The shared SQL store implementations run on
    # the engine's dialect; the reference's pio-env idiom points all
    # three repositories at the same SQL source.
    for t in ("PGSQL", "MYSQL"):
        reg.register_event_backend(
            t, lambda cfg, _t=t: SQLEventStore(
                _sql_dialect(_t, cfg, "EVENTDATA")))
        reg.register_meta_backend(
            t, lambda cfg, _t=t: MetaStore(
                dialect=_sql_dialect(_t, cfg, "METADATA")))
        reg.register_model_backend(
            t, lambda cfg, _t=t: SQLModelStore(
                _sql_dialect(_t, cfg, "MODELDATA")))
