"""Profile / A-B the ALS training program on the real chip.

Modes:

- default: run the ML-20M-shape train (bench.py protocol), print phase
  timings, and capture a JAX profiler trace of a short warm run —
  the artifact behind docs/perf/als_trace_analysis.md.
- ``--ab``: run the optimization matrix and print one line per
  configuration — the decision data for flipping defaults:
    * baseline (materialized solve pass, XLA recursion, f32 gathers)
    * PIO_PALLAS_GRAM=1 (fused gather→Gram Pallas kernel)
    * PIO_PALLAS_SOLVE=1 (VMEM-resident Pallas solve kernel)
    * in-body solves (no solve-buffer materialization)
    * bf16 gathers
- ``--opcount``: CHIP-FREE — trace the TPU train program abstractly
  and report device ops/iteration for the XLA vs fused gather→Gram
  paths (the r5 dispatch-wall metric), then assert the ≥10× collapse
  regression guard. Runs on any host; no accelerator touched.
"""

import argparse
import glob
import os
import time

import numpy as np


def _measure(prep, params, label):
    from predictionio_tpu.models import als
    from bench import V5E_PEAK_BF16, _train_flops

    als._compiled_bucketed.cache_clear()
    t0 = time.perf_counter()
    U, V = als.als_train_prepared(prep, params)
    t_cold = time.perf_counter() - t0
    warms = []
    for _ in range(2):
        t0 = time.perf_counter()
        U, V = als.als_train_prepared(prep, params)
        warms.append(time.perf_counter() - t0)
    t_warm = min(warms)
    assert np.isfinite(U).all() and np.isfinite(V).all()
    flops = _train_flops(prep, params.rank, params.iterations)
    thr = prep.nnz * params.iterations / t_warm / 1e6
    print(f"{label:34} cold={t_cold:7.1f}s warm={t_warm:6.2f}s "
          f"thr={thr:7.1f}M/s mfu_wall={flops / t_warm / V5E_PEAK_BF16:.4f}",
          flush=True)
    return t_warm


def _measure_device(prep, params, label, repeats=3):
    """Device-side warm time: run the compiled train and fetch ONE
    scalar (U.sum()+V.sum()) instead of the 42 MB factor output — the
    tunneled chip executes lazily and moves d2h bytes at ~20 MB/s, so
    the big fetch adds ~4.7 s of pure image artifact and its variance
    swamps 20% device-level wins."""
    import jax
    import jax.numpy as jnp

    from predictionio_tpu import ops
    from predictionio_tpu.models import als

    u_bufs, i_bufs = prep.device_buffers()
    train = als._compiled_bucketed(
        prep.u_side.geometry, prep.i_side.geometry,
        prep.n_users, prep.n_items, params.rank, params.iterations,
        bool(params.implicit), bool(params.weighted_reg),
        None, bool(params.bf16_gather), als._gram_precision(),
        ops.resolve_gram_mode(jax.default_backend()))
    V0 = jnp.asarray(
        als.init_factors(prep.n_items, params.rank, params.seed)[
            prep.i_side.perm])
    reg = np.float32(params.reg)
    alpha = np.float32(params.alpha)

    def once():
        t0 = time.perf_counter()
        U, V = train(u_bufs, i_bufs, V0, reg, alpha)
        s = float(jnp.sum(U) + jnp.sum(V))   # 4-byte fetch forces exec
        return time.perf_counter() - t0, s

    t_cold, s = once()
    assert np.isfinite(s), label
    t_dev = min(once()[0] for _ in range(repeats))
    thr = prep.nnz * params.iterations / t_dev / 1e6
    print(f"{label:44} cold={t_cold:7.1f}s dev={t_dev:6.2f}s "
          f"thr_dev={thr:7.1f}M/s", flush=True)
    return t_dev


def _tune(args):
    """On-device A/B of the layout/solve knobs the r5 trace flagged:
    the chunked solve pass (41 chunks x ~50 small ops each) and the
    gather slab size. Prints one line per configuration; the winner
    becomes the default."""
    from bench import synthetic_ml20m
    from predictionio_tpu.models import als
    from predictionio_tpu.models.als import ALSParams, RatingsCOO
    from predictionio_tpu.utils import compilecache

    compilecache.enable()
    users, items, ratings = synthetic_ml20m(args.nnz)
    coo = RatingsCOO(users, items, ratings, 138_493, 26_744)
    params = ALSParams(rank=args.rank, iterations=args.iters, reg=0.05,
                       seed=1)

    chunks = [int(c) for c in args.chunks.split(",")] if args.chunks else []
    slabs = [int(s) for s in args.slabs.split(",")] if args.slabs else []
    entry_chunk, entry_slab = als._SOLVE_CHUNK, als._SLAB_ELEMS
    preps = {}

    def prep_for(slab):
        if slab not in preps:
            als._SLAB_ELEMS = slab
            preps[slab] = als.als_prepare(coo)
        return preps[slab]

    base_slab = als._SLAB_ELEMS
    for chunk in chunks or [als._SOLVE_CHUNK]:
        for slab in slabs or [base_slab]:
            als._SOLVE_CHUNK = chunk
            als._compiled_bucketed.cache_clear()
            try:
                _measure_device(prep_for(slab), params,
                                f"chunk={chunk} slab={slab}")
            except Exception as exc:  # OOM etc: report, keep going
                print(f"chunk={chunk} slab={slab}: {type(exc).__name__}: "
                      f"{str(exc)[:120]}", flush=True)
    # restore the values in effect at entry (not re-spelled literals,
    # which would silently revert a future default change — r5 review)
    als._SOLVE_CHUNK, als._SLAB_ELEMS = entry_chunk, entry_slab


def _sharded_ckpt_overhead(args):
    """Per-boundary cost of block-wise checkpointing on the sharded
    path: straight fused run vs checkpoint_every=1 (one boundary per
    iteration — worst case). Runs on a virtual 8-device CPU mesh so it
    works chip-free; the number to record is (ckpt - straight)/nblocks.
    """
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")

    # the 8-device shard_map programs take minutes of XLA-CPU compile
    # on a 1-core box; persist them so repeat measurements pay once
    from predictionio_tpu.utils import compilecache

    compilecache.enable()

    from jax.sharding import Mesh

    from bench import synthetic_ml20m
    from predictionio_tpu.models.als import ALSParams, RatingsCOO
    from predictionio_tpu.models.als_sharded import (
        als_prepare_sharded, als_train_sharded_prepared)
    from predictionio_tpu.utils.checkpoint import TrainCheckpointer

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))
    n_dev = int(np.prod(mesh.devices.shape))
    users, items, ratings = synthetic_ml20m(args.nnz)
    coo = RatingsCOO(users, items, ratings, 138_493, 26_744)
    prep = als_prepare_sharded(coo, n_dev)
    p = ALSParams(rank=args.rank, iterations=args.iters, reg=0.05, seed=1)

    def run(ck=None, every=0):
        t0 = time.perf_counter()
        als_train_sharded_prepared(prep, p, mesh,
                                   checkpointer=ck, checkpoint_every=every)
        return time.perf_counter() - t0

    run()  # compile
    straight = min(run() for _ in range(2))
    with tempfile.TemporaryDirectory() as td:
        with TrainCheckpointer(os.path.join(td, "a")) as ck:
            run(ck, 1)  # compile the 1-iter block program
        times = []
        for sub in ("b", "c"):
            with TrainCheckpointer(os.path.join(td, sub)) as ck:
                times.append(run(ck, 1))
    ckpt = min(times)
    per = (ckpt - straight) / p.iterations * 1000
    print(f"sharded nnz={coo.nnz} rank={p.rank} iters={p.iterations} "
          f"mesh={n_dev}dev", flush=True)
    print(f"straight={straight:.2f}s blockwise(every=1)={ckpt:.2f}s "
          f"per_boundary_overhead={per:.1f}ms", flush=True)


def _opcount(args):
    """Chip-free dispatch-count report + regression guard.

    Traces the TPU train program abstractly (ShapeDtypeStructs, no
    device buffers) on the CPU host and counts device ops/iteration
    for the XLA path vs the fused gather→Gram path. Exits non-zero if
    the collapse ratio falls below the ISSUE-17 acceptance floor of
    10× — this is the device-ops-count regression guard, cheap enough
    for CI.
    """
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")

    from bench import synthetic_ml20m
    from predictionio_tpu.models.als import ALSParams, RatingsCOO, als_prepare
    from predictionio_tpu.utils import opcount

    users, items, ratings = synthetic_ml20m(args.nnz)
    coo = RatingsCOO(users, items, ratings, 138_493, 26_744)
    prep = als_prepare(coo)
    params = ALSParams(rank=args.rank, iterations=args.iters, reg=0.05,
                       seed=1)
    rep = opcount.als_dispatch_report(prep, params)
    print(f"nnz={coo.nnz} rank={params.rank} "
          f"geometry: u={[(b.C, b.nb) for b in prep.u_side.buckets]} "
          f"i={[(b.C, b.nb) for b in prep.i_side.buckets]}", flush=True)
    print(f"device_ops_per_iter_xla   = {rep['device_ops_per_iter_xla']}",
          flush=True)
    print(f"device_ops_per_iter_fused = {rep['device_ops_per_iter']}",
          flush=True)
    print(f"dispatch_collapse_ratio   = "
          f"{rep['dispatch_collapse_ratio']:.1f}x", flush=True)
    if rep["dispatch_collapse_ratio"] < 10:
        print("FAIL: dispatch collapse below the 10x acceptance floor",
              flush=True)
        sys.exit(1)
    print("OK: collapse >= 10x", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nnz", type=int, default=None,
                    help="ratings count (default 20M; 400k under "
                         "--sharded-ckpt, which runs on CPU)")
    ap.add_argument("--rank", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--ab", action="store_true",
                    help="run the optimization A/B matrix")
    ap.add_argument("--trace-dir", default="/tmp/als_trace")
    ap.add_argument("--trace-iters", type=int, default=2)
    ap.add_argument("--tune", action="store_true",
                    help="on-device A/B of solve-chunk / slab-size "
                         "knobs (device-side timing, scalar fetch)")
    ap.add_argument("--chunks", default="",
                    help="comma list of PIO_ALS_SOLVE_CHUNK values "
                         "for --tune (default: current)")
    ap.add_argument("--slabs", default="",
                    help="comma list of PIO_ALS_SLAB_ELEMS values "
                         "for --tune (default: current)")
    ap.add_argument("--sharded-ckpt", action="store_true",
                    help="measure the per-boundary overhead of "
                         "block-wise checkpointing on the sharded "
                         "trainer (8-device CPU mesh)")
    ap.add_argument("--opcount", action="store_true",
                    help="chip-free device-ops/iter report (XLA vs "
                         "fused gather-Gram) + >=10x collapse guard")
    args = ap.parse_args()

    if args.sharded_ckpt:
        if args.nnz is None:
            args.nnz = 400_000  # CPU-mesh measurement, not TPU scale
        _sharded_ckpt_overhead(args)
        return
    if args.opcount:
        if args.nnz is None:
            args.nnz = 500_000  # abstract trace: geometry, not scale
        _opcount(args)
        return
    if args.nnz is None:
        args.nnz = 20_000_000

    # every mode below is a CHIP measurement: abort (don't mislabel)
    # if the backend silently fell back to CPU (r5 review)
    from profile_common import resolve_platform

    resolve_platform("")

    if args.tune:
        _tune(args)
        return

    from bench import synthetic_ml20m
    from predictionio_tpu.models import als
    from predictionio_tpu.models.als import (ALSParams, RatingsCOO,
                                             als_prepare,
                                             als_train_prepared)
    from predictionio_tpu.utils import compilecache

    compilecache.enable()

    users, items, ratings = synthetic_ml20m(args.nnz)
    coo = RatingsCOO(users, items, ratings, 138_493, 26_744)
    t0 = time.perf_counter()
    prep = als_prepare(coo)
    print(f"prepare_sec={time.perf_counter() - t0:.3f}", flush=True)
    for side, nm in ((prep.u_side, "u"), (prep.i_side, "i")):
        print(f"  {nm}: dense nb={side.dense.nb if side.dense else 0} "
              f"buckets={[(b.C, b.nb) for b in side.buckets]}", flush=True)

    params = ALSParams(rank=args.rank, iterations=args.iters, reg=0.05,
                       seed=1)

    if args.ab:
        os.environ["PIO_PALLAS_GRAM"] = "0"
        _measure(prep, params, "baseline (materialized, XLA solve)")
        os.environ["PIO_PALLAS_GRAM"] = "1"
        _measure(prep, params, "fused gather-Gram (pallas)")
        os.environ["PIO_PALLAS_GRAM"] = "0"
        os.environ["PIO_PALLAS_SOLVE"] = "1"
        _measure(prep, params, "pallas VMEM solve")
        del os.environ["PIO_PALLAS_SOLVE"]
        del os.environ["PIO_PALLAS_GRAM"]
        saved = als._SOLVE_BUF_MB
        als._SOLVE_BUF_MB = 0
        _measure(prep, params, "in-body solves (no solve buffer)")
        os.environ["PIO_PALLAS_SOLVE"] = "1"
        _measure(prep, params, "in-body + pallas solve")
        del os.environ["PIO_PALLAS_SOLVE"]
        als._SOLVE_BUF_MB = saved
        p16 = ALSParams(rank=args.rank, iterations=args.iters, reg=0.05,
                        seed=1, bf16_gather=True)
        _measure(prep, p16, "bf16 gathers")
        os.environ["PIO_PALLAS_SOLVE"] = "1"
        _measure(prep, p16, "bf16 gathers + pallas solve")
        del os.environ["PIO_PALLAS_SOLVE"]
        return

    t0 = time.perf_counter()
    U, V = als_train_prepared(prep, params)
    print(f"train_sec_incl_compile={time.perf_counter() - t0:.3f}",
          flush=True)
    _measure(prep, params, "warm")

    import jax

    tparams = ALSParams(rank=args.rank, iterations=args.trace_iters,
                        reg=0.05, seed=1)
    als_train_prepared(prep, tparams)  # compile outside the trace
    os.makedirs(args.trace_dir, exist_ok=True)
    with jax.profiler.trace(args.trace_dir):
        als_train_prepared(prep, tparams)
    print(f"trace written to {args.trace_dir}", flush=True)
    for f in glob.glob(os.path.join(args.trace_dir, "**", "*"),
                       recursive=True):
        if os.path.isfile(f):
            print("  ", f, os.path.getsize(f), flush=True)


if __name__ == "__main__":
    main()
