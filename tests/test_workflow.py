"""Train→persist→deploy round trip through the real workflow + the
recommendation template (the in-process core of the reference's
quickstart scenario; SURVEY.md §4 Tier 2)."""

import numpy as np
import pytest

from predictionio_tpu.core.workflow import prepare_deploy, run_train
from predictionio_tpu.data.event import Event

FACTORY = "predictionio_tpu.templates.recommendation.engine:engine_factory"


def seed_ratings(storage, app_name="TestApp", n_users=30, n_items=20, seed=0):
    app = storage.meta.create_app(app_name)
    storage.events.init_channel(app.id)
    rng = np.random.default_rng(seed)
    # block structure: even users like even items, odd users like odd items
    evs = []
    for u in range(n_users):
        for i in range(n_items):
            if rng.random() < 0.5:
                r = 5.0 if (u % 2) == (i % 2) else 1.0
                evs.append(Event(event="rate", entity_type="user", entity_id=str(u),
                                 target_entity_type="item", target_entity_id=str(i),
                                 properties={"rating": r}))
    # a few implicit buys
    evs.append(Event(event="buy", entity_type="user", entity_id="0",
                     target_entity_type="item", target_entity_id="0"))
    storage.events.insert_batch(evs, app.id)
    return app


VARIANT = {
    "id": "default",
    "engineFactory": FACTORY,
    "datasource": {"params": {"appName": "TestApp"}},
    "algorithms": [{"name": "als",
                    "params": {"rank": 8, "numIterations": 8, "lambda": 0.05}}],
}


class TestTrainDeploy:
    def test_round_trip(self, storage):
        seed_ratings(storage)
        instance_id = run_train(FACTORY, variant=VARIANT, storage=storage,
                                use_mesh=False)
        ei = storage.meta.get_engine_instance(instance_id)
        assert ei.status == "COMPLETED"
        assert ei.end_time is not None

        deployed = prepare_deploy(engine_factory=FACTORY, storage=storage)
        assert deployed.instance.id == instance_id
        res = deployed.query({"user": "0", "num": 5})
        assert len(res["itemScores"]) == 5
        items = [int(s["item"]) for s in res["itemScores"]]
        # user 0 (even) should prefer even items
        even = sum(1 for i in items if i % 2 == 0)
        assert even >= 4, f"expected even-item preference, got {items}"
        # scores sorted descending
        scores = [s["score"] for s in res["itemScores"]]
        assert scores == sorted(scores, reverse=True)

    def test_unknown_user_empty(self, storage):
        seed_ratings(storage)
        run_train(FACTORY, variant=VARIANT, storage=storage, use_mesh=False)
        deployed = prepare_deploy(engine_factory=FACTORY, storage=storage)
        assert deployed.query({"user": "zzz", "num": 3}) == {"itemScores": []}

    def test_latest_instance_wins(self, storage):
        seed_ratings(storage)
        run_train(FACTORY, variant=VARIANT, storage=storage, use_mesh=False)
        second = run_train(FACTORY, variant=VARIANT, storage=storage,
                           use_mesh=False)
        deployed = prepare_deploy(engine_factory=FACTORY, storage=storage)
        assert deployed.instance.id == second

    def test_train_failure_marks_failed(self, storage):
        storage.meta.create_app("TestApp")  # no events → DataSource raises
        with pytest.raises(ValueError):
            run_train(FACTORY, variant=VARIANT, storage=storage, use_mesh=False)
        eis = storage.meta.list_engine_instances()
        assert eis and eis[0].status == "FAILED"
        assert prepare_deploy_fails(storage)


def prepare_deploy_fails(storage):
    try:
        prepare_deploy(engine_factory=FACTORY, storage=storage)
    except ValueError:
        return True
    return False


class TestEvalWorkflow:
    def test_grid_search(self, storage):
        from predictionio_tpu.controller import (
            EngineParams,
            Evaluation,
            OptionAverageMetric,
        )
        from predictionio_tpu.core.workflow import run_evaluation
        from predictionio_tpu.templates.recommendation.engine import (
            ALSAlgorithmParams,
            DataSourceParams,
        )

        seed_ratings(storage)

        class RMSE(OptionAverageMetric):
            higher_is_better = False
            header = "SquaredError"

            def calculate_one_opt(self, q, p, a):
                scores = p.get("itemScores", [])
                if not scores or scores[0]["score"] is None:
                    return None
                return (scores[0]["score"] - a) ** 2

        class Ev(Evaluation):
            engine_factory = FACTORY
            metric = RMSE()

        dsp = DataSourceParams(app_name="TestApp", eval_k=2)
        candidates = [
            EngineParams(dsp, None,
                         [("als", ALSAlgorithmParams(rank=r, num_iterations=6,
                                                     lambda_=0.05))], None)
            for r in (2, 8)
        ]
        iid, result = run_evaluation(Ev(), candidates, storage=storage,
                                     use_mesh=False)
        vi = storage.meta.get_evaluation_instance(iid)
        assert vi.status == "EVALCOMPLETED"
        assert len(result.candidates) == 2
        assert result.best_score == min(s for _, s, _ in result.candidates)
