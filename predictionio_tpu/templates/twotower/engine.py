"""Two-Tower deep retrieval template.

The new-framework extension target (BASELINE.json config 5; absent in
the reference — SURVEY.md §2c): flax user/item towers trained with
in-batch contrastive loss on positive interaction events, served by
cosine retrieval over the precomputed item-embedding table.

    POST /queries.json {"user": "u1", "num": 4}
    → {"itemScores": [{"item": "i2", "score": 0.93}, ...]}
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    AverageMetric,
    DataSource,
    Engine,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    FirstServing,
    IdentityPreparator,
    WorkflowContext,
)
from predictionio_tpu.models.two_tower import (
    TwoTowerParams,
    two_tower_embed_items,
    two_tower_embed_users,
    two_tower_train,
    two_tower_user_embed,
)
from predictionio_tpu.utils.bimap import BiMap


@dataclass
class DataSourceParams:
    app_name: str = ""
    event_names: List[str] = field(default_factory=lambda: ["view", "buy"])
    # >0 selects the streaming read path with this chunk size (events
    # per columnar chunk); 0 materializes pairs in host RAM
    stream_chunk: int = 0


@dataclass
class TrainingData:
    interactions: Any   # data.pipeline.InteractionData
    stream: bool = False  # True → trainer consumes chunks, not arrays


class TTDataSource(DataSource):
    ParamsClass = DataSourceParams

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        """Columnar read through the streaming pipeline in BOTH modes
        (SURVEY §2d C4) — ~1/50th the transient memory of building a
        Python pair list. ``stream_chunk > 0`` additionally keeps the
        data chunked end-to-end (memory O(chunk + vocabulary), event
        logs larger than host RAM; the trainer double-buffers chunks
        into HBM)."""
        from predictionio_tpu.data.store import read_training_interactions

        p: DataSourceParams = self.params
        data = read_training_interactions(
            p.app_name, entity_type="user", target_entity_type="item",
            event_names=p.event_names,
            chunk_size=p.stream_chunk or 65536,
            # explicit streaming request = log may exceed host RAM;
            # honor O(chunk) over the materializing columnar fast path
            prefer_streaming=p.stream_chunk > 0,
            storage=ctx.storage)
        if data.n_events == 0:
            raise ValueError("no interaction events found")
        return TrainingData(data, stream=p.stream_chunk > 0)

    def read_eval(self, ctx: WorkflowContext):
        """Leave-one-out retrieval evaluation: each user's LAST
        interaction is held out of training and must be retrieved by
        the ``{"user": u}`` query (recall@k under one relevant item)."""
        from predictionio_tpu.data.pipeline import InteractionData

        td = self.read_training(ctx)
        u, i, v = td.interactions.arrays()
        last: Dict[int, int] = {}
        cnt: Dict[int, int] = {}
        for idx, uu in enumerate(u.tolist()):
            last[uu] = idx
            cnt[uu] = cnt.get(uu, 0) + 1
        held = sorted(idx for uu, idx in last.items() if cnt[uu] >= 2)
        if not held:
            raise ValueError("no user has ≥ 2 interactions to hold out")
        keep = np.ones(len(u), bool)
        keep[held] = False
        uk, ik, vk = u[keep], i[keep], v[keep]
        reduced = InteractionData(
            td.interactions.user_ids, td.interactions.item_ids,
            lambda: iter([(uk, ik, vk)]), int(len(uk)))
        inv_u = td.interactions.user_ids.inverse()
        inv_i = td.interactions.item_ids.inverse()
        qa = [({"user": inv_u[int(u[idx])], "num": 10},
               inv_i[int(i[idx])]) for idx in held]
        return [(TrainingData(reduced, stream=False), {"fold": 0}, qa)]


@dataclass
class TTAlgorithmParams:
    embed_dim: int = 32
    out_dim: int = 32
    hidden: List[int] = field(default_factory=lambda: [64])
    batch_size: int = 1024
    epochs: int = 5
    learning_rate: float = 0.01
    temperature: float = 0.1
    seed: int = 0
    # mid-train checkpoint/resume (Orbax); None disables
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 1
    # -- approximate retrieval (predictionio_tpu/ann, ROADMAP item 3):
    # ``ann`` turns on PQ index build at train time and ADC-shortlist
    # serving; exact scoring remains the fallback whenever the index is
    # absent. engine.json spelling: annM, annK, annIters, annShortlist,
    # annSample. Sizing guidance: docs/perf.md "Approximate retrieval".
    ann: bool = False
    ann_m: int = 8            # subspaces (must divide out_dim)
    ann_k: int = 256          # centroids per subspace (≤ 256, uint8 codes)
    ann_iters: int = 8        # Lloyd iterations
    ann_shortlist: int = 128  # k′ re-rank candidates (recall knob)
    ann_sample: int = 65536   # codebook training sample bound
    # OPQ learned rotation before quantization (engine.json annOpq) —
    # better recall at the same code bytes; versions the blob to v2
    ann_opq: bool = False
    # serving-mesh width hint (engine.json annShards): > 1 partitions
    # codes + rerank vectors item-wise over a "shards" mesh axis at
    # deploy time (docs/perf.md "Sharded retrieval")
    ann_shards: int = 0


class TwoTowerModel:
    def __init__(self, user_vars, item_embeds: np.ndarray, user_ids: BiMap,
                 item_ids: BiMap, params: TwoTowerParams,
                 user_embeds: Optional[np.ndarray] = None,
                 ann_index=None, ann_shortlist: int = 128,
                 ann_shards: int = 0) -> None:
        self.user_vars = user_vars
        self.item_embeds = item_embeds
        self.user_ids = user_ids
        self.item_ids = item_ids
        self._inv = item_ids.inverse()
        self.params = params
        # both towers materialized → serving rides the SAME
        # device-resident gather→score→top-k program as the ALS family
        # (r5); load_model recomputes this from user_vars, so it is
        # None only for hand-built models
        self.user_embeds = user_embeds
        #: optional PQ retrieval index (predictionio_tpu/ann) built at
        #: train time; when present the device scorer serves
        #: ADC-shortlist + exact re-rank instead of a full-corpus scan
        self.ann_index = ann_index
        self.ann_shortlist = ann_shortlist
        #: serving-mesh width (0 = unsharded / follow the index blob's
        #: build hint); resolved by ``maybe_ann_scorer``
        self.ann_shards = ann_shards
        self._scorer = None

    def _device_scorer(self):
        """Lazy shared-policy device scorer: ANN (ADC shortlist +
        re-rank) when the model carries a PQ index, else the exact
        resident scorer (models/als) — both share the AOT-ladder /
        PAD-masking serving contract, and both defer to the host path
        on tiny catalogs (`maybe_*_scorer` policy)."""
        if self.user_embeds is None:
            return None
        from predictionio_tpu.models.als import maybe_resident_scorer

        if self.ann_index is not None:
            from predictionio_tpu.ann import maybe_ann_scorer

            s = maybe_ann_scorer(self.user_embeds, self.item_embeds,
                                 self.ann_index, self._scorer,
                                 shortlist=self.ann_shortlist,
                                 shards=self.ann_shards)
            if s is not None:
                self._scorer = s
                return s
        from predictionio_tpu.ann.scorer import ANNScorer

        cached = (None if isinstance(self._scorer, ANNScorer)
                  else self._scorer)
        self._scorer = maybe_resident_scorer(
            self.user_embeds, self.item_embeds, cached)
        return self._scorer

    def recommend(self, user: str, num: int) -> List[Dict[str, Any]]:
        # unknown user (absent from the training BiMap) → clean empty
        # result on EVERY path — exact, ANN and host alike — which the
        # server returns as HTTP 200 {"itemScores": []}, never a
        # KeyError 500 (cold-start contract; tests/test_ann.py)
        uidx = self.user_ids.get(user)
        if uidx is None:
            return []
        scorer = self._device_scorer()
        if scorer is not None:
            iv, vv = scorer.recommend(uidx, num)
            return [{"item": self._inv[int(i)], "score": float(s)}
                    for i, s in zip(iv, vv)]
        ue = (self.user_embeds[uidx] if self.user_embeds is not None else
              two_tower_user_embed(self.user_vars, uidx,
                                   len(self.user_ids), self.params))
        scores = self.item_embeds @ ue
        num = min(num, scores.shape[0])
        top = np.argpartition(-scores, num - 1)[:num]
        top = top[np.argsort(-scores[top])]
        return [{"item": self._inv[int(i)], "score": float(scores[i])}
                for i in top]


class TwoTowerAlgorithm(Algorithm):
    ParamsClass = TTAlgorithmParams

    def sanity_check(self, data: TrainingData) -> None:
        if data.interactions is None or data.interactions.n_events == 0:
            raise ValueError("empty training pairs")

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> TwoTowerModel:
        p: TTAlgorithmParams = self.params
        user_ids = pd.interactions.user_ids
        item_ids = pd.interactions.item_ids
        if pd.stream:
            uidx = np.zeros(0, np.int32)
            iidx = np.zeros(0, np.int32)
        else:
            uidx, iidx, _ = pd.interactions.arrays()
        # explicit checkpoint_dir param wins; else the workflow's
        # per-run checkpoint dir enables restart-from-checkpoint
        ckpt_dir = p.checkpoint_dir
        if ckpt_dir is None and ctx.checkpoint_dir:
            import os

            ckpt_dir = os.path.join(ctx.checkpoint_dir, "two_tower")
        tp = TwoTowerParams(
            embed_dim=p.embed_dim, hidden=list(p.hidden), out_dim=p.out_dim,
            batch_size=p.batch_size, epochs=p.epochs,
            learning_rate=p.learning_rate, temperature=p.temperature,
            seed=p.seed, checkpoint_dir=ckpt_dir,
            checkpoint_every=p.checkpoint_every,
            n_pairs=pd.interactions.n_events)
        uv, iv = two_tower_train(
            uidx, iidx, len(user_ids), len(item_ids), tp, mesh=ctx.mesh,
            pair_chunks=(pd.interactions.chunks if pd.stream else None))
        item_embeds = two_tower_embed_items(iv, len(item_ids), tp)
        user_embeds = two_tower_embed_users(uv, len(user_ids), tp)
        ann_index = None
        if p.ann:
            from predictionio_tpu.models.two_tower import two_tower_build_index

            ann_index = two_tower_build_index(
                item_embeds, m=p.ann_m, k=p.ann_k, iters=p.ann_iters,
                seed=p.seed, sample=p.ann_sample, opq=p.ann_opq,
                shards=p.ann_shards)
        return TwoTowerModel(uv, item_embeds, user_ids, item_ids, tp,
                             user_embeds=user_embeds, ann_index=ann_index,
                             ann_shortlist=p.ann_shortlist,
                             ann_shards=p.ann_shards)

    def predict(self, model: TwoTowerModel, query: Dict[str, Any]) -> Dict[str, Any]:
        return {"itemScores": model.recommend(str(query["user"]),
                                              int(query.get("num", 10)))}

    #: serve_topk_batch skips AOT-bucket PAD sentinels inline
    accepts_padding = True

    def batch_predict(self, model: TwoTowerModel,
                      queries) -> List[Dict[str, Any]]:
        """Micro-batched serving (`pio deploy --batching`,
        batchpredict): all queries in ONE device dispatch via the
        shared `models/als.serve_topk_batch`."""
        from predictionio_tpu.models.als import serve_topk_batch

        return serve_topk_batch(
            model._device_scorer(), model.user_ids, model._inv,
            queries, fallback=lambda q: self.predict(model, q))

    def aot_warm(self, model: TwoTowerModel, ladder, ks=(16,)):
        """Warm the retrieval executable across the bucket ladder —
        two-tower serving rides the SAME gather→score→top-k program as
        the ALS family, so the warmup contract is identical."""
        scorer = model._device_scorer()
        if scorer is None:
            return {"targets": 0, "compiled": 0, "cached": 0}
        return scorer.warm_buckets(ladder, ks)

    def save_model(self, model: TwoTowerModel, instance_dir: Optional[str]) -> bytes:
        # user_embeds is NOT persisted: it is derivable from user_vars
        # in one chunked numpy pass (~35 MB saved per ML-20M blob) and
        # recomputing on load also upgrades pre-r5 blobs to the
        # device-resident serving path.
        # The PQ index rides INSIDE the blob as its self-verifying
        # PIOANN01 wire bytes (memory-backed model stores have no
        # directory) and, when the store has a real directory, ALSO as
        # ann_index.bin + .sha256 + manifest beside model.bin — that is
        # what `pio fsck` audits and `pio index status` reads jax-free.
        d = {
            "user_vars": model.user_vars,
            "item_embeds": model.item_embeds,
            "user_ids": model.user_ids.to_dict(),
            "item_ids": model.item_ids.to_dict(),
            "params": model.params,
            "ann_shortlist": model.ann_shortlist,
            "ann_shards": model.ann_shards,
        }
        if model.ann_index is not None:
            from predictionio_tpu import ann

            d["ann_index"] = model.ann_index.to_bytes()
            if instance_dir:
                ann.save_index(model.ann_index, instance_dir)
        return pickle.dumps(d)

    def load_model(self, blob: Optional[bytes], instance_dir: Optional[str]) -> TwoTowerModel:
        assert blob is not None
        d = pickle.loads(blob)
        user_ids = BiMap(d["user_ids"])
        # index integrity is verified on EVERY load (header payload
        # sha256, plus the file sidecar when directory-backed); an
        # IntegrityError here propagates to prepare_deploy → /reload
        # refuses the candidate and the champion keeps serving
        ann_index = None
        if instance_dir:
            from predictionio_tpu import ann

            ann_index = ann.load_index(instance_dir)
        if ann_index is None and d.get("ann_index") is not None:
            from predictionio_tpu.ann import PQIndex

            ann_index = PQIndex.from_bytes(d["ann_index"])
        return TwoTowerModel(d["user_vars"], d["item_embeds"],
                             user_ids, BiMap(d["item_ids"]),
                             d["params"],
                             user_embeds=two_tower_embed_users(
                                 d["user_vars"], len(user_ids),
                                 d["params"]),
                             ann_index=ann_index,
                             ann_shortlist=d.get("ann_shortlist", 128),
                             ann_shards=d.get("ann_shards", 0))


def engine_factory() -> Engine:
    return Engine(
        data_source_cls=TTDataSource,
        preparator_cls=IdentityPreparator,
        algorithm_cls_map={"twotower": TwoTowerAlgorithm},
        serving_cls=FirstServing,
    )


# -- evaluation (pio eval out of the box) -------------------------------------


class RecallAtK(AverageMetric):
    """With one held-out relevant item, recall@k = hit rate @ k."""

    def __init__(self, k: int = 10) -> None:
        self.k = k

    def calculate_one(self, query, predicted, actual) -> float:
        items = [s["item"] for s in predicted.get("itemScores", [])][: self.k]
        return 1.0 if actual in items else 0.0

    @property
    def header(self) -> str:
        return f"Recall@{self.k}"


class TTEvaluation(Evaluation):
    engine_factory = staticmethod(engine_factory)
    metric = RecallAtK(10)
    other_metrics = (RecallAtK(1),)


class DefaultGrid(EngineParamsGenerator):
    """Embedding-width candidates; app name via $PIO_EVAL_APP_NAME."""

    @property
    def engine_params_list(self):
        import os

        app = os.environ.get("PIO_EVAL_APP_NAME", "MyApp1")
        return [EngineParams(
            data_source_params=DataSourceParams(app_name=app),
            algorithms_params=[("twotower", TTAlgorithmParams(
                embed_dim=d, out_dim=d, hidden=[2 * d], batch_size=256,
                epochs=30))]) for d in (16, 32)]


class ANNGrid(EngineParamsGenerator):
    """Exact-vs-ANN candidates under the same Recall@10 metric — the
    `pio eval` leg of the PQ recall/latency trade-off: the exact
    candidate is the recall ceiling, the ANN candidates show what each
    (m, shortlist) point costs in held-out retrieval quality.

        pio eval ... tt.TTEvaluation tt.ANNGrid

    App name via $PIO_EVAL_APP_NAME; shortlist points via
    $PIO_EVAL_ANN_SHORTLISTS (comma-separated, default "64,128")."""

    @property
    def engine_params_list(self):
        import os

        app = os.environ.get("PIO_EVAL_APP_NAME", "MyApp1")
        shortlists = [
            int(s) for s in os.environ.get(
                "PIO_EVAL_ANN_SHORTLISTS", "64,128").split(",") if s]
        base = dict(embed_dim=32, out_dim=32, hidden=[64], batch_size=256,
                    epochs=30)
        cands = [TTAlgorithmParams(**base)]          # exact ceiling
        cands += [TTAlgorithmParams(**base, ann=True, ann_m=8,
                                    ann_shortlist=sl)
                  for sl in shortlists]
        return [EngineParams(
            data_source_params=DataSourceParams(app_name=app),
            algorithms_params=[("twotower", c)]) for c in cands]
