"""ALS numerics: convergence, implicit feedback, and single↔sharded
parity on the 8-device CPU mesh (ICI-collective semantics in CI,
SURVEY.md §4)."""

import numpy as np
import pytest

from predictionio_tpu.models.als import (
    ALSParams,
    RatingsCOO,
    als_train,
    predict_ratings,
    recommend,
    similar_items,
)


@pytest.fixture(scope="module")
def synthetic():
    rng = np.random.default_rng(0)
    n_u, n_i, k_true = 100, 70, 5
    U = rng.normal(size=(n_u, k_true))
    V = rng.normal(size=(n_i, k_true))
    R = U @ V.T
    mask = rng.random((n_u, n_i)) < 0.3
    uu, ii = np.nonzero(mask)
    coo = RatingsCOO(uu.astype(np.int32), ii.astype(np.int32),
                     R[uu, ii].astype(np.float32), n_u, n_i)
    return coo, R, mask


class TestSingleDevice:
    def test_convergence(self, synthetic):
        coo, R, mask = synthetic
        U, V = als_train(coo, ALSParams(rank=8, iterations=12, reg=0.05))
        pred = predict_ratings(U, V, coo.user_idx, coo.item_idx)
        rmse = float(np.sqrt(np.mean((pred - coo.rating) ** 2)))
        assert rmse < 0.3, rmse
        # held-out generalization beats predicting the mean
        huu, hii = np.nonzero(~mask)
        hrmse = float(np.sqrt(np.mean(
            (predict_ratings(U, V, huu, hii) - R[huu, hii]) ** 2)))
        assert hrmse < R.std()

    def test_implicit_finite_and_ranks_positives_high(self, synthetic):
        coo, R, _ = synthetic
        pos = RatingsCOO(coo.user_idx, coo.item_idx,
                         np.abs(coo.rating), coo.n_users, coo.n_items)
        U, V = als_train(pos, ALSParams(rank=8, iterations=8, reg=0.05,
                                        implicit=True, alpha=2.0))
        assert np.isfinite(U).all() and np.isfinite(V).all()
        scores = U @ V.T
        observed = scores[coo.user_idx, coo.item_idx].mean()
        assert observed > scores.mean()  # observed pairs score higher

    def test_zero_degree_entities_stay_finite(self):
        # user 3 and item 4 have no ratings at all
        coo = RatingsCOO(np.array([0, 1, 2], np.int32),
                         np.array([0, 1, 2], np.int32),
                         np.array([1.0, 2.0, 3.0], np.float32), 5, 6)
        U, V = als_train(coo, ALSParams(rank=4, iterations=3, reg=0.1))
        assert np.isfinite(U).all() and np.isfinite(V).all()
        assert np.allclose(U[3], 0) and np.allclose(V[4], 0)

    def test_recommend_and_similar(self, synthetic):
        coo, _, _ = synthetic
        U, V = als_train(coo, ALSParams(rank=8, iterations=6, reg=0.05))
        top, scores = recommend(U, V, 0, 7)
        assert len(top) == 7 and list(scores) == sorted(scores, reverse=True)
        top2, _ = recommend(U, V, 0, 7, exclude=np.array([top[0]]))
        assert top[0] not in top2
        sim, sscores = similar_items(V, np.array([3]), 5)
        assert 3 not in sim and len(sim) == 5


def _ref_half(idx_self, idx_other, vals, n_self, F, p):
    """Dense per-entity normal-equation solve (the math the bucketed
    MXU program must reproduce), in float64."""
    k = p.rank
    F = F.astype(np.float64)
    X = np.zeros((n_self, k), np.float64)
    for e in range(n_self):
        sel = idx_self == e
        n_e = int(sel.sum())
        if n_e == 0:
            continue
        Fe = F[idx_other[sel]]
        lam = p.reg * n_e if p.weighted_reg else p.reg
        A = Fe.T @ Fe + max(lam, 1e-8) * np.eye(k)
        X[e] = np.linalg.solve(A, Fe.T @ vals[sel].astype(np.float64))
    return X


def _ref_als(coo, p):
    from predictionio_tpu.models.als import init_factors

    V = init_factors(coo.n_items, p.rank, p.seed).astype(np.float64)
    U = np.zeros((coo.n_users, p.rank), np.float64)
    for _ in range(p.iterations):
        U = _ref_half(coo.user_idx, coo.item_idx, coo.rating,
                      coo.n_users, V, p)
        V = _ref_half(coo.item_idx, coo.user_idx, coo.rating,
                      coo.n_items, U, p)
    return U, V


class TestBucketedLayout:
    def test_segmented_heavy_bucket_matches_dense_reference(self, monkeypatch):
        """Shrink the width ladder so the heavy (segmented, one-hot
        aggregated) bucket path runs on a small dataset, and check the
        whole program against a dense float64 reference."""
        import predictionio_tpu.models.als as als_mod

        monkeypatch.setattr(als_mod, "_LADDER", (2, 8))
        monkeypatch.setattr(als_mod, "_C_MAX", 8)
        rng = np.random.default_rng(5)
        n_u, n_i, nnz = 40, 25, 600
        uu = (rng.zipf(1.3, nnz) % n_u).astype(np.int32)
        ii = (rng.zipf(1.3, nnz) % n_i).astype(np.int32)
        # dedupe (user, item) pairs so counts are exact
        keep = np.unique(uu.astype(np.int64) * n_i + ii, return_index=True)[1]
        uu, ii = uu[keep], ii[keep]
        rr = rng.uniform(1, 5, len(uu)).astype(np.float32)
        coo = RatingsCOO(uu, ii, rr, n_u, n_i)

        prep = als_mod.als_prepare(coo)
        assert any(b.seg is not None for b in prep.u_side.buckets), \
            "test dataset must exercise the segmented bucket"
        assert any(b.seg is None for b in prep.u_side.buckets)

        p = ALSParams(rank=4, iterations=2, reg=0.1, seed=2)
        U, V = als_mod.als_train_prepared(prep, p)
        Ur, Vr = _ref_als(coo, p)
        np.testing.assert_allclose(U, Ur, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(V, Vr, rtol=2e-3, atol=2e-3)

    def test_dense_head_matches_dense_reference(self, monkeypatch):
        """Lower the dense-head threshold so the heaviest entities run
        through the dense-weight GEMM path on a small dataset, and
        check the whole program against the float64 reference."""
        import predictionio_tpu.models.als as als_mod

        monkeypatch.setattr(als_mod, "_DENSE_MIN_COUNT", 6)
        rng = np.random.default_rng(9)
        n_u, n_i, nnz = 40, 25, 500
        uu = (rng.zipf(1.3, nnz) % n_u).astype(np.int32)
        ii = rng.integers(0, n_i, nnz).astype(np.int32)
        rr = rng.uniform(1, 5, nnz).astype(np.float32)  # duplicates kept
        coo = RatingsCOO(uu, ii, rr, n_u, n_i)

        prep = als_mod.als_prepare(coo)
        assert prep.u_side.dense is not None and prep.u_side.dense.nb > 0
        assert prep.u_side.buckets, "light entities must stay bucketed"

        p = ALSParams(rank=4, iterations=2, reg=0.1, seed=2)
        U, V = als_mod.als_train_prepared(prep, p)
        Ur, Vr = _ref_als(coo, p)
        np.testing.assert_allclose(U, Ur, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(V, Vr, rtol=2e-3, atol=2e-3)

    def test_dense_head_byte_cap_spills_to_buckets(self, monkeypatch):
        """PIO_ALS_DENSE_HEAD_MB caps the head's weight-row bytes; the
        spilled entities run through the bucket path with identical
        results (ADVICE r3: unbounded head risks host/device OOM)."""
        import predictionio_tpu.models.als as als_mod

        rng = np.random.default_rng(9)
        n_u, n_i, nnz = 40, 25, 500
        uu = (rng.zipf(1.3, nnz) % n_u).astype(np.int32)
        ii = rng.integers(0, n_i, nnz).astype(np.int32)
        rr = rng.uniform(1, 5, nnz).astype(np.float32)
        coo = RatingsCOO(uu, ii, rr, n_u, n_i)
        p = ALSParams(rank=4, iterations=2, reg=0.1, seed=2)

        monkeypatch.setattr(als_mod, "_DENSE_MIN_COUNT", 6)
        prep_full = als_mod.als_prepare(coo)
        assert prep_full.u_side.dense is not None
        full_nb = prep_full.u_side.dense.nb
        assert full_nb > 1
        U_full, V_full = als_mod.als_train_prepared(prep_full, p)

        # MB granularity can't isolate single rows on a tiny catalog, so
        # cap to zero: every head entity must spill to the buckets
        monkeypatch.setenv("PIO_ALS_DENSE_HEAD_MB", "0")
        prep_capped = als_mod.als_prepare(coo)
        side = prep_capped.u_side
        assert side.dense is None or side.dense.nb == 0
        U_cap, V_cap = als_mod.als_train_prepared(prep_capped, p)
        np.testing.assert_allclose(U_cap, U_full, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(V_cap, V_full, rtol=1e-4, atol=1e-5)

    def test_dense_head_equivalent_to_bucketed_implicit(self, monkeypatch):
        """Implicit feedback: the dense-head program must produce the
        same factors as the pure bucketed layout on identical data."""
        import predictionio_tpu.models.als as als_mod

        rng = np.random.default_rng(10)
        n_u, n_i, nnz = 30, 20, 400
        uu = (rng.zipf(1.3, nnz) % n_u).astype(np.int32)
        ii = rng.integers(0, n_i, nnz).astype(np.int32)
        rr = rng.uniform(0.5, 3, nnz).astype(np.float32)
        coo = RatingsCOO(uu, ii, rr, n_u, n_i)
        p = ALSParams(rank=4, iterations=3, reg=0.1, implicit=True,
                      alpha=2.0, seed=2)

        U0, V0 = als_mod.als_train_prepared(als_mod.als_prepare(coo), p)
        monkeypatch.setattr(als_mod, "_DENSE_MIN_COUNT", 6)
        prep = als_mod.als_prepare(coo)
        assert prep.u_side.dense is not None and prep.u_side.dense.nb > 0
        U1, V1 = als_mod.als_train_prepared(prep, p)
        np.testing.assert_allclose(U0, U1, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(V0, V1, rtol=1e-4, atol=1e-5)

    def test_bf16_gather_mode_close_to_f32(self):
        """Opt-in bf16 gathers: same data, loose agreement with the
        f32 path and equivalent reconstruction quality."""
        rng = np.random.default_rng(13)
        n_u, n_i, k_true = 80, 60, 4
        Ut = rng.normal(size=(n_u, k_true))
        Vt = rng.normal(size=(n_i, k_true))
        mask = rng.random((n_u, n_i)) < 0.3
        uu, ii = np.nonzero(mask)
        coo = RatingsCOO(uu.astype(np.int32), ii.astype(np.int32),
                         (Ut @ Vt.T)[uu, ii].astype(np.float32), n_u, n_i)
        p32 = ALSParams(rank=6, iterations=6, reg=0.05, seed=2)
        p16 = ALSParams(rank=6, iterations=6, reg=0.05, seed=2,
                        bf16_gather=True)
        U32, V32 = als_train(coo, p32)
        U16, V16 = als_train(coo, p16)
        r32 = predict_ratings(U32, V32, coo.user_idx, coo.item_idx)
        r16 = predict_ratings(U16, V16, coo.user_idx, coo.item_idx)
        rmse32 = float(np.sqrt(np.mean((r32 - coo.rating) ** 2)))
        rmse16 = float(np.sqrt(np.mean((r16 - coo.rating) ** 2)))
        assert rmse16 < rmse32 + 0.05, (rmse16, rmse32)
        # factors agree to bf16-accumulation noise
        np.testing.assert_allclose(U16, U32, rtol=0.15, atol=0.1)

    def test_in_body_solve_fallback_matches_materialized(self, monkeypatch):
        """The huge-catalog fallback (solve inside each bucket body,
        taken when the solve buffer would exceed PIO_ALS_SOLVE_BUF_MB)
        must produce the same factors as the materialized path."""
        import predictionio_tpu.models.als as als_mod

        rng = np.random.default_rng(7)
        n_u, n_i = 50, 30
        uu = rng.integers(0, n_u, 500).astype(np.int32)
        ii = rng.integers(0, n_i, 500).astype(np.int32)
        keep = np.unique(uu.astype(np.int64) * n_i + ii, return_index=True)[1]
        uu, ii = uu[keep], ii[keep]
        rr = rng.uniform(1, 5, len(uu)).astype(np.float32)
        coo = RatingsCOO(uu, ii, rr, n_u, n_i)
        p = ALSParams(rank=4, iterations=3, reg=0.1, seed=2)
        # include a dense head so the fallback's dense branch is covered
        monkeypatch.setattr(als_mod, "_DENSE_MIN_COUNT", 8)
        prep = als_mod.als_prepare(coo)
        assert prep.u_side.dense is not None and prep.u_side.dense.nb > 0
        U_m, V_m = als_mod.als_train(coo, p)
        monkeypatch.setattr(als_mod, "_SOLVE_BUF_MB", 0)
        als_mod._compiled_bucketed.cache_clear()
        try:
            U_f, V_f = als_mod.als_train(coo, p)
        finally:
            als_mod._compiled_bucketed.cache_clear()
        np.testing.assert_allclose(U_f, U_m, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(V_f, V_m, rtol=1e-4, atol=1e-5)

    def test_slab_size_parity(self, monkeypatch):
        """The slab size (PIO_ALS_SLAB_ELEMS — an on-device tuning knob,
        default 2^20 after the r5 v5e A/B) only re-batches rows into
        scan steps; training results must be invariant to it. Small
        ladder + tiny slabs force multi-slab scans on a small dataset,
        covering regular AND segmented buckets."""
        import predictionio_tpu.models.als as als_mod

        monkeypatch.setattr(als_mod, "_LADDER", (2, 8))
        monkeypatch.setattr(als_mod, "_C_MAX", 8)
        rng = np.random.default_rng(11)
        n_u, n_i = 40, 25
        uu = (rng.zipf(1.3, 600) % n_u).astype(np.int32)
        ii = (rng.zipf(1.3, 600) % n_i).astype(np.int32)
        keep = np.unique(uu.astype(np.int64) * n_i + ii, return_index=True)[1]
        uu, ii = uu[keep], ii[keep]
        rr = rng.uniform(1, 5, len(uu)).astype(np.float32)
        coo = RatingsCOO(uu, ii, rr, n_u, n_i)
        p = ALSParams(rank=4, iterations=2, reg=0.1, seed=2)

        results = []
        for slab_elems in (16, 64, 1 << 20):
            monkeypatch.setattr(als_mod, "_SLAB_ELEMS", slab_elems)
            prep = als_mod.als_prepare(coo)
            if slab_elems == 16:  # smallest: must actually multi-slab
                assert any(b.n_slabs > 1 for b in prep.u_side.buckets)
            results.append(als_mod.als_train_prepared(prep, p))
        als_mod._compiled_bucketed.cache_clear()
        # slab grouping changes f32 accumulation order in the seg
        # aggregation → tiny drift; a layout bug would be order-1 off
        (U0, V0), *rest = results
        for U, V in rest:
            np.testing.assert_allclose(U, U0, rtol=5e-4, atol=1e-5)
            np.testing.assert_allclose(V, V0, rtol=5e-4, atol=1e-5)

    def test_default_ladder_matches_dense_reference(self):
        rng = np.random.default_rng(6)
        n_u, n_i = 30, 20
        uu = rng.integers(0, n_u, 350).astype(np.int32)
        ii = rng.integers(0, n_i, 350).astype(np.int32)
        keep = np.unique(uu.astype(np.int64) * n_i + ii, return_index=True)[1]
        uu, ii = uu[keep], ii[keep]
        rr = rng.uniform(1, 5, len(uu)).astype(np.float32)
        coo = RatingsCOO(uu, ii, rr, n_u, n_i)
        p = ALSParams(rank=4, iterations=2, reg=0.1, seed=2)
        U, V = als_train(coo, p)
        Ur, Vr = _ref_als(coo, p)
        np.testing.assert_allclose(U, Ur, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(V, Vr, rtol=2e-3, atol=2e-3)


def _zipf_coo(seed, n_u, n_i, nnz):
    rng = np.random.default_rng(seed)
    uu = (rng.zipf(1.3, nnz) % n_u).astype(np.int32)
    ii = (rng.zipf(1.3, nnz) % n_i).astype(np.int32)
    keep = np.unique(uu.astype(np.int64) * n_i + ii, return_index=True)[1]
    uu, ii = uu[keep], ii[keep]
    rr = rng.uniform(1, 5, len(uu)).astype(np.float32)
    return RatingsCOO(uu, ii, rr, n_u, n_i)


class TestFusedGram:
    """ISSUE 17: whole-train parity of the fused gather→Gram Pallas
    path (Mosaic interpreter on CPU) against the XLA gather+einsum
    path, plus the dispatch-collapse regression guard."""

    def _train_both(self, coo, p, monkeypatch):
        import predictionio_tpu.models.als as als_mod

        monkeypatch.setenv("PIO_PALLAS_GRAM", "0")
        Ux, Vx = als_mod.als_train(coo, p)
        monkeypatch.setenv("PIO_PALLAS_GRAM", "interpret")
        Uf, Vf = als_mod.als_train(coo, p)
        return (Ux, Vx), (Uf, Vf)

    def test_train_parity_explicit(self, monkeypatch):
        coo = _zipf_coo(21, 60, 40, 900)
        p = ALSParams(rank=8, iterations=2, reg=0.1, seed=2)
        (Ux, Vx), (Uf, Vf) = self._train_both(coo, p, monkeypatch)
        np.testing.assert_allclose(Uf, Ux, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(Vf, Vx, rtol=1e-4, atol=1e-4)

    def test_train_parity_implicit(self, monkeypatch):
        coo = _zipf_coo(22, 50, 30, 700)
        p = ALSParams(rank=8, iterations=2, reg=0.1, seed=2,
                      implicit=True, alpha=2.0)
        (Ux, Vx), (Uf, Vf) = self._train_both(coo, p, monkeypatch)
        np.testing.assert_allclose(Uf, Ux, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(Vf, Vx, rtol=1e-4, atol=1e-4)

    def test_train_parity_seg_and_dense(self, monkeypatch):
        """Shrink the ladder + dense threshold so ONE program runs all
        three aggregation paths (regular buckets, segmented heavy
        bucket, dense head) — each must match with the kernel on."""
        import predictionio_tpu.models.als as als_mod

        monkeypatch.setattr(als_mod, "_LADDER", (2, 8))
        monkeypatch.setattr(als_mod, "_C_MAX", 8)
        monkeypatch.setattr(als_mod, "_DENSE_MIN_COUNT", 10)
        coo = _zipf_coo(23, 40, 25, 700)
        prep = als_mod.als_prepare(coo)
        assert any(b.seg is not None for b in prep.u_side.buckets)
        p = ALSParams(rank=4, iterations=2, reg=0.1, seed=2)
        (Ux, Vx), (Uf, Vf) = self._train_both(coo, p, monkeypatch)
        np.testing.assert_allclose(Uf, Ux, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(Vf, Vx, rtol=1e-4, atol=1e-4)
        # and against the dense float64 reference, same bar as XLA
        Ur, Vr = _ref_als(coo, p)
        np.testing.assert_allclose(Uf, Ur, rtol=2e-3, atol=2e-3)

    def test_kernel_actually_traced(self, monkeypatch):
        """Guard against the silent-skip failure mode: a geometry where
        everything lands in the dense head never calls the kernel and
        'parity' is vacuous. Assert the fused train traces it."""
        from predictionio_tpu.ops import gram as gram_mod
        import predictionio_tpu.models.als as als_mod

        calls = []
        orig = gram_mod.gather_gram
        monkeypatch.setattr(gram_mod, "gather_gram",
                            lambda *a, **k: calls.append(1) or orig(*a, **k))
        monkeypatch.setenv("PIO_PALLAS_GRAM", "interpret")
        coo = _zipf_coo(24, 45, 35, 600)
        # rank 12 is unique in this file → fresh _compiled_bucketed
        # entry, so tracing (and the counter) actually runs
        als_mod.als_train(coo, ALSParams(rank=12, iterations=1, reg=0.1,
                                         seed=2))
        assert calls, "fused train never reached gather_gram"

    def test_off_flag_restores_xla_program(self, monkeypatch):
        """PIO_PALLAS_GRAM=0 must produce a program with zero
        pallas_call Gram dispatches (byte-identical XLA path)."""
        from predictionio_tpu.ops import gram as gram_mod
        import predictionio_tpu.models.als as als_mod

        calls = []
        orig = gram_mod.gather_gram
        monkeypatch.setattr(gram_mod, "gather_gram",
                            lambda *a, **k: calls.append(1) or orig(*a, **k))
        monkeypatch.setenv("PIO_PALLAS_GRAM", "0")
        coo = _zipf_coo(25, 45, 35, 600)
        als_mod.als_train(coo, ALSParams(rank=12, iterations=1, reg=0.1,
                                         seed=3))
        assert not calls, "gram kernel traced with PIO_PALLAS_GRAM=0"

    def test_dispatch_collapse_ratio(self):
        """The ISSUE-17 acceptance floor, chip-free: the fused TPU
        program must dispatch ≥10× fewer device ops per iteration than
        the XLA path on a representative multi-bucket geometry."""
        from predictionio_tpu.models.als import als_prepare
        from predictionio_tpu.utils import opcount

        # the ratio is geometry-dependent (fixed solve/dense overhead
        # amortizes over slab count): toy shapes sit near 8x, this
        # 250k-nnz zipf shape gives ~16x, the 500k bench shape ~100x
        coo = _zipf_coo(26, 20000, 4000, 250_000)
        prep = als_prepare(coo)
        assert len(prep.u_side.buckets) >= 3  # representative ladder
        p = ALSParams(rank=16, iterations=1, reg=0.1, seed=2)
        rep = opcount.als_dispatch_report(prep, p)
        assert rep["dispatch_collapse_ratio"] >= 10, rep

    @pytest.mark.slow
    def test_ml100k_scale_parity(self, monkeypatch):
        """Trained-factors parity at ML-100k scale (the acceptance
        geometry): 100k zipf ratings over 943×1682, default ladder."""
        coo = _zipf_coo(27, 943, 1682, 100_000)
        p = ALSParams(rank=16, iterations=2, reg=0.05, seed=2)
        (Ux, Vx), (Uf, Vf) = self._train_both(coo, p, monkeypatch)
        np.testing.assert_allclose(Uf, Ux, rtol=5e-4, atol=5e-4)
        np.testing.assert_allclose(Vf, Vx, rtol=5e-4, atol=5e-4)


class TestShardedParity:
    def test_explicit_matches_single(self, synthetic, cpu_mesh):
        coo, _, _ = synthetic
        p = ALSParams(rank=8, iterations=8, reg=0.05, seed=3)
        U1, V1 = als_train(coo, p, mesh=None)
        U8, V8 = als_train(coo, p, mesh=cpu_mesh)
        r1 = predict_ratings(U1, V1, coo.user_idx, coo.item_idx)
        r8 = predict_ratings(U8, V8, coo.user_idx, coo.item_idx)
        # same math, different init/order → near-identical predictions
        assert float(np.sqrt(np.mean((r1 - r8) ** 2))) < 0.15
        assert np.corrcoef(r1, r8)[0, 1] > 0.99

    def test_implicit_matches_single(self, synthetic, cpu_mesh):
        coo, _, _ = synthetic
        pos = RatingsCOO(coo.user_idx, coo.item_idx,
                         np.abs(coo.rating), coo.n_users, coo.n_items)
        p = ALSParams(rank=8, iterations=6, reg=0.05, implicit=True,
                      alpha=2.0, seed=3)
        Ua, Va = als_train(pos, p, mesh=None)
        Ub, Vb = als_train(pos, p, mesh=cpu_mesh)
        ra = (Ua @ Va.T)[pos.user_idx, pos.item_idx]
        rb = (Ub @ Vb.T)[pos.user_idx, pos.item_idx]
        assert np.corrcoef(ra, rb)[0, 1] > 0.99

    def test_sharded_matches_dense_reference(self, cpu_mesh):
        """The sharded bucketed kernel against the dense float64
        reference — same tolerance as the single-device path, so the
        mesh port cannot silently drift from the math."""
        rng = np.random.default_rng(9)
        n_u, n_i = 41, 26  # not divisible by 8
        uu = rng.integers(0, n_u, 400).astype(np.int32)
        ii = rng.integers(0, n_i, 400).astype(np.int32)
        keep = np.unique(uu.astype(np.int64) * n_i + ii, return_index=True)[1]
        uu, ii = uu[keep], ii[keep]
        rr = rng.uniform(1, 5, len(uu)).astype(np.float32)
        coo = RatingsCOO(uu, ii, rr, n_u, n_i)
        p = ALSParams(rank=4, iterations=2, reg=0.1, seed=2)
        from predictionio_tpu.models.als_sharded import als_train_sharded

        U, V = als_train_sharded(coo, p, cpu_mesh)
        Ur, Vr = _ref_als(coo, p)
        np.testing.assert_allclose(U, Ur, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(V, Vr, rtol=2e-3, atol=2e-3)

    def test_sharded_seg_bucket_skewed_devices(self, cpu_mesh, monkeypatch):
        """Merged-bounds path: a heavy-tailed dataset where devices have
        very different heavy-entity counts (one user owns most ratings)
        must still give every device one program and correct factors."""
        import predictionio_tpu.models.als as als_mod

        monkeypatch.setattr(als_mod, "_LADDER", (2, 8))
        monkeypatch.setattr(als_mod, "_C_MAX", 8)
        rng = np.random.default_rng(11)
        n_u, n_i = 33, 17
        # user 0 rates almost everything (heavy, lands on device 0);
        # the rest are sparse
        uu = np.concatenate([np.zeros(16, np.int32),
                             rng.integers(1, n_u, 120).astype(np.int32)])
        ii = np.concatenate([np.arange(16, dtype=np.int32) % n_i,
                             rng.integers(0, n_i, 120).astype(np.int32)])
        keep = np.unique(uu.astype(np.int64) * n_i + ii, return_index=True)[1]
        uu, ii = uu[keep], ii[keep]
        rr = rng.uniform(1, 5, len(uu)).astype(np.float32)
        coo = RatingsCOO(uu, ii, rr, n_u, n_i)

        from predictionio_tpu.models.als_sharded import (als_prepare_sharded,
                                                         als_train_sharded)

        prep = als_prepare_sharded(coo, 8)
        assert any(b.seg is not None for b in prep.u_sides[0].buckets)
        geoms = {s.geometry for s in prep.u_sides}
        assert len(geoms) == 1, "all devices must share one geometry"

        p = ALSParams(rank=4, iterations=2, reg=0.1, seed=2)
        U, V = als_train_sharded(coo, p, cpu_mesh)
        Ur, Vr = _ref_als(coo, p)
        np.testing.assert_allclose(U, Ur, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(V, Vr, rtol=2e-3, atol=2e-3)

    def test_sharded_dense_head_matches_reference(self, cpu_mesh,
                                                  monkeypatch):
        """Dense head under shard_map: per-device dense rows over the
        gathered (padded global) other side, max-merged nb_dense."""
        import predictionio_tpu.models.als as als_mod

        monkeypatch.setattr(als_mod, "_DENSE_MIN_COUNT", 6)
        rng = np.random.default_rng(12)
        n_u, n_i, nnz = 33, 17, 400
        uu = (rng.zipf(1.3, nnz) % n_u).astype(np.int32)
        ii = rng.integers(0, n_i, nnz).astype(np.int32)
        rr = rng.uniform(1, 5, nnz).astype(np.float32)
        coo = RatingsCOO(uu, ii, rr, n_u, n_i)

        from predictionio_tpu.models.als_sharded import (als_prepare_sharded,
                                                         als_train_sharded)

        prep = als_prepare_sharded(coo, 8)
        assert prep.u_sides[0].dense is not None
        assert prep.u_sides[0].dense.nb > 0
        assert len({s.geometry for s in prep.u_sides}) == 1

        p = ALSParams(rank=4, iterations=2, reg=0.1, seed=2)
        U, V = als_train_sharded(coo, p, cpu_mesh)
        Ur, Vr = _ref_als(coo, p)
        np.testing.assert_allclose(U, Ur, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(V, Vr, rtol=2e-3, atol=2e-3)

    def test_sharded_fused_gram_parity(self, cpu_mesh, monkeypatch):
        """Fused gather→Gram under shard_map (interpret mode): the
        unchecked-replication wrapper the kernel needs must not change
        the factors vs the XLA sharded path."""
        from predictionio_tpu.models.als_sharded import als_train_sharded

        coo = _zipf_coo(28, 41, 26, 500)
        p = ALSParams(rank=4, iterations=2, reg=0.1, seed=2)
        monkeypatch.setenv("PIO_PALLAS_GRAM", "0")
        Ux, Vx = als_train_sharded(coo, p, cpu_mesh)
        monkeypatch.setenv("PIO_PALLAS_GRAM", "interpret")
        Uf, Vf = als_train_sharded(coo, p, cpu_mesh)
        np.testing.assert_allclose(Uf, Ux, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(Vf, Vx, rtol=1e-4, atol=1e-4)

    def test_uneven_sizes(self, cpu_mesh):
        # sizes deliberately not divisible by 8
        rng = np.random.default_rng(1)
        n_u, n_i = 37, 23
        uu = rng.integers(0, n_u, 300).astype(np.int32)
        ii = rng.integers(0, n_i, 300).astype(np.int32)
        rr = rng.uniform(1, 5, 300).astype(np.float32)
        coo = RatingsCOO(uu, ii, rr, n_u, n_i)
        U, V = als_train(coo, ALSParams(rank=4, iterations=3, reg=0.1),
                         mesh=cpu_mesh)
        assert U.shape == (37, 4) and V.shape == (23, 4)
        assert np.isfinite(U).all() and np.isfinite(V).all()


class TestResidentScorerPolicy:
    """r4 advisor: maybe_resident_scorer must never serve a cached
    scorer built from different factor arrays (stale scores after a
    retrain/swap)."""

    def test_cache_reused_and_invalidated_on_swap(self, monkeypatch):
        from predictionio_tpu.models.als import maybe_resident_scorer

        monkeypatch.setenv("PIO_ALS_SERVE", "device")
        rng = np.random.default_rng(0)
        U1 = rng.normal(size=(6, 4)).astype(np.float32)
        V1 = rng.normal(size=(8, 4)).astype(np.float32)
        s1 = maybe_resident_scorer(U1, V1)
        assert maybe_resident_scorer(U1, V1, s1) is s1  # same arrays → reuse
        V2 = rng.normal(size=(8, 4)).astype(np.float32)
        s2 = maybe_resident_scorer(U1, V2, s1)  # retrain swapped V
        assert s2 is not s1
        assert maybe_resident_scorer(U1, V2, s2) is s2


class TestALSGrid:
    """VERDICT r3 #2: an eval grid over reg/alpha must share ONE
    compiled executable (reg/alpha are traced scalars)."""

    def _coo(self):
        rng = np.random.default_rng(7)
        n_u, n_i, nnz = 50, 30, 600
        return RatingsCOO(rng.integers(0, n_u, nnz).astype(np.int32),
                          rng.integers(0, n_i, nnz).astype(np.int32),
                          rng.uniform(1, 5, nnz).astype(np.float32),
                          n_u, n_i)

    def test_reg_grid_builds_one_program(self, monkeypatch):
        import predictionio_tpu.models.als as als_mod

        coo = self._coo()
        builds = {"n": 0}
        orig = als_mod._make_half

        def counting(*a, **k):
            builds["n"] += 1
            return orig(*a, **k)

        monkeypatch.setattr(als_mod, "_make_half", counting)
        als_mod._compiled_bucketed.cache_clear()

        grid = [ALSParams(rank=4, iterations=3, reg=r, seed=2)
                for r in (0.01, 0.05, 0.1, 0.5, 1.0)]
        results = als_mod.als_train_many(coo, grid)
        assert builds["n"] == 1, \
            f"5 reg candidates built {builds['n']} programs, expected 1"
        assert len(results) == 5
        # each candidate matches its individually-trained counterpart
        for p, (U, V) in zip(grid, results):
            U1, V1 = als_mod.als_train_prepared(als_mod.als_prepare(coo), p)
            np.testing.assert_allclose(U, U1, rtol=1e-5, atol=1e-6)
        # distinct reg values genuinely differ (the scalars really trace)
        assert not np.allclose(results[0][0], results[-1][0])

    def test_alpha_implicit_grid_shares_program(self, monkeypatch):
        import predictionio_tpu.models.als as als_mod

        coo = self._coo()
        builds = {"n": 0}
        orig = als_mod._make_half

        def counting(*a, **k):
            builds["n"] += 1
            return orig(*a, **k)

        monkeypatch.setattr(als_mod, "_make_half", counting)
        als_mod._compiled_bucketed.cache_clear()

        grid = [ALSParams(rank=4, iterations=3, reg=0.1, implicit=True,
                          alpha=a, seed=2) for a in (0.5, 1.0, 2.0, 4.0)]
        results = als_mod.als_train_many(coo, grid)
        assert builds["n"] == 1
        assert not np.allclose(results[0][0], results[-1][0])

    def test_rank_change_rebuilds(self, monkeypatch):
        import predictionio_tpu.models.als as als_mod

        coo = self._coo()
        builds = {"n": 0}
        orig = als_mod._make_half

        def counting(*a, **k):
            builds["n"] += 1
            return orig(*a, **k)

        monkeypatch.setattr(als_mod, "_make_half", counting)
        als_mod._compiled_bucketed.cache_clear()
        grid = [ALSParams(rank=4, iterations=3, reg=0.1, seed=2),
                ALSParams(rank=8, iterations=3, reg=0.1, seed=2)]
        als_mod.als_train_many(coo, grid)
        assert builds["n"] == 2  # rank changes program shape

    def test_sharded_reg_grid_builds_one_program(self, cpu_mesh,
                                                 monkeypatch):
        import predictionio_tpu.models.als as als_mod
        import predictionio_tpu.models.als_sharded as sh_mod

        coo = self._coo()
        builds = {"n": 0}
        orig = als_mod._make_half

        def counting(*a, **k):
            builds["n"] += 1
            return orig(*a, **k)

        # _compiled_sharded resolves _make_half from the als module at
        # call time via its import — patch where it's looked up
        monkeypatch.setattr(sh_mod, "_make_half", counting)
        sh_mod._compiled_sharded.cache_clear()

        grid = [ALSParams(rank=4, iterations=2, reg=r, seed=2)
                for r in (0.01, 0.1, 1.0)]
        results = als_mod.als_train_many(coo, grid, mesh=cpu_mesh)
        assert builds["n"] == 1, \
            f"3 sharded reg candidates built {builds['n']} programs"
        # parity with the single-device grid
        single = als_mod.als_train_many(coo, grid)
        for (U_s, _), (U_1, _) in zip(results, single):
            np.testing.assert_allclose(U_s, U_1, rtol=2e-4, atol=2e-5)


class TestMeshTraining:
    def test_workflow_train_with_mesh(self, storage):
        """use_mesh=True end-to-end: the full train workflow on the CPU mesh."""
        from predictionio_tpu.core.workflow import prepare_deploy, run_train
        from tests.test_workflow import FACTORY, VARIANT, seed_ratings

        seed_ratings(storage)
        run_train(FACTORY, variant=VARIANT, storage=storage, use_mesh=True)
        deployed = prepare_deploy(engine_factory=FACTORY, storage=storage)
        res = deployed.query({"user": "0", "num": 5})
        assert len(res["itemScores"]) == 5
