"""Naive Bayes (multinomial / bernoulli) on TPU.

Replaces MLlib's ``NaiveBayes`` used by the reference's classification
template (SURVEY.md §2c). The per-class aggregation — MLlib's
``aggregateByKey`` over label keys — becomes a single one-hot matmul
``Yᵀ X`` on the MXU; smoothing and log-normalization follow MLlib's
formulas (λ additive smoothing).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np


@dataclass
class NaiveBayesParams:
    lambda_: float = 1.0
    model_type: str = "multinomial"  # or "bernoulli"
    num_classes: int = 0  # 0 → infer from labels


def nb_train(
    X: np.ndarray, y: np.ndarray, params: NaiveBayesParams, mesh=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Train; returns (log_prior [C], log_theta [C, d])."""
    import jax
    import jax.numpy as jnp

    C = params.num_classes or int(y.max()) + 1
    d = X.shape[1]
    lam = params.lambda_
    bern = params.model_type == "bernoulli"

    @jax.jit
    def fit(Xd, yd):
        Xb = (Xd > 0).astype(jnp.float32) if bern else Xd
        Y = jax.nn.one_hot(yd, C, dtype=jnp.float32)  # (n, C)
        class_count = Y.sum(axis=0)                    # (C,)
        feat_sum = Y.T @ Xb                            # (C, d) — MXU matmul
        log_prior = jnp.log(class_count + lam) - jnp.log(
            class_count.sum() + C * lam)
        if bern:
            # P(feature on | class), complement handled at predict time
            log_theta = (jnp.log(feat_sum + lam)
                         - jnp.log(class_count[:, None] + 2.0 * lam))
        else:
            log_theta = (jnp.log(feat_sum + lam)
                         - jnp.log(feat_sum.sum(axis=1, keepdims=True) + d * lam))
        return log_prior, log_theta

    lp, lt = fit(jnp.asarray(X, jnp.float32), jnp.asarray(y, jnp.int32))
    return np.asarray(lp), np.asarray(lt)


def nb_train_scored(num_classes: int, bernoulli: bool):
    """Pure vmappable train+score half of the distributed sweep
    (core/sweep.py): ``one(hyper, Xd, yd, Xe, ye) -> (correct, count)``
    where ``hyper = [lambda_]`` is a TRACED row of the stacked grid —
    smoothing appears only additively in the closed-form fit, so every
    lambda in a bucket shares one compiled program. The fit body and
    the bernoulli/multinomial scoring mirror :func:`nb_train` /
    :func:`nb_predict` exactly (parity with the serial eval path)."""
    import jax
    import jax.numpy as jnp

    C = num_classes

    def one(hyper, Xd, yd, Xe, ye):
        lam = hyper[0]
        d = Xd.shape[1]
        Xb = (Xd > 0).astype(jnp.float32) if bernoulli else Xd
        Y = jax.nn.one_hot(yd, C, dtype=jnp.float32)
        class_count = Y.sum(axis=0)
        feat_sum = Y.T @ Xb
        log_prior = jnp.log(class_count + lam) - jnp.log(
            class_count.sum() + C * lam)
        if bernoulli:
            log_theta = (jnp.log(feat_sum + lam)
                         - jnp.log(class_count[:, None] + 2.0 * lam))
            theta = jnp.exp(log_theta)
            log_neg = jnp.log1p(-jnp.clip(theta, 1e-12, 1 - 1e-12))
            Xeb = (Xe > 0).astype(jnp.float32)
            scores = Xeb @ log_theta.T + (1.0 - Xeb) @ log_neg.T + log_prior
        else:
            log_theta = (jnp.log(feat_sum + lam)
                         - jnp.log(feat_sum.sum(axis=1, keepdims=True) + d * lam))
            scores = Xe @ log_theta.T + log_prior
        pred = jnp.argmax(scores, axis=-1)
        correct = (pred == ye).astype(jnp.float32).sum()
        return correct, jnp.float32(ye.shape[0])

    return one


def nb_sweep_program(X: np.ndarray, y: np.ndarray, Xe: np.ndarray,
                     ye: np.ndarray, num_classes: int, bernoulli: bool):
    """Assemble the ``(geometry, build, data)`` triple core/sweep.py's
    SweepProgram wants for a bucket of NaiveBayes candidates sharing
    (num_classes, model_type). Hyper rows are ``[lambda_]``."""
    geometry = ("nb_scored", int(num_classes), int(X.shape[1]),
                bool(bernoulli), tuple(X.shape), tuple(Xe.shape))
    data = (np.asarray(X, np.float32), np.asarray(y, np.int32),
            np.asarray(Xe, np.float32), np.asarray(ye, np.int32))

    def build():
        return nb_train_scored(int(num_classes), bool(bernoulli))

    return geometry, build, data


def nb_predict(log_prior: np.ndarray, log_theta: np.ndarray, X: np.ndarray,
               model_type: str = "multinomial") -> np.ndarray:
    if model_type == "bernoulli":
        Xb = (X > 0).astype(np.float32)
        theta = np.exp(log_theta)
        log_neg = np.log1p(-np.clip(theta, 1e-12, 1 - 1e-12))
        scores = Xb @ log_theta.T + (1.0 - Xb) @ log_neg.T + log_prior
    else:
        scores = X @ log_theta.T + log_prior
    return np.argmax(scores, axis=-1)
