// Native event-log storage engine.
//
// The reference's event store rides HBase's native RPC/row-key machinery
// ([U] storage/hbase/HBEventsUtil.scala — SURVEY.md §2a); this is the
// framework's own C++ equivalent: an append-only framed binary log per
// (app, channel) namespace with an in-memory index, filtered scans, and
// a native $set/$unset/$delete property fold (the PEventAggregator
// analogue). Exposed as a C ABI consumed via ctypes from
// predictionio_tpu/data/filestore.py.
//
// File format v2 (current; little-endian):
//   8-byte header "PELOGv2\n", then records
//   [u32 rec_len][u8 kind][payload][u32 crc32c]   rec_len = 1 + payload
//   crc32c (Castagnoli, the Kafka/iSCSI polynomial) covers the 5 header
//   bytes AND the payload, so a flipped bit anywhere in a record —
//   including its length field — fails verification on open.
// File format v1 (legacy; readable, still writable via pel_open_ex):
//   no header, records [u32 rec_len][u8 kind][payload] — a headerless
//   file IS a v1 file; torn tails are detected by length plausibility
//   only and mid-record bit flips go unnoticed.
//   kind 0 (event):  i64 time_us, i64 creation_us, then 9 strings each
//                    [u32 len][bytes]: id, event, entityType, entityId,
//                    targetEntityType, targetEntityId, propertiesJson,
//                    tagsJson, prId  (empty string = null for the
//                    nullable fields)
//   kind 1 (tombstone): [u32 len][id bytes]
//
// Recovery on open walks records by checksum (v2) or length framing
// (v1). A torn/corrupt tail is never silently dropped: the cut bytes
// are copied to a `<log>.quarantine-<offset>` sidecar before the
// truncate, and the truncation offset is reported on stderr and via
// pel_info(). A v2 record whose checksum fails mid-file (intact
// framing) is skipped — counted, never indexed, never served — and
// the walk continues so later checksummed records survive.
//
// Semantics matching the Python SPI (data/events.py):
//   - re-appending an existing id overwrites (HBase put semantics)
//   - find() orders by (eventTime, creationTime, insertion seq)
//   - aggregate folds $set/$unset/$delete in that order
//
// Single-writer per file (like the reference's LocalFS model store);
// in-process concurrency is guarded by a per-handle mutex. The file
// model is SINGLE-PROCESS: bulk scans mmap the log, so an external
// truncation mid-scan is a SIGBUS, not a short read — never run two
// processes (or a concurrent manual truncate) against one namespace
// file (the storage registry already hands each process its own
// handle set; multi-process deployments put the Event Server in
// front, as the reference does with HBase).

#include <sys/mman.h>  // mmap for bulk scans
#include <unistd.h>    // truncate

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

// v2 file header: magic + version in one 8-byte stamp. A v1 file has
// no header — its first bytes are a record length, and a real v1
// record can never alias the magic (the "PELO" u32 would demand a
// multi-GB record that the plausibility check rejects anyway).
const unsigned char kMagic[8] = {'P', 'E', 'L', 'O', 'G', 'v', '2', '\n'};

// CRC32C (Castagnoli, reflected poly 0x82F63B78) — software
// table-driven; check value: crc32c("123456789") == 0xE3069283.
struct CrcTable {
  uint32_t t[256];
  CrcTable() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
const CrcTable kCrc;

// zlib-style chaining: crc32c(crc32c(0, a, na), b, nb) equals the CRC
// of the concatenation — used to checksum header + payload in place.
uint32_t crc32c(uint32_t crc, const unsigned char* p, size_t n) {
  crc ^= 0xFFFFFFFFu;
  while (n--) crc = kCrc.t[(crc ^ *p++) & 0xff] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

struct Rec {
  uint64_t payload_off;  // file offset of payload (after frame header)
  uint32_t payload_len;
  int64_t time_us;
  int64_t creation_us;
  uint64_t seq;        // insertion order, tie-break
  std::string id;
  bool alive;
};

struct Handle {
  std::string path;
  FILE* f = nullptr;  // open in "a+b": reads anywhere, writes append
  std::mutex mu;
  std::vector<Rec> recs;
  std::unordered_map<std::string, size_t> by_id;  // id -> index of latest
  std::vector<size_t> sorted;  // alive indices by (time, creation, seq)
  bool sorted_dirty = true;
  uint64_t next_seq = 0;
  int version = 2;       // format of THIS file (detected on open)
  int want_version = 2;  // format for a fresh/wiped file
  // recovery report from the last load_index/wipe (pel_info)
  long long corrupt_records = 0;   // checksum-failed, skipped mid-file
  long long torn_offset = -1;      // where the tail was cut; -1 = clean
  long long quarantined_bytes = 0; // bytes copied to the sidecar
};

uint32_t rd_u32(const unsigned char* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}
int64_t rd_i64(const unsigned char* p) {
  uint64_t v = 0;
  for (int i = 7; i >= 0; --i) v = (v << 8) | p[i];
  return (int64_t)v;
}

void append_padded(std::string* out) {
  while (out->size() % 8) out->push_back('\0');
}

void append_u32(std::string* out, uint32_t v) {
  unsigned char b[4] = {(unsigned char)(v & 0xff),
                        (unsigned char)((v >> 8) & 0xff),
                        (unsigned char)((v >> 16) & 0xff),
                        (unsigned char)((v >> 24) & 0xff)};
  out->append((char*)b, 4);
}

void append_u64(std::string* out, uint64_t v) {
  for (int i = 0; i < 8; ++i) out->push_back((char)((v >> (8 * i)) & 0xff));
}


// Parse the 9 strings of an event payload into string_views over buf.
// Returns false on corruption.
bool parse_event(const unsigned char* buf, uint32_t len, int64_t* time_us,
                 int64_t* creation_us, std::string_view out[9]) {
  if (len < 16) return false;
  *time_us = rd_i64(buf);
  *creation_us = rd_i64(buf + 8);
  uint64_t off = 16;  // 64-bit so a corrupted length field cannot wrap
  for (int i = 0; i < 9; ++i) {
    if (off + 4 > len) return false;
    uint64_t n = rd_u32(buf + off);
    off += 4;
    if (off + n > len) return false;
    out[i] = std::string_view((const char*)buf + off, (size_t)n);
    off += n;
  }
  return off == len;
}

bool read_payload(Handle* h, const Rec& r, std::string* out) {
  if (!h->f) return false;  // failed wipe-reopen: skip, don't crash
  out->resize(r.payload_len);
  if (fseek(h->f, (long)r.payload_off, SEEK_SET) != 0) return false;
  return fread(out->data(), 1, r.payload_len, h->f) == r.payload_len;
}

// RAII read-only mapping of the whole log for bulk scans: the
// time-sorted index visits records in arbitrary FILE order, so the
// per-record fseek+fread pair costs two syscalls per event — mapped,
// a payload is just a pointer. Falls back to read_payload when mmap
// is unavailable (empty file, exotic FS).
struct LogMap {
  const unsigned char* base = nullptr;
  size_t len = 0;

  explicit LogMap(Handle* h) {
    if (!h->f) return;  // wipe-reopen failure leaves a null FILE*; the
    // empty-index scan must stay a no-op, not a null deref
    fflush(h->f);
    long end = (fseek(h->f, 0, SEEK_END) == 0) ? ftell(h->f) : -1;
    if (end <= 0) return;
    void* p = mmap(nullptr, (size_t)end, PROT_READ, MAP_PRIVATE,
                   fileno(h->f), 0);
    if (p == MAP_FAILED) return;
    base = (const unsigned char*)p;
    len = (size_t)end;
  }
  ~LogMap() {
    if (base) munmap((void*)base, len);
  }
  // payload view, or empty on out-of-range / no mapping
  bool view(const Rec& r, std::string_view* out) const {
    if (!base || r.payload_off + r.payload_len > len) return false;
    *out = std::string_view((const char*)base + r.payload_off,
                            r.payload_len);
    return true;
  }
};

void index_record(Handle* h, uint8_t kind, const unsigned char* payload,
                  uint32_t plen, uint64_t payload_off) {
  if (kind == 1) {  // tombstone
    if (plen < 4) return;
    uint32_t n = rd_u32(payload);
    if (4 + n > plen) return;
    std::string id((const char*)payload + 4, n);
    auto it = h->by_id.find(id);
    if (it != h->by_id.end()) {
      h->recs[it->second].alive = false;
      h->by_id.erase(it);
      h->sorted_dirty = true;
    }
    return;
  }
  int64_t t, c;
  std::string_view s[9];
  if (!parse_event(payload, plen, &t, &c, s)) return;
  std::string id(s[0]);
  auto it = h->by_id.find(id);
  if (it != h->by_id.end()) h->recs[it->second].alive = false;
  Rec r{payload_off, plen, t, c, h->next_seq++, id, true};
  h->recs.push_back(std::move(r));
  h->by_id[id] = h->recs.size() - 1;
  h->sorted_dirty = true;
}

// Copy the unreadable tail [off, file_size) to the quarantine sidecar
// BEFORE it is truncated away — corrupt bytes are evidence, not trash.
// Best-effort: a failed copy must not block recovery (availability
// over forensics), it just leaves quarantined_bytes at 0.
void quarantine_tail(Handle* h, uint64_t off, uint64_t file_size) {
  uint64_t left = file_size - off;
  if (left == 0) return;
  std::string qpath =
      h->path + ".quarantine-" + std::to_string((unsigned long long)off);
  FILE* qf = fopen(qpath.c_str(), "wb");
  if (!qf) return;
  if (fseek(h->f, (long)off, SEEK_SET) != 0) { fclose(qf); return; }
  char buf[65536];
  uint64_t copied = 0;
  while (left > 0) {
    size_t want = left < sizeof buf ? (size_t)left : sizeof buf;
    size_t n = fread(buf, 1, want, h->f);
    if (n == 0) break;
    if (fwrite(buf, 1, n, qf) != n) break;
    copied += n;
    left -= n;
  }
  fflush(qf);
  fsync(fileno(qf));
  fclose(qf);
  h->quarantined_bytes = (long long)copied;
}

bool load_index(Handle* h, int want_version) {
  h->want_version = want_version;
  if (fseek(h->f, 0, SEEK_END) != 0) return false;
  uint64_t file_size = (uint64_t)ftell(h->f);
  if (file_size == 0) {  // fresh namespace: stamp the v2 header
    h->version = want_version;
    if (want_version == 2) {
      if (fwrite(kMagic, 1, 8, h->f) != 8) return false;
      fflush(h->f);
    }
    return true;
  }
  unsigned char head[8];
  if (fseek(h->f, 0, SEEK_SET) != 0) return false;
  size_t hn = fread(head, 1, 8, h->f);
  uint64_t off;  // end of last fully-readable record
  if (hn == 8 && memcmp(head, kMagic, 8) == 0) {
    h->version = 2;
    off = 8;
  } else {
    h->version = 1;  // headerless = legacy v1 file
    off = 0;
    if (fseek(h->f, 0, SEEK_SET) != 0) return false;
  }
  uint32_t trailer = (h->version == 2) ? 4 : 0;
  std::string buf;
  bool torn = false;
  for (;;) {
    unsigned char hdr[5];
    size_t n = fread(hdr, 1, 5, h->f);
    if (n == 0) break;                     // clean EOF
    if (n < 5) { torn = true; break; }     // torn tail write
    uint32_t rec_len = rd_u32(hdr);
    // a length that cannot fit in the rest of the file is corruption,
    // not just a torn tail — truncate rather than try a huge resize
    if (rec_len < 1 ||
        off + 5 + (uint64_t)(rec_len - 1) + trailer > file_size) {
      torn = true;
      break;
    }
    uint8_t kind = hdr[4];
    uint32_t plen = rec_len - 1;
    buf.resize((size_t)plen + trailer);
    if (fread(buf.data(), 1, plen + trailer, h->f) != plen + trailer) {
      torn = true;
      break;
    }
    if (h->version == 2) {
      uint32_t stored = rd_u32((const unsigned char*)buf.data() + plen);
      uint32_t actual = crc32c(crc32c(0, hdr, 5),
                               (const unsigned char*)buf.data(), plen);
      if (stored != actual) {
        // damaged record with intact framing: never index (= never
        // serve) it, keep walking so later checksummed records survive
        ++h->corrupt_records;
        off += 5 + (uint64_t)plen + trailer;
        continue;
      }
    }
    index_record(h, kind, (const unsigned char*)buf.data(), plen, off + 5);
    off += 5 + (uint64_t)plen + trailer;
  }
  if (torn) {
    // preserve the cut bytes, then drop the torn tail so later
    // appends stay readable on reopen
    quarantine_tail(h, off, file_size);
    fflush(h->f);
    if (truncate(h->path.c_str(), (off_t)off) != 0) return false;
    fclose(h->f);
    h->f = fopen(h->path.c_str(), "a+b");  // nullptr on failure: caller
    if (!h->f) return false;               // must not fclose again
    h->torn_offset = (long long)off;
    fprintf(stderr,
            "pel: %s: torn/corrupt tail truncated at offset %llu "
            "(%llu bytes -> %s.quarantine-%llu)\n",
            h->path.c_str(), (unsigned long long)off,
            (unsigned long long)(file_size - off), h->path.c_str(),
            (unsigned long long)off);
  }
  return true;
}

void ensure_sorted(Handle* h) {
  if (!h->sorted_dirty) return;
  h->sorted.clear();
  for (size_t i = 0; i < h->recs.size(); ++i)
    if (h->recs[i].alive) h->sorted.push_back(i);
  std::sort(h->sorted.begin(), h->sorted.end(), [&](size_t a, size_t b) {
    const Rec &x = h->recs[a], &y = h->recs[b];
    if (x.time_us != y.time_us) return x.time_us < y.time_us;
    if (x.creation_us != y.creation_us) return x.creation_us < y.creation_us;
    return x.seq < y.seq;
  });
  h->sorted_dirty = false;
}

// ---------------- JSON (minimal, for the property fold) -----------------

// Skip one JSON value starting at s[i]; returns one-past-end index or
// npos on error. Handles strings w/ escapes and nested {}/[].
size_t skip_value(std::string_view s, size_t i) {
  while (i < s.size() && (s[i] == ' ' || s[i] == '\t' || s[i] == '\n' ||
                          s[i] == '\r'))
    ++i;
  if (i >= s.size()) return std::string_view::npos;
  char c = s[i];
  if (c == '"') {
    ++i;
    while (i < s.size()) {
      if (s[i] == '\\') i += 2;
      else if (s[i] == '"') return i + 1;
      else ++i;
    }
    return std::string_view::npos;
  }
  if (c == '{' || c == '[') {
    char close = (c == '{') ? '}' : ']';
    int depth = 1;
    ++i;
    while (i < s.size() && depth > 0) {
      char d = s[i];
      if (d == '"') {
        size_t e = skip_value(s, i);
        if (e == std::string_view::npos) return e;
        i = e;
        continue;
      }
      if (d == '{' || d == '[') ++depth;
      else if (d == '}' || d == ']') --depth;
      ++i;
    }
    return depth == 0 ? i : std::string_view::npos;
  }
  // literal: number / true / false / null
  size_t j = i;
  while (j < s.size() && s[j] != ',' && s[j] != '}' && s[j] != ']' &&
         s[j] != ' ' && s[j] != '\t' && s[j] != '\n' && s[j] != '\r')
    ++j;
  return j;
}

void append_utf8(std::string* out, uint32_t cp) {
  if (cp < 0x80) {
    *out += (char)cp;
  } else if (cp < 0x800) {
    *out += (char)(0xC0 | (cp >> 6));
    *out += (char)(0x80 | (cp & 0x3F));
  } else if (cp < 0x10000) {
    *out += (char)(0xE0 | (cp >> 12));
    *out += (char)(0x80 | ((cp >> 6) & 0x3F));
    *out += (char)(0x80 | (cp & 0x3F));
  } else {
    *out += (char)(0xF0 | (cp >> 18));
    *out += (char)(0x80 | ((cp >> 12) & 0x3F));
    *out += (char)(0x80 | ((cp >> 6) & 0x3F));
    *out += (char)(0x80 | (cp & 0x3F));
  }
}

int hex4(std::string_view s, size_t i) {  // -1 on malformed
  if (i + 4 > s.size()) return -1;
  int v = 0;
  for (int k = 0; k < 4; ++k) {
    char c = s[i + k];
    int d = (c >= '0' && c <= '9')   ? c - '0'
            : (c >= 'a' && c <= 'f') ? c - 'a' + 10
            : (c >= 'A' && c <= 'F') ? c - 'A' + 10
                                     : -1;
    if (d < 0) return -1;
    v = (v << 4) | d;
  }
  return v;
}

// Decode a JSON string token (with quotes) to raw UTF-8 text,
// including \uXXXX escapes and surrogate pairs.
std::string json_unescape(std::string_view tok) {
  std::string out;
  if (tok.size() < 2) return out;
  for (size_t i = 1; i + 1 < tok.size(); ++i) {
    char c = tok[i];
    if (c != '\\') { out += c; continue; }
    ++i;
    if (i + 1 > tok.size()) break;
    switch (tok[i]) {
      case 'n': out += '\n'; break;
      case 't': out += '\t'; break;
      case 'r': out += '\r'; break;
      case 'b': out += '\b'; break;
      case 'f': out += '\f'; break;
      case '/': out += '/'; break;
      case '"': out += '"'; break;
      case '\\': out += '\\'; break;
      case 'u': {
        int hi = hex4(tok, i + 1);
        if (hi < 0) break;
        i += 4;
        uint32_t cp = (uint32_t)hi;
        if (cp >= 0xD800 && cp <= 0xDBFF && i + 2 < tok.size() &&
            tok[i + 1] == '\\' && tok[i + 2] == 'u') {
          int lo = hex4(tok, i + 3);
          if (lo >= 0xDC00 && lo <= 0xDFFF) {
            cp = 0x10000 + ((cp - 0xD800) << 10) + ((uint32_t)lo - 0xDC00);
            i += 6;
          }
        }
        append_utf8(&out, cp);
        break;
      }
      default: out += tok[i];
    }
  }
  return out;
}

std::string json_escape(std::string_view s) {
  std::string out;
  out.reserve(s.size() + 2);
  for (size_t i = 0; i < s.size(); ++i) {
    char c = s[i];
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      default:
        if ((unsigned char)c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += c;  // raw UTF-8 passes through
        }
    }
  }
  return out;
}

// Parse top-level {key: rawvalue} spans of a JSON object.
bool json_object_items(
    std::string_view s,
    std::vector<std::pair<std::string, std::string_view>>* items) {
  size_t i = 0;
  while (i < s.size() && isspace((unsigned char)s[i])) ++i;
  if (i >= s.size() || s[i] != '{') return false;
  ++i;
  for (;;) {
    while (i < s.size() && (isspace((unsigned char)s[i]) || s[i] == ',')) ++i;
    if (i < s.size() && s[i] == '}') return true;
    if (i >= s.size() || s[i] != '"') return false;
    size_t ke = skip_value(s, i);
    if (ke == std::string_view::npos) return false;
    std::string key = json_unescape(s.substr(i, ke - i));
    i = ke;
    while (i < s.size() && isspace((unsigned char)s[i])) ++i;
    if (i >= s.size() || s[i] != ':') return false;
    ++i;
    while (i < s.size() && isspace((unsigned char)s[i])) ++i;
    size_t ve = skip_value(s, i);
    if (ve == std::string_view::npos) return false;
    items->emplace_back(std::move(key), s.substr(i, ve - i));
    i = ve;
  }
}

char* dup_out(const std::string& s) {
  char* p = (char*)malloc(s.size() + 1);
  if (!p) return nullptr;
  memcpy(p, s.data(), s.size());
  p[s.size()] = '\0';
  return p;
}

}  // namespace

extern "C" {

// want_version picks the record format for a FRESH (empty) file: 2 =
// checksummed (default), 1 = legacy (the profile_events.py CRC A/B
// toggle). An existing file always keeps its on-disk format so one
// file never mixes framings.
void* pel_open_ex(const char* path, int want_version) {
  if (want_version != 1 && want_version != 2) return nullptr;
  FILE* f = fopen(path, "a+b");
  if (!f) return nullptr;
  Handle* h = new Handle();
  h->path = path;
  h->f = f;
  if (!load_index(h, want_version)) {
    if (h->f) fclose(h->f);  // may already be closed+nulled by recovery
    delete h;
    return nullptr;
  }
  return h;
}

void* pel_open(const char* path) { return pel_open_ex(path, 2); }

// Recovery/format report for the last open (or wipe): out-params may
// be NULL. torn_offset is -1 when the file opened clean.
void pel_info(void* hv, long long* version, long long* corrupt_records,
              long long* torn_offset, long long* quarantined_bytes) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  if (version) *version = h->version;
  if (corrupt_records) *corrupt_records = h->corrupt_records;
  if (torn_offset) *torn_offset = h->torn_offset;
  if (quarantined_bytes) *quarantined_bytes = h->quarantined_bytes;
}

void pel_close(void* hv) {
  if (!hv) return;
  Handle* h = (Handle*)hv;
  if (h->f) fclose(h->f);
  delete h;
}

namespace {
// Write + index n framed records from an in-memory buffer (shared by
// pel_append_batch, pel_delete and the native NDJSON import below).
// Input frames are the v1 shape ([u32 len][u8 kind][payload], as the
// Python serializer produces); on a v2 file each frame gains its
// crc32c trailer here, so every writer path is checksummed without
// the serializers knowing about record versions.
int append_frames(Handle* h, const unsigned char* buf, long long len,
                  int n) {
  if (!h->f) return -1;
  fseek(h->f, 0, SEEK_END);
  uint64_t base = (uint64_t)ftell(h->f);
  if (h->version == 2) {
    struct Item {
      uint8_t kind;
      uint64_t src_payload;  // payload offset in buf
      uint32_t plen;
      uint64_t disk_payload;  // payload offset in the disk image
    };
    std::string disk;
    disk.reserve((size_t)len + (size_t)n * 4);
    std::vector<Item> items;
    uint64_t off = 0;
    while (off + 5 <= (uint64_t)len && (int)items.size() < n) {
      uint32_t rec_len = rd_u32(buf + off);
      if (rec_len < 1 || off + 4 + rec_len > (uint64_t)len) break;
      uint32_t plen = rec_len - 1;
      items.push_back({buf[off + 4], off + 5, plen, disk.size() + 5});
      disk.append((const char*)buf + off, 5 + (size_t)plen);
      append_u32(&disk, crc32c(0, buf + off, 5 + (size_t)plen));
      off += 5 + (uint64_t)plen;
    }
    if (fwrite(disk.data(), 1, disk.size(), h->f) != disk.size()) return -1;
    fflush(h->f);
    for (const Item& it : items)
      index_record(h, it.kind, buf + it.src_payload, it.plen,
                   base + it.disk_payload);
    return (int)items.size();
  }
  if (fwrite(buf, 1, (size_t)len, h->f) != (size_t)len) return -1;
  fflush(h->f);
  uint64_t off = 0;
  int done = 0;
  while (off + 5 <= (uint64_t)len && done < n) {
    uint32_t rec_len = rd_u32(buf + off);
    if (rec_len < 1 || off + 4 + rec_len > (uint64_t)len) break;
    uint8_t kind = buf[off + 4];
    index_record(h, kind, buf + off + 5, rec_len - 1, base + off + 5);
    off += 4 + rec_len;
    ++done;
  }
  return done;
}
}  // namespace

// Append n framed records (concatenated, as produced by the Python
// serializer). Returns number indexed, or -1 on IO error.
int pel_append_batch(void* hv, const unsigned char* buf, long long len,
                     int n) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  return append_frames(h, buf, len, n);
}

// Durable-ack support: fsync the log so an acked append survives power
// loss, not just process death (fflush alone stops at the page cache).
// One call covers every record appended before it — the group-commit
// path pays this once per batch. Returns 0 on success, -1 on failure.
int pel_sync(void* hv) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  if (!h->f) return -1;
  if (fflush(h->f) != 0) return -1;
  return fsync(fileno(h->f)) == 0 ? 0 : -1;
}

// Tombstone an id. Returns 1 if it existed, 0 otherwise, -1 on IO error.
int pel_delete(void* hv, const char* id, int idlen) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  std::string key(id, idlen);
  if (h->by_id.find(key) == h->by_id.end()) return 0;
  std::string frame;
  uint32_t rec_len = 1 + 4 + (uint32_t)idlen;
  unsigned char hdr[9];
  hdr[0] = rec_len & 0xff; hdr[1] = (rec_len >> 8) & 0xff;
  hdr[2] = (rec_len >> 16) & 0xff; hdr[3] = (rec_len >> 24) & 0xff;
  hdr[4] = 1;  // kind tombstone
  hdr[5] = idlen & 0xff; hdr[6] = (idlen >> 8) & 0xff;
  hdr[7] = (idlen >> 16) & 0xff; hdr[8] = (idlen >> 24) & 0xff;
  frame.append((char*)hdr, 9);
  frame.append(id, idlen);
  // append_frames applies the v2 crc trailer and folds the tombstone
  // into the index (index_record kills the live entry)
  if (append_frames(h, (const unsigned char*)frame.data(),
                    (long long)frame.size(), 1) != 1)
    return -1;
  return 1;
}

// Truncate the log (wipe namespace, keep usable).
int pel_wipe(void* hv) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  fclose(h->f);
  FILE* trunc = fopen(h->path.c_str(), "wb");  // truncate to zero
  if (!trunc) {
    // keep the handle usable and the data intact: report failure
    // instead of clearing the in-memory index over a non-empty file
    h->f = fopen(h->path.c_str(), "a+b");
    return -1;
  }
  fclose(trunc);
  h->f = fopen(h->path.c_str(), "a+b");
  h->recs.clear();
  h->by_id.clear();
  h->sorted.clear();
  h->sorted_dirty = true;
  h->next_seq = 0;
  h->corrupt_records = 0;
  h->torn_offset = -1;
  h->quarantined_bytes = 0;
  // the wiped file is fresh: it takes the handle's requested format
  // (a wiped legacy file upgrades to the checksummed header)
  h->version = h->want_version;
  if (h->f && h->version == 2) {
    if (fwrite(kMagic, 1, 8, h->f) != 8) return -1;
    fflush(h->f);
  }
  return h->f ? 0 : -1;
}

long long pel_count(void* hv) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  return (long long)h->by_id.size();
}

// All live event ids as concatenated [u32 len][bytes] frames, in index
// order. Index-only walk — no payload IO — so a sealed segment about
// to ship can cheaply persist an id-membership filter. Returns the
// byte length via the malloc'd *out, -1 on allocation failure.
long long pel_live_ids(void* hv, char** out) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  std::string result;
  for (const auto& kv : h->by_id) {
    append_u32(&result, (uint32_t)kv.first.size());
    result.append(kv.first);
  }
  *out = dup_out(result);
  return *out ? (long long)result.size() : -1;
}

// Live-event creationTime statistics for the snapshot cache: count of
// alive records with creation_us <= until_us, and their max
// creation_us via *max_out (untouched when the count is 0). The walk
// reads only the in-memory index — no payload IO.
long long pel_creation_stats(void* hv, long long until_us,
                             long long* max_out) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  long long count = 0;
  int64_t max_c = 0;
  for (const Rec& r : h->recs) {
    if (!r.alive || r.creation_us > until_us) continue;
    if (count == 0 || r.creation_us > max_c) max_c = r.creation_us;
    ++count;
  }
  if (count && max_out) *max_out = (long long)max_c;
  return count;
}

// Alive-record creationTime bounds for segment seal metadata: returns
// the alive count and fills *min_out/*max_out (untouched when empty).
// Index-only walk, no payload IO.
long long pel_creation_bounds(void* hv, long long* min_out,
                              long long* max_out) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  long long count = 0;
  int64_t min_c = 0, max_c = 0;
  for (const Rec& r : h->recs) {
    if (!r.alive) continue;
    if (count == 0 || r.creation_us < min_c) min_c = r.creation_us;
    if (count == 0 || r.creation_us > max_c) max_c = r.creation_us;
    ++count;
  }
  if (count) {
    if (min_out) *min_out = (long long)min_c;
    if (max_out) *max_out = (long long)max_c;
  }
  return count;
}

// Fetch one framed record by id into *out (malloc'd). Returns byte
// length, 0 if missing, -1 on error.
long long pel_get(void* hv, const char* id, int idlen, char** out) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  auto it = h->by_id.find(std::string(id, idlen));
  if (it == h->by_id.end()) return 0;
  std::string payload;
  if (!read_payload(h, h->recs[it->second], &payload)) return -1;
  *out = dup_out(payload);
  return *out ? (long long)payload.size() : -1;
}

// Filtered scan. NULL filter = wildcard; event_names is a
// '\n'-joined list or NULL. Times in epoch-us; INT64_MIN/MAX act as
// unbounded. Returns a malloc'd concatenation of [u32 len][payload]
// frames (no kind byte — all events) in scan order; length via
// *out_len; -1 on error.
long long pel_find(void* hv, long long start_us, long long until_us,
                   const char* entity_type, const char* entity_id,
                   const char* target_entity_type,
                   const char* target_entity_id, const char* event_names,
                   int reversed, long long limit, char** out) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  ensure_sorted(h);
  std::vector<std::string_view> names;
  std::string names_buf;
  if (event_names) {
    names_buf = event_names;
    size_t p = 0;
    while (p <= names_buf.size()) {
      size_t q = names_buf.find('\n', p);
      if (q == std::string::npos) q = names_buf.size();
      names.emplace_back(names_buf.data() + p, q - p);
      p = q + 1;
    }
  }
  std::string result;
  long long matched = 0;
  LogMap map(h);
  std::string payload;
  auto visit = [&](size_t idx) -> bool {  // returns false to stop
    if (limit >= 0 && matched >= limit) return false;  // incl. limit=0
    const Rec& r = h->recs[idx];
    if (r.time_us < start_us || r.time_us >= until_us) return true;
    std::string_view pv;
    if (!map.view(r, &pv)) {
      if (!read_payload(h, r, &payload)) return true;
      pv = payload;
    }
    int64_t t, c;
    std::string_view s[9];
    if (!parse_event((const unsigned char*)pv.data(),
                     (uint32_t)pv.size(), &t, &c, s))
      return true;
    if (entity_type && s[2] != entity_type) return true;
    if (entity_id && s[3] != entity_id) return true;
    if (target_entity_type && s[4] != target_entity_type) return true;
    if (target_entity_id && s[5] != target_entity_id) return true;
    if (event_names) {
      bool ok = false;
      for (auto& n : names)
        if (s[1] == n) { ok = true; break; }
      if (!ok) return true;
    }
    append_u32(&result, (uint32_t)pv.size());
    result.append(pv.data(), pv.size());
    ++matched;
    return !(limit >= 0 && matched >= limit);
  };
  if (reversed) {
    for (auto it = h->sorted.rbegin(); it != h->sorted.rend(); ++it)
      if (!visit(*it)) break;
  } else {
    for (size_t idx : h->sorted)
      if (!visit(idx)) break;
  }
  *out = dup_out(result);
  return *out ? (long long)result.size() : -1;
}

// Native $set/$unset/$delete fold (PEventAggregator equivalent).
// Returns malloc'd JSON:
//   {"<entityId>": {"f": first_us, "l": last_us, "p": {..props..}}, ...}
// -1 on error.
long long pel_aggregate(void* hv, const char* entity_type,
                        long long start_us, long long until_us, char** out) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  ensure_sorted(h);
  struct Ent {
    // insertion-ordered props: vector + map of key -> vector index
    std::vector<std::pair<std::string, std::string>> props;
    std::unordered_map<std::string, size_t> pos;
    int64_t first_us = 0, last_us = 0;
  };
  std::map<std::string, Ent> state;
  LogMap map(h);
  std::string payload;
  for (size_t idx : h->sorted) {
    const Rec& r = h->recs[idx];
    if (r.time_us < start_us || r.time_us >= until_us) continue;
    std::string_view pv;
    if (!map.view(r, &pv)) {
      if (!read_payload(h, r, &payload)) continue;
      pv = payload;
    }
    int64_t t, c;
    std::string_view s[9];
    if (!parse_event((const unsigned char*)pv.data(),
                     (uint32_t)pv.size(), &t, &c, s))
      continue;
    if (entity_type && s[2] != entity_type) continue;
    std::string eid(s[3]);
    if (s[1] == "$set") {
      std::vector<std::pair<std::string, std::string_view>> items;
      if (!json_object_items(s[6], &items)) continue;
      auto it = state.find(eid);
      if (it == state.end()) {
        Ent e;
        e.first_us = t;
        e.last_us = t;
        for (auto& kv : items) {
          e.pos[kv.first] = e.props.size();
          e.props.emplace_back(kv.first, std::string(kv.second));
        }
        state.emplace(std::move(eid), std::move(e));
      } else {
        Ent& e = it->second;
        for (auto& kv : items) {
          auto p = e.pos.find(kv.first);
          if (p == e.pos.end()) {
            e.pos[kv.first] = e.props.size();
            e.props.emplace_back(kv.first, std::string(kv.second));
          } else {
            e.props[p->second].second = std::string(kv.second);
          }
        }
        if (t > e.last_us) e.last_us = t;
      }
    } else if (s[1] == "$unset") {
      auto it = state.find(eid);
      if (it == state.end()) continue;
      std::vector<std::pair<std::string, std::string_view>> items;
      if (!json_object_items(s[6], &items)) continue;
      Ent& e = it->second;
      for (auto& kv : items) {
        auto p = e.pos.find(kv.first);
        if (p != e.pos.end()) {
          e.props[p->second].first.clear();  // mark dead (empty key)
          e.props[p->second].second.clear();
          e.pos.erase(p);
        }
      }
      if (t > e.last_us) e.last_us = t;
    } else if (s[1] == "$delete") {
      state.erase(eid);
    }
  }
  std::string outj = "{";
  bool first_e = true;
  for (auto& [eid, e] : state) {
    if (!first_e) outj += ",";
    first_e = false;
    outj += "\"" + json_escape(eid) + "\":{\"f\":" +
            std::to_string(e.first_us) + ",\"l\":" +
            std::to_string(e.last_us) + ",\"p\":{";
    bool first_p = true;
    for (auto& kv : e.props) {
      if (kv.first.empty() && kv.second.empty()) continue;  // unset
      if (!first_p) outj += ",";
      first_p = false;
      outj += "\"" + json_escape(kv.first) + "\":" + kv.second;
    }
    outj += "}}";
  }
  outj += "}";
  *out = dup_out(outj);
  return *out ? (long long)outj.size() : -1;
}

// Columnar training-read scan (the HBase-scan→RDD[Rating] analogue,
// SURVEY.md §3.1 step "DataSource.readTraining"): one pass over the
// sorted index emitting numpy-ready fixed-width columns plus
// first-seen-deduped id tables, so the training read never
// materializes a per-event Python object (measured 7 µs/event on the
// generic find() path — ~140 s of pure parse at ML-20M scale).
//
// Filters mirror pel_find (NULL = wildcard). value_key (may be NULL)
// names a top-level property extracted per event as f64 — mirroring
// the templates' float(properties[key]): JSON numbers, numeric
// strings, and booleans parse; anything else (or absent) is NaN and
// the caller applies its per-event-name policy. Events with an empty
// targetEntityId are skipped (training pairs need both sides).
// created_after_us/created_until_us bound creationTime (exclusive
// lower / inclusive upper; pass the ±2^62 sentinels for unbounded) —
// the snapshot cache's delta predicate, evaluated on the in-memory
// index before any payload read.
//
// Blob layout (little-endian; every section 8-byte aligned):
//   u64 n_events, u64 n_entities, u64 n_targets, u64 n_names
//   i64 time_us[n]
//   f64 value[n]
//   u32 ent_idx[n]   (+pad)   first-seen dense indices — exactly the
//   u32 tgt_idx[n]   (+pad)   vocabulary order the Python two-pass
//   u16 name_idx[n]  (+pad)   reader assigns (BiMap parity)
//   name table:   n_names   × [u32 len][bytes], then pad to 8
//   entity table: n_entities × [u32 len][bytes], then pad to 8
//   target table: n_targets  × [u32 len][bytes]
// Returns blob length, -1 on IO/alloc error, -2 if >65535 distinct
// event names (u16 name_idx would overflow; caller falls back).

namespace {

// Value grammar shared with the Python fallback (store.py _NUM_RE):
// optional sign, decimal digits with optional fraction, optional
// decimal exponent — the JSON number grammar — plus true/false.
// DELIBERATELY narrower than both strtod and Python float(): no hex,
// no inf/nan words, no underscore literals — so the native and
// generic training reads keep/drop exactly the same events.
bool decimal_number_shape(std::string_view t) {
  size_t i = 0, n = t.size();
  if (i < n && (t[i] == '+' || t[i] == '-')) ++i;
  size_t digits = 0;
  while (i < n && t[i] >= '0' && t[i] <= '9') { ++i; ++digits; }
  if (i < n && t[i] == '.') {
    ++i;
    while (i < n && t[i] >= '0' && t[i] <= '9') { ++i; ++digits; }
  }
  if (digits == 0) return false;
  if (i < n && (t[i] == 'e' || t[i] == 'E')) {
    ++i;
    if (i < n && (t[i] == '+' || t[i] == '-')) ++i;
    size_t ed = 0;
    while (i < n && t[i] >= '0' && t[i] <= '9') { ++i; ++ed; }
    if (ed == 0) return false;
  }
  return i == n;
}

double parse_number_token(std::string_view tok) {
  double nan = NAN;
  if (tok.empty()) return nan;
  if (tok == "true") return 1.0;   // float(True) == 1.0 in the
  if (tok == "false") return 0.0;  // Python reference semantics
  if (tok.front() == '"') {        // numeric string: "4.5"
    if (tok.size() < 2 || tok.back() != '"') return nan;
    tok = tok.substr(1, tok.size() - 2);
  }
  // surrounding SPACES tolerated (float(" 4.5 ") parses). Spaces
  // only: other whitespace inside a JSON string arrives here as its
  // two-byte escape (\t, \n), which the shape check rejects — the
  // Python side strips only spaces to match (store.py _parse_value).
  while (!tok.empty() && tok.front() == ' ') tok.remove_prefix(1);
  while (!tok.empty() && tok.back() == ' ') tok.remove_suffix(1);
  if (!decimal_number_shape(tok)) return nan;
  char buf[64];
  if (tok.size() >= sizeof(buf)) return nan;
  memcpy(buf, tok.data(), tok.size());
  buf[tok.size()] = '\0';
  // overflow ("1e999") yields inf → non-finite → dropped, same as the
  // Python fallback's isfinite gate
  return strtod(buf, nullptr);
}

// Extract a top-level key's value from a properties-JSON object.
double extract_number(std::string_view s, std::string_view key) {
  double nan = NAN;
  size_t i = 0;
  while (i < s.size() && isspace((unsigned char)s[i])) ++i;
  if (i >= s.size() || s[i] != '{') return nan;
  ++i;
  for (;;) {
    while (i < s.size() && (isspace((unsigned char)s[i]) || s[i] == ',')) ++i;
    if (i >= s.size() || s[i] == '}') return nan;
    if (s[i] != '"') return nan;
    size_t ke = skip_value(s, i);
    if (ke == std::string_view::npos) return nan;
    std::string_view ktok = s.substr(i, ke - i);
    bool match;
    if (ktok.find('\\') == std::string_view::npos) {
      match = ktok.size() == key.size() + 2 &&
              ktok.substr(1, key.size()) == key;
    } else {
      match = json_unescape(ktok) == key;
    }
    i = ke;
    while (i < s.size() && isspace((unsigned char)s[i])) ++i;
    if (i >= s.size() || s[i] != ':') return nan;
    ++i;
    while (i < s.size() && isspace((unsigned char)s[i])) ++i;
    size_t ve = skip_value(s, i);
    if (ve == std::string_view::npos) return nan;
    if (match) return parse_number_token(s.substr(i, ve - i));
    i = ve;
  }
}

}  // namespace

// ---------------- native NDJSON import (the `pio import` hot path) ------
//
// Parses newline-delimited event JSON (the reference wire shape) and
// appends frames directly — no Python Event objects, no re-serialize.
// STRICT fast grammar: a line is only consumed natively when every
// part is the common shape (known keys, strict ISO-8601 eventTime,
// validation rules pass trivially); anything unusual — including
// anything INVALID — gets status 1 and the caller routes that line
// through the Python `Event.from_json` path, which raises the proper
// EventValidationError. So the native path can only ever accept what
// Python would accept, never diverge on rejects.
//
// Per-line status (written to status_out, one byte per line):
//   0 = appended natively, 1 = fallback to Python, 2 = blank line.

namespace {

// ---- strict RFC-8259 JSON validation --------------------------------
//
// skip_value/json_object_items are LENIENT walkers (fine for reading
// back our own serializer's output); the import path must instead be
// STRICTLY NARROWER than Python's json.loads — a line the validator
// passes must be a line Python would parse identically. Rejections
// fall back to Python (which raises the proper error), so being too
// strict only costs speed, never correctness; being too loose would
// persist garbage (r5 review: a raw '{"a":}' span poisoned every
// later read of the namespace).

size_t jv_ws(std::string_view s, size_t i) {
  while (i < s.size() && (s[i] == ' ' || s[i] == '\t' || s[i] == '\n' ||
                          s[i] == '\r'))
    ++i;
  return i;
}

size_t jv_string(std::string_view s, size_t i) {  // expects s[i] == '"'
  ++i;
  while (i < s.size()) {
    unsigned char c = (unsigned char)s[i];
    if (c == '"') return i + 1;
    if (c == '\\') {
      if (i + 1 >= s.size()) return std::string_view::npos;
      char e = s[i + 1];
      if (e == 'u') {
        int v = hex4(s, i + 2);
        if (v < 0) return std::string_view::npos;
        i += 6;
        // Surrogates must pair. json.loads ACCEPTS lone surrogates,
        // but the Python import path then dies at utf-8 encode time —
        // while json_unescape would emit raw surrogate bytes into the
        // frame and poison every later read of the namespace (r5
        // review). Reject → fall back → Python raises properly.
        if (v >= 0xDC00 && v <= 0xDFFF) return std::string_view::npos;
        if (v >= 0xD800 && v <= 0xDBFF) {
          if (i + 6 > s.size() || s[i] != '\\' || s[i + 1] != 'u')
            return std::string_view::npos;
          int lo = hex4(s, i + 2);
          if (lo < 0xDC00 || lo > 0xDFFF) return std::string_view::npos;
          i += 6;
        }
      } else if (e == '"' || e == '\\' || e == '/' || e == 'b' ||
                 e == 'f' || e == 'n' || e == 'r' || e == 't') {
        i += 2;
      } else {
        return std::string_view::npos;
      }
    } else if (c < 0x20) {
      return std::string_view::npos;  // raw control char: invalid JSON
    } else {
      ++i;
    }
  }
  return std::string_view::npos;
}

size_t jv_number(std::string_view s, size_t i) {
  size_t n = s.size();
  if (i < n && s[i] == '-') ++i;
  if (i >= n) return std::string_view::npos;
  if (s[i] == '0') {
    ++i;  // no leading zeros
  } else if (s[i] >= '1' && s[i] <= '9') {
    while (i < n && s[i] >= '0' && s[i] <= '9') ++i;
  } else {
    return std::string_view::npos;
  }
  if (i < n && s[i] == '.') {
    ++i;
    size_t d = 0;
    while (i < n && s[i] >= '0' && s[i] <= '9') { ++i; ++d; }
    if (d == 0) return std::string_view::npos;
  }
  if (i < n && (s[i] == 'e' || s[i] == 'E')) {
    ++i;
    if (i < n && (s[i] == '+' || s[i] == '-')) ++i;
    size_t d = 0;
    while (i < n && s[i] >= '0' && s[i] <= '9') { ++i; ++d; }
    if (d == 0) return std::string_view::npos;
  }
  return i;
}

size_t json_validate(std::string_view s, size_t i, int depth = 0) {
  constexpr size_t npos = std::string_view::npos;
  if (depth > 64) return npos;  // Python's default recursion guard is
  i = jv_ws(s, i);              // far higher; stricter is safe
  if (i >= s.size()) return npos;
  char c = s[i];
  if (c == '"') return jv_string(s, i);
  if (c == '{') {
    i = jv_ws(s, i + 1);
    if (i < s.size() && s[i] == '}') return i + 1;
    // Duplicate keys make the fast paths diverge from Python:
    // json.loads keeps the LAST value while span/number extraction
    // (json_object_items, extract_number) takes the FIRST. Reject the
    // whole line so it falls back to Python, whose dict round-trip
    // normalizes the duplicates away. Keys compare UNESCAPED — an
    // escaped and a literal spelling of one char are the same dict key.
    std::vector<std::string> seen_keys;
    for (;;) {
      i = jv_ws(s, i);
      if (i >= s.size() || s[i] != '"') return npos;
      size_t key_start = i;
      i = jv_string(s, i);
      if (i == npos) return npos;
      std::string key = json_unescape(s.substr(key_start, i - key_start));
      for (const std::string& k : seen_keys)
        if (k == key) return npos;
      seen_keys.push_back(std::move(key));
      i = jv_ws(s, i);
      if (i >= s.size() || s[i] != ':') return npos;
      i = json_validate(s, i + 1, depth + 1);
      if (i == npos) return npos;
      i = jv_ws(s, i);
      if (i >= s.size()) return npos;
      if (s[i] == '}') return i + 1;
      if (s[i] != ',') return npos;
      ++i;
    }
  }
  if (c == '[') {
    i = jv_ws(s, i + 1);
    if (i < s.size() && s[i] == ']') return i + 1;
    for (;;) {
      i = json_validate(s, i, depth + 1);
      if (i == npos) return npos;
      i = jv_ws(s, i);
      if (i >= s.size()) return npos;
      if (s[i] == ']') return i + 1;
      if (s[i] != ',') return npos;
      ++i;
    }
  }
  if (s.compare(i, 4, "true") == 0) return i + 4;
  if (s.compare(i, 5, "false") == 0) return i + 5;
  if (s.compare(i, 4, "null") == 0) return i + 4;
  if (c == '-' || (c >= '0' && c <= '9')) return jv_number(s, i);
  return npos;  // incl. NaN/Infinity: Python accepts, we fall back
}

// Hinnant days-from-civil: days since 1970-01-01 for y-m-d.
int64_t days_from_civil(int64_t y, unsigned m, unsigned d) {
  y -= m <= 2;
  const int64_t era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = (unsigned)(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + (int64_t)doe - 719468;
}

bool all_digits(std::string_view s) {
  if (s.empty()) return false;
  for (char c : s)
    if (c < '0' || c > '9') return false;
  return true;
}

int to_int(std::string_view s) {
  int v = 0;
  for (char c : s) v = v * 10 + (c - '0');
  return v;
}

// Strict ISO-8601, the subset EVERY supported Python (>= 3.10, where
// fromisoformat is narrowest) accepts: YYYY-MM-DD[T ]HH:MM:SS with an
// optional .fff or .ffffff fraction (exactly 3 or 6 digits — 3.10
// rejects other widths) and an optional Z or ±HH:MM offset (3.10
// rejects ±HHMM/±HH). Anything else falls back to Python, which
// applies the running interpreter's own rules.
bool parse_iso8601_us(std::string_view s, int64_t* out_us) {
  if (s.size() < 19) return false;
  if (!all_digits(s.substr(0, 4)) || s[4] != '-' ||
      !all_digits(s.substr(5, 2)) || s[7] != '-' ||
      !all_digits(s.substr(8, 2)) || (s[10] != 'T' && s[10] != ' ') ||
      !all_digits(s.substr(11, 2)) || s[13] != ':' ||
      !all_digits(s.substr(14, 2)) || s[16] != ':' ||
      !all_digits(s.substr(17, 2)))
    return false;
  int year = to_int(s.substr(0, 4)), mon = to_int(s.substr(5, 2)),
      day = to_int(s.substr(8, 2)), hh = to_int(s.substr(11, 2)),
      mm = to_int(s.substr(14, 2)), ss = to_int(s.substr(17, 2));
  if (year < 1 || mon < 1 || mon > 12 || day < 1 || hh > 23 || mm > 59 ||
      ss > 59)
    return false;
  // real calendar dates only — fromisoformat rejects 2026-02-30, and
  // days_from_civil would silently normalize it (r5 review)
  static const int mdays[12] = {31, 28, 31, 30, 31, 30,
                                31, 31, 30, 31, 30, 31};
  int dmax = mdays[mon - 1];
  if (mon == 2 &&
      (year % 4 == 0 && (year % 100 != 0 || year % 400 == 0)))
    dmax = 29;
  if (day > dmax) return false;
  size_t i = 19;
  int64_t frac_us = 0;
  if (i < s.size() && s[i] == '.') {
    ++i;
    size_t f0 = i;
    while (i < s.size() && s[i] >= '0' && s[i] <= '9') ++i;
    size_t nd = i - f0;
    if (nd != 3 && nd != 6) return false;  // the 3.10-safe widths
    frac_us = to_int(s.substr(f0, nd));
    for (size_t k = nd; k < 6; ++k) frac_us *= 10;
  }
  int64_t tz_off_s = 0;
  if (i == s.size()) {
    tz_off_s = 0;  // naive = UTC (parse_event_time semantics)
  } else if (s[i] == 'Z' && i + 1 == s.size()) {
    tz_off_s = 0;
  } else if (s[i] == '+' || s[i] == '-') {
    int sign = s[i] == '-' ? -1 : 1;
    ++i;
    // ±HH:MM only (3.10-safe; ±HHMM/±HH fall back)
    if (i + 5 != s.size() || !all_digits(s.substr(i, 2)) ||
        s[i + 2] != ':' || !all_digits(s.substr(i + 3, 2)))
      return false;
    int oh = to_int(s.substr(i, 2));
    int om = to_int(s.substr(i + 3, 2));
    if (oh > 23 || om > 59) return false;
    tz_off_s = sign * (oh * 3600 + om * 60);
    i += 5;
  } else {
    return false;
  }
  int64_t days = days_from_civil(year, (unsigned)mon, (unsigned)day);
  *out_us =
      ((days * 86400 + hh * 3600 + mm * 60 + ss) - tz_off_s) * 1000000 +
      frac_us;
  return true;
}

uint64_t splitmix64(uint64_t* st) {
  uint64_t z = (*st += 0x9E3779B97F4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

void hex32(uint64_t a, uint64_t b, char out[32]) {
  static const char* h = "0123456789abcdef";
  for (int i = 0; i < 16; ++i) out[i] = h[(a >> (60 - 4 * i)) & 0xF];
  for (int i = 0; i < 16; ++i) out[16 + i] = h[(b >> (60 - 4 * i)) & 0xF];
}

void frame_str(std::string* payload, std::string_view s) {
  append_u32(payload, (uint32_t)s.size());
  payload->append(s.data(), s.size());
}

}  // namespace

long long pel_append_jsonl(void* hv, const char* buf, long long len,
                           long long now_us, unsigned long long rng_seed,
                           char* status_out, long long max_lines,
                           char* ids_out /* 32 bytes per line or NULL */) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  std::string_view all(buf, (size_t)len);
  std::string frames;
  frames.reserve((size_t)len + (size_t)len / 4);
  uint64_t rs = rng_seed ? rng_seed : 0x6a09e667f3bcc909ull;
  long long line_no = 0;
  long long appended = 0;
  size_t pos = 0;
  std::string payload, unesc[7];
  while (pos <= all.size() && line_no < max_lines) {
    size_t eol = all.find('\n', pos);
    if (eol == std::string_view::npos) {
      if (pos >= all.size()) break;
      eol = all.size();
    }
    std::string_view line = all.substr(pos, eol - pos);
    pos = eol + 1;
    // trim whitespace
    while (!line.empty() && (line.front() == ' ' || line.front() == '\r' ||
                             line.front() == '\t'))
      line.remove_prefix(1);
    while (!line.empty() && (line.back() == ' ' || line.back() == '\r' ||
                             line.back() == '\t'))
      line.remove_suffix(1);
    long long ln = line_no++;
    if (ids_out) memset(ids_out + ln * 32, 0, 32);
    if (line.empty()) {
      status_out[ln] = 2;
      continue;
    }
    // STRICT whole-line validation first: the line must be exactly one
    // valid JSON value with nothing after it. Only then is the lenient
    // span extraction below safe (on a valid line it is exact).
    {
      size_t e = json_validate(line, 0);
      if (e == std::string_view::npos || jv_ws(line, e) != line.size()) {
        status_out[ln] = 1;
        continue;
      }
    }
    // parse the top-level object into raw spans
    std::vector<std::pair<std::string, std::string_view>> items;
    if (!json_object_items(line, &items)) {
      status_out[ln] = 1;
      continue;
    }
    std::string_view ev, etype, eid, ttype, tid, props, tags, prid, evid,
        etime, ctime;
    bool ok = true, saw_ttype = false, saw_tid = false;
    for (auto& kv : items) {
      const std::string& k = kv.first;
      std::string_view v = kv.second;
      if (k == "event") ev = v;
      else if (k == "entityType") etype = v;
      else if (k == "entityId") eid = v;
      else if (k == "targetEntityType") { ttype = v; saw_ttype = true; }
      else if (k == "targetEntityId") { tid = v; saw_tid = true; }
      else if (k == "properties") props = v;
      else if (k == "tags") tags = v;
      else if (k == "prId") prid = v;
      else if (k == "eventId") evid = v;
      else if (k == "eventTime") etime = v;
      else if (k == "creationTime") ctime = v;  // export round-trips
      // carry it (the reference's export format always writes it)
      else { ok = false; break; }  // unknown key → proper Python error
    }
    // nulls / wrong types / reserved-$ events / empty requireds /
    // target one-sided → all fall back (Python validates or rejects)
    auto is_str = [](std::string_view v) {
      return v.size() >= 2 && v.front() == '"' && v.back() == '"';
    };
    if (!ok || !is_str(ev) || !is_str(etype) || !is_str(eid) ||
        (saw_ttype != saw_tid) ||
        (saw_ttype && (!is_str(ttype) || !is_str(tid))) ||
        (!props.empty() && (props.front() != '{')) ||
        (!tags.empty() && (tags.front() != '[')) ||
        (!prid.empty() && !is_str(prid)) ||
        (!evid.empty() && !is_str(evid)) ||
        (!etime.empty() && !is_str(etime)) ||
        (!ctime.empty() && !is_str(ctime))) {
      status_out[ln] = 1;
      continue;
    }
    unesc[0] = json_unescape(ev);
    unesc[1] = json_unescape(etype);
    unesc[2] = json_unescape(eid);
    unesc[3] = saw_ttype ? json_unescape(ttype) : std::string();
    unesc[4] = saw_tid ? json_unescape(tid) : std::string();
    unesc[5] = prid.empty() ? std::string() : json_unescape(prid);
    unesc[6] = evid.empty() ? std::string() : json_unescape(evid);
    if (unesc[0].empty() || unesc[1].empty() || unesc[2].empty() ||
        unesc[0][0] == '$' ||  // reserved/$-validation: Python's job
        (saw_ttype && (unesc[3].empty() || unesc[4].empty()))) {
      status_out[ln] = 1;
      continue;
    }
    auto parse_time_field = [](std::string_view tok, int64_t* out) {
      std::string ts = json_unescape(tok);
      // strip() semantics of parse_event_time
      std::string_view tv(ts);
      while (!tv.empty() && tv.front() == ' ') tv.remove_prefix(1);
      while (!tv.empty() && tv.back() == ' ') tv.remove_suffix(1);
      return parse_iso8601_us(tv, out);
    };
    // per-line default timestamps: now_us + line index, so a chunk of
    // defaulted lines keeps its within-chunk arrival order under the
    // (eventTime, creationTime, seq) sort and creationTime watermarks
    // advance strictly monotonically across chunks
    int64_t t_us = now_us + ln, c_us = now_us + ln;
    if (!etime.empty() && !parse_time_field(etime, &t_us)) {
      status_out[ln] = 1;
      continue;
    }
    if (!ctime.empty() && !parse_time_field(ctime, &c_us)) {
      status_out[ln] = 1;
      continue;
    }
    char idbuf[32];
    std::string_view event_id;
    if (!unesc[6].empty()) {
      event_id = unesc[6];
    } else {
      hex32(splitmix64(&rs), splitmix64(&rs), idbuf);
      event_id = std::string_view(idbuf, 32);
    }
    if (ids_out && event_id.size() == 32)
      memcpy(ids_out + ln * 32, event_id.data(), 32);
    payload.clear();
    append_u64(&payload, (uint64_t)t_us);
    append_u64(&payload, (uint64_t)c_us);
    frame_str(&payload, event_id);
    frame_str(&payload, unesc[0]);
    frame_str(&payload, unesc[1]);
    frame_str(&payload, unesc[2]);
    frame_str(&payload, unesc[3]);
    frame_str(&payload, unesc[4]);
    frame_str(&payload, props.empty() ? std::string_view("{}") : props);
    frame_str(&payload, tags.empty() ? std::string_view("[]") : tags);
    frame_str(&payload, unesc[5]);
    append_u32(&frames, (uint32_t)payload.size() + 1);
    frames.push_back('\0');  // kind 0 = event
    frames.append(payload);
    status_out[ln] = 0;
    ++appended;
  }
  if (appended) {
    int done = append_frames(h, (const unsigned char*)frames.data(),
                             (long long)frames.size(), (int)appended);
    if (done != appended) return -1;
  }
  return appended;
}

long long pel_scan_columnar(void* hv, long long start_us, long long until_us,
                            long long created_after_us,
                            long long created_until_us,
                            const char* entity_type,
                            const char* target_entity_type,
                            const char* event_names, const char* value_key,
                            char** out) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  ensure_sorted(h);
  std::vector<std::string_view> names_filter;
  std::string names_buf;
  if (event_names) {
    names_buf = event_names;
    size_t p = 0;
    while (p <= names_buf.size()) {
      size_t q = names_buf.find('\n', p);
      if (q == std::string::npos) q = names_buf.size();
      names_filter.emplace_back(names_buf.data() + p, q - p);
      p = q + 1;
    }
  }
  std::string_view vkey = value_key ? std::string_view(value_key)
                                    : std::string_view();
  struct Vocab {
    std::unordered_map<std::string, uint32_t> idx;
    std::string table;  // [u32 len][bytes] concatenated, first-seen order
    uint32_t add(std::string_view s) {
      auto it = idx.find(std::string(s));  // one lookup alloc; fine
      if (it != idx.end()) return it->second;
      uint32_t i = (uint32_t)idx.size();
      idx.emplace(std::string(s), i);
      append_u32(&table, (uint32_t)s.size());
      table.append(s.data(), s.size());
      return i;
    }
  };
  Vocab ents, tgts, names;
  std::vector<int64_t> times;
  std::vector<double> values;
  std::vector<uint32_t> ent_idx, tgt_idx;
  std::vector<uint16_t> name_idx;
  LogMap map(h);
  std::string payload;
  for (size_t idx : h->sorted) {
    const Rec& r = h->recs[idx];
    if (r.time_us < start_us || r.time_us >= until_us) continue;
    // creationTime window (delta scans for the snapshot cache):
    // exclusive lower / inclusive upper, straight off the index — no
    // payload read for records outside the window
    if (r.creation_us <= created_after_us ||
        r.creation_us > created_until_us)
      continue;
    std::string_view pv;
    if (!map.view(r, &pv)) {
      if (!read_payload(h, r, &payload)) continue;
      pv = payload;
    }
    int64_t t, c;
    std::string_view s[9];
    if (!parse_event((const unsigned char*)pv.data(),
                     (uint32_t)pv.size(), &t, &c, s))
      continue;
    if (entity_type && s[2] != entity_type) continue;
    if (target_entity_type && s[4] != target_entity_type) continue;
    if (s[5].empty()) continue;  // no target entity: not a pair
    if (event_names) {
      bool ok = false;
      for (auto& n : names_filter)
        if (s[1] == n) { ok = true; break; }
      if (!ok) continue;
    }
    if (names.idx.size() >= 65535 &&
        names.idx.find(std::string(s[1])) == names.idx.end())
      return -2;
    times.push_back(t);
    values.push_back(vkey.empty() ? NAN
                                  : extract_number(s[6], vkey));
    ent_idx.push_back(ents.add(s[3]));
    tgt_idx.push_back(tgts.add(s[5]));
    name_idx.push_back((uint16_t)names.add(s[1]));
  }
  uint64_t n = times.size();
  std::string blob;
  blob.reserve(32 + n * 26 + ents.table.size() + tgts.table.size() +
               names.table.size() + 64);
  append_u64(&blob, n);
  append_u64(&blob, ents.idx.size());
  append_u64(&blob, tgts.idx.size());
  append_u64(&blob, names.idx.size());
  blob.append((const char*)times.data(), n * 8);
  blob.append((const char*)values.data(), n * 8);
  blob.append((const char*)ent_idx.data(), n * 4);
  append_padded(&blob);
  blob.append((const char*)tgt_idx.data(), n * 4);
  append_padded(&blob);
  blob.append((const char*)name_idx.data(), n * 2);
  append_padded(&blob);
  blob.append(names.table);
  append_padded(&blob);
  blob.append(ents.table);
  append_padded(&blob);
  blob.append(tgts.table);
  *out = dup_out(blob);
  return *out ? (long long)blob.size() : -1;
}

// Extended columnar scan for the segmented log. Same filters as
// pel_scan_columnar, richer blob: a creationTime column (so
// multi-segment merges can restore global (time, creation, seq)
// order), entity/target TYPE index columns + tables (so a compaction
// sidecar built with wildcard filters can answer typed scans later),
// and N value columns extracted in one walk (value_keys is a
// '\n'-joined list; 0 keys emits 0 value columns).
//
// Blob layout (little-endian, sections 8-aligned):
//   u64 n, n_ent, n_tgt, n_names, n_etypes, n_ttypes, n_keys   (56 B)
//   i64 times[n]; i64 creation[n]; f64 values[n] * n_keys
//   u32 ent_idx[n] pad; u32 tgt_idx[n] pad; u16 name_idx[n] pad
//   u16 etype_idx[n] pad; u16 ttype_idx[n] pad
//   name table pad; entity table pad; target table pad
//   etype table pad; ttype table          ([u32 len][bytes] each)
// Returns blob length via *out; -2 when a u16 vocab overflows.
long long pel_scan_columnar_ex(void* hv, long long start_us,
                               long long until_us,
                               long long created_after_us,
                               long long created_until_us,
                               const char* entity_type,
                               const char* target_entity_type,
                               const char* event_names,
                               const char* value_keys, char** out) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  ensure_sorted(h);
  auto split_list = [](const char* src, std::string* buf,
                       std::vector<std::string_view>* parts) {
    if (!src) return;
    *buf = src;
    size_t p = 0;
    while (p <= buf->size()) {
      size_t q = buf->find('\n', p);
      if (q == std::string::npos) q = buf->size();
      parts->emplace_back(buf->data() + p, q - p);
      p = q + 1;
    }
  };
  std::vector<std::string_view> names_filter, vkeys;
  std::string names_buf, vkeys_buf;
  split_list(event_names, &names_buf, &names_filter);
  split_list(value_keys, &vkeys_buf, &vkeys);
  struct Vocab {
    std::unordered_map<std::string, uint32_t> idx;
    std::string table;
    uint32_t add(std::string_view s) {
      auto it = idx.find(std::string(s));
      if (it != idx.end()) return it->second;
      uint32_t i = (uint32_t)idx.size();
      idx.emplace(std::string(s), i);
      append_u32(&table, (uint32_t)s.size());
      table.append(s.data(), s.size());
      return i;
    }
    bool full16(std::string_view s) const {
      return idx.size() >= 65535 && idx.find(std::string(s)) == idx.end();
    }
  };
  Vocab ents, tgts, names, etypes, ttypes;
  std::vector<int64_t> times, creations;
  std::vector<std::vector<double>> values(vkeys.size());
  std::vector<uint32_t> ent_idx, tgt_idx;
  std::vector<uint16_t> name_idx, etype_idx, ttype_idx;
  LogMap map(h);
  std::string payload;
  for (size_t idx : h->sorted) {
    const Rec& r = h->recs[idx];
    if (r.time_us < start_us || r.time_us >= until_us) continue;
    if (r.creation_us <= created_after_us ||
        r.creation_us > created_until_us)
      continue;
    std::string_view pv;
    if (!map.view(r, &pv)) {
      if (!read_payload(h, r, &payload)) continue;
      pv = payload;
    }
    int64_t t, c;
    std::string_view s[9];
    if (!parse_event((const unsigned char*)pv.data(),
                     (uint32_t)pv.size(), &t, &c, s))
      continue;
    if (entity_type && s[2] != entity_type) continue;
    if (target_entity_type && s[4] != target_entity_type) continue;
    if (s[5].empty()) continue;  // no target entity: not a pair
    if (event_names) {
      bool ok = false;
      for (auto& n : names_filter)
        if (s[1] == n) { ok = true; break; }
      if (!ok) continue;
    }
    if (names.full16(s[1]) || etypes.full16(s[2]) || ttypes.full16(s[4]))
      return -2;
    times.push_back(t);
    creations.push_back(c);
    for (size_t k = 0; k < vkeys.size(); ++k)
      values[k].push_back(extract_number(s[6], vkeys[k]));
    ent_idx.push_back(ents.add(s[3]));
    tgt_idx.push_back(tgts.add(s[5]));
    name_idx.push_back((uint16_t)names.add(s[1]));
    etype_idx.push_back((uint16_t)etypes.add(s[2]));
    ttype_idx.push_back((uint16_t)ttypes.add(s[4]));
  }
  uint64_t n = times.size();
  std::string blob;
  blob.reserve(56 + n * (40 + 8 * vkeys.size()) + ents.table.size() +
               tgts.table.size() + names.table.size() + 128);
  append_u64(&blob, n);
  append_u64(&blob, ents.idx.size());
  append_u64(&blob, tgts.idx.size());
  append_u64(&blob, names.idx.size());
  append_u64(&blob, etypes.idx.size());
  append_u64(&blob, ttypes.idx.size());
  append_u64(&blob, (uint64_t)vkeys.size());
  blob.append((const char*)times.data(), n * 8);
  blob.append((const char*)creations.data(), n * 8);
  for (auto& col : values) blob.append((const char*)col.data(), n * 8);
  blob.append((const char*)ent_idx.data(), n * 4);
  append_padded(&blob);
  blob.append((const char*)tgt_idx.data(), n * 4);
  append_padded(&blob);
  blob.append((const char*)name_idx.data(), n * 2);
  append_padded(&blob);
  blob.append((const char*)etype_idx.data(), n * 2);
  append_padded(&blob);
  blob.append((const char*)ttype_idx.data(), n * 2);
  append_padded(&blob);
  blob.append(names.table);
  append_padded(&blob);
  blob.append(ents.table);
  append_padded(&blob);
  blob.append(tgts.table);
  append_padded(&blob);
  blob.append(etypes.table);
  append_padded(&blob);
  blob.append(ttypes.table);
  *out = dup_out(blob);
  return *out ? (long long)blob.size() : -1;
}

// ---------------- native NDJSON export (`pio export`) -------------------
//
// The inverse of the import path: stream frames back out as event
// wire JSON with zero per-event Python objects. Semantic parity with
// Event.to_json_str — same key order, same millisecond-truncated
// +00:00 timestamps — but json-loads-equal rather than byte-equal:
// stored property spans re-emit verbatim (raw UTF-8 passes through
// where Python's ensure_ascii would \u-escape; a "4.50" survives as
// "4.50" instead of renormalizing to 4.5). Cursor API so 20M-event
// exports stream in bounded chunks: events [cursor, cursor+max) of
// the time-sorted order; the caller must not interleave writes
// between calls (single importer process — the file-model contract).

namespace {

// Hinnant civil-from-days: inverse of days_from_civil.
void civil_from_days(int64_t z, int64_t* y, unsigned* m, unsigned* d) {
  z += 719468;
  const int64_t era = (z >= 0 ? z : z - 146096) / 146097;
  const unsigned doe = (unsigned)(z - era * 146097);
  const unsigned yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
  const int64_t yr = (int64_t)yoe + era * 400;
  const unsigned doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
  const unsigned mp = (5 * doy + 2) / 153;
  *d = doy - (153 * mp + 2) / 5 + 1;
  *m = mp + (mp < 10 ? 3 : -9);
  *y = yr + (*m <= 2);
}

// format_event_time parity: ISO-8601, millisecond-TRUNCATED, +00:00.
void append_iso_ms(std::string* out, int64_t us) {
  int64_t days = us / 86400000000LL;
  int64_t rem = us - days * 86400000000LL;
  if (rem < 0) { rem += 86400000000LL; --days; }
  int64_t y; unsigned mo, dd;
  civil_from_days(days, &y, &mo, &dd);
  unsigned hh = (unsigned)(rem / 3600000000LL);
  unsigned mi = (unsigned)(rem / 60000000LL % 60);
  unsigned ss = (unsigned)(rem / 1000000LL % 60);
  unsigned ms = (unsigned)(rem / 1000LL % 1000);
  char buf[48];
  snprintf(buf, sizeof buf,
           "%04lld-%02u-%02uT%02u:%02u:%02u.%03u+00:00",
           (long long)y, mo, dd, hh, mi, ss, ms);
  *out += buf;
}

void append_json_str(std::string* out, std::string_view s) {
  *out += '"';
  *out += json_escape(s);
  *out += '"';
}

}  // namespace

// Export events [cursor, cursor+max_events) of the sorted order as
// NDJSON. Returns the number of index entries VISITED (0 = cursor
// past the end — distinct from "visited but all unreadable", which
// returns the count with an empty blob so the caller keeps walking),
// -1 on error. *out is always malloc'd on success; blob byte length
// via *out_len.
long long pel_export_jsonl(void* hv, long long cursor,
                           long long max_events, char** out,
                           long long* out_len) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> g(h->mu);
  ensure_sorted(h);
  std::string blob;
  LogMap map(h);
  std::string payload;
  long long end = (long long)h->sorted.size();
  if (cursor < 0) cursor = 0;
  long long stop = (max_events >= 0 && cursor + max_events < end)
                       ? cursor + max_events : end;
  if (cursor >= end) {  // past the end: nothing allocated, no leak
    *out_len = 0;
    return 0;
  }
  for (long long i = cursor; i < stop; ++i) {
    const Rec& r = h->recs[h->sorted[(size_t)i]];
    std::string_view pv;
    if (!map.view(r, &pv)) {
      if (!read_payload(h, r, &payload)) continue;
      pv = payload;
    }
    int64_t t, c;
    std::string_view s[9];
    if (!parse_event((const unsigned char*)pv.data(), (uint32_t)pv.size(),
                     &t, &c, s))
      continue;
    // Event.to_json key order exactly
    blob += "{\"eventId\":";
    append_json_str(&blob, s[0]);
    blob += ",\"event\":";
    append_json_str(&blob, s[1]);
    blob += ",\"entityType\":";
    append_json_str(&blob, s[2]);
    blob += ",\"entityId\":";
    append_json_str(&blob, s[3]);
    // per-FIELD gating, matching Event.to_json's independent None
    // checks (frame "" ↔ None) — degenerate half-present targets must
    // export identically on both paths (r5 review)
    if (!s[4].empty()) {
      blob += ",\"targetEntityType\":";
      append_json_str(&blob, s[4]);
    }
    if (!s[5].empty()) {
      blob += ",\"targetEntityId\":";
      append_json_str(&blob, s[5]);
    }
    blob += ",\"properties\":";
    blob.append(s[6].empty() ? std::string_view("{}") : s[6]);
    blob += ",\"eventTime\":\"";
    append_iso_ms(&blob, t);
    blob += '"';
    if (!s[7].empty() && s[7] != "[]") {
      blob += ",\"tags\":";
      blob.append(s[7].data(), s[7].size());
    }
    if (!s[8].empty()) {
      blob += ",\"prId\":";
      append_json_str(&blob, s[8]);
    }
    blob += ",\"creationTime\":\"";
    append_iso_ms(&blob, c);
    blob += "\"}\n";
  }
  *out = dup_out(blob);
  if (!*out) return -1;
  *out_len = (long long)blob.size();
  return stop - cursor;
}

void pel_free(char* p) { free(p); }

}  // extern "C"
