from predictionio_tpu.parallel.mesh import (
    MeshConfig,
    make_mesh,
    shard_batch,
    replicated,
)

__all__ = ["MeshConfig", "make_mesh", "shard_batch", "replicated"]
