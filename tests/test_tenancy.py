"""Multi-tenant QoS: token buckets, the quotas.json policy store,
weighted-fair admission, the tenant-scoped ingest 429, hot-partition
writer sharding (read parity + SIGKILL-during-split crash safety), and
the ``profile_serving.py --tenants`` isolation drill."""

import datetime as dt
import http.client
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.pel_integrity import fsck_home
from predictionio_tpu.server.event_server import EventServer
from predictionio_tpu.server.tenancy import (FairInflight, TenantQuotas,
                                             TokenBucket)
from predictionio_tpu.utils.faults import FAULTS
from test_ingest import _mem_storage, _post, _setup_app
from test_servers import ServerThread, free_port

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


# -- TokenBucket ---------------------------------------------------------------


class TestTokenBucket:
    def test_burst_then_refusal_then_refill(self):
        clk = FakeClock()
        b = TokenBucket(rate=10.0, burst=5.0, clock=clk)
        assert b.take(5)
        assert not b.take(1)
        clk.advance(0.11)  # ~1 token accrues at 10/s
        assert b.take(1)
        assert not b.take(1)

    def test_retry_after_is_proportional_to_deficit(self):
        clk = FakeClock()
        b = TokenBucket(rate=10.0, burst=5.0, clock=clk)
        assert b.take(5)
        # 1 token needs 0.1s at 10/s; 5 tokens need 0.5s — the hint
        # prices the deficit, it is not a constant
        assert b.retry_after(1) == pytest.approx(0.1)
        assert b.retry_after(5) == pytest.approx(0.5)

    def test_bucket_never_overfills(self):
        clk = FakeClock()
        b = TokenBucket(rate=100.0, burst=3.0, clock=clk)
        clk.advance(60.0)
        assert b.take(3)
        assert not b.take(1)


# -- TenantQuotas --------------------------------------------------------------


class TestTenantQuotas:
    def test_default_policy_is_unlimited(self, tmp_path):
        q = TenantQuotas(str(tmp_path / "quotas.json"))
        for _ in range(1000):
            ok, ra = q.admit("7", 50)
            assert ok and ra == 0.0

    def test_override_throttles_one_app_only(self, tmp_path):
        clk = FakeClock()
        q = TenantQuotas(str(tmp_path / "quotas.json"), clock=clk)
        q.set_quota("7", rate=2.0, burst=2.0)
        assert q.admit("7")[0]
        assert q.admit("7")[0]
        ok, ra = q.admit("7")
        assert not ok and ra == pytest.approx(0.5)  # 1-token deficit at 2/s
        # the neighbour app never sees tenant 7's throttle
        assert q.admit("8")[0]

    def test_describe_and_field_floors(self, tmp_path):
        q = TenantQuotas(str(tmp_path / "quotas.json"))
        q.set_quota("7", rate=50.0, weight=2.0, writer_shards=4,
                    deadline_ms=750.0)
        eff = q.describe("7")
        assert eff == {"rate": 50.0, "burst": 50.0, "weight": 2.0,
                       "writer_shards": 4, "deadline_ms": 750.0}
        q.set_quota("9", weight=-3.0, writer_shards=0, deadline_ms=-1.0)
        assert q.weight("9") == 0.0
        assert q.writer_shards("9") == 1
        assert q.deadline_ms("9") == 0.0

    def test_clearing_an_override_restores_defaults(self, tmp_path):
        q = TenantQuotas(str(tmp_path / "quotas.json"))
        q.set_quota("7", rate=1.0, burst=1.0)
        assert q.admit("7")[0]
        assert not q.admit("7")[0]
        q.set_quota("7", rate=None, burst=None)
        assert q.admit("7", 100)[0]  # back to unlimited

    def test_quota_edit_does_not_refill_a_drained_bucket(self, tmp_path):
        clk = FakeClock()
        q = TenantQuotas(str(tmp_path / "quotas.json"), clock=clk)
        q.set_quota("7", rate=1.0, burst=3.0)
        for _ in range(3):
            assert q.admit("7")[0]
        assert not q.admit("7")[0]
        # editing an UNRELATED field must not hand the burster a
        # fresh burst allowance...
        q.set_quota("7", weight=2.0)
        assert not q.admit("7")[0]
        # ...but an actual rate/burst change rebuilds the bucket
        q.set_quota("7", rate=100.0, burst=100.0)
        assert q.admit("7")[0]

    def test_garbled_policy_file_keeps_previous_policy(self, tmp_path):
        clk = FakeClock()
        path = tmp_path / "quotas.json"
        q = TenantQuotas(str(path), clock=clk)
        q.set_quota("7", rate=1.0, burst=5.0)
        assert q.admit("7", 5)[0]
        path.write_text("{not json", encoding="utf-8")
        clk.advance(2.0)  # get past the 1s mtime-probe throttle
        # only 2 tokens accrued: a 5-event submit still over-draws —
        # proving the old policy survived the torn file (an unlimited
        # fallback would have admitted it)
        ok, ra = q.admit("7", 5)
        assert not ok and ra == pytest.approx(3.0)

    def test_quota_exhausted_fault_drills_the_429_path(self, tmp_path):
        """``tenant.quota.exhausted`` empties the bucket on demand:
        even an unlimited app gets its 429 + Retry-After, and the gate
        recovers the moment the drill is disarmed."""
        q = TenantQuotas(str(tmp_path / "quotas.json"))
        assert q.admit("9")[0]
        FAULTS.arm("tenant.quota.exhausted", error="drill")
        try:
            ok, ra = q.admit("9")
            assert not ok and ra > 0
        finally:
            FAULTS.disarm("tenant.quota.exhausted")
        assert q.admit("9")[0]


# -- FairInflight --------------------------------------------------------------


class TestFairInflight:
    def test_single_tenant_owns_the_whole_limit(self):
        f = FairInflight(4, clock=FakeClock())
        assert all(f.try_acquire("a") for _ in range(4))
        assert not f.try_acquire("a")  # global cap, not the share
        f.release("a")
        assert f.try_acquire("a")

    def test_burster_sheds_first_under_contention(self):
        clk = FakeClock()
        f = FairInflight(4, clock=clk)
        # both tenants active: each share is ceil(4 * 1/2) = 2
        for app in ("a", "b"):
            assert f.try_acquire(app)
            f.release(app)
        assert f.try_acquire("a") and f.try_acquire("a")
        assert not f.try_acquire("a")  # "a" is at its share...
        assert f.try_acquire("b")      # ...while "b" still gets a seat
        assert f.inflight("a") == 2 and f.inflight("b") == 1
        assert f.total == 3

    def test_weights_skew_the_shares(self):
        clk = FakeClock()
        weights = {"heavy": 3.0, "light": 1.0}
        f = FairInflight(4, weight_of=lambda a: weights.get(a, 1.0),
                         clock=clk)
        for app in ("heavy", "light"):
            assert f.try_acquire(app)
            f.release(app)
        # heavy: ceil(4 * 3/4) = 3; light: ceil(4 * 1/4) = 1
        for _ in range(3):
            assert f.try_acquire("heavy")
        assert not f.try_acquire("heavy")
        assert f.try_acquire("light")
        assert not f.try_acquire("light")

    def test_idle_tenants_stop_diluting_the_shares(self):
        clk = FakeClock()
        f = FairInflight(4, active_window=5.0, clock=clk)
        assert f.try_acquire("b")
        f.release("b")
        assert f.share("a") == 2  # "b" still in the active window
        clk.advance(6.0)
        assert f.share("a") == 4  # "b" aged out: "a" is alone again

    def test_release_of_unknown_app_is_harmless(self):
        f = FairInflight(2, clock=FakeClock())
        f.release("ghost")
        assert f.total == 0
        assert f.try_acquire("a")


# -- the tenant-scoped 429 through a live Event Server -------------------------


class TestIngestQuota429:
    def test_429_is_tenant_scoped_with_honest_retry_after(self, tmp_path):
        st = _mem_storage()
        limited, lkey = _setup_app(st, "limited")
        unmetered, ukey = _setup_app(st, "unmetered")
        quotas = TenantQuotas(str(tmp_path / "quotas.json"))
        quotas.set_quota(str(limited.id), rate=1.0, burst=3.0)
        port = free_port()
        server = EventServer(storage=st, host="127.0.0.1", port=port,
                             tenant_quotas=quotas)
        ev = {"event": "buy", "entityType": "user", "entityId": "u1",
              "targetEntityType": "item", "targetEntityId": "i1"}
        with ServerThread(server):
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            results = [_post(conn, f"/events.json?accessKey={lkey}", ev)
                       for _ in range(6)]
            throttled = [r for r in results if r[0] == 429]
            assert throttled, f"no 429 for the over-quota app: {results}"
            for status, body, headers in throttled:
                # fleet-standard shed shape: machine-usable float in
                # the body, RFC 9110 integral header, never shorter
                # than the computed wait
                assert body["retryAfterSec"] > 0
                assert int(headers["Retry-After"]) >= 1
            # the unmetered neighbour never sees tenant 7's throttle
            for _ in range(6):
                status, _, _ = _post(
                    conn, f"/events.json?accessKey={ukey}", ev)
                assert status == 201
            conn.close()
        assert server._m_quota._values.get(
            (str(limited.id),), 0) >= len(throttled)
        assert server._m_quota._values.get((str(unmetered.id),), 0) == 0


# -- hot-partition writer sharding --------------------------------------------

APP_PARITY = 91
APP_HOT = 92
_BASE = dt.datetime(2026, 3, 1, tzinfo=dt.timezone.utc)


def _native_store(path):
    from predictionio_tpu.data.filestore import NativeEventLogStore

    try:
        return NativeEventLogStore(str(path))  # builds the engine
    except RuntimeError as e:  # no g++ in this environment
        pytest.skip(str(e))


def _mk_events(n):
    # DISTINCT event times: cross-shard merge ties are broken by shard
    # order, so identical timestamps could legally reorder vs the
    # unsharded file — the parity claim is about real streams, which
    # have distinct microsecond timestamps
    return [Event(event="rate", entity_type="user",
                  entity_id=f"u{i % 17}",
                  target_entity_type="item", target_entity_id=f"i{i % 11}",
                  properties={"rating": float(i % 5)},
                  event_time=_BASE + dt.timedelta(seconds=i))
            for i in range(n)]


def _rows(events):
    return [(e.event, e.entity_type, e.entity_id, e.target_entity_type,
             e.target_entity_id, e.properties.get("rating"), e.event_time)
            for e in events]


def _col_rows(cols):
    return sorted(
        (cols.entity_ids[cols.entity_idx[i]],
         cols.target_ids[cols.target_idx[i]],
         cols.names[cols.name_idx[i]],
         float(cols.values[i]), int(cols.times_us[i]))
        for i in range(len(cols.entity_idx)))


class TestShardReadParity:
    def test_sharded_reads_match_unsharded(self, tmp_path):
        flat = _native_store(tmp_path / "flat")
        sharded = _native_store(tmp_path / "sharded")
        sharded.set_shard_policy(lambda app: 4)
        events = _mk_events(120)
        flat_ids = flat.insert_batch(events, APP_PARITY)
        shard_ids = sharded.insert_batch(events, APP_PARITY)
        try:
            # the fan-out actually happened: >1 shard file on disk
            shard_files = [p for p in os.listdir(tmp_path / "sharded")
                           if p.startswith(f"events_{APP_PARITY}")
                           and p.endswith(".pel")]
            assert len(shard_files) > 1, shard_files

            # find(): identical streams, identical ORDER (the k-way
            # merge restores the global event-time order)
            assert _rows(sharded.find(APP_PARITY)) == \
                _rows(flat.find(APP_PARITY))
            assert _rows(sharded.find(APP_PARITY, reversed=True)) == \
                _rows(flat.find(APP_PARITY, reversed=True))
            # filtered reads agree too (entity filter crosses shards)
            assert _rows(sharded.find(APP_PARITY, entity_id="u3")) == \
                _rows(flat.find(APP_PARITY, entity_id="u3"))

            # creation_stats: same live count either way
            assert sharded.creation_stats(APP_PARITY)[0] == \
                flat.creation_stats(APP_PARITY)[0] == 120

            # scan_columnar: same training matrix from either layout
            f_cols = flat.scan_columnar(APP_PARITY, value_key="rating")
            s_cols = sharded.scan_columnar(APP_PARITY, value_key="rating")
            assert _col_rows(s_cols) == _col_rows(f_cols)

            # tombstones: delete the same logical event in both;
            # every read path agrees afterwards
            assert flat.delete(flat_ids[37], APP_PARITY)
            assert sharded.delete(shard_ids[37], APP_PARITY)
            assert sharded.get(shard_ids[37], APP_PARITY) is None
            assert sharded.creation_stats(APP_PARITY)[0] == \
                flat.creation_stats(APP_PARITY)[0] == 119
            assert _rows(sharded.find(APP_PARITY)) == \
                _rows(flat.find(APP_PARITY))
        finally:
            flat.close()
            sharded.close()

        # restart WITHOUT the policy: shard discovery keeps reads
        # covering every shard file ever written
        reopened = _native_store(tmp_path / "sharded")
        try:
            assert reopened.creation_stats(APP_PARITY)[0] == 119
            assert len(_rows(reopened.find(APP_PARITY))) == 119
        finally:
            reopened.close()

    def test_hot_shard_fault_collapses_the_hash(self, tmp_path):
        """``segments.shard.hot`` bypasses the entity hash: every
        append lands on writer shard 0, and the per-shard append
        series (``pio_eventlog_shard_appends_total``) shows exactly
        the skew the runbook tells operators to watch for."""
        store = _native_store(tmp_path / "hot")
        store.set_shard_policy(lambda app: 4)
        counter = store._m_shard_appends
        before = dict(counter._values)
        FAULTS.arm("segments.shard.hot", error="hot partition drill")
        try:
            store.insert_batch(_mk_events(40), APP_HOT)
        finally:
            FAULTS.disarm("segments.shard.hot")
        app = str(APP_HOT)  # label tuples are stringified
        deltas = {k: counter._values.get(k, 0) - before.get(k, 0)
                  for k in counter._values
                  if k[0] == app and counter._values.get(k, 0) !=
                  before.get(k, 0)}
        assert deltas == {(app, "0"): 40}
        try:
            # disarmed, the hash spreads the very next batch again
            before = dict(counter._values)
            store.insert_batch(_mk_events(40), APP_HOT)
            spread = {k for k in counter._values
                      if k[0] == app and
                      counter._values.get(k, 0) > before.get(k, 0)}
            assert len(spread) > 1, spread
        finally:
            store.close()


# -- SIGKILL during the shard split -------------------------------------------

_SPLIT_CHILD = """
import datetime as dt
import os

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.filestore import NativeEventLogStore

home = os.path.join(os.getcwd(), "home")
store = NativeEventLogStore(os.path.join(home, "eventlog"))
store.set_shard_policy(lambda app: 4)  # the split: 1 -> 4 writer shards
base = dt.datetime(2026, 4, 1, tzinfo=dt.timezone.utc)
i = 0
while True:
    events = [Event(event="rate", entity_type="user",
                    entity_id=str((i * 40 + j) % 257),
                    target_entity_type="item", target_entity_id=str(j % 13),
                    properties={"rating": float(j % 5)},
                    event_time=base + dt.timedelta(seconds=i * 40 + j))
              for j in range(40)]
    store.insert_batch(events, 7)
    i += 1
"""


@pytest.mark.slow
def test_sigkill_during_split_leaves_a_clean_home(tmp_path):
    """kill -9 a writer mid-split (appends fanning across brand-new
    shard files): ``pio fsck`` must come back clean after repair (a
    torn ACTIVE tail is a legitimate crash artifact, quarantined — not
    corruption), and a restarted store must read every shard with
    ``find``/``creation_stats`` agreeing on the surviving count."""
    probe = _native_store(tmp_path / "probe")  # g++ gate for the child
    probe.close()
    home = tmp_path / "home"
    log_dir = home / "eventlog"
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT}
    proc = subprocess.Popen([sys.executable, "-c", _SPLIT_CHILD],
                            cwd=str(tmp_path), env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE)

    def split_visible():
        if not log_dir.is_dir():
            return False
        shards = [p for p in os.listdir(log_dir)
                  if p.startswith("events_7") and p.endswith(".pel")]
        return len(shards) >= 3  # the split materialized on disk

    deadline = time.monotonic() + 120.0
    try:
        while not split_visible():
            if proc.poll() is not None:
                raise AssertionError("writer died before the kill: "
                                     + proc.stderr.read().decode())
            if time.monotonic() > deadline:
                raise AssertionError("writer produced no shard files")
            time.sleep(0.02)
    finally:
        try:
            proc.send_signal(signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()

    # fsck with repair quarantines any torn tails; a second pass must
    # then be fully clean — nothing else in the home was damaged
    fsck_home(str(home), repair=True)
    assert fsck_home(str(home))["corrupt"] == 0

    store = _native_store(log_dir)
    try:
        rows = _rows(store.find(7))
        assert rows  # the committed prefix survived
        assert rows == sorted(rows, key=lambda r: r[-1])  # merged order
        assert store.creation_stats(7)[0] == len(rows)
    finally:
        store.close()


# -- the end-to-end isolation drill -------------------------------------------


@pytest.mark.slow
def test_tenants_chaos_harness_proves_isolation():
    """Run the full ``profile_serving.py --tenants`` drill: a 10x
    burster against two quiet tenants; quiet p99 within 1.5x of the
    solo baseline, zero quiet-tenant 429/503, the burster throttled
    with an honest Retry-After, zero serving-path compiles."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "profile_serving.py"),
         "--tenants", "--n-users", "20000", "--n-items", "8000",
         "--rank", "32", "--queries", "400"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=900)
    assert r.returncode == 0, (r.stdout[-4000:], r.stderr[-4000:])
    payloads = [line for line in r.stdout.splitlines()
                if line.startswith("{")]
    assert payloads, r.stdout[-4000:]
    doc = json.loads(payloads[-1])
    assert doc["metric"] == "tenant_qos_isolation"
    assert doc["ok"] is True
