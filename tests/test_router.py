"""Fleet-router tests (server/router.py): replica state machine driven
by active /health polling + passive breaker ejection, P2C routing,
retry budget, hedging, Retry-After honoring, deadline/trace
propagation, replica identity resets, manifest watching, and the
zero-downtime rolling reload (docs/operations.md "Fleet deployment")."""

import asyncio
import contextlib
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.server.http import HTTPServer, Response, Router
from predictionio_tpu.server.router import (
    DOWN,
    OK,
    FleetRouter,
    Replica,
    _Attempt,
)
from predictionio_tpu.utils.faults import FAULTS
from tests.test_servers import ServerThread, free_port, http


@pytest.fixture(autouse=True)
def disarm_faults():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


def http_full(method, url, body=None, headers=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode() or "null"), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "null"), dict(e.headers)


def cval(counter, *labels):
    """Current value of one labelled counter series (counters are
    process-global, so tests assert DELTAS around the action)."""
    return counter._values.get(tuple(labels), 0)


def wait_until(cond, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


class StubReplica:
    """A scriptable engine-server stand-in speaking the replica
    contract the router depends on: /health with identity fields,
    /queries.json, /events.json (non-idempotent), /reload."""

    def __init__(self, port, instance="stub", latency=0.0):
        self.port = port
        self.instance = instance
        self.health_status = "ok"
        self.health_retry_after = None   # retryAfterSec on not-ready
        self.latency = latency           # seconds per query
        self.query_status = 200
        self.query_retry_after = None    # Retry-After header on errors
        self.fail_first = 0              # answer 500 to the first N queries
        self.started_at = 1000.0
        self.reload_generation = 0
        self.queries = 0
        self.events = 0
        self.reloads = 0
        router = Router()
        router.route("GET", "/", self._root)
        router.route("GET", "/health", self._health)
        router.route("GET", "/reload", self._reload)
        router.route("POST", "/queries.json", self._query)
        router.route("POST", "/events.json", self._event)
        self.http = HTTPServer(router, "127.0.0.1", port,
                               access_log=False, server_name="stub")

    @property
    def url(self):
        return f"127.0.0.1:{self.port}"

    async def serve_forever(self):
        await self.http.serve_forever()

    async def _root(self, req):
        return Response.json({"status": "stub"})

    async def _health(self, req):
        body = {"status": self.health_status, "instance": self.instance,
                "startedAt": self.started_at,
                "reloadGeneration": self.reload_generation}
        if self.health_status == "not-ready":
            if self.health_retry_after is not None:
                body["retryAfterSec"] = self.health_retry_after
            resp = Response.json(body, status=503)
            resp.headers["Retry-After"] = "1"
            return resp
        return Response.json(body)

    async def _query(self, req):
        self.queries += 1
        if self.latency:
            await asyncio.sleep(self.latency)
        if self.fail_first > 0:
            self.fail_first -= 1
            return Response.json({"message": "induced failure"}, status=500)
        if self.query_status != 200:
            resp = Response.json({"message": "induced"},
                                 status=self.query_status)
            if self.query_retry_after is not None:
                resp.headers["Retry-After"] = self.query_retry_after
            return resp
        return Response.json({"instance": self.instance,
                              "seen": dict(req.headers)})

    async def _event(self, req):
        self.events += 1
        if self.query_status != 200:
            return Response.json({"message": "induced"},
                                 status=self.query_status)
        return Response.json({"eventId": "stub"}, status=201)

    async def _reload(self, req):
        self.reloads += 1
        self.reload_generation += 1
        return Response.json({"reloadGeneration": self.reload_generation})


@contextlib.contextmanager
def fleet(n=2, router_kwargs=None, stub_latency=None):
    """n live stub replicas + a router over them, all on daemon
    threads. Yields (router, stubs, threads)."""
    stubs = [StubReplica(free_port(), instance=f"stub-{i}",
                         latency=(stub_latency or [0.0] * n)[i])
             for i in range(n)]
    with contextlib.ExitStack() as stack:
        threads = [stack.enter_context(ServerThread(s)) for s in stubs]
        router = FleetRouter([s.url for s in stubs],
                             host="127.0.0.1", port=free_port(),
                             **(router_kwargs or {}))
        stack.enter_context(ServerThread(router))
        yield router, stubs, threads


class TestReplicaUnits:
    def test_parse_hostport_accepts_bare_and_url_forms(self):
        assert Replica.parse_hostport("10.0.0.1:8000") == ("10.0.0.1", 8000)
        assert Replica.parse_hostport("http://h:81") == ("h", 81)
        with pytest.raises(ValueError, match="host:port"):
            Replica.parse_hostport("no-port-here")

    def test_availability_gates(self):
        r = Replica(f"127.0.0.1:{free_port()}")
        r.state = OK
        assert r.available(now=0.0)
        r.draining = True
        assert not r.available(now=0.0)
        r.draining = False
        r.backoff_until = 10.0
        assert not r.available(now=0.0)       # inside Retry-After window
        assert r.available(now=10.0)
        r.state = DOWN
        assert not r.available(now=10.0)

    def test_attempt_retryable_classification(self):
        r = Replica(f"127.0.0.1:{free_port()}")
        assert _Attempt(r, 0, {}, b"").retryable       # transport
        assert _Attempt(r, 500, {}, b"").retryable
        assert _Attempt(r, 429, {}, b"").retryable
        assert not _Attempt(r, 200, {}, b"").retryable
        assert not _Attempt(r, 404, {}, b"").retryable  # client's problem


class TestRouting:
    def test_spreads_queries_over_healthy_replicas(self):
        with fleet(2, {"hedge": False}) as (router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            for _ in range(20):
                code, body = http("POST", f"{base}/queries.json",
                                  {"user": "1"})
                assert code == 200
            assert stubs[0].queries + stubs[1].queries == 20
            # sequential load carries no inflight signal, so P2C may
            # legitimately favor the replica with the lower EWMA — but
            # the fresh-replica floor guarantees both get work
            assert stubs[0].queries >= 1 and stubs[1].queries >= 1

    def test_dead_replica_is_absorbed_by_passive_ejection(self):
        # passive path only: health polls far apart, so the breaker —
        # fed by live request failures — must do the ejecting. The
        # stopped stub's sockets stay half-open (the loop just stops),
        # so the per-try timeout is what surfaces the failure — the
        # worst case of a kill: a peer that neither answers nor resets.
        with fleet(2, {"hedge": False, "health_interval": 30.0,
                       "per_try_timeout_ms": 300.0}) as (
                router, stubs, threads):
            base = f"http://127.0.0.1:{router.http.port}"
            assert http("POST", f"{base}/queries.json", {})[0] == 200
            threads[0].__exit__(None, None, None)  # stub-0 goes dark
            before = stubs[1].queries
            for _ in range(20):
                assert http("POST", f"{base}/queries.json", {})[0] == 200
            assert stubs[1].queries - before >= 15
            dead = next(r for r in router.replicas
                        if r.name == stubs[0].url)
            assert dead.breaker.state == "open"

    def test_injected_replica_down_is_retried_to_200(self):
        with fleet(2, {"hedge": False}) as (router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            assert http("POST", f"{base}/queries.json", {})[0] == 200
            before = cval(router._m_retries, "transport", "-")
            FAULTS.arm("router.replica.down", error="replica gone", count=1)
            code, _ = http("POST", f"{base}/queries.json", {})
            assert code == 200
            assert cval(router._m_retries, "transport", "-") == before + 1

    def test_transient_500s_are_retried_until_success(self):
        with fleet(1, {"hedge": False}) as (router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            stubs[0].fail_first = 2
            before = cval(router._m_retries, "500", "-")
            code, _ = http("POST", f"{base}/queries.json", {})
            assert code == 200
            assert stubs[0].queries == 3
            assert cval(router._m_retries, "500", "-") == before + 2


class TestRetryPolicy:
    def test_non_idempotent_post_is_never_retried(self):
        with fleet(1, {"hedge": False}) as (router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            stubs[0].query_status = 500
            before = cval(router._m_retry_denied, "non_idempotent", "-")
            code, _ = http("POST", f"{base}/events.json", {"event": "buy"})
            assert code == 500          # passthrough, not masked
            assert stubs[0].events == 1  # exactly ONE delivery attempt
            assert cval(router._m_retry_denied,
                        "non_idempotent", "-") == before + 1

    def test_retry_budget_caps_amplification(self):
        with fleet(1, {"hedge": False, "retry_budget_ratio": 0.0,
                       "retry_budget_burst": 1.0}) as (router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            stubs[0].query_status = 500
            denied = cval(router._m_retry_denied, "budget", "-")
            code, _ = http("POST", f"{base}/queries.json", {})
            assert code == 500
            # one original + the single budgeted retry, then denial
            assert stubs[0].queries == 2
            assert cval(router._m_retry_denied, "budget", "-") >= denied + 1
            # keep failing: the breaker (threshold 3) ejects the
            # replica, and with nothing left the router answers 503
            code, _ = http("POST", f"{base}/queries.json", {})
            assert code == 500
            code, body, headers = http_full(
                "POST", f"{base}/queries.json", {})
            assert code == 503
            assert "no replica available" in body["message"]
            assert int(headers["Retry-After"]) >= 1

    def test_replica_retry_after_is_honored(self):
        with fleet(2, {"hedge": False, "health_interval": 30.0}) as (
                router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            stubs[0].query_status = 503
            stubs[0].query_retry_after = "30"
            # keep querying until the throttling replica has answered
            # one 503 (the retry masks it: the client still sees 200)
            for _ in range(20):
                assert http("POST", f"{base}/queries.json", {})[0] == 200
                if stubs[0].queries:
                    break
            assert stubs[0].queries >= 1
            throttled = next(r for r in router.replicas
                             if r.name == stubs[0].url)
            assert throttled.backoff_until > 0
            seen = stubs[0].queries
            for _ in range(10):
                assert http("POST", f"{base}/queries.json", {})[0] == 200
            # inside its Retry-After window the replica gets NOTHING
            assert stubs[0].queries == seen


class TestHedging:
    def test_slow_primary_is_hedged_first_answer_wins(self):
        with fleet(2, {"hedge_min_ms": 30.0}) as (router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            assert http("POST", f"{base}/queries.json", {})[0] == 200
            won = cval(router._m_hedges, "won", "-")
            launched = cval(router._m_hedges, "launched", "-")
            FAULTS.arm("router.replica.slow", latency=0.8, count=1)
            t0 = time.perf_counter()
            code, _ = http("POST", f"{base}/queries.json", {})
            elapsed = time.perf_counter() - t0
            assert code == 200
            # answered at ~the 30ms hedge delay, not the 800ms stall
            assert elapsed < 0.6
            assert cval(router._m_hedges, "launched", "-") == launched + 1
            assert cval(router._m_hedges, "won", "-") == won + 1


class TestHealthAndIdentity:
    def test_health_flap_marks_down_then_recovers(self):
        with fleet(2, {"hedge": False, "health_interval": 0.1}) as (
                router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            assert http("GET", f"{base}/health")[0] == 200
            FAULTS.arm("router.health.flap", error="partitioned")
            assert wait_until(lambda: all(r.state == DOWN
                                          for r in router.replicas))
            code, body, headers = http_full("GET", f"{base}/health")
            assert code == 503 and body["status"] == "not-ready"
            assert int(headers["Retry-After"]) >= 1
            assert http("POST", f"{base}/queries.json", {})[0] == 503
            FAULTS.disarm()
            assert wait_until(lambda: all(r.state == OK
                                          for r in router.replicas))
            assert http("POST", f"{base}/queries.json", {})[0] == 200

    def test_restarted_replica_identity_resets_breaker_and_ewma(self):
        with fleet(1, {"hedge": False, "health_interval": 0.1}) as (
                router, stubs, _):
            rep = router.replicas[0]
            assert wait_until(lambda: rep.instance == "stub-0")
            for _ in range(3):
                rep.breaker.record_failure()
            rep.ewma_sec = 1.5
            assert rep.breaker.state == "open"
            # same process flapping: the breaker stays open across polls
            time.sleep(0.3)
            assert rep.breaker.state == "open"
            # ...but a NEW process id means a restart: forgive the past
            stubs[0].instance = "stub-0-reborn"
            assert wait_until(lambda: rep.instance == "stub-0-reborn")
            assert rep.breaker.state == "closed"
            assert rep.ewma_sec == 0.0
            base = f"http://127.0.0.1:{router.http.port}"
            assert http("POST", f"{base}/queries.json", {})[0] == 200

    def test_not_ready_health_backs_off_by_its_hint(self):
        with fleet(2, {"hedge": False, "health_interval": 0.1}) as (
                router, stubs, _):
            stubs[0].health_status = "not-ready"
            stubs[0].health_retry_after = 30.0
            rep = next(r for r in router.replicas
                       if r.name == stubs[0].url)
            assert wait_until(lambda: rep.state == "not-ready"
                              and rep.backoff_until > 0)
            base = f"http://127.0.0.1:{router.http.port}"
            before = stubs[0].queries
            for _ in range(5):
                assert http("POST", f"{base}/queries.json", {})[0] == 200
            assert stubs[0].queries == before


class TestPropagation:
    def test_deadline_shrinks_and_trace_headers_flow_through(self):
        with fleet(1, {"hedge": False}) as (router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
            code, body = http(
                "POST", f"{base}/queries.json", {"user": "1"},
                headers={"X-PIO-Deadline-Ms": "5000", "traceparent": tp,
                         "X-PIO-Trace-Id": "trace-42"})
            assert code == 200
            seen = body["seen"]
            fwd = float(seen["x-pio-deadline-ms"])
            # the hop budget SHRINKS: below what the client sent, but
            # not collapsed (router overhead is a few ms)
            assert 4000 < fwd < 5000
            assert seen["traceparent"] == tp
            assert seen["x-pio-trace-id"] == "trace-42"


class TestRollingReload:
    def test_rolling_reload_serves_zero_errors(self):
        with fleet(3, {"hedge": False, "health_interval": 0.2,
                       "drain_timeout": 5.0, "ready_timeout": 10.0}) as (
                router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            assert http("POST", f"{base}/queries.json", {})[0] == 200
            stop = threading.Event()
            statuses = []

            def hammer():
                while not stop.is_set():
                    statuses.append(
                        http("POST", f"{base}/queries.json", {})[0])

            t = threading.Thread(target=hammer)
            t.start()
            try:
                code, body, _ = http_full(
                    "POST", f"{base}/router/reload?rolling=1", timeout=60)
            finally:
                time.sleep(0.2)
                stop.set()
                t.join(timeout=10)
            assert code == 200 and body["ok"] is True
            assert len(body["replicas"]) == 3
            assert all(e["result"] == "ok" for e in body["replicas"])
            assert all(s.reloads == 1 for s in stubs)
            assert all(e["reloadGeneration"] == 1 for e in body["replicas"])
            # a full-fleet model swap served zero errors
            assert statuses and set(statuses) == {200}

    def test_non_rolling_reload_hits_every_replica(self):
        with fleet(2, {"hedge": False}) as (router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            code, body, _ = http_full("POST", f"{base}/router/reload",
                                      timeout=60)
            assert code == 200 and body["ok"] is True
            assert body["rolling"] is False
            assert all(s.reloads == 1 for s in stubs)


class TestEndpointsAndManifest:
    def test_status_root_and_metrics(self):
        with fleet(2, {"hedge": False, "health_interval": 0.1}) as (
                router, stubs, _):
            base = f"http://127.0.0.1:{router.http.port}"
            assert wait_until(
                lambda: all(r.state == OK for r in router.replicas))
            code, body = http("GET", f"{base}/")
            assert code == 200
            assert body["status"] == "router" and body["available"] == 2
            code, body = http("GET", f"{base}/router/status")
            assert code == 200
            snaps = {s["url"]: s for s in body["replicas"]}
            assert set(snaps) == {f"http://{s.url}" for s in stubs}
            for i, s in enumerate(stubs):
                snap = snaps[f"http://{s.url}"]
                assert snap["state"] == "ok"
                assert snap["instance"] == f"stub-{i}"
                assert snap["breaker"] == "closed"
            assert body["retryBudgetTokens"] > 0
            req = urllib.request.Request(f"{base}/metrics")
            with urllib.request.urlopen(req, timeout=10) as r:
                text = r.read().decode()
            for name in ("pio_router_replica_state",
                         "pio_router_retry_budget_remaining",
                         "pio_router_replica_seconds"):
                assert name in text

    def test_manifest_watch_adds_and_removes_replicas(self, tmp_path):
        s1 = StubReplica(free_port(), instance="m-0")
        s2 = StubReplica(free_port(), instance="m-1")
        manifest = tmp_path / "fleet.txt"
        manifest.write_text(f"# fleet\n{s1.url}\n")
        with ServerThread(s1), ServerThread(s2):
            router = FleetRouter(manifest=str(manifest),
                                 host="127.0.0.1", port=free_port(),
                                 hedge=False, health_interval=0.1)
            with ServerThread(router):
                assert [r.name for r in router.replicas] == [s1.url]
                manifest.write_text(f"{s1.url}\n{s2.url}\n")
                os.utime(manifest, (time.time() + 5, time.time() + 5))
                assert wait_until(lambda: len(router.replicas) == 2)
                assert wait_until(
                    lambda: all(r.state == OK for r in router.replicas))
                manifest.write_text(f"{s2.url}\n")
                os.utime(manifest, (time.time() + 10, time.time() + 10))
                assert wait_until(lambda: len(router.replicas) == 1)
                assert router.replicas[0].name == s2.url
                base = f"http://127.0.0.1:{router.http.port}"
                code, body = http("POST", f"{base}/queries.json", {})
                assert code == 200 and body["instance"] == "m-1"
