"""JAX implementations of the algorithm library.

These replace Spark MLlib in the reference (reference: MLlib ALS /
LogisticRegressionWithLBFGS / NaiveBayes used by the engine templates,
SURVEY.md §2c). Everything here is mesh-aware: pass a
``jax.sharding.Mesh`` to shard the computation over devices with ICI
collectives; pass None to run on one chip.
"""
